"""Gang admission & TPU capacity scheduler (ISSUE 4).

Pins the acceptance contract end to end:

- all-or-nothing admission: a job that doesn't fit creates ZERO pods and
  carries a Queued condition;
- priority order with FIFO-within-priority and starvation-resistant aging;
- preemption frees exactly the victim's chips and requeues it;
- reservations released on terminal cleanup (and deletion), waking the queue;
- the --contention bench shows a late high-priority job admitted ahead of
  earlier low-priority arrivals.
"""

from __future__ import annotations

import json
import os
import urllib.request

import pytest

from k8s_tpu import scheduler as scheduler_mod
from k8s_tpu.api import register, v1alpha2, validation
from k8s_tpu.api.meta import ObjectMeta
from k8s_tpu.client import Clientset, FakeCluster
from k8s_tpu.client.informer import SharedInformerFactory
from k8s_tpu.client.record import FakeRecorder
from k8s_tpu.controller_v2 import pod as pod_mod
from k8s_tpu.controller_v2 import service as service_mod
from k8s_tpu.controller_v2 import status as status_mod
from k8s_tpu.controller_v2 import tpu_config
from k8s_tpu.controller_v2.control import FakePodControl, FakeServiceControl
from k8s_tpu.controller_v2.controller import (
    TFJobController,
    cluster_chips_from_env,
)
from k8s_tpu.scheduler import GangScheduler, chips_from_nodes

NS = "default"


# -- pure scheduler unit tier --------------------------------------------------


class TestAdmissionOrdering:
    def test_fifo_within_priority(self):
        s = GangScheduler(total_chips=16, aging_interval_s=1000)
        # blocker holds the whole cluster so arrivals queue
        assert s.sync_admit("ns/blocker", 16, 0, now=0.0).admitted
        a = s.sync_admit("ns/a", 16, 0, now=1.0)
        b = s.sync_admit("ns/b", 16, 0, now=2.0)
        assert a.queued and b.queued
        assert [e.key for e in s.queue.ordered(now=3.0)] == ["ns/a", "ns/b"]
        # equal-priority arrivals never name the blocker as a victim
        assert a.victims == [] and b.victims == []

    def test_priority_order_beats_fifo(self):
        s = GangScheduler(total_chips=16, aging_interval_s=1000)
        assert s.sync_admit("ns/blocker", 16, 9, now=0.0).admitted
        s.sync_admit("ns/lo", 16, 0, now=1.0)
        s.sync_admit("ns/hi", 16, 5, now=2.0)
        assert [e.key for e in s.queue.ordered(now=3.0)] == ["ns/hi", "ns/lo"]
        # blocker done -> the released chips seat the HIGH-priority job even
        # though the low-priority one asked first
        s.release("ns/blocker")
        assert s.sync_admit("ns/lo", 16, 0, now=4.0).queued
        assert s.sync_admit("ns/hi", 16, 5, now=4.0).admitted

    def test_aging_boosts_starved_low_priority_job(self):
        s = GangScheduler(total_chips=16, aging_interval_s=10,
                          max_aging_boost=5)
        assert s.sync_admit("ns/blocker", 16, 9, now=0.0).admitted
        s.sync_admit("ns/old-lo", 16, 0, now=0.0)     # parked at t=0
        s.sync_admit("ns/new-hi", 16, 3, now=55.0)    # arrives much later
        # at t=60 the old job has aged min(6, 5)=5 effective-priority steps
        # while the newcomer has 0: 0+5 > 3+0 -> the starved job goes first
        assert [e.key for e in s.queue.ordered(now=60.0)] == \
            ["ns/old-lo", "ns/new-hi"]
        s.release("ns/blocker")
        assert s.sync_admit("ns/new-hi", 16, 3, now=61.0).queued
        d = s.sync_admit("ns/old-lo", 16, 0, now=61.0)
        assert d.admitted and d.wait_s == pytest.approx(61.0)

    def test_aging_never_drives_preemption(self):
        # base priorities only: an aged job outranks the QUEUE, it never
        # evicts a genuinely more important RUNNING gang
        s = GangScheduler(total_chips=16, aging_interval_s=1, max_aging_boost=5)
        assert s.sync_admit("ns/blocker", 16, 3, now=0.0).admitted
        d = s.sync_admit("ns/lo", 16, 0, now=1000.0)  # eff 5 > 3, base 0 < 3
        assert d.queued and d.victims == []

    def test_no_backfill_past_a_waiting_higher_priority_giant(self):
        s = GangScheduler(total_chips=32, aging_interval_s=1000)
        assert s.sync_admit("ns/run", 16, 0, now=0.0).admitted
        # the giant (32 chips) waits at the head; 16 chips sit free
        assert s.sync_admit("ns/giant", 32, 5, now=1.0).queued
        # a small job WOULD fit those 16 — but seating it would recycle
        # exactly the chips the giant is waiting for, forever: strict
        # head-of-line order parks it behind the giant instead
        assert s.sync_admit("ns/small", 16, 0, now=2.0).queued
        s.release("ns/run")
        assert s.sync_admit("ns/giant", 32, 5, now=3.0).admitted
        # with the giant seated the queue drains on: small next, once
        # capacity returns
        s.release("ns/giant")
        assert s.sync_admit("ns/small", 16, 0, now=4.0).admitted


class TestPreemption:
    def test_preemption_frees_exactly_victim_chips_and_requeues(self):
        s = GangScheduler(total_chips=32, aging_interval_s=1000)
        assert s.sync_admit("ns/victim", 32, 0, now=0.0).admitted
        d = s.sync_admit("ns/vip", 16, 10, now=1.0)
        assert not d.admitted and d.victims == ["ns/victim"]
        done = s.preempt("ns/vip", 16, 10, "prod", d.victims, now=2.0)
        assert done.admitted and done.newly_admitted
        assert done.victims == ["ns/victim"]
        # exactly the victim's chips came back: 32 freed, 16 re-reserved
        assert s.capacity.in_use() == 16
        assert s.capacity.available() == 16
        assert set(s.capacity.reservations) == {"ns/vip"}
        # the victim is back in the queue at its ORIGINAL base priority,
        # marked with who evicted it
        entry = s.queue.get("ns/victim")
        assert entry is not None and entry.priority == 0
        assert s.preempted_by("ns/victim") == "ns/vip"
        assert s.preemptions_total == 1

    def test_victims_lowest_priority_first_newest_grant_first(self):
        s = GangScheduler(total_chips=32, aging_interval_s=1000)
        assert s.sync_admit("ns/old-p0", 8, 0, now=0.0).admitted
        assert s.sync_admit("ns/new-p0", 8, 0, now=1.0).admitted
        assert s.sync_admit("ns/p1", 16, 1, now=2.0).admitted
        d = s.sync_admit("ns/vip", 16, 10, now=3.0)
        # 16 needed, 0 free: the newest p0 grant loses first, then the
        # older p0; the p1 gang survives untouched
        assert d.victims == ["ns/new-p0", "ns/old-p0"]

    def test_no_preemption_of_equal_or_higher_priority(self):
        s = GangScheduler(total_chips=16, aging_interval_s=1000)
        assert s.sync_admit("ns/a", 16, 5, now=0.0).admitted
        assert s.sync_admit("ns/same", 16, 5, now=1.0).victims == []
        assert s.sync_admit("ns/below", 16, 4, now=2.0).victims == []

    def test_no_victims_when_even_total_eviction_cannot_fit(self):
        s = GangScheduler(total_chips=32, aging_interval_s=1000)
        assert s.sync_admit("ns/a", 32, 0, now=0.0).admitted
        d = s.sync_admit("ns/huge", 64, 10, now=1.0)
        # demand beyond the whole cluster: parked as infeasible (and never
        # allowed to name victims — eviction could not help)
        assert d.queued and d.victims == [] \
            and d.reason == "infeasible-demand-exceeds-cluster"

    def test_infeasible_job_does_not_starve_feasible_work(self):
        # demand > TOTAL cluster: the job can never run, with or without
        # preemption — it must park with a reason that says so and must
        # not head-of-line-block feasible jobs behind it forever
        s = GangScheduler(total_chips=16, aging_interval_s=1000)
        d = s.sync_admit("ns/impossible", 32, 5, now=0.0)
        assert d.queued and d.reason == "infeasible-demand-exceeds-cluster"
        assert s.sync_admit("ns/feasible", 8, 0, now=1.0).admitted
        assert s.queue.get("ns/impossible") is not None  # still parked

    def test_parked_resyncs_do_not_flood_the_event_ring(self):
        s = GangScheduler(total_chips=16, aging_interval_s=1000)
        assert s.sync_admit("ns/run", 16, 0, now=0.0).admitted
        for i in range(500):  # a parked job resyncing for hours
            assert s.sync_admit("ns/waiter", 16, 0, now=float(i)).queued
        events = s.events()
        assert sum(1 for e in events if e["type"] == "queue") == 1
        # the admit history survived the resync storm
        assert any(e["type"] == "admit" and e["key"] == "ns/run"
                   for e in events)

    def test_preempt_reselects_victims_under_the_lock(self):
        # the sync_admit victim hint can go stale before preempt() runs
        # (another worker admitted meanwhile): preempt must re-select
        # atomically and evict the CURRENT holder, never a stale name
        s = GangScheduler(total_chips=16, aging_interval_s=1000)
        assert s.sync_admit("ns/a", 16, 0, now=0.0).admitted
        d = s.sync_admit("ns/vip", 16, 10, now=1.0)
        assert d.victims == ["ns/a"]
        s.release("ns/a")  # a finished...
        # ...and a restart-adopted gang (reality-wins path, which bypasses
        # the queue) grabbed the freed chips before preempt() ran
        assert s.sync_admit("ns/b", 16, 0, running=True, now=2.0).admitted
        done = s.preempt("ns/vip", 16, 10, "prod", d.victims, now=3.0)
        assert done.admitted and done.victims == ["ns/b"]
        assert s.preempted_by("ns/b") == "ns/vip"
        assert s.preempted_by("ns/a") is None

    def test_preempt_skips_raced_away_victims(self):
        s = GangScheduler(total_chips=32, aging_interval_s=1000)
        assert s.sync_admit("ns/victim", 32, 0, now=0.0).admitted
        d = s.sync_admit("ns/vip", 16, 10, now=1.0)
        s.release("ns/victim")  # victim finished in between
        done = s.preempt("ns/vip", 16, 10, "prod", d.victims, now=2.0)
        assert done.admitted and done.victims == []  # nothing evicted
        assert s.preemptions_total == 0


class TestCapacityLedger:
    def test_release_is_idempotent_never_double_counts(self):
        s = GangScheduler(total_chips=16, aging_interval_s=1000)
        assert s.sync_admit("ns/a", 16, 0, now=0.0).admitted
        assert s.release("ns/a") == 16
        assert s.release("ns/a") == 0  # second release: already gone
        assert s.capacity.in_use() == 0
        assert s.capacity.available() == 16

    def test_forget_clears_queue_and_preemption_marker(self):
        s = GangScheduler(total_chips=16, aging_interval_s=1000)
        assert s.sync_admit("ns/a", 16, 0, now=0.0).admitted
        d = s.sync_admit("ns/vip", 16, 10, now=1.0)
        s.preempt("ns/vip", 16, 10, "prod", d.victims, now=2.0)
        s.sync_admit("ns/b", 16, 0, now=3.0)
        assert s.queue_depth() == 2  # the evicted job + ns/b
        s.forget("ns/a")
        assert s.queue.get("ns/a") is None
        assert s.preempted_by("ns/a") is None

    def test_resize_gang_atomic(self):
        """ISSUE 13: an autoscale replica patch resizes the reservation
        atomically — shrink always frees the delta, a grow fits whole or
        changes NOTHING (the never-partially-placed contract), and an
        unreserved key is refused (first admission stays with
        sync_admit's queue order)."""
        s = GangScheduler(total_chips=12, aging_interval_s=1000)
        assert s.sync_admit("ns/a", 8, 0, now=0.0).admitted
        assert s.reserved_chips("ns/a") == 8
        # grow past capacity: denied, hold unchanged
        d = s.resize("ns/a", 16)
        assert not d.admitted and d.reason == "insufficient-capacity"
        assert s.reserved_chips("ns/a") == 8
        # grow inside capacity: the whole delta lands
        assert s.resize("ns/a", 12).admitted
        assert s.capacity.available() == 0
        # shrink frees the delta
        d = s.resize("ns/a", 4)
        assert d.admitted and d.reason == "shrunk"
        assert s.capacity.available() == 8
        # no-op and guard rails
        assert s.resize("ns/a", 4).reason == "unchanged"
        assert not s.resize("ns/never", 4).admitted
        assert not s.resize("ns/a", 0).admitted
        assert s.reserved_chips("ns/never") is None

    def test_adoption_reality_wins_after_restart(self):
        # controller restart: a gang whose pods already run re-reserves
        # unconditionally, even past nominal capacity
        s = GangScheduler(total_chips=16, aging_interval_s=1000)
        assert s.sync_admit("ns/a", 16, 0, now=0.0).admitted
        d = s.sync_admit("ns/b", 16, 0, running=True, now=1.0)
        assert d.admitted and d.reason == "adopted"
        assert s.capacity.in_use() == 32  # over-reserved until one drains
        # ...but a deliberately preempted job may NOT re-adopt
        d2 = s.sync_admit("ns/c", 16, 10, now=2.0)
        s.preempt("ns/c", 16, 10, "prod", d2.victims, now=3.0)
        victim = d2.victims[0]
        assert not s.sync_admit(victim, 16, 0, running=True, now=4.0).admitted

    def test_chips_from_nodes(self):
        nodes = [
            {"status": {"allocatable": {"cloud-tpus.google.com/v5e": "16",
                                        "cpu": "8"}}},
            {"status": {"allocatable": {"cloud-tpus.google.com/v4": 8}}},
            {"status": {"allocatable": {"nvidia.com/gpu": 4}}},
            {"status": {"allocatable": {"cloud-tpus.google.com/v5e": "junk"}}},
            {},
        ]
        assert chips_from_nodes(nodes) == 24

    def test_resource_prefix_matches_api_constant(self):
        # scheduler/ may not import the api package (stdlib-only gate), so
        # the prefix is duplicated by value — this pins the two together
        from k8s_tpu.api.v1alpha2 import constants
        from k8s_tpu.scheduler.capacity import TPU_RESOURCE_PREFIX

        assert TPU_RESOURCE_PREFIX == constants.TPU_RESOURCE_PREFIX


# -- API: fields, defaulting, validation --------------------------------------


def _tpu_job_dict(name: str, replicas: int = 4, priority=None, queue=None):
    from k8s_tpu.cmd.genjob import tfjob_template

    return tfjob_template(name, NS, tpu=True, tpu_replicas=replicas,
                          priority=priority, queue=queue)


class TestApiFields:
    def test_defaults_fill_priority_and_queue(self):
        job = register.tfjob_from_unstructured(_tpu_job_dict("j"))
        register.default_tfjob(job)
        assert job.spec.priority == 0
        assert job.spec.queue == "default"

    def test_round_trip(self):
        job = register.tfjob_from_unstructured(
            _tpu_job_dict("j", priority=7, queue="research"))
        assert job.spec.priority == 7 and job.spec.queue == "research"
        d = job.to_dict()
        assert d["spec"]["priority"] == 7 and d["spec"]["queue"] == "research"

    @pytest.mark.parametrize("priority", ["high", True, 10**7, 1.5])
    def test_invalid_priority_rejected(self, priority):
        job = register.tfjob_from_unstructured(_tpu_job_dict("j"))
        register.default_tfjob(job)
        job.spec.priority = priority
        with pytest.raises(validation.ValidationError, match="priority"):
            validation.validate_v1alpha2_tfjob_spec(job.spec)

    @pytest.mark.parametrize("queue", ["-bad", "x" * 70, "", 42])
    def test_invalid_queue_rejected(self, queue):
        job = register.tfjob_from_unstructured(_tpu_job_dict("j"))
        register.default_tfjob(job)
        job.spec.queue = queue
        with pytest.raises(validation.ValidationError, match="queue"):
            validation.validate_v1alpha2_tfjob_spec(job.spec)

    def test_valid_fields_pass(self):
        job = register.tfjob_from_unstructured(
            _tpu_job_dict("j", priority=-10, queue="team-a.batch"))
        register.default_tfjob(job)
        validation.validate_v1alpha2_tfjob_spec(job.spec)


class TestChipsForTfjob:
    def test_single_slice(self):
        job = register.tfjob_from_unstructured(_tpu_job_dict("j", replicas=4))
        register.default_tfjob(job)
        assert tpu_config.chips_for_tfjob(job) == 16  # 4 hosts x 4 chips

    def test_multislice_flattened(self):
        from k8s_tpu.harness.bench_operator import _tpu_gang_job

        job = register.tfjob_from_unstructured(_tpu_gang_job("j", NS, 6))
        register.default_tfjob(job)
        # 6 hosts across slices at 4 chips/host, regardless of slice split
        assert tpu_config.chips_for_tfjob(job) == 24

    def test_cpu_only_job_prices_at_zero(self):
        from k8s_tpu.e2e.components import core_component

        job = register.tfjob_from_unstructured(core_component(
            {"name": "cpu", "namespace": NS, "num_masters": 0,
             "num_workers": 2, "num_ps": 0, "command": ["true"]},
            "v1alpha2"))
        register.default_tfjob(job)
        assert tpu_config.chips_for_tfjob(job) == 0


# -- controller tier (alwaysReady stores, FakePodControl seams) ---------------


def make_tpu_tfjob(name: str, uid: str, replicas: int = 4,
                   priority: int | None = None) -> v1alpha2.TFJob:
    template = {
        "spec": {
            "containers": [{
                "name": "tensorflow",
                "image": "img",
                "ports": [{"name": "tfjob-port", "containerPort": 2222}],
                "resources": {"limits": {"cloud-tpus.google.com/v5e": 4}},
            }]
        }
    }
    return v1alpha2.TFJob(
        metadata=ObjectMeta(name=name, namespace=NS, uid=uid),
        spec=v1alpha2.TFJobSpec(
            tf_replica_specs={
                "TPU": v1alpha2.TFReplicaSpec(replicas=replicas,
                                              template=template,
                                              restart_policy="ExitCode")
            },
            priority=priority,
        ),
    )


def make_pod_for(job: v1alpha2.TFJob, index: int, phase: str = "Running"):
    key = tpu_config.tfjob_key(job)
    labels = tpu_config.gen_labels(key)
    labels[tpu_config.LABEL_REPLICA_TYPE] = "tpu"
    labels[tpu_config.LABEL_REPLICA_INDEX] = str(index)
    return {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {
            "name": f"{NS}-{job.metadata.name}-tpu-{index}-x",
            "namespace": NS, "labels": labels,
            "ownerReferences": [{
                "apiVersion": "kubeflow.org/v1alpha2", "kind": "TFJob",
                "name": job.metadata.name, "uid": job.metadata.uid,
                "controller": True,
            }],
        },
        "spec": {"containers": [{"name": "tensorflow"}]},
        "status": {"phase": phase},
    }


def build_controller(jobs: list[v1alpha2.TFJob], cluster_chips: int,
                     pods: list[dict] | None = None):
    fc = FakeCluster()
    cs = Clientset(fc)
    stored = []
    for job in jobs:
        cs.tfjobs(NS).create(job)
        stored.append(cs.tfjobs_unstructured(NS).get(job.metadata.name))
    factory = SharedInformerFactory(fc, resync_period=0)
    tc = TFJobController(
        cs, informer_factory=factory, enable_gang_scheduling=False,
        pod_control=FakePodControl(), service_control=FakeServiceControl(),
        recorder=FakeRecorder(), cluster_chips=cluster_chips,
    )
    tc.tfjob_informer.store.replace(stored)
    tc.pod_informer.store.replace(pods or [])
    tc.service_informer.store.replace([])
    tc.node_informer.store.replace([])
    captured = []
    tc.update_status_handler = lambda job: captured.append(job)
    return tc, captured


def _clear_expectations(tc: TFJobController, job: v1alpha2.TFJob) -> None:
    """alwaysReady stores have no informer echoes: drop the expectations a
    create/delete wave raised so the next sync may proceed."""
    key = tpu_config.tfjob_key(job)
    tc.expectations.delete_expectations(
        pod_mod.gen_expectation_pods_key(key, "tpu"))
    tc.expectations.delete_expectations(
        service_mod.gen_expectation_services_key(key, "tpu"))


def _condition(job, ctype: str):
    return status_mod.get_condition(job.status, ctype)


class TestControllerAdmission:
    def test_job_that_does_not_fit_creates_zero_pods_and_parks_queued(self):
        job = make_tpu_tfjob("big", "uid-1", replicas=4)  # 16 chips
        tc, captured = build_controller([job], cluster_chips=8)
        assert tc.sync_tfjob(f"{NS}/big") is True
        # ZERO pods, ZERO services — all-or-nothing means nothing
        assert tc.pod_control.templates == []
        assert tc.service_control.services == []
        assert captured, "parked status must be written"
        queued = _condition(captured[-1], v1alpha2.TFJobQueued)
        assert queued is not None and queued.status == "True"
        assert queued.reason == status_mod.TFJOB_QUEUED_REASON
        assert tc.scheduler.queue_depth() == 1
        assert tc.scheduler.capacity.in_use() == 0

    def test_job_that_fits_is_admitted_and_reconciles(self):
        job = make_tpu_tfjob("fits", "uid-1", replicas=4)
        tc, captured = build_controller([job], cluster_chips=16)
        assert tc.sync_tfjob(f"{NS}/fits") is True
        assert len(tc.pod_control.templates) == 4
        assert set(tc.scheduler.capacity.reservations) == {f"{NS}/fits"}
        assert tc.scheduler.capacity.in_use() == 16
        assert _condition(captured[-1], v1alpha2.TFJobQueued) is None

    def test_terminal_cleanup_releases_reservation_and_wakes_queue(self):
        a = make_tpu_tfjob("job-a", "uid-a", replicas=4)
        b = make_tpu_tfjob("job-b", "uid-b", replicas=4)
        tc, captured = build_controller([a, b], cluster_chips=16)
        assert tc.sync_tfjob(f"{NS}/job-a") is True     # admitted
        _clear_expectations(tc, a)
        assert tc.sync_tfjob(f"{NS}/job-b") is True     # parked
        assert tc.scheduler.queue_depth() == 1
        # persist B's parked status into the store (the stubbed status
        # handler doesn't), so its re-admission can flip Queued -> False
        b_parked = next(j for j in reversed(captured)
                        if j.metadata.name == "job-b")

        # drive A terminal: Succeeded condition on the stored object
        a.status.conditions = [status_mod.new_condition(
            v1alpha2.TFJobSucceeded, "TFJobSucceeded", "done")]
        tc.tfjob_informer.store.replace([a.to_dict(), b_parked.to_dict()])
        assert tc.sync_tfjob(f"{NS}/job-a") is True
        # reservation gone, chips free, and the parked job was woken
        assert tc.scheduler.capacity.reservations == {}
        assert tc.scheduler.capacity.in_use() == 0
        # next sync of B is now admitted
        assert tc.sync_tfjob(f"{NS}/job-b") is True
        assert set(tc.scheduler.capacity.reservations) == {f"{NS}/job-b"}
        queued = _condition(captured[-1], v1alpha2.TFJobQueued)
        assert queued is not None and queued.status == "False"
        assert queued.reason == status_mod.TFJOB_ADMITTED_REASON

    def test_deleted_job_releases_everything(self):
        a = make_tpu_tfjob("job-a", "uid-a", replicas=4)
        tc, _ = build_controller([a], cluster_chips=16)
        assert tc.sync_tfjob(f"{NS}/job-a") is True
        assert tc.scheduler.capacity.in_use() == 16
        tc._delete_tfjob(a.to_dict())
        assert tc.scheduler.capacity.in_use() == 0

    def test_preemption_end_to_end(self):
        lo = make_tpu_tfjob("lo", "uid-lo", replicas=4, priority=0)
        hi = make_tpu_tfjob("hi", "uid-hi", replicas=4, priority=10)
        lo_pods = [make_pod_for(lo, i) for i in range(4)]
        tc, captured = build_controller([lo, hi], cluster_chips=16,
                                        pods=lo_pods)
        gen = tc.metrics["generation"]
        preempt_before = tc.metrics["preemptions_total"].labels(gen).value

        assert tc.sync_tfjob(f"{NS}/lo") is True       # lo admitted + running
        _clear_expectations(tc, lo)
        assert tc.sync_tfjob(f"{NS}/hi") is True       # hi preempts lo
        # hi holds exactly its own chips; lo requeued and marked
        assert set(tc.scheduler.capacity.reservations) == {f"{NS}/hi"}
        assert tc.scheduler.capacity.in_use() == 16
        assert tc.scheduler.preempted_by(f"{NS}/lo") == f"{NS}/hi"
        assert len(tc.pod_control.templates) == 4      # hi's gang created
        assert tc.metrics["preemptions_total"].labels(gen).value \
            == preempt_before + 1

        # the victim's own sync parks it and tears down its gang.  Persist
        # its Running status into the store first (stubbed handler): the
        # preemption marker must beat reality-wins re-adoption.
        lo_running = next(j for j in reversed(captured)
                          if j.metadata.name == "lo")
        stored_hi = tc.tfjob_informer.store.get_by_key(f"{NS}/hi")
        tc.tfjob_informer.store.replace([lo_running.to_dict(), stored_hi])
        _clear_expectations(tc, hi)
        assert tc.sync_tfjob(f"{NS}/lo") is True
        assert sorted(tc.pod_control.delete_pod_names) == sorted(
            p["metadata"]["name"] for p in lo_pods)
        lo_status = next(j for j in reversed(captured)
                         if j.metadata.name == "lo")
        queued = _condition(lo_status, v1alpha2.TFJobQueued)
        assert queued is not None and queued.status == "True"
        assert queued.reason == status_mod.TFJOB_PREEMPTED_REASON
        running = _condition(lo_status, v1alpha2.TFJobRunning)
        assert running is not None and running.status == "False"

    def test_cluster_chips_env(self, monkeypatch):
        monkeypatch.setenv("K8S_TPU_CLUSTER_CHIPS", "64")
        assert cluster_chips_from_env() == 64
        monkeypatch.setenv("K8S_TPU_CLUSTER_CHIPS", "garbage")
        assert cluster_chips_from_env() is None
        monkeypatch.setenv("K8S_TPU_CLUSTER_CHIPS", "0")
        assert cluster_chips_from_env() == 0
        monkeypatch.delenv("K8S_TPU_CLUSTER_CHIPS")
        assert cluster_chips_from_env() is None

    def test_negative_cluster_chips_ignored_like_env_path(self):
        job = make_tpu_tfjob("j", "uid-1", replicas=4)
        tc, _ = build_controller([job], cluster_chips=-1)
        # garbage knob -> unlimited (admission off), exactly like the env
        # path; NOT a permanently-unschedulable cluster
        assert tc.scheduler.unlimited
        assert tc.sync_tfjob(f"{NS}/j") is True
        assert len(tc.pod_control.templates) == 4

    def test_capacity_derived_from_nodes_when_unpinned(self):
        job = make_tpu_tfjob("big", "uid-1", replicas=4)  # 16 chips
        fc = FakeCluster()
        cs = Clientset(fc)
        cs.tfjobs(NS).create(job)
        stored = cs.tfjobs_unstructured(NS).get("big")
        tc = TFJobController(
            cs, informer_factory=SharedInformerFactory(fc, resync_period=0),
            enable_gang_scheduling=False, pod_control=FakePodControl(),
            service_control=FakeServiceControl(), recorder=FakeRecorder(),
        )
        tc.tfjob_informer.store.replace([stored])
        tc.pod_informer.store.replace([])
        tc.service_informer.store.replace([])
        tc.node_informer.store.replace([{
            "apiVersion": "v1", "kind": "Node",
            "metadata": {"name": "tpu-node"},
            "status": {"allocatable": {"cloud-tpus.google.com/v5e": "8"}},
        }])
        tc.update_status_handler = lambda j: None
        assert tc.sync_tfjob(f"{NS}/big") is True
        # 8 allocatable chips derived from the node, 16 demanded -> parked
        assert tc.scheduler.total_chips == 8
        assert tc.pod_control.templates == []
        assert tc.scheduler.queue_depth() == 1


# -- /debug/scheduler ---------------------------------------------------------


class TestDebugEndpoint:
    def test_404_when_no_scheduler_active(self):
        old = scheduler_mod.active()
        try:
            scheduler_mod.set_active(None)
            code, body, ctype = scheduler_mod.debug_response("")
            assert code == 404 and "no scheduler active" in body
        finally:
            scheduler_mod.set_active(old)

    def test_state_document_and_filters(self):
        s = GangScheduler(total_chips=32, aging_interval_s=1000)
        s.sync_admit("ns/a", 16, 0, queue="prod", now=0.0)
        s.sync_admit("ns/b", 32, 0, queue="batch", now=1.0)
        code, body, ctype = scheduler_mod.debug_scheduler_response(s, "")
        assert code == 200 and ctype == "application/json"
        state = json.loads(body)
        assert state["total_chips"] == 32
        assert state["in_use_chips"] == 16
        assert state["available_chips"] == 16
        assert [r["key"] for r in state["reservations"]] == ["ns/a"]
        assert [e["key"] for e in state["queue"]] == ["ns/b"]
        # effective = base + capped aging boost (debug_state uses wall time,
        # so only bound it)
        entry = state["queue"][0]
        assert entry["priority"] <= entry["effective_priority"] \
            <= entry["priority"] + 5
        assert entry["preempted_by"] is None
        # ?queue= filter + ?events=0
        code, body, _ = scheduler_mod.debug_scheduler_response(
            s, "queue=prod&events=0")
        state = json.loads(body)
        assert [r["key"] for r in state["reservations"]] == ["ns/a"]
        assert state["queue"] == [] and "events" not in state

    def test_served_by_metrics_server(self):
        from k8s_tpu.util.metrics_server import MetricsServer

        old = scheduler_mod.active()
        server = MetricsServer(0)
        server.start()
        try:
            s = GangScheduler(total_chips=8)
            s.sync_admit("ns/x", 8, 0, now=0.0)
            scheduler_mod.set_active(s)
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/debug/scheduler") as r:
                state = json.loads(r.read())
            assert state["total_chips"] == 8
            assert state["in_use_chips"] == 8
        finally:
            server.stop()
            scheduler_mod.set_active(old)


# -- stdlib-only gate ---------------------------------------------------------


class TestStdlibGate:
    def test_scheduler_package_is_stdlib_only(self):
        from k8s_tpu.harness.py_checks import check_stdlib_only

        pkg = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "k8s_tpu", "scheduler")
        names = [n for n in os.listdir(pkg) if n.endswith(".py")]
        assert names, "scheduler package has files"
        for name in names:
            assert check_stdlib_only(
                os.path.join(pkg, name), package="k8s_tpu.scheduler") == []

    def test_gate_flags_foreign_imports(self):
        from k8s_tpu.harness.py_checks import (
            _stdlib_only_package_of,
            check_stdlib_only,
        )

        bad = b"import yaml\nfrom k8s_tpu.util import metrics\n"
        findings = check_stdlib_only("k8s_tpu/scheduler/bad.py", source=bad,
                                     package="k8s_tpu.scheduler")
        assert len(findings) == 2
        assert "yaml" in findings[0] and "k8s_tpu.util" in findings[1]
        # the lint driver routes scheduler/ files through the gate
        assert _stdlib_only_package_of(
            "k8s_tpu/scheduler/scheduler.py") == "k8s_tpu.scheduler"
        assert _stdlib_only_package_of(
            "k8s_tpu/trace/tracer.py") == "k8s_tpu.trace"
        assert _stdlib_only_package_of("k8s_tpu/util/metrics.py") is None


# -- satellites: genjob flags + example manifest ------------------------------


class TestGenjobFlags:
    def test_template_carries_priority_and_queue(self):
        from k8s_tpu.cmd.genjob import tfjob_template

        job = tfjob_template("j", NS, tpu=True, tpu_replicas=4,
                             priority=3, queue="research")
        assert job["spec"]["priority"] == 3
        assert job["spec"]["queue"] == "research"
        # unset flags leave the manifest clean (server-side defaulting)
        job = tfjob_template("j", NS, tpu=True, tpu_replicas=4)
        assert "priority" not in job["spec"] and "queue" not in job["spec"]

    def test_cli_dump(self, capsys):
        from k8s_tpu.cmd import genjob

        assert genjob.main(["--nr-tfjobs", "2", "--use-tpu", "--dump",
                            "--priority", "5", "--queue", "prod"]) == 0
        import yaml as yaml_mod

        docs = list(yaml_mod.safe_load_all(capsys.readouterr().out))
        assert len(docs) == 2
        for doc in docs:
            assert doc["spec"]["priority"] == 5
            assert doc["spec"]["queue"] == "prod"


class TestExampleManifest:
    def test_priority_example_loads_and_validates(self):
        from k8s_tpu.api import manifest

        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "examples", "tf_job_priority.yaml")
        jobs = manifest.load_tfjobs_from_file(path)
        assert [j.metadata.name for j in jobs] == \
            ["nightly-batch", "prod-finetune"]
        assert [j.spec.priority for j in jobs] == [0, 100]
        assert [j.spec.queue for j in jobs] == ["batch", "prod"]
        # both price identically and cannot co-run on a 16-chip cluster
        for j in jobs:
            assert tpu_config.chips_for_tfjob(j) == 16


# -- the --contention bench (acceptance criterion) ----------------------------


class TestContentionBench:
    def test_high_priority_admitted_ahead_of_backlog(self):
        from k8s_tpu.harness.bench_operator import bench_contention

        r = bench_contention(jobs=2, replicas=2, hi_priority=10,
                             runtime_s=0.3, timeout_s=45.0)
        # the late VIP preempted the running gang and jumped the backlog
        assert r["preemptions"] >= 1
        assert r["hi_jumped_backlog"] is True
        order = r["admission_order"]
        assert order.index("hi-0") < order.index("lo-1")
        # the victim (and the backlog) were genuinely parked at some point
        assert r["queued_jobs_observed"] >= 1
        # everyone eventually ran: waits exist for every job
        assert r["admission_wait_p50_s"] >= 0.0
        assert 0.0 < r["utilization"] <= 1.0

    def test_cli_flag_wiring(self, capsys):
        from k8s_tpu.harness import bench_operator

        assert bench_operator.main(
            ["--contention", "--contention-jobs", "2",
             "--contention-replicas", "2", "--contention-runtime", "0.3",
             "--timeout", "45"]) == 0
        line = capsys.readouterr().out.strip().splitlines()[-1]
        out = json.loads(line)
        assert out["metric"] == "contention_hi_admission_wait"
        assert out["hi_jumped_backlog"] is True

"""KV block transfer plane (models/kvxfer.py, ISSUE 15) — protocol
level: framing, typed refusals, dead-peer/truncated-frame teardown,
connection pooling.  No jax anywhere (the unit tier's constraint): the
engine seam is a plain callable here."""

from __future__ import annotations

import socket
import threading
import time

import numpy as np
import pytest

from k8s_tpu.models import kvxfer


def _migrate_payload(n_blocks=3, bs=4):
    statics = {"v": kvxfer.PROTOCOL_VERSION, "wire_int8": False,
               "trace_id": "abc123",
               "req": {"first": 7, "max_new_tokens": 8, "eos": None,
                       "temperature": 0.0, "top_k": None,
                       "speculative": 0, "block_size": bs}}
    arrays = {
        "ids": np.arange(n_blocks * bs, dtype=np.int32),
        "key": np.asarray([1, 2], np.uint32),
        "blk/layer0/k": np.arange(n_blocks * bs * 2,
                                  dtype=np.float32).reshape(n_blocks,
                                                            bs, 2),
        "blk/layer0/v": np.ones((n_blocks, bs, 2), np.float32),
    }
    return statics, arrays


class TestFraming:
    def test_round_trip(self):
        statics, arrays = _migrate_payload()
        data = kvxfer.encode_frame(kvxfer.OP_MIGRATE, statics, arrays)
        a, b = socket.socketpair()
        try:
            a.sendall(data)
            op, st, arr = kvxfer.read_frame(b)
        finally:
            a.close()
            b.close()
        assert op == kvxfer.OP_MIGRATE
        assert st == statics
        assert set(arr) == set(arrays)
        for name in arrays:
            assert arr[name].dtype == arrays[name].dtype
            assert np.array_equal(arr[name], arrays[name])

    def test_truncated_frame_raises_peer_gone(self):
        statics, arrays = _migrate_payload()
        data = kvxfer.encode_frame(kvxfer.OP_MIGRATE, statics, arrays)
        a, b = socket.socketpair()
        try:
            a.sendall(data[:len(data) // 2])
            a.close()  # EOF mid-frame
            with pytest.raises(kvxfer.KvPeerGone):
                kvxfer.read_frame(b)
        finally:
            b.close()

    def test_garbage_header_raises_peer_gone_not_alloc(self):
        a, b = socket.socketpair()
        try:
            # a length prefix claiming a multi-MB header
            a.sendall((1 << 25).to_bytes(4, "big") + b"x" * 64)
            with pytest.raises(kvxfer.KvPeerGone):
                kvxfer.read_frame(b)
        finally:
            a.close()
            b.close()

    def test_non_json_header_is_peer_gone(self):
        a, b = socket.socketpair()
        try:
            payload = b"not json at all"
            a.sendall(len(payload).to_bytes(4, "big") + payload)
            with pytest.raises(kvxfer.KvPeerGone):
                kvxfer.read_frame(b)
        finally:
            a.close()
            b.close()


class TestParseDest:
    def test_ok(self):
        assert kvxfer.parse_dest("10.0.0.1:8472") == ("10.0.0.1", 8472)

    @pytest.mark.parametrize("bad", ["nohost", ":8472", "h:not-int",
                                     "h:0", "h:70000", ""])
    def test_bad(self, bad):
        with pytest.raises(ValueError):
            kvxfer.parse_dest(bad)


class _FakeEngineSeat:
    """A seat_fn stand-in: records the payload, fires the seated
    callback, returns canned tokens (or raises a scripted error)."""

    def __init__(self, tokens=(7, 8, 9), error=None, seat_delay=0.0):
        self.tokens = list(tokens)
        self.error = error
        self.seat_delay = seat_delay
        self.calls = []

    def __call__(self, statics, arrays, on_seated):
        self.calls.append((statics, arrays))
        if self.error is not None:
            raise self.error
        if self.seat_delay:
            time.sleep(self.seat_delay)
        on_seated()
        return self.tokens


class _PoolExhausted(RuntimeError):
    """Name-mapped refusal (the receiver maps by type NAME so this
    module never imports the engine)."""


_PoolExhausted.__name__ = "PoolExhausted"


class TestReceiverSender:
    def _pair(self, seat):
        recv = kvxfer.KvReceiver(seat, port=0)
        send = kvxfer.KvSender()
        return recv, send, f"127.0.0.1:{recv.port}"

    def test_migrate_round_trip_and_pooling(self):
        seat = _FakeEngineSeat(tokens=(1, 2, 3))
        recv, send, dest = self._pair(seat)
        try:
            statics, arrays = _migrate_payload()
            tokens, seated_s = send.migrate(dest, statics, arrays)
            assert tokens == [1, 2, 3]
            assert seated_s >= 0.0
            # the decode side saw the exact bytes
            st, arr = seat.calls[0]
            assert st["req"]["first"] == 7
            assert np.array_equal(arr["blk/layer0/k"],
                                  arrays["blk/layer0/k"])
            # second migration reuses the pooled connection
            send.migrate(dest, statics, arrays)
            assert send.stats()["pooled_connections"] == 1
            assert recv.stats()["migrations"] == 2
            assert recv.stats()["blocks_in"] == 6
        finally:
            send.close()
            recv.stop()

    def test_typed_refusal_travels(self):
        seat = _FakeEngineSeat(error=_PoolExhausted("no room"))
        recv, send, dest = self._pair(seat)
        try:
            statics, arrays = _migrate_payload()
            with pytest.raises(kvxfer.KvTransferError) as ei:
                send.migrate(dest, statics, arrays)
            assert ei.value.kind == "pool_exhausted"
            # the refusal completed the conversation: the socket is
            # reusable and a later migration succeeds
            seat.error = None
            tokens, _ = send.migrate(dest, statics, arrays)
            assert tokens == [7, 8, 9]
        finally:
            send.close()
            recv.stop()

    def test_bad_request_refusal_kind(self):
        seat = _FakeEngineSeat(error=ValueError("shape mismatch"))
        recv, send, dest = self._pair(seat)
        try:
            with pytest.raises(kvxfer.KvTransferError) as ei:
                send.migrate(dest, *_migrate_payload())
            assert ei.value.kind == "bad_request"
        finally:
            send.close()
            recv.stop()

    def test_dead_peer_mid_conversation(self):
        """Receiver dies between seated and tokens: the sender raises
        KvPeerGone (kind peer_gone), not a hang."""
        seat = _FakeEngineSeat(seat_delay=0.5)
        recv, send, dest = self._pair(seat)

        def chaos():
            time.sleep(0.15)  # after the migrate frame landed
            recv.stop()

        t = threading.Thread(target=chaos, daemon=True)
        t.start()
        try:
            with pytest.raises(kvxfer.KvTransferError):
                send.migrate(dest, *_migrate_payload())
        finally:
            t.join()
            send.close()

    def test_truncated_frame_tears_down_connection_only(self):
        """A garbage client connection is torn down; the receiver keeps
        serving real migrations afterwards."""
        seat = _FakeEngineSeat()
        recv, send, dest = self._pair(seat)
        try:
            raw = socket.create_connection(("127.0.0.1", recv.port))
            raw.sendall(b"\x00\x00\x00\x10short")  # truncated
            raw.close()
            deadline = time.monotonic() + 5
            while recv.stats()["peer_gone"] < 1 \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            assert recv.stats()["peer_gone"] >= 1
            tokens, _ = send.migrate(dest, *_migrate_payload())
            assert tokens == [7, 8, 9]
        finally:
            send.close()
            recv.stop()

    def test_stale_pooled_connection_gets_fresh_retry(self):
        """The receiver closing an idle keep-alive is not a failure:
        the sender retries once on a fresh socket."""
        seat = _FakeEngineSeat()
        recv, send, dest = self._pair(seat)
        try:
            send.migrate(dest, *_migrate_payload())
            # sever the pooled socket server-side
            with recv._lock:
                conns = list(recv._conns)
            for c in conns:
                c.shutdown(socket.SHUT_RDWR)
            time.sleep(0.05)
            tokens, _ = send.migrate(dest, *_migrate_payload())
            assert tokens == [7, 8, 9]
        finally:
            send.close()
            recv.stop()


class TestEnvKnobs:
    def test_role(self, monkeypatch):
        monkeypatch.delenv(kvxfer.ENV_ROLE, raising=False)
        assert kvxfer.env_role() == ""
        monkeypatch.setenv(kvxfer.ENV_ROLE, "Prefill")
        assert kvxfer.env_role() == "prefill"
        monkeypatch.setenv(kvxfer.ENV_ROLE, "decode")
        assert kvxfer.env_role() == "decode"
        monkeypatch.setenv(kvxfer.ENV_ROLE, "garbage")
        assert kvxfer.env_role() == ""

    def test_port(self, monkeypatch):
        monkeypatch.delenv(kvxfer.ENV_PORT, raising=False)
        assert kvxfer.env_kvxfer_port() is None
        monkeypatch.setenv(kvxfer.ENV_PORT, "8472")
        assert kvxfer.env_kvxfer_port() == 8472
        monkeypatch.setenv(kvxfer.ENV_PORT, "0")
        assert kvxfer.env_kvxfer_port() == 0
        monkeypatch.setenv(kvxfer.ENV_PORT, "garbage")
        assert kvxfer.env_kvxfer_port() is None
        monkeypatch.setenv(kvxfer.ENV_PORT, "70000")
        assert kvxfer.env_kvxfer_port() is None

    def test_int8(self, monkeypatch):
        monkeypatch.delenv(kvxfer.ENV_INT8, raising=False)
        assert kvxfer.env_kvxfer_int8() is False
        monkeypatch.setenv(kvxfer.ENV_INT8, "1")
        assert kvxfer.env_kvxfer_int8() is True

    def test_default_port_matches_genjob(self):
        from k8s_tpu.cmd import genjob

        assert genjob.KVXFER_PORT == kvxfer.DEFAULT_PORT

    def test_dedup_default_on(self, monkeypatch):
        monkeypatch.delenv(kvxfer.ENV_DEDUP, raising=False)
        assert kvxfer.env_kvxfer_dedup() is True
        monkeypatch.setenv(kvxfer.ENV_DEDUP, "1")
        assert kvxfer.env_kvxfer_dedup() is True
        for off in ("0", "false", "off", "no"):
            monkeypatch.setenv(kvxfer.ENV_DEDUP, off)
            assert kvxfer.env_kvxfer_dedup() is False


class TestReplyTimeoutNoDuplicate:
    def test_reply_timeout_does_not_resend(self):
        """A reply timeout on a pooled connection must NOT be treated
        as a stale keep-alive: the migrate frame already reached the
        receiver, and a re-send would seat (and decode) the request a
        second time on an already-slow decode pod."""
        seat = _FakeEngineSeat()
        recv = kvxfer.KvReceiver(seat, port=0)
        send = kvxfer.KvSender(reply_timeout_s=0.25)
        dest = f"127.0.0.1:{recv.port}"
        try:
            send.migrate(dest, *_migrate_payload())  # pools the socket
            assert len(seat.calls) == 1
            seat.seat_delay = 1.0  # slower than the reply timeout
            with pytest.raises(kvxfer.KvPeerGone, match="timed out"):
                send.migrate(dest, *_migrate_payload())
            time.sleep(1.2)  # let the slow seat finish server-side
            # exactly TWO migrate frames ever reached the receiver —
            # the timed-out attempt was not re-sent
            assert len(seat.calls) == 2
        finally:
            send.close()
            recv.stop()


class _DedupStale(RuntimeError):
    """Receiver-side refusal kind for an evicted dedup promise (the
    engine's real exception carries the same class attribute)."""

    kind = "dedup_stale"


class _StaleOnSkipSeat:
    """Seat that refuses any SLICED migrate frame with ``dedup_stale``
    (as if the promised prefix was evicted between offer and seat) but
    accepts the full re-send."""

    def __init__(self):
        self.calls = []

    def __call__(self, statics, arrays, on_seated):
        self.calls.append((statics, arrays))
        if statics.get("skip"):
            raise _DedupStale("promised prefix evicted")
        on_seated()
        return [7, 8, 9]


class TestDedupHandshake:
    def _pair(self, seat, index_fn=None):
        recv = kvxfer.KvReceiver(seat, port=0, index_fn=index_fn)
        send = kvxfer.KvSender()
        return recv, send, f"127.0.0.1:{recv.port}"

    def test_offer_need_ships_only_missing_blk_rows(self):
        """Receiver promises the first 2 of 3 blocks: the migrate frame
        carries ``skip`` and only the last block's ``blk/``/``blkscale/``
        rows — ``ids`` (and every non-block array) stay whole."""
        seat = _FakeEngineSeat()
        recv, send, dest = self._pair(seat, index_fn=lambda fps: 2)
        try:
            statics, arrays = _migrate_payload(n_blocks=3)
            arrays["blkscale/layer0/k"] = np.arange(
                3 * 4, dtype=np.float32).reshape(3, 4)
            info = {}
            tokens, _ = send.migrate(dest, statics, arrays,
                                     fingerprints=["f0", "f1"],
                                     info=info)
            assert tokens == [7, 8, 9]
            st, arr = seat.calls[0]
            assert st["skip"] == 2
            assert arr["blk/layer0/k"].shape[0] == 1
            assert np.array_equal(arr["blk/layer0/k"],
                                  arrays["blk/layer0/k"][2:])
            assert np.array_equal(arr["blkscale/layer0/k"],
                                  arrays["blkscale/layer0/k"][2:])
            assert np.array_equal(arr["ids"], arrays["ids"])  # whole
            assert info["skipped_blocks"] == 2
            assert info["skipped_bytes"] > 0
            assert send.stats()["dedup_blocks_skipped"] == 2
            assert send.stats()["dedup_bytes_saved"] == \
                info["skipped_bytes"]
            assert send.stats()["blocks_out"] == 1
            assert recv.stats()["dedup_offers"] == 1
            assert recv.stats()["dedup_blocks_promised"] == 2
        finally:
            send.close()
            recv.stop()

    def test_receiver_promise_clamped_to_offer_length(self):
        """A buggy/over-eager index answer can never make the sender
        skip more blocks than it offered."""
        seat = _FakeEngineSeat()
        recv, send, dest = self._pair(seat, index_fn=lambda fps: 99)
        try:
            statics, arrays = _migrate_payload(n_blocks=3)
            send.migrate(dest, statics, arrays,
                         fingerprints=["f0", "f1"])
            st, arr = seat.calls[0]
            assert st["skip"] == 2
            assert arr["blk/layer0/k"].shape[0] == 1
        finally:
            send.close()
            recv.stop()

    def test_zero_have_ships_full_frame(self):
        seat = _FakeEngineSeat()
        recv, send, dest = self._pair(seat, index_fn=lambda fps: 0)
        try:
            statics, arrays = _migrate_payload(n_blocks=3)
            info = {}
            send.migrate(dest, statics, arrays,
                         fingerprints=["f0", "f1"], info=info)
            st, arr = seat.calls[0]
            assert "skip" not in st
            assert arr["blk/layer0/k"].shape[0] == 3
            assert info["skipped_blocks"] == 0
            assert send.stats()["dedup_blocks_skipped"] == 0
            assert recv.stats()["dedup_offers"] == 1
            assert recv.stats()["dedup_blocks_promised"] == 0
        finally:
            send.close()
            recv.stop()

    def test_index_probe_failure_is_advisory(self):
        """A crashing index probe means "ship everything", never a
        failed migration."""

        def boom(fps):
            raise RuntimeError("index wedged")

        seat = _FakeEngineSeat()
        recv, send, dest = self._pair(seat, index_fn=boom)
        try:
            tokens, _ = send.migrate(dest, *_migrate_payload(),
                                     fingerprints=["f0", "f1"])
            assert tokens == [7, 8, 9]
            assert seat.calls[0][1]["blk/layer0/k"].shape[0] == 3
        finally:
            send.close()
            recv.stop()

    def test_legacy_receiver_memoized_and_full_migrate(self):
        """A receiver with no dedup seam answers the offer with the
        closed protocol's ``protocol`` error and closes: the sender
        memoizes the peer, reconnects, and runs the classic full
        conversation — later migrations never re-offer (observable:
        the pooled keep-alive survives the second call)."""
        seat = _FakeEngineSeat()
        recv, send, dest = self._pair(seat, index_fn=None)
        try:
            statics, arrays = _migrate_payload(n_blocks=3)
            tokens, _ = send.migrate(dest, statics, arrays,
                                     fingerprints=["f0", "f1"])
            assert tokens == [7, 8, 9]
            assert send.stats()["legacy_peers"] == 1
            assert send.stats()["dedup_blocks_skipped"] == 0
            st, arr = seat.calls[0]
            assert "skip" not in st
            assert arr["blk/layer0/k"].shape[0] == 3
            # second migration: no offer prologue (a re-offer would
            # error-and-close this stream again), pooled socket reused
            send.migrate(dest, statics, arrays,
                         fingerprints=["f0", "f1"])
            assert send.stats()["pooled_connections"] == 1
            assert send.stats()["legacy_peers"] == 1
            assert recv.stats()["migrations"] == 2
        finally:
            send.close()
            recv.stop()

    def test_dedup_stale_refusal_resends_full_once(self):
        """Eviction race: the receiver promised blocks it has since
        lost and refuses the sliced frame with ``dedup_stale`` — the
        sender re-sends the FULL chain once on the same live stream."""
        seat = _StaleOnSkipSeat()
        recv, send, dest = self._pair(seat, index_fn=lambda fps: 2)
        try:
            statics, arrays = _migrate_payload(n_blocks=3)
            info = {}
            tokens, _ = send.migrate(dest, statics, arrays,
                                     fingerprints=["f0", "f1"],
                                     info=info)
            assert tokens == [7, 8, 9]
            assert len(seat.calls) == 2
            assert seat.calls[0][0]["skip"] == 2
            assert "skip" not in seat.calls[1][0]
            assert seat.calls[1][1]["blk/layer0/k"].shape[0] == 3
            # nothing was actually skipped end-to-end
            assert info["skipped_blocks"] == 0
            assert send.stats()["dedup_blocks_skipped"] == 0
            assert send.stats()["dedup_stale"] == 1
            # the conversation completed on one connection: reusable
            assert send.stats()["pooled_connections"] == 1
        finally:
            send.close()
            recv.stop()

    def test_no_fingerprints_means_no_offer(self):
        """The classic call shape never pays the handshake round trip
        (and never trips a dedup-capable receiver's offer counter)."""
        seat = _FakeEngineSeat()
        recv, send, dest = self._pair(seat, index_fn=lambda fps: 2)
        try:
            send.migrate(dest, *_migrate_payload())
            assert recv.stats()["dedup_offers"] == 0
            assert seat.calls[0][1]["blk/layer0/k"].shape[0] == 3
        finally:
            send.close()
            recv.stop()


class TestFetch:
    def test_round_trip(self):
        served = {"n_blocks": 2, "v": kvxfer.PROTOCOL_VERSION}
        blocks = {"ids": np.arange(8, dtype=np.int32),
                  "blk/layer0/k": np.ones((2, 4, 2), np.float32)}
        calls = []

        def fetch_fn(statics, arrays):
            calls.append((statics, arrays))
            return served, blocks

        recv = kvxfer.KvReceiver(_FakeEngineSeat(), port=0,
                                 fetch_fn=fetch_fn)
        send = kvxfer.KvSender()
        try:
            st, arr = send.fetch(
                f"127.0.0.1:{recv.port}",
                {"v": kvxfer.PROTOCOL_VERSION},
                {"ids": np.arange(12, dtype=np.int32)})
            assert st["n_blocks"] == 2
            assert np.array_equal(arr["blk/layer0/k"],
                                  blocks["blk/layer0/k"])
            assert np.array_equal(calls[0][1]["ids"],
                                  np.arange(12, dtype=np.int32))
            assert recv.stats()["fetches"] == 1
            assert recv.stats()["fetch_blocks_out"] == 2
        finally:
            send.close()
            recv.stop()

    def test_miss_is_zero_blocks_not_error(self):
        recv = kvxfer.KvReceiver(_FakeEngineSeat(), port=0,
                                 fetch_fn=lambda s, a: None)
        send = kvxfer.KvSender()
        try:
            st, arr = send.fetch(
                f"127.0.0.1:{recv.port}",
                {"v": kvxfer.PROTOCOL_VERSION},
                {"ids": np.arange(4, dtype=np.int32)})
            assert st["n_blocks"] == 0
            assert not arr
            assert recv.stats()["fetches"] == 0
        finally:
            send.close()
            recv.stop()

    def test_legacy_receiver_is_protocol_refusal(self):
        """A receiver with no fetch seam answers the closed protocol's
        error (and closed the stream behind it — the sender must not
        pool that socket)."""
        recv = kvxfer.KvReceiver(_FakeEngineSeat(), port=0)
        send = kvxfer.KvSender()
        try:
            with pytest.raises(kvxfer.KvTransferError) as ei:
                send.fetch(f"127.0.0.1:{recv.port}",
                           {"v": kvxfer.PROTOCOL_VERSION},
                           {"ids": np.arange(4, dtype=np.int32)})
            assert ei.value.kind == "protocol"
            assert send.stats()["pooled_connections"] == 0
        finally:
            send.close()
            recv.stop()

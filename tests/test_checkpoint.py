"""Checkpoint/resume: orbax round-trips, sharded restore, preemption save.

SURVEY.md §5 "Checkpoint / resume": the reference left checkpoints to user
code; the rebuild's workload layer owns them, so these tests cover the full
resume contract a gang restart relies on.
"""

import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from k8s_tpu.models.checkpoint import Checkpointer
from k8s_tpu.parallel import MeshConfig, make_mesh


def _state(value: float):
    return {
        "params": {"w": jnp.full((16, 8), value, jnp.float32),
                   "b": jnp.zeros((8,), jnp.float32)},
        "step": jnp.zeros((), jnp.int32),
    }


class TestRoundtrip:
    def test_save_restore(self, tmp_path):
        ckpt = Checkpointer(os.fspath(tmp_path))
        state = _state(3.0)
        assert ckpt.save(0, state)
        ckpt.wait()
        restored, step = ckpt.restore_latest(_state(0.0))
        assert step == 0
        np.testing.assert_array_equal(restored["params"]["w"],
                                      state["params"]["w"])
        ckpt.close()

    def test_restore_or_init_fresh(self, tmp_path):
        ckpt = Checkpointer(os.fspath(tmp_path))
        target = _state(7.0)
        state, next_step = ckpt.restore_or_init(target)
        assert next_step == 0
        assert state is target
        ckpt.close()

    def test_restore_or_init_resumes_at_next_step(self, tmp_path):
        ckpt = Checkpointer(os.fspath(tmp_path))
        ckpt.save(4, _state(1.0))
        ckpt.wait()
        _, next_step = ckpt.restore_or_init(_state(0.0))
        assert next_step == 5
        ckpt.close()

    def test_max_to_keep_prunes(self, tmp_path):
        ckpt = Checkpointer(os.fspath(tmp_path), max_to_keep=2)
        for s in range(4):
            ckpt.save(s, _state(float(s)))
        ckpt.wait()
        assert ckpt.all_steps() == [2, 3]
        ckpt.close()

    def test_save_interval_skips_off_steps(self, tmp_path):
        ckpt = Checkpointer(os.fspath(tmp_path), save_interval_steps=10)
        assert ckpt.maybe_save(0, _state(0.0))
        assert not ckpt.maybe_save(3, _state(0.0))
        assert ckpt.maybe_save(10, _state(1.0))
        ckpt.wait()
        assert ckpt.all_steps() == [0, 10]
        ckpt.close()


class TestShardedRestore:
    def test_restore_preserves_shardings(self, tmp_path):
        mesh = make_mesh(MeshConfig(dp=2, fsdp=2, sp=1, tp=2), jax.devices())
        sharding = NamedSharding(mesh, P("fsdp", "tp"))
        w = jax.device_put(jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
                           sharding)
        state = {"w": w}
        ckpt = Checkpointer(os.fspath(tmp_path))
        ckpt.save(0, state)
        ckpt.wait()

        target = {"w": jax.device_put(jnp.zeros((8, 8), jnp.float32),
                                      sharding)}
        restored, step = ckpt.restore_latest(target)
        assert step == 0
        assert restored["w"].sharding.is_equivalent_to(sharding, 2)
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.asarray(w))
        ckpt.close()

    def test_train_resume_continuity(self, tmp_path):
        """Stop training at step k, resume from checkpoint, final params
        match an uninterrupted run (the gang-restart correctness story)."""
        def step_fn(state):
            g = 0.1 * jnp.ones_like(state["params"]["w"])
            return {
                "params": {"w": state["params"]["w"] - g,
                           "b": state["params"]["b"]},
                "step": state["step"] + 1,
            }

        # uninterrupted: 6 steps
        s = _state(1.0)
        for _ in range(6):
            s = step_fn(s)

        # interrupted at 3, resumed, 3 more
        ckpt = Checkpointer(os.fspath(tmp_path))
        s2 = _state(1.0)
        for _ in range(3):
            s2 = step_fn(s2)
        ckpt.save(2, s2)
        ckpt.wait()

        restored, next_step = ckpt.restore_or_init(_state(0.0))
        assert next_step == 3
        for _ in range(3):
            restored = step_fn(restored)
        np.testing.assert_allclose(restored["params"]["w"],
                                   s["params"]["w"], atol=1e-6)
        ckpt.close()


class TestPreemptionSave:
    def test_sigterm_triggers_save(self, tmp_path, monkeypatch):
        from k8s_tpu.util import signals

        # isolate module state so other tests' handlers don't interfere
        monkeypatch.setattr(signals, "_callbacks", [])
        monkeypatch.setattr(signals, "_stop", __import__("threading").Event())
        monkeypatch.setattr(signals, "_installed", False)
        monkeypatch.setattr(signals, "_setup_called", False)
        monkeypatch.setattr(signals, "_prev_handlers", {})

        ckpt = Checkpointer(os.fspath(tmp_path))
        live = {"state": _state(9.0), "step": 41}
        unsub = ckpt.save_on_preemption(
            lambda: live["state"], lambda: live["step"])
        try:
            os.kill(os.getpid(), signal.SIGTERM)
            # handler runs synchronously in the main thread
            assert signals._stop.is_set()
            assert ckpt.latest_step() == 41
            restored, _ = ckpt.restore_latest(_state(0.0))
            np.testing.assert_array_equal(restored["params"]["w"],
                                          live["state"]["params"]["w"])
        finally:
            unsub()  # restore the process SIGTERM disposition
            ckpt.close()


class TestObservabilityHooks:
    def test_xla_dump_env(self, tmp_path, monkeypatch):
        from k8s_tpu.launcher import bootstrap

        monkeypatch.setenv("XLA_FLAGS", "--existing=1")
        enabled = bootstrap.setup_observability(
            {"XLA_DUMP_TO": os.fspath(tmp_path)})
        assert enabled == {"xla_dump_to": os.fspath(tmp_path)}
        assert f"--xla_dump_to={tmp_path}" in os.environ["XLA_FLAGS"]
        assert "--existing=1" in os.environ["XLA_FLAGS"]

    def test_profile_trace_roundtrip(self, tmp_path):
        from k8s_tpu.launcher import bootstrap

        env = {"JAX_PROFILE_DIR": os.fspath(tmp_path)}
        enabled = bootstrap.setup_observability(env)
        assert enabled["profile_dir"] == os.fspath(tmp_path)
        jnp.dot(jnp.ones((8, 8)), jnp.ones((8, 8))).block_until_ready()
        bootstrap.stop_observability(env)
        # a trace directory with content exists
        files = [p for p in tmp_path.rglob("*") if p.is_file()]
        assert files, "profiler wrote no trace files"

    def test_disabled_is_noop(self):
        from k8s_tpu.launcher import bootstrap

        assert bootstrap.setup_observability({}) == {}


class TestFitLoop:
    def _setup(self):
        import dataclasses

        from k8s_tpu.models import train
        from k8s_tpu.models.transformer import Transformer, tiny_test

        cfg = dataclasses.replace(tiny_test(), layers=1, hidden=32,
                                  ffn_hidden=64, heads=2, kv_heads=2)
        model = Transformer(cfg)
        mesh = make_mesh(MeshConfig(dp=2, fsdp=2, sp=1, tp=2), jax.devices())
        tokens = jax.random.randint(
            jax.random.PRNGKey(0), (8, 16), 0, cfg.vocab_size)
        params = model.init(jax.random.PRNGKey(1), tokens)
        opt = train.default_optimizer(lr=1e-2)
        state = train.init_state(params, opt)

        def apply_fn(p, x):
            return model.apply(p, x)

        def data_iter():
            while True:
                yield (tokens, tokens)

        return train, apply_fn, opt, state, mesh, data_iter

    def test_fit_trains_and_checkpoints(self, tmp_path):
        train, apply_fn, opt, state, mesh, data_iter = self._setup()
        final, losses = train.fit(
            apply_fn, train.lm_loss, opt, state, mesh, data_iter(),
            steps=4, checkpoint_dir=os.fspath(tmp_path), checkpoint_every=2,
            preemption_save=False)
        assert len(losses) == 4
        assert losses[-1] < losses[0]
        ckpt = Checkpointer(os.fspath(tmp_path))
        assert ckpt.latest_step() == 3
        ckpt.close()

    def test_fit_resumes_from_checkpoint(self, tmp_path):
        train, apply_fn, opt, state, mesh, data_iter = self._setup()
        # run 3 of 6 steps, checkpointing every step
        train.fit(apply_fn, train.lm_loss, opt, state, mesh, data_iter(),
                  steps=3, checkpoint_dir=os.fspath(tmp_path),
                  checkpoint_every=1, preemption_save=False)
        # "restart": a fresh process re-inits state (fit donates the old
        # buffers), then fit to 6 — resumes at step 3
        train, apply_fn, opt, state, mesh, data_iter = self._setup()
        _, losses2 = train.fit(
            apply_fn, train.lm_loss, opt, state, mesh, data_iter(),
            steps=6, checkpoint_dir=os.fspath(tmp_path), checkpoint_every=1,
            preemption_save=False)
        assert len(losses2) == 3  # only ran the remaining steps


class TestSignalsLifecycle:
    """on_shutdown / setup_signal_handler composition (review findings)."""

    @pytest.fixture(autouse=True)
    def _isolate(self, monkeypatch):
        import threading

        from k8s_tpu.util import signals

        monkeypatch.setattr(signals, "_callbacks", [])
        monkeypatch.setattr(signals, "_stop", threading.Event())
        monkeypatch.setattr(signals, "_installed", False)
        monkeypatch.setattr(signals, "_setup_called", False)
        monkeypatch.setattr(signals, "_prev_handlers", {})
        self.signals = signals
        yield

    def test_setup_after_on_shutdown_does_not_raise(self):
        unsub = self.signals.on_shutdown(lambda: None)
        stop = self.signals.setup_signal_handler()  # must not raise
        assert not stop.is_set()
        unsub()

    def test_unsubscribe_restores_original_handlers(self):
        orig = signal.getsignal(signal.SIGTERM)
        unsub = self.signals.on_shutdown(lambda: None)
        assert signal.getsignal(signal.SIGTERM) is self.signals._handler
        unsub()
        assert signal.getsignal(signal.SIGTERM) is orig

    def test_unsubscribe_keeps_handler_for_operator_binaries(self):
        self.signals.setup_signal_handler()
        unsub = self.signals.on_shutdown(lambda: None)
        unsub()
        # setup_signal_handler owns the handler: it must stay installed
        assert signal.getsignal(signal.SIGTERM) is self.signals._handler

    def test_reset_clears_first_signal_latch(self):
        fired = []
        unsub = self.signals.on_shutdown(lambda: fired.append(1))
        os.kill(os.getpid(), signal.SIGTERM)
        assert fired == [1]
        assert self.signals._stop.is_set()
        self.signals.reset()
        assert not self.signals._stop.is_set()
        # a post-reset signal runs callbacks again instead of hard-exiting
        os.kill(os.getpid(), signal.SIGTERM)
        assert fired == [1, 1]
        unsub()

    def test_callback_unsubscribed_stops_firing(self):
        fired = []
        unsub = self.signals.on_shutdown(lambda: fired.append(1))
        unsub()
        keep = self.signals.on_shutdown(lambda: fired.append(2))
        os.kill(os.getpid(), signal.SIGTERM)
        assert fired == [2]
        keep()


class TestFitResultContract:
    def test_completed_resume_is_not_preempted(self, tmp_path):
        """A successful resumed run returns fewer losses than steps but
        preempted=False — drivers must key off the flag, not the count."""
        import tests.test_checkpoint as _self  # reuse TestFitLoop setup
        helper = TestFitLoop()
        train, apply_fn, opt, state, mesh, data_iter = helper._setup()
        train.fit(apply_fn, train.lm_loss, opt, state, mesh, data_iter(),
                  steps=2, checkpoint_dir=os.fspath(tmp_path),
                  checkpoint_every=1, preemption_save=False)
        train, apply_fn, opt, state, mesh, data_iter = helper._setup()
        result = train.fit(
            apply_fn, train.lm_loss, opt, state, mesh, data_iter(),
            steps=4, checkpoint_dir=os.fspath(tmp_path), checkpoint_every=1,
            preemption_save=False)
        assert len(result.losses) == 2 < 4
        assert result.preempted is False
        assert result.start_step == 2

    def test_stale_latch_cleared_for_library_reruns(self, monkeypatch):
        import threading

        from k8s_tpu.util import signals

        monkeypatch.setattr(signals, "_callbacks", [])
        monkeypatch.setattr(signals, "_stop", threading.Event())
        monkeypatch.setattr(signals, "_installed", False)
        monkeypatch.setattr(signals, "_setup_called", False)
        monkeypatch.setattr(signals, "_prev_handlers", {})

        # run 1 consumed a signal
        unsub = signals.on_shutdown(lambda: None)
        os.kill(os.getpid(), signal.SIGTERM)
        assert signals._stop.is_set()
        unsub()
        # run 2 registers fresh: the latch must clear, else its first
        # signal would os._exit(1) without running any callback
        fired = []
        unsub2 = signals.on_shutdown(lambda: fired.append(1))
        assert not signals._stop.is_set()
        os.kill(os.getpid(), signal.SIGTERM)
        assert fired == [1]
        unsub2()

"""bench.py Recorder persistence rules: what may enter the last-good
on-hardware record decides what evidence a relay-outage round can present.
Locked down here without touching a device (the Recorder is pure file+dict
machinery)."""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


@pytest.fixture()
def lastgood(tmp_path):
    return str(tmp_path / "LASTGOOD.json")


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    for k in ("BENCH_NO_PERSIST", "BENCH_ALLOW_SINGLE_REPEAT"):
        monkeypatch.delenv(k, raising=False)


class TestRepeatsGate:
    def test_single_repeat_never_persists(self, lastgood):
        r = bench.Recorder(lastgood)
        r.record("transformer", {"tokens_per_sec_per_chip": 1.0,
                                 "repeats": 1},
                 on_hardware=True, device_kind="TPU v5e")
        assert "transformer" not in r.last_good["benchmarks"]
        assert not os.path.exists(lastgood)
        # the fresh result is still available to build_output
        got, stale = r.get("transformer", allow_stale=True)
        assert got["repeats"] == 1 and not stale

    def test_missing_repeats_key_treated_as_single(self, lastgood):
        r = bench.Recorder(lastgood)
        r.record("decode", {"tokens_per_sec_per_chip": 9.9},
                 on_hardware=True)
        assert "decode" not in r.last_good["benchmarks"]

    def test_override_flag_persists_single_repeat(self, lastgood,
                                                  monkeypatch):
        monkeypatch.setenv("BENCH_ALLOW_SINGLE_REPEAT", "1")
        r = bench.Recorder(lastgood)
        r.record("transformer", {"tokens_per_sec_per_chip": 2.0,
                                 "repeats": 1}, on_hardware=True)
        assert "transformer" in r.last_good["benchmarks"]

    def test_multi_repeat_persists_with_provenance(self, lastgood):
        r = bench.Recorder(lastgood)
        r.record("resnet50", {"value": 5.0, "repeats": 3},
                 on_hardware=True, device_kind="TPU v5e")
        disk = json.load(open(lastgood))
        rec = disk["benchmarks"]["resnet50"]
        assert rec["repeats"] == 3
        assert rec["measured_at"] and rec["device_kind"] == "TPU v5e"

    def test_no_persist_env_blocks_hardware_write(self, lastgood,
                                                  monkeypatch):
        monkeypatch.setenv("BENCH_NO_PERSIST", "1")
        r = bench.Recorder(lastgood)
        r.record("resnet50", {"value": 5.0, "repeats": 3},
                 on_hardware=True)
        assert not os.path.exists(lastgood)


class TestSchemaGuard:
    def test_stale_record_missing_required_keys_reads_as_absent(
            self, lastgood):
        # a record written by OLDER code (schema drift) must read as
        # absent, not KeyError inside die()
        with open(lastgood, "w") as f:
            json.dump({"benchmarks": {"decode_depth": {"old": 1}}}, f)
        r = bench.Recorder(lastgood)
        got, stale = r.get("decode_depth", allow_stale=True)
        assert got is None and not stale

    def test_round5_record_names_have_required_keys(self):
        # every battery item that persists must be consumable later
        for name in ("resnet50", "transformer", "decode", "vit",
                     "decode_depth"):
            assert name in bench._REQUIRED_KEYS, name

"""Native (C++) runtime core: parity with the pure-Python workqueue and
expectations implementations, plus an end-to-end operator run on top of it.

The reference's hot loop is compiled Go (client-go workqueue +
k8s.io/kubernetes expectations); libk8stpu_runtime is our compiled
equivalent, and these tests pin its semantics to the Python reference
implementation parameter-for-parameter.
"""

from __future__ import annotations

import threading
import time

import pytest

from k8s_tpu import native
from k8s_tpu.controller_v2.expectations import (
    ControllerExpectations,
    new_controller_expectations,
)
from k8s_tpu.util.workqueue import RateLimitingQueue, new_rate_limiting_queue

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native runtime not buildable (no g++)"
)


def make_pair():
    from k8s_tpu.native.runtime import NativeRateLimitingQueue

    return RateLimitingQueue(), NativeRateLimitingQueue()


class TestQueueParity:
    def test_dedup_while_queued(self):
        for q in make_pair():
            q.add("default/a")
            q.add("default/a")
            q.add("default/b")
            assert len(q) == 2, type(q).__name__

    def test_readd_while_processing_requeues_after_done(self):
        for q in make_pair():
            q.add("default/a")
            item, shutdown = q.get(1)
            assert (item, shutdown) == ("default/a", False)
            q.add("default/a")  # goes dirty, not queued
            assert len(q) == 0
            q.done("default/a")
            assert len(q) == 1

    def test_get_timeout(self):
        for q in make_pair():
            t0 = time.monotonic()
            assert q.get(0.05) == (None, False)
            assert time.monotonic() - t0 >= 0.04

    def test_shutdown_unblocks_getters(self):
        for q in make_pair():
            results = []

            def worker():
                results.append(q.get(5))

            t = threading.Thread(target=worker)
            t.start()
            time.sleep(0.05)
            q.shut_down()
            t.join(timeout=2)
            assert not t.is_alive()
            assert results == [(None, True)]
            assert q.shutting_down()

    def test_add_after_orders_by_deadline(self):
        for q in make_pair():
            q.add_after("late", 0.2)
            q.add_after("early", 0.02)
            assert q.get(1)[0] == "early", type(q).__name__
            assert q.get(1)[0] == "late", type(q).__name__

    def test_rate_limited_backoff_grows_and_forget_resets(self):
        for q in make_pair():
            # exp backoff: 5ms, 10ms, 20ms...
            q.add_rate_limited("k")
            assert q.num_requeues("k") == 1
            assert q.get(1)[0] == "k"
            q.done("k")
            q.add_rate_limited("k")
            q.add_rate_limited("k")
            assert q.num_requeues("k") == 3
            q.forget("k")
            assert q.num_requeues("k") == 0

    def test_backoff_delay_actually_waits(self):
        from k8s_tpu.native.runtime import NativeRateLimitingQueue

        q = NativeRateLimitingQueue(base_delay=0.1, max_delay=1.0)
        q.add_rate_limited("k")  # first failure: 0.1s delay
        t0 = time.monotonic()
        assert q.get(0.02) == (None, False)  # not yet available
        assert q.get(2)[0] == "k"
        assert time.monotonic() - t0 >= 0.05


class TestExpectationsParity:
    def impls(self):
        from k8s_tpu.native.runtime import NativeControllerExpectations

        return ControllerExpectations(), NativeControllerExpectations()

    def test_unknown_key_is_satisfied(self):
        for e in self.impls():
            assert e.satisfied("ns/j/pods") is True

    def test_expect_then_observe(self):
        for e in self.impls():
            e.expect_creations("k", 2)
            assert e.satisfied("k") is False
            e.creation_observed("k")
            assert e.satisfied("k") is False
            e.creation_observed("k")
            assert e.satisfied("k") is True

    def test_pending_expectations_accumulate(self):
        """The burst-accumulation semantics our Python impl deliberately
        chose over upstream replace (see expectations.py docstring)."""
        for e in self.impls():
            e.expect_creations("k", 1)
            e.expect_creations("k", 1)
            e.creation_observed("k")
            assert e.satisfied("k") is False, type(e).__name__
            e.creation_observed("k")
            assert e.satisfied("k") is True

    def test_deletions_and_raise(self):
        for e in self.impls():
            e.expect_deletions("k", 1)
            assert e.satisfied("k") is False
            e.raise_expectations("k", 1, 0)
            e.deletion_observed("k")
            assert e.satisfied("k") is False
            e.creation_observed("k")
            assert e.satisfied("k") is True

    def test_delete_expectations(self):
        for e in self.impls():
            e.expect_creations("k", 5)
            e.delete_expectations("k")
            assert e.satisfied("k") is True

    def test_ttl_expiry(self):
        from k8s_tpu.native.runtime import NativeControllerExpectations

        e = NativeControllerExpectations(ttl_seconds=0.05)
        e.expect_creations("k", 5)
        assert e.satisfied("k") is False
        time.sleep(0.08)
        assert e.satisfied("k") is True


class TestFactories:
    def test_factories_pick_native_when_available(self):
        from k8s_tpu.native.runtime import (
            NativeControllerExpectations,
            NativeRateLimitingQueue,
        )

        assert isinstance(new_rate_limiting_queue(), NativeRateLimitingQueue)
        assert isinstance(new_controller_expectations(), NativeControllerExpectations)

    def test_disable_env_forces_python(self, monkeypatch):
        monkeypatch.setenv("K8S_TPU_NATIVE", "0")
        assert isinstance(new_rate_limiting_queue(), RateLimitingQueue)
        assert isinstance(new_controller_expectations(), ControllerExpectations)


class TestOperatorOnNativeRuntime:
    def test_v2_job_runs_on_native_queue(self):
        """Full LocalCluster pass with the controller on the native queue +
        expectations (the factories select them automatically here)."""
        import datetime
        import os

        from k8s_tpu.api import manifest
        from k8s_tpu.e2e.local import LocalCluster
        from k8s_tpu.harness import tf_job_client
        from k8s_tpu.native.runtime import NativeRateLimitingQueue

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        [job] = manifest.load_tfjobs_from_file(
            os.path.join(repo, "examples", "tpu_smoke.yaml")
        )
        job.spec.tf_replica_specs["TPU"].template["spec"]["containers"][0].pop(
            "command"
        )  # commandless: kubelet simulator exits 0
        with LocalCluster(version="v1alpha2") as lc:
            assert isinstance(lc.controller.queue, NativeRateLimitingQueue)
            created = tf_job_client.create_tf_job(
                lc.clientset, job.to_dict(), version="v1alpha2"
            )
            finished = tf_job_client.wait_for_job(
                lc.clientset,
                created["metadata"]["namespace"],
                created["metadata"]["name"],
                version="v1alpha2",
                timeout=datetime.timedelta(seconds=30),
                polling_interval=datetime.timedelta(milliseconds=50),
            )
        conds = [c["type"] for c in finished["status"]["conditions"]]
        assert "Succeeded" in conds

"""Disaggregated prefill/decode serving (ISSUE 15): engine block
export/import seams, cross-engine token identity, CoW donor integrity
under grafts, receive-side pool refusal, and the HTTP + kvxfer + router
hop end to end."""

from __future__ import annotations

import json
import threading
import time
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

from k8s_tpu.models.engine import Engine, PoolExhausted
from k8s_tpu.models.transformer import Transformer, TransformerConfig


def tiny(**kw):
    base = dict(vocab_size=61, hidden=32, ffn_hidden=64, layers=2,
                heads=4, kv_heads=4, max_seq_len=64, dtype=jnp.float32,
                remat=False)
    base.update(kw)
    return TransformerConfig(**base)


def init_params(cfg, seed=0):
    import jax

    model = Transformer(cfg)
    return model.init(jax.random.PRNGKey(seed),
                      jnp.zeros((1, 5), jnp.int32))["params"]


def prompt_of(n, salt=3):
    return [(i * 7 + salt) % 61 for i in range(n)]


def block_bytes(engine: Engine, block: int) -> dict:
    """One pool block's host bytes (test probe; engine quiescent)."""
    from k8s_tpu.models.engine import _flatten_tree

    return _flatten_tree(engine._gather_fn(
        engine._pool, np.asarray([block], np.int32)))


def migrate(src: Engine, dst: Engine, prompt, max_new, **kw):
    """Engine-level migration helper: export on ``src``, seat on
    ``dst``; returns the emitted tokens."""
    exp = src.prefill_export(prompt, max_new, **kw)
    if exp["done"]:
        return exp["tokens"]
    return dst.submit_prefilled(
        exp["ids"], exp["blocks"], first_token=exp["first"],
        key=exp["key"], max_new_tokens=max_new,
        eos_id=kw.get("eos_id"), temperature=kw.get("temperature", 0.0),
        top_k=kw.get("top_k"), speculative=kw.get("speculative", 0),
        block_size=exp["block_size"])


@pytest.fixture(scope="module")
def fp_world():
    cfg = tiny()
    params = init_params(cfg)
    a = Engine(cfg, params, slots=2, queue_limit=32)
    b = Engine(cfg, params, slots=2, queue_limit=32)
    yield cfg, params, a, b
    a.shutdown()
    b.shutdown()


class TestExportImport:
    def test_export_is_deterministic_and_bit_exact(self, fp_world):
        """The same prompt prefilled on two engines exports the SAME
        block bytes — the chain the wire carries is exactly local
        prefill's device state."""
        _cfg, _params, a, b = fp_world
        p = prompt_of(37)
        ea = a.prefill_export(p, 8)
        eb = b.prefill_export(p, 8)
        assert set(ea["blocks"]) == set(eb["blocks"])
        for path in ea["blocks"]:
            assert ea["blocks"][path].dtype == eb["blocks"][path].dtype
            np.testing.assert_array_equal(ea["blocks"][path],
                                          eb["blocks"][path])
        assert ea["first"] == eb["first"]
        np.testing.assert_array_equal(ea["key"], eb["key"])

    @pytest.mark.parametrize("kw", [
        {},                                               # greedy
        {"temperature": 1.0, "seed": 42},                 # sampled
        {"temperature": 0.7, "top_k": 5, "seed": 9},      # top-k
        {"speculative": 3, "seed": 4},                    # spec lane
    ])
    def test_migrated_token_identity(self, fp_world, kw):
        """Fixed-seed migrated output == local output on every lane:
        same pool bytes, same PRNG carry, row-independent batched
        math."""
        _cfg, _params, a, b = fp_world
        p = prompt_of(21)
        local = a.submit(np.asarray(p, np.int32), 10,
                         temperature=kw.get("temperature", 0.0),
                         top_k=kw.get("top_k"),
                         seed=kw.get("seed", 0),
                         speculative=kw.get("speculative", 0))
        migrated = migrate(a, b, p, 10, **kw)
        assert migrated == local
        a.debug_check_blocks()
        b.debug_check_blocks()

    def test_migrated_prefix_immediately_shareable(self, fp_world):
        """A grafted chain lands in the receiver's radix tree: a LOCAL
        request with the same prompt attaches by reference."""
        _cfg, _params, a, b = fp_world
        p = prompt_of(33, salt=11)
        local = a.submit(np.asarray(p, np.int32), 6)
        before = b.stats()["prefix_hits"]
        assert migrate(a, b, p, 6) == local
        again = b.submit(np.asarray(p, np.int32), 6)
        assert again == local
        assert b.stats()["prefix_hits"] == before + 1

    def test_first_token_eos_never_migrates(self, fp_world):
        _cfg, _params, a, b = fp_world
        p = prompt_of(9)
        first = a.submit(np.asarray(p, np.int32), 1)[0]
        exports_before = a.stats()["kv_blocks_out"]
        exp = a.prefill_export(p, 4, eos_id=first)
        assert exp["done"] and exp["tokens"] == [first]
        assert exp["n_blocks"] == 0
        assert a.stats()["kv_blocks_out"] == exports_before

    def test_int8_pool_migrates_bit_exact(self):
        """int8 pools ship their native quantized leaves + scales —
        the migrated output is token-identical to the local int8
        engine (no wire re-quantization)."""
        cfg = tiny(kv_cache_dtype="int8")
        params = init_params(cfg)
        a = Engine(cfg, params, slots=2, queue_limit=16)
        b = Engine(cfg, params, slots=2, queue_limit=16)
        try:
            p = prompt_of(25)
            exp = a.prefill_export(p, 8, temperature=1.0)
            k_paths = [pa for pa in exp["blocks"]
                       if pa.endswith("/k")]
            assert k_paths and all(
                exp["blocks"][pa].dtype == np.int8 for pa in k_paths)
            assert any(pa.endswith("k_scale") for pa in exp["blocks"])
            local = a.submit(np.asarray(p, np.int32), 8,
                             temperature=1.0)
            assert migrate(a, b, p, 8, temperature=1.0) == local
        finally:
            a.shutdown()
            b.shutdown()


class TestCowDonorIntegrity:
    def test_graft_never_touches_donor_blocks(self, fp_world):
        """A graft writes only freshly-allocated blocks: tree blocks a
        previous request donated stay bit-identical, and a
        copy-on-write off them after the graft still matches the
        oracle."""
        _cfg, _params, a, b = fp_world
        template = prompt_of(35, salt=23)
        b.submit(np.asarray(template, np.int32), 6)  # seeds b's tree
        # donor blocks: the template's tree entries on b
        donors = [n.block for n in
                  b._tree.match(template, len(template) - 1)[0]]
        assert donors
        before = {d: {pa: arr.copy() for pa, arr in
                      block_bytes(b, d).items()}
                  for d in donors}
        # migrate an unrelated chain in
        other = prompt_of(30, salt=41)
        assert migrate(a, b, other, 6) == a.submit(
            np.asarray(other, np.int32), 6)
        for d in donors:
            after = block_bytes(b, d)
            for pa in before[d]:
                np.testing.assert_array_equal(before[d][pa], after[pa])
        # the template still serves identically (CoW path included)
        diverged = template[:-2] + [7, 9]
        oracle = a.submit(np.asarray(diverged, np.int32), 6)
        assert b.submit(np.asarray(diverged, np.int32), 6) == oracle
        b.debug_check_blocks()


class TestPoolExhaustion:
    def test_receive_side_refusal(self):
        """An import that cannot fit even after evicting every unpinned
        tree leaf refuses with PoolExhausted BEFORE queuing; it seats
        fine once the blocks free."""
        cfg = tiny()
        params = init_params(cfg)
        # slots=1, no prefix headroom: pool = null + maxb blocks
        a = Engine(cfg, params, slots=2, queue_limit=16)
        b = Engine(cfg, params, slots=1, queue_limit=16,
                   prefix_blocks=0)
        try:
            hog_prompt = prompt_of(40)
            exp = a.prefill_export(prompt_of(33, salt=5), 8)

            done = threading.Event()
            out = {}

            def hog():
                # occupies the only slot and (40+20 tokens) all 4 blocks
                out["tokens"] = b.submit(
                    np.asarray(hog_prompt, np.int32), 20)
                done.set()

            t = threading.Thread(target=hog, daemon=True)
            t.start()
            deadline = time.monotonic() + 10
            while b.stats()["blocks_in_use"] < 3 \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
            assert b.stats()["blocks_in_use"] >= 3
            with pytest.raises(PoolExhausted) as ei:
                b.submit_prefilled(
                    exp["ids"], exp["blocks"],
                    first_token=exp["first"], key=exp["key"],
                    max_new_tokens=8, block_size=exp["block_size"])
            assert ei.value.needed > ei.value.available
            assert done.wait(30)
            # blocks freed: the same import now seats
            toks = b.submit_prefilled(
                exp["ids"], exp["blocks"], first_token=exp["first"],
                key=exp["key"], max_new_tokens=8,
                block_size=exp["block_size"])
            assert toks == a.submit(
                np.asarray(prompt_of(33, salt=5), np.int32), 8)
        finally:
            a.shutdown()
            b.shutdown()


class TestImportValidation:
    def test_block_size_mismatch_refused(self, fp_world):
        _cfg, _params, a, b = fp_world
        exp = a.prefill_export(prompt_of(20), 4)
        with pytest.raises(ValueError, match="block_size"):
            b.submit_prefilled(exp["ids"], exp["blocks"],
                               first_token=exp["first"],
                               key=exp["key"], max_new_tokens=4,
                               block_size=exp["block_size"] * 2)

    def test_manifest_mismatch_refused(self, fp_world):
        _cfg, _params, a, b = fp_world
        exp = a.prefill_export(prompt_of(20), 4)
        broken = dict(exp["blocks"])
        victim = next(iter(broken))
        del broken[victim]
        with pytest.raises(ValueError, match="manifest"):
            b.submit_prefilled(exp["ids"], broken,
                               first_token=exp["first"],
                               key=exp["key"], max_new_tokens=4,
                               block_size=exp["block_size"])

    def test_shape_mismatch_refused(self, fp_world):
        _cfg, _params, a, b = fp_world
        exp = a.prefill_export(prompt_of(20), 4)
        broken = dict(exp["blocks"])
        victim = next(iter(broken))
        broken[victim] = broken[victim][:, :-1]
        with pytest.raises(ValueError, match="shape"):
            b.submit_prefilled(exp["ids"], broken,
                               first_token=exp["first"],
                               key=exp["key"], max_new_tokens=4,
                               block_size=exp["block_size"])

    def test_int8_pool_refuses_fp_content(self):
        cfg = tiny(kv_cache_dtype="int8")
        params = init_params(cfg)
        b = Engine(cfg, params, slots=1, queue_limit=8)
        a = Engine(cfg, params, slots=1, queue_limit=8)
        try:
            exp = a.prefill_export(prompt_of(20), 4)
            broken = {pa: (arr.astype(np.float32)
                           if pa.endswith("/k") else arr)
                      for pa, arr in exp["blocks"].items()}
            with pytest.raises(ValueError, match="int8"):
                b.submit_prefilled(exp["ids"], broken,
                                   first_token=exp["first"],
                                   key=exp["key"], max_new_tokens=4,
                                   block_size=exp["block_size"])
        finally:
            a.shutdown()
            b.shutdown()

    def test_windowed_engine_refuses_disagg(self):
        cfg = tiny(window_size=16, prefill_chunk=8)
        params = init_params(cfg)
        eng = Engine(cfg, params, slots=1, queue_limit=8)
        try:
            with pytest.raises(ValueError, match="paged"):
                eng.prefill_export(prompt_of(10), 4)
        finally:
            eng.shutdown()


def _post(port, body, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/generate",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


class TestHttpDisagg:
    @pytest.fixture(scope="class")
    def http_world(self):
        from k8s_tpu.models import server as server_mod
        from k8s_tpu.util import metrics as metrics_mod

        cfg = tiny()
        params = init_params(cfg)
        pre = server_mod.LmServer(config=cfg, params=params, slots=2,
                                  role="prefill",
                                  registry=metrics_mod.Registry())
        dec = server_mod.LmServer(config=cfg, params=params, slots=2,
                                  role="decode",
                                  registry=metrics_mod.Registry())
        ref = server_mod.LmServer(config=cfg, params=params, slots=2,
                                  registry=metrics_mod.Registry())
        servers = [server_mod.serve(s) for s in (pre, dec, ref)]
        yield (pre, dec, ref,
               [h.server_address[1] for h in servers])
        for h in servers:
            h.shutdown()
        for s in (pre, dec, ref):
            s.close()

    def test_roles_and_receiver_surface(self, http_world):
        pre, dec, _ref, ports = http_world
        with urllib.request.urlopen(
                f"http://127.0.0.1:{ports[1]}/healthz") as r:
            info = json.loads(r.read())["serving"]
        assert info["role"] == "decode"
        assert info["kvxfer_port"] == dec._kv_receiver.port
        assert pre._kv_receiver is None  # prefill pods never seat
        assert dec._kv_sender is None    # decode pods never export

    def test_http_migration_identity_and_counters(self, http_world):
        pre, dec, _ref, ports = http_world
        p = prompt_of(30, salt=17)
        kv = f"127.0.0.1:{dec._kv_receiver.port}"
        local = _post(ports[2], {"tokens": p, "max_new_tokens": 8,
                                 "temperature": 1.0, "seed": 5})
        routed = _post(ports[0], {"tokens": p, "max_new_tokens": 8,
                                  "temperature": 1.0, "seed": 5,
                                  "kv_dest": kv})
        assert routed["tokens"] == local["tokens"]
        assert pre.engine.stats()["kv_exports"] >= 1
        assert dec.engine.stats()["kv_imports"] >= 1
        # the decode pod's own exposition carries the migration counter
        with urllib.request.urlopen(
                f"http://127.0.0.1:{ports[1]}/metrics") as r:
            text = r.read().decode()
        assert "serve_kv_blocks_migrated_total" in text

    def test_bad_kv_dest_is_client_error(self, http_world):
        _pre, _dec, _ref, ports = http_world
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(ports[0], {"tokens": prompt_of(12),
                             "max_new_tokens": 4,
                             "kv_dest": "not-a-dest"})
        assert ei.value.code == 400
        assert json.loads(ei.value.read())["field"] == "kv_dest"

    def test_dead_kv_dest_maps_to_502(self, http_world):
        _pre, _dec, _ref, ports = http_world
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(ports[0], {"tokens": prompt_of(12),
                             "max_new_tokens": 4,
                             "kv_dest": "127.0.0.1:1"})
        assert ei.value.code == 502

    def test_request_recorder_sees_the_hop(self, http_world):
        """The prefill→decode hop is visible in /debug/requests on
        BOTH sides: sender timeline retires ``migrated`` with the
        migrate phase billed; the decode timeline is kind ``migrated``
        with the graft's migrate phase and the shared trace id."""
        from k8s_tpu.models import requestlog

        pre, dec, _ref, ports = http_world
        rec = requestlog.RequestRecorder(max_requests=64)
        old = requestlog.active()
        requestlog.set_active(rec)
        pre.engine._reqlog = rec
        dec.engine._reqlog = rec
        try:
            p = prompt_of(31, salt=29)
            kv = f"127.0.0.1:{dec._kv_receiver.port}"
            _post(ports[0],
                  {"tokens": p, "max_new_tokens": 6, "kv_dest": kv})
            entries = rec.snapshot()
            sender = [e for e in entries
                      if e["kind"] == "prefill_export"]
            seated = [e for e in entries if e["kind"] == "migrated"]
            assert sender and seated
            assert sender[-1]["retire"] == "migrated"
            assert sender[-1]["migrate"]["direction"] == "out"
            assert sender[-1]["migrate"]["blocks"] >= 1
            assert sender[-1]["phase_s"]["migrate"] > 0
            assert seated[-1]["migrate"]["direction"] == "in"
            assert seated[-1]["phase_s"]["migrate"] > 0
            assert "migrate" in requestlog.PHASES
        finally:
            pre.engine._reqlog = None
            dec.engine._reqlog = None
            requestlog.set_active(old)

    def test_wire_int8_path_serves(self):
        """fp pool + K8S_TPU_KVXFER_INT8: the wire carries quantized
        content (lossy by contract — no identity assertion), the
        request completes, and the receiver dequantizes into a working
        seat."""
        from k8s_tpu.models import server as server_mod
        from k8s_tpu.util import metrics as metrics_mod

        cfg = tiny()
        params = init_params(cfg)
        pre = server_mod.LmServer(config=cfg, params=params, slots=2,
                                  role="prefill", kvxfer_int8=True,
                                  registry=metrics_mod.Registry())
        dec = server_mod.LmServer(config=cfg, params=params, slots=2,
                                  role="decode",
                                  registry=metrics_mod.Registry())
        hs = [server_mod.serve(s) for s in (pre, dec)]
        try:
            kv = f"127.0.0.1:{dec._kv_receiver.port}"
            out = _post(hs[0].server_address[1],
                        {"tokens": prompt_of(30), "max_new_tokens": 6,
                         "kv_dest": kv})
            assert len(out["tokens"]) == 6
            assert dec.engine.stats()["kv_imports"] == 1
        finally:
            for h in hs:
                h.shutdown()
            pre.close()
            dec.close()


class TestEvictableAccounting:
    def test_whole_unpinned_chain_counts(self):
        """The receive-side backpressure pre-check must count a whole
        unpinned tree CHAIN as evictable (eviction frees leaves bottom-
        up, exposing parents) — counting only current leaves refused
        imports a warm pod could seat."""
        cfg = tiny()
        params = init_params(cfg)
        eng = Engine(cfg, params, slots=1, queue_limit=8,
                     prefix_blocks=8)
        try:
            # a 63-token prompt inserts a 3-deep chain (full blocks)
            eng.submit(np.asarray(prompt_of(63), np.int32), 1)
            assert eng._tree.nodes == 3
            assert eng._evictable_blocks() == 3
            # pin the chain's first block via a sharing slot-less ref:
            # simulate by retaining it — its descendants then stay
            # uncounted too (they can never become removable leaves
            # while an ancestor... the PINNED node itself blocks only
            # itself; children below a pinned node still evict), so
            # pin the LEAF: ancestors must drop out of the count
            leaf = eng._tree.match(prompt_of(63), 62)[0][-1]
            eng._pool_alloc.retain(leaf.block)
            try:
                assert eng._evictable_blocks() == 0
            finally:
                eng._pool_alloc.release(leaf.block)
            assert eng._evictable_blocks() == 3
        finally:
            eng.shutdown()


class TestLanePolicyOutranksPhaseSplit:
    def test_exclusive_routed_request_serves_locally(self):
        """batch_sampling=0 routes temperature>0 requests to the
        exclusive lane; a kv_dest on such a request must NOT force it
        through the batched migration path — the operator's routing
        policy outranks the router's phase split."""
        from k8s_tpu.models import server as server_mod
        from k8s_tpu.util import metrics as metrics_mod

        cfg = tiny()
        params = init_params(cfg)
        pre = server_mod.LmServer(config=cfg, params=params, slots=2,
                                  role="prefill", batch_sampling=False,
                                  registry=metrics_mod.Registry())
        dec = server_mod.LmServer(config=cfg, params=params, slots=2,
                                  role="decode",
                                  registry=metrics_mod.Registry())
        hs = [server_mod.serve(s) for s in (pre, dec)]
        try:
            kv = f"127.0.0.1:{dec._kv_receiver.port}"
            out = _post(hs[0].server_address[1],
                        {"tokens": prompt_of(30), "max_new_tokens": 6,
                         "temperature": 1.0, "seed": 3,
                         "kv_dest": kv})
            assert len(out["tokens"]) == 6
            # served locally on the exclusive lane: nothing migrated
            assert pre.engine.stats()["kv_exports"] == 0
            assert dec.engine.stats()["kv_imports"] == 0
        finally:
            for h in hs:
                h.shutdown()
            pre.close()
            dec.close()

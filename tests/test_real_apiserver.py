"""Real-apiserver path: the wire protocol is the only contract.

The reference's controller unit tests stub HTTP with utiltesting.FakeHandler
(pkg/controller.v2/service_control_test.go:35); its real-cluster coverage
lives in py/deploy.py + py/test_runner.py.  This tier covers the gap the
fakes can't: k8s_tpu.client.rest.RestClient + informers + leader election +
the operator *binary* all running against a real-protocol HTTP apiserver
(k8s_tpu.e2e.apiserver.ApiServer) — zero FakeCluster imports on the operator
side of the wire.
"""

from __future__ import annotations

import datetime
import os
import subprocess
import sys
import threading
import time

import pytest

from k8s_tpu.client import errors
from k8s_tpu.client.clientset import Clientset
from k8s_tpu.client.gvr import (
    NAMESPACES,
    NODES,
    PODS,
    SERVICES,
    TFJOBS_V1ALPHA2,
)
from k8s_tpu.client.informer import SharedInformerFactory
from k8s_tpu.client.rest import ClusterConfig, RestClient
from k8s_tpu.e2e.apiserver import ApiServer
from k8s_tpu.e2e.components import core_component
from k8s_tpu.e2e.kubelet import KubeletSimulator
from k8s_tpu.harness import tf_job_client

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from k8s_tpu.e2e.multiprocess import free_port as _free_port

FAST = dict(
    timeout=datetime.timedelta(seconds=60),
    polling_interval=datetime.timedelta(milliseconds=100),
)


def wait_until(pred, timeout=15.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


@pytest.fixture()
def server():
    s = ApiServer(watch_timeout=60.0)
    s.start()
    yield s
    s.stop()


@pytest.fixture()
def client(server):
    return RestClient(ClusterConfig(host=server.url))


class TestRestProtocol:
    """CRUD/selectors/errors over the wire (FakeHandler pattern, extended)."""

    def test_create_get_update_patch_delete(self, client):
        pod = {"metadata": {"name": "p1", "namespace": "default",
                            "labels": {"a": "b"}}, "spec": {}}
        created = client.create(PODS, "default", pod)
        assert created["metadata"]["uid"]
        got = client.get(PODS, "default", "p1")
        got["spec"]["nodeName"] = "n1"
        assert client.update(PODS, "default", got)["spec"]["nodeName"] == "n1"
        patched = client.patch_merge(PODS, "default", "p1",
                                     {"status": {"phase": "Running"}})
        assert patched["status"]["phase"] == "Running"
        client.delete(PODS, "default", "p1")
        with pytest.raises(errors.ApiError) as exc:
            client.get(PODS, "default", "p1")
        assert exc.value.code == 404 and exc.value.reason == "NotFound"

    def test_selectors(self, client):
        client.create(PODS, "default", {"metadata": {
            "name": "a", "namespace": "default", "labels": {"x": "1"}}})
        client.create(PODS, "default", {"metadata": {
            "name": "b", "namespace": "default", "labels": {"x": "2"}},
            "status": {"phase": "Running"}})
        assert [p["metadata"]["name"] for p in
                client.list(PODS, "default", label_selector="x=2")] == ["b"]
        assert [p["metadata"]["name"] for p in
                client.list(PODS, "default",
                            field_selector={"status.phase": "Running"})] == ["b"]

    def test_conflict_and_already_exists(self, client):
        client.create(PODS, "default", {"metadata": {"name": "p", "namespace": "default"}})
        with pytest.raises(errors.ApiError) as exc:
            client.create(PODS, "default", {"metadata": {"name": "p", "namespace": "default"}})
        assert exc.value.code == 409
        stale = client.get(PODS, "default", "p")
        client.update(PODS, "default", client.get(PODS, "default", "p"))
        with pytest.raises(errors.ApiError) as exc:
            client.update(PODS, "default", stale)  # stale resourceVersion
        assert exc.value.reason == "Conflict"

    def test_cluster_scoped_and_crd_resources(self, client):
        client.create(NODES, "", {"metadata": {"name": "n1"}})
        assert client.get(NODES, "", "n1")["kind"] == "Node"
        client.create(NAMESPACES, "", {"metadata": {"name": "kubeflow"}})
        assert any(n["metadata"]["name"] == "kubeflow"
                   for n in client.list(NAMESPACES))
        job = {"apiVersion": "kubeflow.org/v1alpha2", "kind": "TFJob",
               "metadata": {"name": "j1", "namespace": "default"}, "spec": {}}
        client.create(TFJOBS_V1ALPHA2, "default", job)
        assert client.get(TFJOBS_V1ALPHA2, "default", "j1")["kind"] == "TFJob"

    def test_owner_gc_over_the_wire(self, client):
        job = client.create(TFJOBS_V1ALPHA2, "default", {
            "apiVersion": "kubeflow.org/v1alpha2", "kind": "TFJob",
            "metadata": {"name": "owner", "namespace": "default"}, "spec": {}})
        client.create(PODS, "default", {"metadata": {
            "name": "child", "namespace": "default",
            "ownerReferences": [{"uid": job["metadata"]["uid"], "controller": True}]}})
        client.delete(TFJOBS_V1ALPHA2, "default", "owner", propagation="Foreground")
        with pytest.raises(errors.ApiError):
            client.get(PODS, "default", "child")

    def test_named_namespaced_object_without_namespace_404s(self, server, client):
        # real apiservers reject /api/v1/pods/<name>; the fixture must too,
        # or client URL bugs would pass against it
        client.create(PODS, "default", {"metadata": {"name": "p", "namespace": "default"}})
        import urllib.request
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"{server.url}/api/v1/pods/p")
        assert exc.value.code == 404

    def test_bearer_token_auth(self):
        with ApiServer(token="sekret") as s:
            denied = RestClient(ClusterConfig(host=s.url))
            with pytest.raises(errors.ApiError) as exc:
                denied.list(PODS, "default")
            assert exc.value.code == 401
            ok = RestClient(ClusterConfig(host=s.url, token="sekret"))
            assert ok.list(PODS, "default") == []


class TestWatchStreaming:
    def test_watch_delivers_events(self, server, client):
        w = client.watch(PODS, "default")
        events = []

        def consume():
            for ev in w:
                events.append(ev)
                if len(events) >= 3:
                    return

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        time.sleep(0.3)  # let the stream attach before mutating
        client.create(PODS, "default", {"metadata": {"name": "w1", "namespace": "default"}})
        client.patch_merge(PODS, "default", "w1", {"status": {"phase": "Running"}})
        client.delete(PODS, "default", "w1")
        t.join(10)
        w.stop()
        assert [e[0] for e in events] == ["ADDED", "MODIFIED", "DELETED"]
        assert events[0][1]["metadata"]["name"] == "w1"

    def test_watch_timeout_ends_stream(self, client):
        with ApiServer(watch_timeout=0.3) as s:
            c = RestClient(ClusterConfig(host=s.url))
            w = c.watch(PODS, "default")
            start = time.monotonic()
            assert w.next() is None  # server closes at its watch timeout
            assert w.stopped
            assert time.monotonic() - start < 5

    def test_informer_steady_state_does_not_relist(self):
        """rv-resumed watches: after the initial list, any number of
        server-side watch-stream ends must cost ZERO further relists — at
        the 200-concurrent-job design point a relist is O(N) churn per
        cycle (the round-2 scale bottleneck, BASELINE.md)."""
        with ApiServer(watch_timeout=0.25) as s:
            for i in range(200):
                s.cluster.create(
                    PODS, "default",
                    {"metadata": {"name": f"pre-{i:03d}", "namespace": "default"}},
                )
            backend = RestClient(ClusterConfig(host=s.url))
            seen = []
            factory = SharedInformerFactory(backend, resync_period=0)
            informer = factory.informer_for(PODS)
            informer.add_event_handler(
                on_add=lambda o: seen.append(o["metadata"]["name"]))
            factory.start()
            assert factory.wait_for_cache_sync(10)
            lists_after_sync = sum(
                1 for a in s.cluster.actions if a.verb == "list")
            # span several watch-timeout cycles, with events in each
            for i in range(4):
                time.sleep(0.4)
                backend.create(
                    PODS, "default",
                    {"metadata": {"name": f"live-{i}", "namespace": "default"}})
            assert wait_until(
                lambda: all(f"live-{i}" in seen for i in range(4)))
            lists_now = sum(1 for a in s.cluster.actions if a.verb == "list")
            assert lists_now == lists_after_sync, (
                f"steady-state watch cycles relisted "
                f"({lists_now - lists_after_sync} extra lists)")
            assert len(informer.store.list()) == 204
            factory.stop()

    def test_informer_recovers_from_410_expired(self):
        """A watch resume past the server's retained event window gets 410
        and must fall back to a relist, not wedge."""
        with ApiServer(watch_timeout=0.25) as s:
            s.cluster.EVENT_HISTORY_LIMIT = 4
            backend = RestClient(ClusterConfig(host=s.url))
            seen = []
            factory = SharedInformerFactory(backend, resync_period=0)
            informer = factory.informer_for(PODS)
            informer.add_event_handler(
                on_add=lambda o: seen.append(o["metadata"]["name"]))
            factory.start()
            assert factory.wait_for_cache_sync(10)
            # Burst enough events inside one watch gap to trim the history
            # past the informer's resume point.  The burst happens while the
            # informer is between streams often enough across cycles that a
            # 410 is effectively guaranteed; either way the invariant below
            # must hold.
            for i in range(12):
                s.cluster.create(
                    PODS, "default",
                    {"metadata": {"name": f"burst-{i}", "namespace": "default"}})
            assert wait_until(
                lambda: len(informer.store.list()) == 12, timeout=10)
            # still live after any 410/relist:
            backend.create(
                PODS, "default",
                {"metadata": {"name": "post-410", "namespace": "default"}})
            assert wait_until(lambda: "post-410" in seen)
            factory.stop()

    def test_informer_over_rest_relists_after_stream_end(self, client):
        """The reflector's list→watch→relist loop against a short server
        watch timeout: events before AND after a forced relist arrive."""
        with ApiServer(watch_timeout=0.5) as s:
            backend = RestClient(ClusterConfig(host=s.url))
            seen = []
            factory = SharedInformerFactory(backend, resync_period=0)
            informer = factory.informer_for(PODS)
            informer.add_event_handler(
                on_add=lambda o: seen.append(("add", o["metadata"]["name"])))
            factory.start()
            assert factory.wait_for_cache_sync(10)
            backend.create(PODS, "default",
                           {"metadata": {"name": "before", "namespace": "default"}})
            assert wait_until(lambda: ("add", "before") in seen)
            time.sleep(1.2)  # at least one server-side stream end + relist
            backend.create(PODS, "default",
                           {"metadata": {"name": "after", "namespace": "default"}})
            assert wait_until(lambda: ("add", "after") in seen)
            factory.stop()


class TestOperatorBinaryE2E:
    """cmd.operator_v2 subprocess + kubelet sim + harness client, all over
    REST — the full job lifecycle with no in-process fakes on either side."""

    def _spawn_operator(self, url):
        return subprocess.Popen(
            [sys.executable, "-m", "k8s_tpu.cmd.operator_v2",
             "--master", url, "--namespace", "default", "--threadiness", "1"],
            cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )

    def test_full_job_lifecycle(self, server):
        rest = RestClient(ClusterConfig(host=server.url))
        clientset = Clientset(rest)
        operator = self._spawn_operator(server.url)
        kubelet = KubeletSimulator(clientset, "default").start()
        try:
            # operator is up once its leader-election lock appears
            assert wait_until(
                lambda: self._has_lock(clientset), timeout=30
            ), self._operator_tail(operator)

            component = core_component(
                {"name": "rest-e2e", "num_workers": 2, "num_ps": 1}, "v1alpha2"
            )
            tf_job_client.create_tf_job(clientset, component, "v1alpha2")
            job = tf_job_client.wait_for_job(
                clientset, "default", "rest-e2e", "v1alpha2", **FAST
            )
            conditions = {c["type"]: c["status"]
                          for c in job["status"]["conditions"]}
            assert conditions.get("Succeeded") == "True", job["status"]
            # per-index headless services were created over the wire
            services = rest.list(SERVICES, "default")
            assert len(services) >= 2

            tf_job_client.delete_tf_job(clientset, "default", "rest-e2e", "v1alpha2")
            assert wait_until(
                lambda: not rest.list(PODS, "default"), timeout=20
            ), "pods not GC'd after job delete"
        finally:
            kubelet.stop()
            operator.terminate()
            try:
                operator.wait(10)
            except subprocess.TimeoutExpired:
                operator.kill()

    @staticmethod
    def _has_lock(clientset) -> bool:
        try:
            obj = clientset.endpoints("default").get("tf-operator-v2")
        except errors.ApiError:
            return False
        return bool(obj)

    @staticmethod
    def _operator_tail(proc) -> str:
        proc.terminate()
        try:
            out, _ = proc.communicate(timeout=5)
        except subprocess.TimeoutExpired:
            proc.kill()
            return "operator hung"
        return (out or b"").decode(errors="replace")[-2000:]


class TestOperatorV1BinaryE2E:
    """cmd.operator (the v1 binary) over REST through its REAL config
    surface: a kubeconfig file, leader election, the /metrics endpoint,
    and the chaos-flag safety interlock — run() was previously only
    exercised as parsed flags."""

    def _kubeconfig(self, tmp_path, url) -> str:
        import yaml

        path = tmp_path / "kubeconfig"
        path.write_text(yaml.safe_dump({
            "current-context": "e2e",
            "contexts": [{"name": "e2e",
                          "context": {"cluster": "c", "user": "u"}}],
            "clusters": [{"name": "c", "cluster": {"server": url}}],
            "users": [{"name": "u", "user": {}}],
        }))
        return str(path)

    def test_v1_binary_reconciles_over_wire(self, server, tmp_path):
        import urllib.request

        rest = RestClient(ClusterConfig(host=server.url))
        clientset = Clientset(rest)
        mport = _free_port()
        operator = subprocess.Popen(
            [sys.executable, "-m", "k8s_tpu.cmd.operator",
             "--kubeconfig", self._kubeconfig(tmp_path, server.url),
             "--namespace", "default", "--threadiness", "1",
             "--metrics-port", str(mport), "--metrics-host", "127.0.0.1"],
            cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        kubelet = KubeletSimulator(clientset, "default").start()
        try:
            assert wait_until(
                lambda: self._has_v1_lock(clientset), timeout=30
            ), TestOperatorBinaryE2E._operator_tail(operator)
            # the metrics endpoint is live while leading
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{mport}/healthz", timeout=10) as r:
                assert r.status == 200
            component = core_component(
                {"name": "v1-rest-e2e", "num_workers": 1, "num_ps": 1},
                "v1alpha1")
            tf_job_client.create_tf_job(clientset, component, "v1alpha1")
            job = tf_job_client.wait_for_job(
                clientset, "default", "v1-rest-e2e", "v1alpha1", **FAST)
            assert job["status"]["phase"] == "Done", job["status"]
        finally:
            kubelet.stop()
            operator.terminate()
            try:
                operator.wait(10)
            except subprocess.TimeoutExpired:
                operator.kill()

    def test_chaos_flag_requires_explicit_interlock(self, server, tmp_path):
        env = {k: v for k, v in os.environ.items()
               if k != "K8S_TPU_ALLOW_CHAOS"}
        r = subprocess.run(
            [sys.executable, "-m", "k8s_tpu.cmd.operator",
             "--kubeconfig", self._kubeconfig(tmp_path, server.url),
             "--chaos-level", "2"],
            cwd=REPO, capture_output=True, text=True, timeout=60, env=env)
        assert r.returncode != 0
        assert "K8S_TPU_ALLOW_CHAOS" in r.stderr

    @staticmethod
    def _has_v1_lock(clientset) -> bool:
        try:
            return bool(clientset.endpoints("default").get("tf-operator"))
        except errors.ApiError:
            return False

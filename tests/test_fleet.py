"""Fleet telemetry plane tests (ISSUE 8): Prometheus text-format parser
(including the round-trip regression over every family util/metrics.py
exposes), scrape-target discovery from cached pod dicts, per-job
aggregation (rates/gauges/merged-histogram quantiles), multi-window SLO
burn-rate rules, /debug/fleet 404-when-inactive parity on both HTTP
servers, the /debug index, genjob --serve fleet discoverability, and
the --fleet bench at smoke scale."""

from __future__ import annotations

import json
import urllib.error
import urllib.request

import pytest

from k8s_tpu import fleet
from k8s_tpu.fleet.aggregate import (
    FleetAggregator,
    fraction_above,
    quantile_from_buckets,
)
from k8s_tpu.fleet.plane import FleetPlane
from k8s_tpu.fleet.slo import RuleError, SloEvaluator, parse_rules


def _get(url):
    try:
        with urllib.request.urlopen(url) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _target(job="ns/j1", pod="p0", index="0", url="http://x/0"):
    ns, _, name = job.partition("/")
    return fleet.ScrapeTarget(job, ns, name, pod, index, url)


# -- parser -------------------------------------------------------------------


class TestParser:
    def test_counters_gauges_labels_and_escapes(self):
        text = (
            "# HELP hits Total hits.\n"
            "# TYPE hits counter\n"
            'hits{job="ns/j1",outcome="ok"} 3\n'
            'hits{job="ns/j2",outcome="a\\"b\\\\c\\nd"} 1.5\n'
            "# TYPE temp gauge\n"
            "temp 2.25\n")
        fams = fleet.parse_exposition(text)
        assert fams["hits"].kind == "counter"
        assert fams["hits"].help == "Total hits."
        values = fams["hits"].values()
        assert values[(("job", "ns/j1"), ("outcome", "ok"))] == 3
        assert values[(("job", "ns/j2"),
                       ("outcome", 'a"b\\c\nd'))] == 1.5
        assert fams["temp"].values()[()] == 2.25

    def test_histogram_le_ordering_violation_rejected(self):
        text = (
            "# TYPE h histogram\n"
            'h_bucket{le="0.1"} 5\n'
            'h_bucket{le="0.5"} 3\n'   # cumulative counts DECREASE
            'h_bucket{le="+Inf"} 5\n'
            "h_sum 1\nh_count 5\n")
        with pytest.raises(fleet.ParseError, match="decrease"):
            fleet.parse_exposition(text)

    def test_histogram_missing_inf_rejected(self):
        text = ("# TYPE h histogram\n"
                'h_bucket{le="0.1"} 5\n'
                "h_sum 1\nh_count 5\n")
        with pytest.raises(fleet.ParseError, match=r"\+Inf"):
            fleet.parse_exposition(text)

    def test_histogram_inf_count_mismatch_rejected(self):
        text = ("# TYPE h histogram\n"
                'h_bucket{le="+Inf"} 4\n'
                "h_sum 1\nh_count 5\n")
        with pytest.raises(fleet.ParseError, match="_count"):
            fleet.parse_exposition(text)

    def test_histogram_samples_before_type_line_still_fold(self):
        """An exporter emitting bucket lines BEFORE its # TYPE line must
        not have its histogram silently dropped into untyped families —
        and the folded family still gets the +Inf validation."""
        text = ('h_bucket{le="0.1"} 3\n'
                'h_bucket{le="+Inf"} 5\n'
                "h_sum 1.5\n"
                "h_count 5\n"
                "# TYPE h histogram\n")
        fams = fleet.parse_exposition(text)
        assert set(fams) == {"h"}
        pts = fleet.histogram_points(fams["h"])
        assert pts[()]["count"] == 5 and pts[()]["buckets"][0] == (0.1, 3)
        # the validation applies to folded families too
        with pytest.raises(fleet.ParseError, match=r"\+Inf"):
            fleet.parse_exposition('h_bucket{le="0.1"} 3\n'
                                   "# TYPE h histogram\n")

    def test_sample_without_type_is_untyped(self):
        fams = fleet.parse_exposition("mystery 7\n")
        assert fams["mystery"].kind == "untyped"
        assert fams["mystery"].values()[()] == 7

    def test_malformed_lines_rejected(self):
        for bad in ("novalue\n", 'x{le="0.1" 3\n', "x nope\n",
                    'x{a="b"}\n'):  # labels but no value
            with pytest.raises(fleet.ParseError):
                fleet.parse_exposition(bad)

    def test_round_trip_every_util_metrics_family(self):
        """THE regression pin (ISSUE 8 satellite): every family
        util/metrics.py exposes — counter/gauge/histogram, including
        the Proxy families of the flight recorder AND the fleet plane
        itself — parses back losslessly through the fleet parser, and
        render() is a fixed point."""
        from k8s_tpu import flight
        from k8s_tpu.util import metrics as metrics_mod

        reg = metrics_mod.Registry()
        cm = metrics_mod.controller_metrics("v2", reg)
        cm["sync_duration"].labels("v2").observe(0.012)
        cm["sync_total"].labels("v2", "success").inc()
        cm["creates_total"].labels("v2", "pod", "success").inc(5)
        cm["workqueue_depth"].labels("v2").set(3)
        cm["admission_wait"].labels("v2").observe(42.0)
        sm = metrics_mod.serving_metrics(reg)
        sm["requests"].labels("ok").inc(7)
        sm["duration"].observe(0.3)
        sm["duration"].observe(7.5)  # lands in a high bucket
        sm["occupancy"].set(3.5)
        sm["queue_depth"].set(2)
        # ISSUE 12 per-request phase families (TTFT/TPOT split)
        sm["ttft"].observe(0.21)
        sm["tpot"].observe(0.008)
        sm["queue_wait"].observe(0.04)
        sm["step_duration"].observe(0.006)
        sm["prefill_convoy"].inc(2)
        # ISSUE 17 tiered-KV families (spill tier + migration dedup)
        sm["kv_spilled_blocks"].set(12)
        sm["kv_spill_bytes"].set(1 << 20)
        sm["kv_promotions"].inc(4)
        sm["kvxfer_dedup_skipped"].inc(9)
        flight.reset_all()
        metrics_mod.flight_metrics(reg)
        flight.ACCOUNTING.record("GET", "pods", 200, 0.004)
        flight.ACCOUNTING.record("LIST", "tfjobs", 200, 0.1)
        flight.WATCH.record_relist("pods", flight.RELIST_INITIAL)
        flight.EVENTS.record_recorded()
        metrics_mod.fleet_metrics(reg)
        plane = FleetPlane(
            lambda: [_target()],
            interval_s=0.5, windows=(1.0, 4.0),
            fetch=lambda url, t: ("# TYPE serve_tokens_total counter\n"
                                  "serve_tokens_total 5\n"))
        prev = fleet.active()
        fleet.set_active(plane)
        try:
            plane.scrape_once()
            text = reg.expose()
        finally:
            fleet.set_active(prev)
        fams = fleet.parse_exposition(text)
        # every family present with its declared kind, every sample line
        # accounted for (no drift between exposition and parser)
        sample_lines = [ln for ln in text.splitlines()
                        if ln and not ln.startswith("#")]
        assert sum(len(f.samples) for f in fams.values()) \
            == len(sample_lines)
        for expected in ("tfjob_sync_duration_seconds",
                         "serve_request_duration_seconds",
                         "apiserver_requests_total",
                         "apiserver_request_duration_seconds",
                         "watch_relists_total", "events_recorded_total",
                         "fleet_scrape_total",
                         "fleet_scrape_duration_seconds", "fleet_targets",
                         # ISSUE 12: the per-request phase families the
                         # serving pods export and the fleet plane
                         # merges/burn-rates
                         "serve_ttft_seconds", "serve_tpot_seconds",
                         "serve_queue_wait_seconds",
                         "serve_step_duration_seconds",
                         "serve_prefill_convoy_total",
                         # ISSUE 17: the tiered-KV families (spill tier
                         # occupancy, promotions, migration dedup)
                         "serve_kv_spilled_blocks",
                         "serve_kv_spill_bytes",
                         "serve_kv_promotions_total",
                         "serve_kvxfer_dedup_blocks_skipped_total"):
            assert expected in fams, f"family {expected} missing"
        assert fams["tfjob_sync_duration_seconds"].kind == "histogram"
        assert fams["fleet_scrape_total"].kind == "counter"
        assert fams["serve_ttft_seconds"].kind == "histogram"
        assert fams["serve_tpot_seconds"].kind == "histogram"
        assert fams["serve_prefill_convoy_total"].kind == "counter"
        assert fams["serve_kv_spilled_blocks"].kind == "gauge"
        assert fams["serve_kv_promotions_total"].kind == "counter"
        assert fams["serve_kvxfer_dedup_blocks_skipped_total"].kind \
            == "counter"
        assert fams["serve_kv_spilled_blocks"].values()[()] == 12
        # the TTFT histogram decomposes: the fleet plane's merged-bucket
        # quantiles (and serve_ttft_seconds:p99<… SLO rules) work on it
        assert fleet.histogram_points(
            fams["serve_ttft_seconds"])[()]["count"] == 1
        # histograms decompose cleanly (le ordering, +Inf == _count)
        pts = fleet.histogram_points(fams["serve_request_duration_seconds"])
        assert pts[()]["count"] == 2
        # render -> reparse is a fixed point (lossless round trip)
        fams2 = fleet.parse_exposition(fleet.render(fams))
        assert {n: f.samples for n, f in fams.items()} \
            == {n: f.samples for n, f in fams2.items()}
        assert {n: (f.kind, f.help) for n, f in fams.items()} \
            == {n: (f.kind, f.help) for n, f in fams2.items()}


# -- discovery ----------------------------------------------------------------


def _pod(name="p0", job="j1", ns="ns", phase="Running", port="9100",
         via_env=False, **meta_extra):
    meta = {
        "name": name, "namespace": ns,
        "labels": {"tf-replica-type": "worker", "tf-replica-index": "0",
                   "tf_job_key": f"{ns}-{job}"},
        "ownerReferences": [{"kind": "TFJob", "name": job,
                             "controller": True, "uid": "u1"}],
    }
    meta.update(meta_extra)
    pod = {"metadata": meta, "status": {"phase": phase}, "spec": {}}
    if port is not None:
        if via_env:
            pod["spec"]["containers"] = [
                {"name": "tensorflow",
                 "env": [{"name": "K8S_TPU_FLEET_SCRAPE_PORT",
                          "value": port}]}]
        else:
            meta.setdefault("annotations", {})[
                "kubeflow.org/fleet-scrape-port"] = port
    return pod


class TestDiscovery:
    def test_annotation_port_and_pod_ip(self):
        pod = _pod()
        pod["status"]["podIP"] = "10.0.0.7"
        [t] = fleet.targets_from_pods([pod])
        assert t.job == "ns/j1"
        assert t.url == "http://10.0.0.7:9100/metrics"
        assert t.index == "0"

    def test_env_port_fallback_and_dns_host(self):
        # no annotation, no podIP: port from the container env, host from
        # the per-index headless-service DNS name derived from labels
        [t] = fleet.targets_from_pods([_pod(via_env=True)])
        assert t.url == ("http://ns-j1-worker-0.ns.svc.cluster.local"
                         ":9100/metrics")

    def test_host_and_path_annotation_overrides(self):
        pod = _pod(port=None, annotations={
            "kubeflow.org/fleet-scrape-port": "9200",
            "kubeflow.org/fleet-scrape-host": "127.0.0.1",
            "kubeflow.org/fleet-scrape-path": "stats",
        })
        [t] = fleet.targets_from_pods([pod])
        assert t.url == "http://127.0.0.1:9200/stats"

    def test_serve_weight_annotation(self):
        """ISSUE 14: the fleet-serve-weight annotation rides discovery
        to the router's weighted ring; absent/garbage/non-positive all
        default to 1.0 rather than dropping the pod."""
        pod = _pod()
        pod["metadata"]["annotations"][
            "kubeflow.org/fleet-serve-weight"] = "4.0"
        pod["status"]["podIP"] = "10.0.0.7"
        [t] = fleet.targets_from_pods([pod])
        assert t.weight == 4.0
        for bad in ("chonky", "", "-2", "0"):
            pod = _pod()
            pod["metadata"]["annotations"][
                "kubeflow.org/fleet-serve-weight"] = bad
            pod["status"]["podIP"] = "10.0.0.7"
            [t] = fleet.targets_from_pods([pod])
            assert t.weight == 1.0, bad
        pod = _pod()
        pod["status"]["podIP"] = "10.0.0.7"
        [t] = fleet.targets_from_pods([pod])
        assert t.weight == 1.0

    def test_store_index_matches_discovery_predicate(self):
        """The informer's fleet-scrape index and discovery share one
        predicate: a pod is indexed iff it declares a scrape port."""
        from k8s_tpu.client.informer import (
            FLEET_SCRAPE_INDEX,
            FLEET_SCRAPE_KEY,
            Store,
            index_fleet_scrape_pods,
        )

        store = Store()
        store.add_index(FLEET_SCRAPE_INDEX, index_fleet_scrape_pods)
        annotated = _pod(name="annotated")
        via_env = _pod(name="via-env", via_env=True)
        plain = _pod(name="plain", port=None)
        for p in (annotated, via_env, plain):
            store.add(p)
        indexed = store.by_index(FLEET_SCRAPE_INDEX, FLEET_SCRAPE_KEY)
        assert sorted(p["metadata"]["name"] for p in indexed) \
            == ["annotated", "via-env"]
        # removing the port removes the pod from the index on update
        updated = _pod(name="annotated", port=None)
        store.add(updated)
        assert [p["metadata"]["name"]
                for p in store.by_index(FLEET_SCRAPE_INDEX,
                                        FLEET_SCRAPE_KEY)] == ["via-env"]

    def test_undiscoverable_pods_skipped(self):
        pods = [
            _pod(name="no-port", port=None),
            _pod(name="pending", phase="Pending"),
            _pod(name="deleting", deletionTimestamp="2026-01-01T00:00:00Z"),
            _pod(name="opted-out", annotations={
                "kubeflow.org/fleet-scrape-port": "9100",
                "kubeflow.org/fleet-scrape": "false"}),
            _pod(name="bad-port", port="70000"),
            _pod(name="garbage-port", port="nope"),
        ]
        orphan = _pod(name="orphan")
        orphan["metadata"]["ownerReferences"] = []
        orphan["status"]["podIP"] = "10.0.0.9"
        pods.append(orphan)
        assert fleet.targets_from_pods(pods) == []


# -- aggregation --------------------------------------------------------------


class TestAggregator:
    def _fam(self, text):
        return fleet.parse_exposition(text)

    def test_counter_rates_sum_across_pods(self):
        agg = FleetAggregator()
        for t in range(5):
            for pod, rate in (("p0", 10.0), ("p1", 30.0)):
                agg.ingest("ns/j", pod, self._fam(
                    "# TYPE serve_tokens_total counter\n"
                    f"serve_tokens_total {rate * t}\n"), float(t))
        assert agg.counter_rate("ns/j", "serve_tokens_total", 10.0, 4.0) \
            == pytest.approx(40.0)

    def test_counter_reset_does_not_go_negative(self):
        agg = FleetAggregator()
        for t, v in enumerate([100.0, 150.0, 5.0, 25.0]):  # restart at t=2
            agg.ingest("ns/j", "p0", self._fam(
                "# TYPE serve_tokens_total counter\n"
                f"serve_tokens_total {v}\n"), float(t))
        # deltas: 50 + (reset: 5) + 20 over 3s
        assert agg.counter_rate("ns/j", "serve_tokens_total", 10.0, 3.0) \
            == pytest.approx(75.0 / 3.0)

    def test_gauge_stats_and_windowed_max(self):
        agg = FleetAggregator()
        for t in range(4):
            for pod, v in (("p0", 2.0), ("p1", 6.0)):
                agg.ingest("ns/j", pod, self._fam(
                    "# TYPE serve_queue_depth gauge\n"
                    f"serve_queue_depth {v}\n"), float(t))
            agg.cycle_done(float(t), stale_after_s=10.0)
        stats = agg.gauge_stats("ns/j", "serve_queue_depth")
        assert stats["max"] == 6.0 and stats["mean"] == 4.0 \
            and stats["pods"] == 2
        assert agg.gauge_window_mean(
            "ns/j", "serve_queue_depth", 10.0, 3.0,
            of="max") == pytest.approx(6.0)
        assert agg.gauge_window_mean(
            "ns/j", "serve_queue_depth", 10.0, 3.0,
            of="mean") == pytest.approx(4.0)

    def test_pod_gauge_latest_round_trip(self):
        """ISSUE 13 drive-by pin: the per-POD rollup accessor the
        router's least-outstanding fallback tie-breaks on — per-target
        values survive the scrape -> parse -> ingest -> read round trip
        (the per-job merge must not erase pod identity), stale pods are
        pruned by cycle_done, and unknown jobs/families answer None."""
        agg = FleetAggregator()
        for t in range(3):
            for pod, v in (("p0", 7.0), ("p1", 1.0), ("p2", 3.0)):
                agg.ingest("ns/j", pod, self._fam(
                    "# TYPE serve_queue_depth gauge\n"
                    f"serve_queue_depth {v}\n"), float(t))
            agg.cycle_done(float(t), stale_after_s=10.0)
        assert agg.pod_gauge_latest("ns/j", "serve_queue_depth") \
            == {"p0": 7.0, "p1": 1.0, "p2": 3.0}
        assert agg.pod_gauge_latest("ns/other", "serve_queue_depth") \
            is None
        assert agg.pod_gauge_latest("ns/j", "serve_nope") is None
        # a scaled-down pod's reading is pruned with the gauge cycle
        for t in (20.0, 21.0):
            for pod, v in (("p0", 5.0), ("p1", 2.0)):  # p2 gone
                agg.ingest("ns/j", pod, self._fam(
                    "# TYPE serve_queue_depth gauge\n"
                    f"serve_queue_depth {v}\n"), t)
            agg.cycle_done(t, stale_after_s=10.0)
        assert agg.pod_gauge_latest("ns/j", "serve_queue_depth") \
            == {"p0": 5.0, "p1": 2.0}

    def test_histogram_merge_and_quantiles(self):
        agg = FleetAggregator()
        # two pods, identical distribution: 90% <= 0.1, 10% in (0.1, 1.0]
        for t in (0.0, 10.0):
            for pod in ("p0", "p1"):
                n = 100 * (t + 1)
                agg.ingest("ns/j", pod, self._fam(
                    "# TYPE serve_request_duration_seconds histogram\n"
                    f'serve_request_duration_seconds_bucket{{le="0.1"}} '
                    f"{0.9 * n}\n"
                    f'serve_request_duration_seconds_bucket{{le="1.0"}} '
                    f"{n}\n"
                    f'serve_request_duration_seconds_bucket{{le="+Inf"}} '
                    f"{n}\n"
                    f"serve_request_duration_seconds_sum {n}\n"
                    f"serve_request_duration_seconds_count {n}\n"), t)
        win = agg.histogram_window(
            "ns/j", "serve_request_duration_seconds", 20.0, 10.0)
        assert win["count"] == pytest.approx(2000.0)
        # p50 inside the first bucket, p99 interpolated inside (0.1, 1.0]
        assert agg.quantile("ns/j", "serve_request_duration_seconds",
                            0.99, 20.0, 10.0) \
            == pytest.approx(0.1 + 0.9 * 0.09 / 0.10)
        assert fraction_above(win["buckets"], 0.1) == pytest.approx(0.1)

    def test_fraction_above_counts_inf_tail_as_bad(self):
        """An SLO bound above the exporter's largest finite bucket must
        not neuter the rule: the +Inf tail counts as bad."""
        buckets = [(0.1, 90.0), (1.0, 95.0), (float("inf"), 100.0)]
        # conservative: the (0.1, 1.0] observations straddling 0.5 count
        # as good; only the 5% tail is provably above
        assert fraction_above(buckets, 0.5) == pytest.approx(0.05)
        # threshold past the top finite bound: only the tail can exceed
        # it, and it does — 5% of observations are unbounded
        assert fraction_above(buckets, 6.0) == pytest.approx(0.05)

    def test_quantile_helpers_edge_cases(self):
        assert quantile_from_buckets([], 0.99) is None
        assert quantile_from_buckets([(float("inf"), 0.0)], 0.5) is None
        # everything in +Inf: the estimate floors at the last finite bound
        assert quantile_from_buckets(
            [(0.5, 0.0), (float("inf"), 10.0)], 0.99) == 0.5

    def test_job_registry_lru_bound(self):
        agg = FleetAggregator(max_jobs=2)
        for job in ("ns/a", "ns/b", "ns/c"):
            agg.ingest(job, "p0", self._fam(
                "# TYPE serve_tokens_total counter\n"
                "serve_tokens_total 1\n"), 0.0)
        assert agg.jobs() == ["ns/b", "ns/c"]


# -- SLO rules ----------------------------------------------------------------


def _hist_text(fast, slow):
    total = fast + slow
    return ("# TYPE serve_request_duration_seconds histogram\n"
            f'serve_request_duration_seconds_bucket{{le="0.1"}} {fast}\n'
            f'serve_request_duration_seconds_bucket{{le="0.5"}} {fast}\n'
            f'serve_request_duration_seconds_bucket{{le="2.5"}} {total}\n'
            f'serve_request_duration_seconds_bucket{{le="+Inf"}} {total}\n'
            f"serve_request_duration_seconds_sum {total}\n"
            f"serve_request_duration_seconds_count {total}\n")


class TestSlo:
    def test_parse_rules(self):
        rules = parse_rules(
            "serve_request_duration_seconds:p99<0.5, serve_queue_depth"
            ":max<48")
        assert [r.name for r in rules] == [
            "serve_request_duration_seconds:p99<0.5",
            "serve_queue_depth:max<48"]
        assert rules[0].quantile == 0.99 and rules[1].quantile is None

    def test_bad_rules_rejected(self):
        for bad in ("nope", "f:p98<1", "f:p99<abc", "f:p99<0"):
            with pytest.raises(RuleError):
                parse_rules(bad)

    def test_breach_needs_both_windows(self):
        agg = FleetAggregator()
        ev = SloEvaluator(parse_rules(
            "serve_request_duration_seconds:p99<0.5"), agg,
            windows=(4.0, 16.0))
        # 20 cycles of healthy traffic, then 2 bad cycles: the short
        # window burns immediately; breach fires only once the long
        # window's bad fraction crosses the budget too
        transitions = []
        sink = (lambda job, rule, state, breached:
                transitions.append((breached, state["burn_short"])))
        t = 0.0
        for _ in range(20):
            agg.ingest("ns/j", "p0", fleet.parse_exposition(
                _hist_text(fast=100 * (t + 1), slow=0)), t)
            ev.evaluate(["ns/j"], t, sinks=(sink,))
            t += 1.0
        assert transitions == []  # healthy: no transition at all
        for _ in range(3):
            agg.ingest("ns/j", "p0", fleet.parse_exposition(
                _hist_text(fast=2100.0, slow=200.0 * (t - 19))), t)
            ev.evaluate(["ns/j"], t, sinks=(sink,))
            t += 1.0
        assert transitions and transitions[0][0] is True
        assert transitions[0][1] >= 1.0
        [state] = ev.state("ns/j")
        assert state["breached"] and state["burn_long"] >= 1.0
        assert ev.breached("ns/j")
        assert ev.breaches()[("ns/j",
                              "serve_request_duration_seconds:p99<0.5")] == 1

    def test_ttft_p99_rule_breaches_on_slow_first_tokens(self):
        """ISSUE 12: the worked `serve_ttft_seconds:p99<0.5` rule from
        docs/observability.md — the per-request TTFT histogram the
        serving engine now exports flows through the fleet plane into a
        burn-rate breach with zero new plumbing (the rule syntax gained
        the family for free because it is a plain histogram)."""
        def ttft_text(fast, slow):
            total = fast + slow
            return (
                "# TYPE serve_ttft_seconds histogram\n"
                f'serve_ttft_seconds_bucket{{le="0.1"}} {fast}\n'
                f'serve_ttft_seconds_bucket{{le="0.5"}} {fast}\n'
                f'serve_ttft_seconds_bucket{{le="2.5"}} {total}\n'
                f'serve_ttft_seconds_bucket{{le="+Inf"}} {total}\n'
                f"serve_ttft_seconds_sum {total}\n"
                f"serve_ttft_seconds_count {total}\n")

        agg = FleetAggregator()
        ev = SloEvaluator(parse_rules("serve_ttft_seconds:p99<0.5"),
                          agg, windows=(4.0, 16.0))
        transitions = []
        sink = (lambda job, rule, state, breached:
                transitions.append((breached, state["burn_short"])))
        t = 0.0
        for _ in range(20):  # healthy: every first token under 100ms
            agg.ingest("ns/serve", "p0", fleet.parse_exposition(
                ttft_text(fast=100 * (t + 1), slow=0)), t)
            ev.evaluate(["ns/serve"], t, sinks=(sink,))
            t += 1.0
        assert transitions == []
        for _ in range(3):  # a prefill convoy: 10%+ of TTFTs go slow
            agg.ingest("ns/serve", "p0", fleet.parse_exposition(
                ttft_text(fast=2100.0, slow=200.0 * (t - 19))), t)
            ev.evaluate(["ns/serve"], t, sinks=(sink,))
            t += 1.0
        assert transitions and transitions[0][0] is True
        assert transitions[0][1] >= 1.0  # burning >= the budget rate
        assert ev.breached("ns/serve")
        assert ev.breaches()[("ns/serve",
                              "serve_ttft_seconds:p99<0.5")] == 1

    def test_gauge_rule_and_recovery_transition(self):
        agg = FleetAggregator()
        ev = SloEvaluator(parse_rules("serve_queue_depth:max<10"), agg,
                          windows=(2.0, 8.0))
        transitions = []
        sink = (lambda job, rule, state, breached:
                transitions.append(breached))
        t = 0.0
        for depth in [25.0] * 10 + [1.0] * 12:
            agg.ingest("ns/j", "p0", fleet.parse_exposition(
                "# TYPE serve_queue_depth gauge\n"
                f"serve_queue_depth {depth}\n"), t)
            agg.cycle_done(t, stale_after_s=100.0)
            ev.evaluate(["ns/j"], t, sinks=(sink,))
            t += 1.0
        assert transitions == [True, False]  # breached, then recovered
        assert not ev.breached("ns/j")

    def test_mean_reducer_is_windowed_not_instantaneous(self):
        """A single-cycle spike in the fleet mean must not breach a
        mean rule: both windows read windowed history, so the long
        window genuinely resists the transient."""
        agg = FleetAggregator()
        ev = SloEvaluator(parse_rules("serve_queue_depth:mean<10"), agg,
                          windows=(2.0, 16.0))
        transitions = []
        sink = (lambda job, rule, state, breached:
                transitions.append(breached))
        t = 0.0
        # one-cycle spike: 10x the bound trips the SHORT window alone
        # (burn ~34/10), but diluted over the 16s window the mean stays
        # under the bound — multi-window resistance in action
        for depth in [1.0] * 16 + [100.0] + [1.0] * 4:
            agg.ingest("ns/j", "p0", fleet.parse_exposition(
                "# TYPE serve_queue_depth gauge\n"
                f"serve_queue_depth {depth}\n"), t)
            agg.cycle_done(t, stale_after_s=100.0)
            ev.evaluate(["ns/j"], t, sinks=(sink,))
            t += 1.0
        assert transitions == []  # the long window absorbed the spike

    def test_forget_drops_aggregator_rings_no_breach_refire(self):
        """plane.forget() clears the aggregation rings too: a deleted
        job must not be resurrected from stale samples on the next
        cycle and re-fire its breach sinks."""
        fired = []
        plane = FleetPlane(
            lambda: [], interval_s=0.5, windows=(1.0, 4.0),
            slo_rules="serve_queue_depth:max<1",
            fetch=lambda url, t: "")
        plane.add_sink(lambda job, rule, state, breached:
                       fired.append((job, breached)))
        import time as time_mod

        now = time_mod.time()
        for i in range(6):
            plane.aggregator.ingest("ns/dead", "p0", fleet.parse_exposition(
                "# TYPE serve_queue_depth gauge\nserve_queue_depth 9\n"),
                now - 6 + i)
            plane.aggregator.cycle_done(now - 6 + i, stale_after_s=100.0)
        plane.scrape_once()
        assert fired == [("ns/dead", True)]  # breached while alive
        plane.forget("ns/dead")
        assert "ns/dead" not in plane.aggregator.jobs()
        plane.scrape_once()
        plane.scrape_once()
        assert fired == [("ns/dead", True)]  # no resurrection, no re-fire

    def test_data_gap_holds_state_instead_of_recovering(self):
        """A scrape outage / ring eviction leaves NO samples in either
        window — that is a gap, not a recovery: the breached verdict
        holds and no spurious SloRecovered fires."""
        agg = FleetAggregator()
        ev = SloEvaluator(parse_rules("serve_queue_depth:max<1"), agg,
                          windows=(2.0, 8.0))
        transitions = []
        sink = (lambda job, rule, state, breached:
                transitions.append(breached))
        for t in range(10):
            agg.ingest("ns/j", "p0", fleet.parse_exposition(
                "# TYPE serve_queue_depth gauge\nserve_queue_depth 9\n"),
                float(t))
            agg.cycle_done(float(t), stale_after_s=100.0)
            ev.evaluate(["ns/j"], float(t), sinks=(sink,))
        assert transitions == [True]
        agg.forget("ns/j")  # all samples gone; the job itself persists
        ev.evaluate(["ns/j"], 11.0, sinks=(sink,))
        assert transitions == [True]  # no recovery fired
        assert ev.breached("ns/j")    # verdict held across the gap
        [state] = ev.state("ns/j")
        assert state["burn_short"] is None  # the gap itself is visible

    def test_partial_gap_holds_breach_too(self):
        """Short window empty while the long window still holds old
        samples (the mid-outage shape): a breached rule must NOT flip
        to recovered — only full two-window data can affirm recovery."""
        agg = FleetAggregator()
        ev = SloEvaluator(parse_rules("serve_queue_depth:max<1"), agg,
                          windows=(2.0, 60.0))
        transitions = []
        sink = (lambda job, rule, state, breached:
                transitions.append(breached))
        for t in range(10):
            agg.ingest("ns/j", "p0", fleet.parse_exposition(
                "# TYPE serve_queue_depth gauge\nserve_queue_depth 9\n"),
                float(t))
            agg.cycle_done(float(t), stale_after_s=1000.0)
            ev.evaluate(["ns/j"], float(t), sinks=(sink,))
        assert transitions == [True]
        # pods stop answering: evaluate 20s later — the 2s window is
        # empty, the 60s window still sees the old breaching samples
        ev.evaluate(["ns/j"], 29.0, sinks=(sink,))
        [state] = ev.state("ns/j")
        assert state["burn_short"] is None
        assert state["burn_long"] is not None
        assert transitions == [True] and ev.breached("ns/j")

    def test_vanished_jobs_pruned_from_rule_state(self):
        """Rule state for jobs absent from the evaluated set is pruned
        (bounded-everything: churn can't accumulate (job, rule) state)."""
        agg = FleetAggregator()
        ev = SloEvaluator(parse_rules("serve_queue_depth:max<1"), agg,
                          windows=(2.0, 8.0))
        for t in range(5):
            agg.ingest("ns/old", "p0", fleet.parse_exposition(
                "# TYPE serve_queue_depth gauge\nserve_queue_depth 9\n"),
                float(t))
            agg.cycle_done(float(t), stale_after_s=100.0)
            ev.evaluate(["ns/old"], float(t))
        assert ev.state("ns/old")
        ev.evaluate(["ns/new"], 6.0)  # old job gone from the set
        assert ev.state("ns/old") == [] and ev.breaches() == {}

    def test_forget_drops_rule_state(self):
        agg = FleetAggregator()
        ev = SloEvaluator(parse_rules("serve_queue_depth:max<1"), agg,
                          windows=(2.0, 8.0))
        for t in range(10):
            agg.ingest("ns/j", "p0", fleet.parse_exposition(
                "# TYPE serve_queue_depth gauge\nserve_queue_depth 9\n"),
                float(t))
            agg.cycle_done(float(t), stale_after_s=100.0)
            ev.evaluate(["ns/j"], float(t))
        assert ev.breached("ns/j")
        ev.forget("ns/j")
        assert not ev.breached("ns/j") and ev.state("ns/j") == []

    def test_broken_sink_does_not_stall_evaluation(self):
        agg = FleetAggregator()
        ev = SloEvaluator(parse_rules("serve_queue_depth:max<1"), agg,
                          windows=(2.0, 8.0))
        def boom(*a):
            raise RuntimeError("sink exploded")
        for t in range(10):
            agg.ingest("ns/j", "p0", fleet.parse_exposition(
                "# TYPE serve_queue_depth gauge\nserve_queue_depth 9\n"),
                float(t))
            agg.cycle_done(float(t), stale_after_s=100.0)
            ev.evaluate(["ns/j"], float(t), sinks=(boom,))
        assert ev.breached("ns/j")  # state advanced despite the sink


# -- plane (scrape loop + failure tracking + events ring) ---------------------


class TestPlane:
    def test_scrape_failures_tracked_never_raised(self):
        calls = {"n": 0}

        def fetch(url, timeout):
            calls["n"] += 1
            if url.endswith("/1"):
                raise OSError("connection refused")
            if url.endswith("/2"):
                return "# TYPE h histogram\nh_bucket{le=\"0.1\"} 3\n"  # no +Inf
            return "# TYPE serve_tokens_total counter\nserve_tokens_total 5\n"

        targets = [_target(pod=f"p{i}", index=str(i), url=f"http://x/{i}")
                   for i in range(3)]
        plane = FleetPlane(lambda: targets, interval_s=0.5,
                           windows=(1.0, 4.0), fetch=fetch)
        plane.scrape_once(now=1.0)
        counts = plane.stats.counts()
        assert counts[("ns/j1", "ok")] == 1
        assert counts[("ns/j1", "http_error")] == 1
        assert counts[("ns/j1", "parse_error")] == 1
        kinds = [e["kind"] for e in plane.events()]
        assert kinds.count("scrape_failure") == 2
        [t2] = [t for t in plane.stats.targets() if t["pod"] == "p1"]
        assert t2["consecutive_failures"] == 1
        assert "refused" in t2["last_error"]

    def test_url_override_rewrites_targets(self):
        seen = []

        def fetch(url, timeout):
            seen.append(url)
            return "# TYPE serve_tokens_total counter\nserve_tokens_total 1\n"

        plane = FleetPlane(lambda: [_target(url="http://dns:9100/metrics")],
                           interval_s=0.5, windows=(1.0, 4.0), fetch=fetch)
        plane.url_override = lambda t: "http://127.0.0.1:7/rewritten"
        plane.scrape_once()
        assert seen == ["http://127.0.0.1:7/rewritten"]

    def test_scrape_counters_lru_bounded_and_forgettable(self):
        """fleet_scrape_total cardinality is bounded under job churn:
        least-recently-scraped jobs evict past the cap, and a deleted
        job's counters drop via forget() (plane.forget forwards)."""
        from k8s_tpu.fleet.scrape import ScrapeStats

        stats = ScrapeStats(max_count_jobs=2)
        for job in ("ns/a", "ns/b", "ns/c"):
            stats.record(_target(job=job), "ok", 0.001)
        assert {j for j, _o in stats.counts()} == {"ns/b", "ns/c"}
        stats.forget("ns/c")
        assert {j for j, _o in stats.counts()} == {"ns/b"}

        def fetch(url, timeout):
            return "# TYPE serve_tokens_total counter\nserve_tokens_total 1\n"
        plane = FleetPlane(lambda: [_target()], interval_s=0.5,
                           windows=(1.0, 4.0), fetch=fetch)
        plane.scrape_once()
        assert ("ns/j1", "ok") in plane.stats.counts()
        plane.forget("ns/j1")
        assert plane.stats.counts() == {}

    def test_inflight_targets_not_resubmitted(self):
        """A target whose previous scrape is still in flight is skipped
        by the next cycle (a fleet-wide outage with every fetch riding
        its deadline cannot stack duplicate futures), and a completed
        scrape clears its in-flight mark."""
        started = []

        def fetch(url, timeout):
            started.append(url)
            return "# TYPE serve_tokens_total counter\nserve_tokens_total 1\n"

        target = _target()
        plane = FleetPlane(lambda: [target], interval_s=0.5,
                           windows=(1.0, 4.0), fetch=fetch)
        # simulate a still-running scrape from the previous cycle
        plane.loop._inflight.add(target.key())
        plane.scrape_once()
        assert started == []  # skipped, not double-fetched
        plane.loop._inflight.clear()
        plane.scrape_once()
        assert started == ["http://x/0"]
        # the completed scrape discarded its own in-flight mark
        assert plane.loop._inflight == set()
        plane.scrape_once()
        assert started == ["http://x/0"] * 2

    def test_events_since_contract(self):
        def fetch(url, timeout):
            raise OSError("down")
        plane = FleetPlane(lambda: [_target()], interval_s=0.5,
                           windows=(1.0, 4.0), fetch=fetch)
        plane.scrape_once()
        plane.scrape_once()
        events = plane.events()
        assert len(events) == 2
        seqs = [e["seq"] for e in events]
        assert seqs == sorted(seqs)
        assert plane.events(since=seqs[0]) == [events[1]]
        code, body, _ = fleet.debug_fleet_response(
            plane, f"job=ns/j1&since={seqs[-1]}")
        payload = json.loads(body)
        assert code == 200 and payload["events"] == []
        assert payload["last_seq"] == seqs[-1]  # echoed, not reset to 0


# -- /debug endpoints on both servers -----------------------------------------


class TestFleetEndpoint:
    def _plane_with_data(self):
        import time

        # real wall-clock timestamps: the debug responder's rollup reads
        # "now", so fake sample times would fall outside every window
        plane = FleetPlane(
            lambda: [_target()], interval_s=0.5, windows=(1.0, 4.0),
            fetch=lambda url, t: ("# TYPE serve_tokens_total counter\n"
                                  "serve_tokens_total 5\n"))
        plane.scrape_once()
        time.sleep(0.02)
        plane.scrape_once()
        return plane

    def test_metrics_server_404_when_inactive_then_serves(self):
        from k8s_tpu.util.metrics_server import MetricsServer

        prev = fleet.active()
        fleet.set_active(None)
        srv = MetricsServer(0).start()
        try:
            code, body = _get(f"http://127.0.0.1:{srv.port}/debug/fleet")
            assert code == 404 and "inactive" in body
            plane = self._plane_with_data()
            fleet.set_active(plane)
            code, body = _get(f"http://127.0.0.1:{srv.port}/debug/fleet")
            assert code == 200
            summary = json.loads(body)
            assert summary["jobs"]["ns/j1"]["targets"] == 1
            code, body = _get(
                f"http://127.0.0.1:{srv.port}/debug/fleet?job=ns/j1")
            payload = json.loads(body)
            assert payload["job"] == "ns/j1"
            assert "serve_tokens_total" in payload["rollup"]["counters"]
            assert [t["pod"] for t in payload["targets"]] == ["p0"]
        finally:
            srv.stop()
            fleet.set_active(prev)

    def test_dashboard_serves_same_responder(self):
        from k8s_tpu.client.clientset import Clientset
        from k8s_tpu.client.fake import FakeCluster
        from k8s_tpu.dashboard.backend import DashboardServer

        prev = fleet.active()
        fleet.set_active(None)
        server = DashboardServer(Clientset(FakeCluster()),
                                 host="127.0.0.1", port=0)
        server.start_background()
        try:
            code, body = _get(f"http://127.0.0.1:{server.port}/debug/fleet")
            assert code == 404 and "inactive" in body
            fleet.set_active(self._plane_with_data())
            code, body = _get(f"http://127.0.0.1:{server.port}/debug/fleet")
            assert code == 200
            assert json.loads(body)["jobs"]["ns/j1"]["targets"] == 1
        finally:
            server.shutdown()
            fleet.set_active(prev)

    def test_debug_index_on_both_servers(self):
        """The /debug index satellite: both processes list the live
        debug endpoints with active/inactive state."""
        from k8s_tpu.client.clientset import Clientset
        from k8s_tpu.client.fake import FakeCluster
        from k8s_tpu.dashboard.backend import DashboardServer
        from k8s_tpu.util.metrics_server import MetricsServer

        prev = fleet.active()
        fleet.set_active(None)
        srv = MetricsServer(0).start()
        dash = DashboardServer(Clientset(FakeCluster()),
                               host="127.0.0.1", port=0)
        dash.start_background()
        try:
            for base in (f"http://127.0.0.1:{srv.port}",
                         f"http://127.0.0.1:{dash.port}"):
                for path in ("/debug", "/debug/"):
                    code, body = _get(base + path)
                    assert code == 200, (base, path)
                    endpoints = {e["path"]: e
                                 for e in json.loads(body)["endpoints"]}
                    assert set(endpoints) == {
                        "/debug/traces", "/debug/scheduler",
                        "/debug/timeline", "/debug/fleet",
                        "/debug/compiles", "/debug/requests",
                        "/debug/engine", "/debug/router"}
                    assert endpoints["/debug/fleet"]["active"] is False
                    for e in endpoints.values():
                        assert "activation" in e and "params" in e
            fleet.set_active(self._plane_with_data())
            code, body = _get(f"http://127.0.0.1:{srv.port}/debug/")
            endpoints = {e["path"]: e
                         for e in json.loads(body)["endpoints"]}
            assert endpoints["/debug/fleet"]["active"] is True
        finally:
            srv.stop()
            dash.shutdown()
            fleet.set_active(prev)

    def test_fleet_families_in_metrics_exposition(self):
        from k8s_tpu.util import metrics as metrics_mod

        reg = metrics_mod.Registry()
        metrics_mod.fleet_metrics(reg)
        prev = fleet.active()
        fleet.set_active(self._plane_with_data())
        try:
            text = reg.expose()
        finally:
            fleet.set_active(prev)
        assert ('fleet_scrape_total{job="ns/j1",outcome="ok"} 2'
                in text)
        assert 'fleet_targets{job="ns/j1"} 1' in text
        assert "fleet_scrape_duration_seconds_count 2" in text
        # and the exposition itself round-trips through the parser
        fams = fleet.parse_exposition(text)
        assert fams["fleet_scrape_duration_seconds"].kind == "histogram"


# -- genjob --serve fleet discoverability (satellite) -------------------------


class TestGenjobFleetDiscovery:
    def test_serve_job_is_fleet_discoverable_by_default(self):
        from k8s_tpu.api import manifest
        from k8s_tpu.cmd import genjob

        [job] = genjob.generate(1, serve=True, timestamp=7)
        template = job["spec"]["tfReplicaSpecs"]["Worker"]["template"]
        assert template["metadata"]["annotations"][
            "kubeflow.org/fleet-scrape-port"] == "8000"
        c = template["spec"]["containers"][0]
        env = {e["name"]: e["value"] for e in c["env"]}
        assert env["K8S_TPU_FLEET_SCRAPE_PORT"] == "8000"
        assert "K8S_TPU_FLEET_INTERVAL_S" not in env
        manifest.load_tfjob(job)  # defaults+validates as v1alpha2
        # and discovery actually picks the shape up once it's a Running
        # pod (annotations travel template -> pod via the pod template)
        pod = {"metadata": {
            "name": "p0", "namespace": "default",
            "annotations": dict(template["metadata"]["annotations"]),
            "labels": {"tf-replica-type": "worker",
                       "tf-replica-index": "0",
                       "tf_job_key": "default-tfjob-7-0"},
            "ownerReferences": [{"kind": "TFJob", "name": "tfjob-7-0",
                                 "controller": True}]},
            "status": {"phase": "Running", "podIP": "10.1.2.3"},
            "spec": {}}
        [t] = fleet.targets_from_pods([pod])
        assert t.url == "http://10.1.2.3:8000/metrics"

    def test_serve_job_fleet_knobs(self):
        from k8s_tpu.cmd import genjob

        [job] = genjob.generate(1, serve=True, timestamp=8,
                                fleet_scrape_port=9999,
                                fleet_interval_s=5.0)
        template = job["spec"]["tfReplicaSpecs"]["Worker"]["template"]
        assert template["metadata"]["annotations"][
            "kubeflow.org/fleet-scrape-port"] == "9999"
        env = {e["name"]: e["value"]
               for e in template["spec"]["containers"][0]["env"]}
        assert env["K8S_TPU_FLEET_SCRAPE_PORT"] == "9999"
        assert env["K8S_TPU_FLEET_INTERVAL_S"] == "5.0"

    def test_serve_job_fleet_opt_out(self):
        from k8s_tpu.cmd import genjob

        [job] = genjob.generate(1, serve=True, timestamp=9,
                                fleet_scrape_port=None)
        template = job["spec"]["tfReplicaSpecs"]["Worker"]["template"]
        assert "metadata" not in template
        env = {e["name"] for e in template["spec"]["containers"][0]["env"]}
        assert "K8S_TPU_FLEET_SCRAPE_PORT" not in env


# -- env knobs ----------------------------------------------------------------


class TestEnvKnobs:
    def test_windows_from_env(self, monkeypatch):
        monkeypatch.setenv("K8S_TPU_FLEET_WINDOWS", "5, 60")
        assert fleet.windows_from_env() == (5.0, 60.0)
        for bad in ("garbage", "60,5", "5", "5,abc", ""):
            monkeypatch.setenv("K8S_TPU_FLEET_WINDOWS", bad)
            assert fleet.windows_from_env() == fleet.DEFAULT_WINDOWS

    def test_scrape_enable_and_sizes(self, monkeypatch):
        monkeypatch.delenv("K8S_TPU_FLEET_SCRAPE", raising=False)
        assert not fleet.scrape_enabled_from_env()
        monkeypatch.setenv("K8S_TPU_FLEET_SCRAPE", "1")
        assert fleet.scrape_enabled_from_env()
        monkeypatch.setenv("K8S_TPU_FLEET_INTERVAL_S", "0.5")
        assert fleet.interval_from_env() == 0.5
        monkeypatch.setenv("K8S_TPU_FLEET_INTERVAL_S", "-3")
        assert fleet.interval_from_env() == fleet.DEFAULT_INTERVAL_S


# -- the --fleet bench at smoke scale -----------------------------------------


class TestFleetBenchSmoke:
    def test_embedded_assertions_pass_at_smoke_scale(self):
        """The acceptance loop end to end, CI-sized: real controller +
        informers + kubelet simulator, fake serving pods behind loopback
        HTTP, aggregation/quantile truth, the zero-apiserver-call steady
        window, and breach-within-two-intervals — at 8 pods instead of
        the bench_smoke tier's 32."""
        from k8s_tpu.harness.bench_operator import bench_fleet

        r = bench_fleet(pods=8, jobs=2, interval_s=0.2, steady_cycles=4,
                        timeout_s=60.0)
        assert r["steady_apiserver_calls"] == 0
        assert r["breach_timeline_ok"] and r["breach_event_ok"]
        assert r["breach_detect_latency_s"] <= r["breach_budget_s"]
        for check in r["rates"].values():
            assert check["measured"] == pytest.approx(check["truth"],
                                                      rel=0.10)
        for p99 in r["fleet_p99"].values():
            assert p99 == pytest.approx(r["p99_reference"], abs=0.02)

    def test_failed_assertions_still_write_the_artifact(self, tmp_path,
                                                        monkeypatch):
        """A fleet regression in the non-gating tier must leave the
        numbers behind (the bench_churn.json contract)."""
        import argparse

        from k8s_tpu.harness import bench_operator

        # poison the quantile reference so the p99 assertion fails while
        # everything else still runs to completion
        monkeypatch.setattr(bench_operator._FleetPodStubs, "TRUE_P99", 9.9)
        out = tmp_path / "bench_fleet.json"
        args = argparse.Namespace(
            fleet_pods=4, fleet_jobs=2, fleet_interval=0.2,
            fleet_steady_cycles=2, fleet_out=str(out), timeout=60.0)
        with pytest.raises(RuntimeError, match="fleet bench assertions"):
            bench_operator.run_fleet(args)
        payload = json.loads(out.read_text())
        assert payload["failures"]
        assert any("p99" in f for f in payload["failures"])
        assert payload["pods"] == 4

"""Parallel layer tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from k8s_tpu.parallel import MeshConfig, make_mesh
from k8s_tpu.parallel import collectives, sharding
from k8s_tpu.parallel.mesh import (
    DcnConfig,
    chips_in_topology,
    device_slice_groups,
    make_hybrid_mesh,
    parse_topology,
)
from k8s_tpu.parallel.ring_attention import (
    reference_attention,
    ring_attention,
)


def test_eight_devices_available():
    assert len(jax.devices()) == 8


class TestMesh:
    def test_auto_config(self):
        cfg = MeshConfig.auto(8, tp=2)
        assert cfg.num_devices == 8
        assert cfg.tp == 2 and cfg.fsdp == 4 and cfg.dp == 1

    def test_auto_rejects_indivisible(self):
        with pytest.raises(ValueError):
            MeshConfig.auto(8, tp=3)

    def test_make_mesh_axes(self):
        mesh = make_mesh(MeshConfig(dp=2, fsdp=2, sp=1, tp=2))
        assert dict(mesh.shape) == {"dp": 2, "pp": 1, "fsdp": 2, "ep": 1,
                                    "sp": 1, "tp": 2}

    def test_make_mesh_six_axes(self):
        mesh = make_mesh(MeshConfig(pp=2, ep=2, tp=2))
        assert dict(mesh.shape) == {"dp": 1, "pp": 2, "fsdp": 1, "ep": 2,
                                    "sp": 1, "tp": 2}

    def test_auto_six_axes(self):
        cfg = MeshConfig.auto(8, tp=2, pp=2)
        assert cfg.pp == 2 and cfg.tp == 2 and cfg.fsdp == 2
        assert cfg.num_devices == 8

    def test_hybrid_mesh_slice_boundary_is_outer_stride(self):
        """2 slices x 4 devices, dp across DCN, fsdp*tp within ICI: each
        dp block must contain exactly one slice's devices."""
        devices = jax.devices()
        mesh = make_hybrid_mesh(
            MeshConfig(fsdp=2, tp=2), DcnConfig(dp=2), devices)
        assert dict(mesh.shape) == {
            "dp": 2, "pp": 1, "fsdp": 2, "ep": 1, "sp": 1, "tp": 2,
        }
        arr = mesh.devices
        slice0 = set(devices[:4])  # contiguous chunks = virtual slices
        dp0 = set(arr[0].flatten())
        dp1 = set(arr[1].flatten())
        assert dp0 == slice0
        assert dp1 == set(devices[4:])

    def test_hybrid_mesh_combines_same_axis(self):
        """DCN fsdp=2 x ICI fsdp=2 -> one fsdp axis of 4 with slice
        boundary outermost: positions [i, :2] all from one slice."""
        devices = jax.devices()
        mesh = make_hybrid_mesh(
            MeshConfig(fsdp=2, tp=2), DcnConfig(fsdp=2), devices)
        assert mesh.shape["fsdp"] == 4 and mesh.shape["tp"] == 2
        arr = mesh.devices  # [dp=1, pp=1, fsdp=4, ep=1, sp=1, tp=2]
        fsdp_axis = arr.reshape(4, 2)
        assert set(fsdp_axis[:2].flatten()) == set(devices[:4])
        assert set(fsdp_axis[2:].flatten()) == set(devices[4:])

    def test_hybrid_mesh_runs_sharded_step(self):
        """A psum-bearing computation executes over the hybrid mesh."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = make_hybrid_mesh(
            MeshConfig(fsdp=2, tp=2), DcnConfig(dp=2), jax.devices())
        x = jnp.arange(16.0).reshape(8, 2)
        x = jax.device_put(x, NamedSharding(mesh, P(("dp", "fsdp"))))
        total = jax.jit(
            lambda x: jnp.sum(x),
            out_shardings=NamedSharding(mesh, P()),
        )(x)
        assert float(total) == float(np.arange(16.0).sum())

    def test_hybrid_mesh_validates_device_count(self):
        with pytest.raises(ValueError, match="hybrid mesh needs"):
            make_hybrid_mesh(
                MeshConfig(fsdp=2), DcnConfig(dp=2), jax.devices())

    def test_device_slice_groups_chunks_evenly(self):
        groups = device_slice_groups(jax.devices(), 4)
        assert [len(g) for g in groups] == [2, 2, 2, 2]
        with pytest.raises(ValueError, match="not divisible"):
            device_slice_groups(jax.devices(), 3)

    def test_topology_parsing(self):
        assert parse_topology("4x4") == (4, 4)
        assert chips_in_topology("2x2x4") == 16
        with pytest.raises(ValueError):
            parse_topology("4xx")


class TestSharding:
    def test_logical_to_spec_tp_and_fsdp(self):
        spec = sharding.logical_to_spec(("mlp", "embed"))
        # mlp -> tp; embed (unassigned) picks up fsdp
        assert spec == P("tp", "fsdp")

    def test_bias_replicated(self):
        spec = sharding.logical_to_spec((None,))
        assert spec == P(None)

    def test_fsdp_sharding_tree(self):
        mesh = make_mesh(MeshConfig(fsdp=8))
        params = {
            "w": jnp.zeros((16, 64)),
            "b": jnp.zeros((64,)),
            "odd": jnp.zeros((3, 5)),  # not divisible by 8 -> replicated
        }
        shardings = sharding.fsdp_sharding(params, mesh)
        assert shardings["w"].spec == P(None, "fsdp")
        assert shardings["b"].spec == P()
        assert shardings["odd"].spec == P()
        sharded = sharding.apply_shardings(params, shardings)
        assert sharded["w"].sharding.spec == P(None, "fsdp")


class TestCollectives:
    def test_ring_shift_under_shard_map(self):
        from functools import partial

        from jax import lax, shard_map

        mesh = make_mesh(MeshConfig(sp=8))

        @partial(
            shard_map,
            mesh=mesh,
            in_specs=P("sp"),
            out_specs=(P("sp"), P("sp"), P("sp")),
            check_vma=False,
        )
        def f(x):
            total = lax.psum(jnp.sum(x), "sp")
            down = collectives.ring_shift(x, "sp")
            up = collectives.ring_shift(x, "sp", reverse=True)
            return jnp.broadcast_to(total, x.shape), down, up

        x = jnp.arange(8.0)
        total, down, up = f(x)
        assert np.allclose(total, 28.0)
        assert np.allclose(down, np.roll(np.arange(8.0), 1))
        assert np.allclose(up, np.roll(np.arange(8.0), -1))

    def test_ring_all_gather_matches_lax(self):
        from functools import partial

        from jax import lax, shard_map

        mesh = make_mesh(MeshConfig(sp=8))

        @partial(shard_map, mesh=mesh, in_specs=P("sp"),
                 out_specs=(P("sp", None), P("sp", None)), check_vma=False)
        def gather(x):
            ours = collectives.ring_all_gather(x, "sp")
            ref = lax.all_gather(x, "sp")
            return ours, ref

        x = jax.random.normal(jax.random.PRNGKey(0), (16, 4))
        ours, ref = gather(x)
        np.testing.assert_allclose(np.asarray(ours), np.asarray(ref),
                                   atol=1e-6)

    def test_ring_reduce_scatter_matches_psum_scatter(self):
        from functools import partial

        from jax import lax, shard_map

        mesh = make_mesh(MeshConfig(sp=8))

        @partial(shard_map, mesh=mesh, in_specs=P(None, "sp"),
                 out_specs=(P("sp"), P("sp")), check_vma=False)
        def rs(x):
            # x local: [n, chunk] — one chunk addressed to each rank
            ours = collectives.ring_reduce_scatter(x, "sp")
            ref = lax.psum_scatter(x, "sp", scatter_dimension=0,
                                   tiled=False)
            return ours[None], ref[None]

        x = jax.random.normal(jax.random.PRNGKey(1), (8, 8 * 4))
        ours, ref = rs(x)
        np.testing.assert_allclose(np.asarray(ours), np.asarray(ref),
                                   atol=1e-5)

    def test_collective_matmul_matches_dense(self):
        from functools import partial

        from jax import shard_map

        mesh = make_mesh(MeshConfig(sp=8))
        x = jax.random.normal(jax.random.PRNGKey(2), (32, 12))
        w = jax.random.normal(jax.random.PRNGKey(3), (12, 6))

        @partial(shard_map, mesh=mesh, in_specs=(P("sp"), P(None, None)),
                 out_specs=P(None, None), check_vma=False)
        def mm(x_shard, w):
            return collectives.collective_matmul(x_shard, w, "sp")

        np.testing.assert_allclose(np.asarray(mm(x, w)), np.asarray(x @ w),
                                   atol=1e-5)

    def test_collective_matmul_is_differentiable(self):
        from functools import partial

        from jax import shard_map

        mesh = make_mesh(MeshConfig(sp=8))
        x = jax.random.normal(jax.random.PRNGKey(4), (16, 8))
        w = jax.random.normal(jax.random.PRNGKey(5), (8, 4))

        @partial(shard_map, mesh=mesh, in_specs=(P("sp"), P(None, None)),
                 out_specs=P(None, None), check_vma=False)
        def mm(x_shard, w):
            return collectives.collective_matmul(x_shard, w, "sp")

        g_ours = jax.grad(lambda w: jnp.sum(mm(x, w) ** 2))(w)
        g_ref = jax.grad(lambda w: jnp.sum((x @ w) ** 2))(w)
        np.testing.assert_allclose(np.asarray(g_ours), np.asarray(g_ref),
                                   atol=1e-4)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        mesh = make_mesh(MeshConfig(sp=8))
        B, L, H, D = 2, 64, 4, 16
        key = jax.random.PRNGKey(0)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (B, L, H, D), jnp.float32)
        k = jax.random.normal(kk, (B, L, H, D), jnp.float32)
        v = jax.random.normal(kv, (B, L, H, D), jnp.float32)

        expected = reference_attention(q, k, v, causal=causal)
        got = ring_attention(mesh, q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)

    def test_with_tp_and_batch_axes(self):
        mesh = make_mesh(MeshConfig(dp=2, sp=2, tp=2))
        B, L, H, D = 4, 32, 4, 8
        key = jax.random.PRNGKey(1)
        q, k, v = (
            jax.random.normal(s, (B, L, H, D), jnp.float32)
            for s in jax.random.split(key, 3)
        )
        expected = reference_attention(q, k, v, causal=True)
        got = ring_attention(mesh, q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)

    def test_jit_compiles_once(self):
        mesh = make_mesh(MeshConfig(sp=8))
        B, L, H, D = 1, 32, 2, 8

        @jax.jit
        def fn(q, k, v):
            return ring_attention(mesh, q, k, v, causal=True)

        q = jnp.ones((B, L, H, D))
        out = fn(q, q, q)
        assert out.shape == (B, L, H, D)
        assert not bool(jnp.any(jnp.isnan(out)))


class TestRingFlashAttention:
    """Ring + Pallas-flash composition (parallel.ring_flash): exact vs the
    O(L²) reference for values AND all three gradients — the backward is a
    hand-built second ring pass, so it gets its own grad coverage."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        from k8s_tpu.parallel.ring_flash import ring_flash_attention

        mesh = make_mesh(MeshConfig(sp=4, dp=2))
        B, L, H, D = 2, 128, 2, 32
        q, k, v = (
            jax.random.normal(s, (B, L, H, D), jnp.float32) * 0.5
            for s in jax.random.split(jax.random.PRNGKey(0), 3)
        )
        expected = reference_attention(q, k, v, causal=causal)
        got = ring_flash_attention(mesh, q, k, v, causal=causal,
                                   block_q=16, block_k=16)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_gradients_match_reference(self, causal):
        from k8s_tpu.parallel.ring_flash import ring_flash_attention

        mesh = make_mesh(MeshConfig(sp=4, dp=2))
        B, L, H, D = 2, 64, 2, 16
        q, k, v = (
            jax.random.normal(s, (B, L, H, D), jnp.float32) * 0.5
            for s in jax.random.split(jax.random.PRNGKey(1), 3)
        )

        def loss_ring(q, k, v):
            out = ring_flash_attention(mesh, q, k, v, causal=causal,
                                       block_q=16, block_k=16)
            return jnp.sum(jnp.sin(out))

        def loss_ref(q, k, v):
            return jnp.sum(jnp.sin(reference_attention(q, k, v, causal=causal)))

        got = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       atol=5e-5)

    def test_non_dividing_heads_rejected(self):
        from k8s_tpu.parallel.ring_flash import ring_flash_attention_local

        with pytest.raises(ValueError, match="Hkv dividing H"):
            ring_flash_attention_local(
                jnp.ones((1, 8, 4, 8)), jnp.ones((1, 8, 3, 8)),
                jnp.ones((1, 8, 3, 8)))

    def test_transformer_ring_flash_path(self):
        """use_ring_attention + use_flash_attention composes in the model."""
        from k8s_tpu.models.transformer import Transformer, TransformerConfig

        mesh = make_mesh(MeshConfig(sp=4, dp=2))
        cfg_rf = TransformerConfig(
            vocab_size=64, hidden=32, ffn_hidden=64, layers=1, heads=2,
            kv_heads=2, max_seq_len=64, dtype=jnp.float32, remat=False,
            use_ring_attention=True, use_flash_attention=True,
            flash_block_q=16, flash_block_k=16)
        cfg_plain = TransformerConfig(
            vocab_size=64, hidden=32, ffn_hidden=64, layers=1, heads=2,
            kv_heads=2, max_seq_len=64, dtype=jnp.float32, remat=False)
        toks = jax.random.randint(jax.random.PRNGKey(0), (2, 64), 0, 64)
        m_rf = Transformer(cfg_rf)
        m_plain = Transformer(cfg_plain)
        params = m_plain.init(jax.random.PRNGKey(1), toks)
        out_rf = m_rf.apply(params, toks, mesh=mesh)
        out_plain = m_plain.apply(params, toks)
        np.testing.assert_allclose(np.asarray(out_rf), np.asarray(out_plain),
                                   atol=2e-4)


class TestUlyssesAttention:
    """All-to-all sequence parallelism (parallel.ulysses): exact vs the
    O(L²) reference, plain and flash, values and gradients."""

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("use_flash", [False, True])
    def test_matches_reference(self, causal, use_flash):
        from k8s_tpu.parallel.ulysses import ulysses_attention

        mesh = make_mesh(MeshConfig(sp=4, dp=2))
        B, L, H, D = 2, 128, 4, 16
        q, k, v = (
            jax.random.normal(s, (B, L, H, D), jnp.float32) * 0.5
            for s in jax.random.split(jax.random.PRNGKey(0), 3)
        )
        got = ulysses_attention(mesh, q, k, v, causal=causal,
                                use_flash=use_flash, block_q=16, block_k=16)
        want = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5)

    def test_gradients_match_reference(self):
        from k8s_tpu.parallel.ulysses import ulysses_attention

        mesh = make_mesh(MeshConfig(sp=4, dp=2))
        B, L, H, D = 2, 64, 4, 16
        q, k, v = (
            jax.random.normal(s, (B, L, H, D), jnp.float32) * 0.5
            for s in jax.random.split(jax.random.PRNGKey(1), 3)
        )

        def loss_u(q, k, v):
            return jnp.sum(jnp.sin(ulysses_attention(
                mesh, q, k, v, causal=True, use_flash=True,
                block_q=16, block_k=16)))

        def loss_r(q, k, v):
            return jnp.sum(jnp.sin(reference_attention(q, k, v, causal=True)))

        got = jax.grad(loss_u, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       atol=5e-5)

    def test_head_divisibility_required(self):
        from k8s_tpu.parallel.ulysses import ulysses_attention

        mesh = make_mesh(MeshConfig(sp=4, dp=2))
        q = jnp.ones((2, 64, 2, 8))  # 2 heads, sp=4 -> indivisible
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention(mesh, q, q, q)

    def test_transformer_ulysses_path(self):
        from k8s_tpu.models.transformer import Transformer, TransformerConfig

        mesh = make_mesh(MeshConfig(sp=4, dp=2))
        cfg_u = TransformerConfig(
            vocab_size=64, hidden=32, ffn_hidden=64, layers=1, heads=4,
            kv_heads=4, max_seq_len=64, dtype=jnp.float32, remat=False,
            use_ring_attention=True, sp_strategy="ulysses")
        cfg_plain = TransformerConfig(
            vocab_size=64, hidden=32, ffn_hidden=64, layers=1, heads=4,
            kv_heads=4, max_seq_len=64, dtype=jnp.float32, remat=False)
        toks = jax.random.randint(jax.random.PRNGKey(0), (2, 64), 0, 64)
        params = Transformer(cfg_plain).init(jax.random.PRNGKey(1), toks)
        out_u = Transformer(cfg_u).apply(params, toks, mesh=mesh)
        out_plain = Transformer(cfg_plain).apply(params, toks)
        np.testing.assert_allclose(np.asarray(out_u), np.asarray(out_plain),
                                   atol=2e-4)


class TestFsdpDivisibility:
    def test_logical_to_spec_prefers_largest_divisible_dim(self):
        from jax.sharding import PartitionSpec as P

        from k8s_tpu.parallel.sharding import logical_to_spec

        # largest dim (10) not divisible by fsdp=4 -> shard dim 0 (8)
        spec = logical_to_spec(
            ("a", "b"), rules={"a": None, "b": None},
            shape=(8, 10), fsdp_size=4,
        )
        assert spec == P("fsdp", None)
        # nothing divisible -> replicate rather than crash
        spec = logical_to_spec(
            ("a", "b"), rules={"a": None, "b": None},
            shape=(6, 10), fsdp_size=4,
        )
        assert spec == P(None, None)
        # no shape -> legacy first-candidate behavior
        spec = logical_to_spec(("a", "b"), rules={"a": None, "b": None})
        assert spec == P("fsdp", None)


class TestZigzagRingFlash:
    """Load-balanced (zigzag) causal ring flash: rank r owns global blocks
    (r, 2sp-1-r), so every ring step costs every rank one chunk-equivalent
    of flash work instead of the contiguous layout's all-or-nothing column.
    External layout stays contiguous; exactness vs the O(L^2) reference is
    the whole contract."""

    def test_values_match_reference(self):
        from k8s_tpu.parallel.ring_flash import ring_flash_attention

        mesh = make_mesh(MeshConfig(sp=4, dp=2))
        B, L, H, D = 2, 128, 2, 32
        q, k, v = (
            jax.random.normal(s, (B, L, H, D), jnp.float32) * 0.5
            for s in jax.random.split(jax.random.PRNGKey(7), 3)
        )
        expected = reference_attention(q, k, v, causal=True)
        got = ring_flash_attention(mesh, q, k, v, causal=True,
                                   block_q=16, block_k=16, layout="zigzag")
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   atol=2e-5)

    def test_gradients_match_reference(self):
        from k8s_tpu.parallel.ring_flash import ring_flash_attention

        mesh = make_mesh(MeshConfig(sp=4, dp=2))
        B, L, H, D = 2, 64, 2, 16
        q, k, v = (
            jax.random.normal(s, (B, L, H, D), jnp.float32) * 0.5
            for s in jax.random.split(jax.random.PRNGKey(8), 3)
        )

        def loss_zz(q, k, v):
            out = ring_flash_attention(mesh, q, k, v, causal=True,
                                       block_q=16, block_k=16,
                                       layout="zigzag")
            return jnp.sum(jnp.sin(out))

        def loss_ref(q, k, v):
            return jnp.sum(jnp.sin(reference_attention(q, k, v, causal=True)))

        got = jax.grad(loss_zz, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       atol=5e-5)

    def test_zigzag_equals_contiguous(self):
        """Same math, different placement: the two layouts must agree to
        numerical noise on identical inputs."""
        from k8s_tpu.parallel.ring_flash import ring_flash_attention

        mesh = make_mesh(MeshConfig(sp=8))
        B, L, H, D = 1, 128, 2, 16
        q, k, v = (
            jax.random.normal(s, (B, L, H, D), jnp.float32)
            for s in jax.random.split(jax.random.PRNGKey(9), 3)
        )
        a = ring_flash_attention(mesh, q, k, v, causal=True,
                                 block_q=8, block_k=8, layout="contiguous")
        b = ring_flash_attention(mesh, q, k, v, causal=True,
                                 block_q=8, block_k=8, layout="zigzag")
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)

    def test_non_causal_rejected(self):
        from k8s_tpu.parallel.ring_flash import ring_flash_attention_local

        with pytest.raises(ValueError, match="CAUSAL"):
            ring_flash_attention_local(
                jnp.ones((1, 8, 2, 8)), jnp.ones((1, 8, 2, 8)),
                jnp.ones((1, 8, 2, 8)), causal=False, layout="zigzag")

    def test_transformer_zigzag_path(self):
        """ring_layout="zigzag" composes in the model and matches the
        contiguous layout's logits exactly."""
        import dataclasses

        from k8s_tpu.models.transformer import Transformer, TransformerConfig

        mesh = make_mesh(MeshConfig(sp=4, dp=2))
        cfg = TransformerConfig(
            vocab_size=64, hidden=32, ffn_hidden=64, layers=1, heads=2,
            kv_heads=2, max_seq_len=64, dtype=jnp.float32, remat=False,
            use_ring_attention=True, use_flash_attention=True,
            flash_block_q=16, flash_block_k=16,
        )
        tokens = (jnp.arange(2 * 64, dtype=jnp.int32).reshape(2, 64) * 5) % 64
        model = Transformer(cfg)
        params = model.init(jax.random.PRNGKey(0), tokens)
        out_contig = model.apply(params, tokens, mesh=mesh)
        cfg_zz = dataclasses.replace(cfg, ring_layout="zigzag")
        out_zz = Transformer(cfg_zz).apply(params, tokens, mesh=mesh)
        np.testing.assert_allclose(np.asarray(out_zz),
                                   np.asarray(out_contig), atol=3e-5)


class TestGQARingFlash:
    """Grouped-query attention through the flash ring: K/V ride the ring at
    their native Hkv = H/group heads (per-hop ICI traffic / group) and are
    expanded only inside each flash call; dk/dv group-sum back to Hkv.
    Exactness vs the repeat-then-attend reference is the contract."""

    @staticmethod
    def _ref(q, k, v, group):
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)
        return reference_attention(q, k, v, causal=True)

    @pytest.mark.parametrize("layout", ["contiguous", "zigzag"])
    def test_values_match_repeat_reference(self, layout):
        from k8s_tpu.parallel.ring_flash import ring_flash_attention

        mesh = make_mesh(MeshConfig(sp=4, dp=2))
        B, L, H, Hkv, D = 2, 128, 4, 2, 16
        ks = jax.random.split(jax.random.PRNGKey(11), 3)
        q = jax.random.normal(ks[0], (B, L, H, D), jnp.float32) * 0.5
        k = jax.random.normal(ks[1], (B, L, Hkv, D), jnp.float32) * 0.5
        v = jax.random.normal(ks[2], (B, L, Hkv, D), jnp.float32) * 0.5
        expected = self._ref(q, k, v, H // Hkv)
        got = ring_flash_attention(mesh, q, k, v, causal=True,
                                   block_q=16, block_k=16, layout=layout)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   atol=2e-5)

    @pytest.mark.parametrize("layout", ["contiguous", "zigzag"])
    def test_gradients_match_repeat_reference(self, layout):
        """dk/dv are SUMS over the query-head group — exactly what grad of
        the repeat-then-attend reference produces for the unrepeated KV."""
        from k8s_tpu.parallel.ring_flash import ring_flash_attention

        mesh = make_mesh(MeshConfig(sp=4, dp=2))
        B, L, H, Hkv, D = 2, 64, 4, 2, 16
        ks = jax.random.split(jax.random.PRNGKey(12), 3)
        q = jax.random.normal(ks[0], (B, L, H, D), jnp.float32) * 0.5
        k = jax.random.normal(ks[1], (B, L, Hkv, D), jnp.float32) * 0.5
        v = jax.random.normal(ks[2], (B, L, Hkv, D), jnp.float32) * 0.5
        group = H // Hkv

        def loss_ring(q, k, v):
            out = ring_flash_attention(mesh, q, k, v, causal=True,
                                       block_q=16, block_k=16, layout=layout)
            return jnp.sum(jnp.sin(out))

        def loss_ref(q, k, v):
            return jnp.sum(jnp.sin(self._ref(q, k, v, group)))

        got = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            assert g.shape == w.shape
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       atol=5e-5)

    def test_transformer_gqa_ring_matches_repeated(self):
        """The model's GQA fast path (native-Hkv ring) must produce the
        same logits as forcing the pre-ring repeat."""
        from k8s_tpu.models.transformer import Transformer, TransformerConfig

        mesh = make_mesh(MeshConfig(sp=4, dp=2))
        cfg = TransformerConfig(
            vocab_size=64, hidden=32, ffn_hidden=64, layers=1, heads=4,
            kv_heads=2, max_seq_len=64, dtype=jnp.float32, remat=False,
            use_ring_attention=True, use_flash_attention=True,
            flash_block_q=16, flash_block_k=16,
        )
        tokens = (jnp.arange(2 * 64, dtype=jnp.int32).reshape(2, 64) * 3) % 64
        model = Transformer(cfg)
        params = model.init(jax.random.PRNGKey(0), tokens)
        out_gqa = model.apply(params, tokens, mesh=mesh)
        # control: same params through the ulysses path repeats KV up front
        import dataclasses

        cfg_u = dataclasses.replace(cfg, sp_strategy="ulysses")
        out_rep = Transformer(cfg_u).apply(params, tokens, mesh=mesh)
        np.testing.assert_allclose(np.asarray(out_gqa), np.asarray(out_rep),
                                   atol=3e-5)

    def test_gqa_under_tensor_parallel_heads(self):
        """Native-Hkv ring with the head axis ALSO sharded over tp: the
        per-shard q/kv group alignment must reproduce the global mapping."""
        from k8s_tpu.parallel.ring_flash import ring_flash_attention

        mesh = make_mesh(MeshConfig(sp=2, tp=2, dp=2))
        B, L, H, Hkv, D = 2, 64, 4, 2, 16
        ks = jax.random.split(jax.random.PRNGKey(13), 3)
        q = jax.random.normal(ks[0], (B, L, H, D), jnp.float32) * 0.5
        k = jax.random.normal(ks[1], (B, L, Hkv, D), jnp.float32) * 0.5
        v = jax.random.normal(ks[2], (B, L, Hkv, D), jnp.float32) * 0.5
        expected = self._ref(q, k, v, H // Hkv)
        got = ring_flash_attention(mesh, q, k, v, causal=True,
                                   block_q=16, block_k=16)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   atol=2e-5)

    def test_model_falls_back_to_repeat_when_tp_exceeds_kv_heads(self):
        """kv_heads=1 with tp=2 cannot shard natively; the model must take
        the pre-ring repeat fallback and still match the repeated control."""
        import dataclasses

        from k8s_tpu.models.transformer import Transformer, TransformerConfig

        mesh = make_mesh(MeshConfig(sp=2, tp=2, dp=2))
        cfg = TransformerConfig(
            vocab_size=64, hidden=32, ffn_hidden=64, layers=1, heads=4,
            kv_heads=1, max_seq_len=64, dtype=jnp.float32, remat=False,
            use_ring_attention=True, use_flash_attention=True,
            flash_block_q=16, flash_block_k=16,
        )
        tokens = (jnp.arange(2 * 64, dtype=jnp.int32).reshape(2, 64) * 7) % 64
        model = Transformer(cfg)
        params = model.init(jax.random.PRNGKey(1), tokens)
        out = model.apply(params, tokens, mesh=mesh)
        cfg_u = dataclasses.replace(cfg, sp_strategy="ulysses")
        out_rep = Transformer(cfg_u).apply(params, tokens, mesh=mesh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(out_rep),
                                   atol=3e-5)


class TestWindowedRingFlash:
    """Sliding-window attention across the sp ring
    (ring_flash_attention_windowed): only the ceil((window-1)/chunk)
    preceding chunks are exchanged — O(window/Lc) ICI hops instead of sp —
    with a bounded-hop custom VJP.  Exactness vs the masked reference across
    the window/chunk regimes (within-chunk, exact-chunk, boundary band,
    multi-chunk, wrap-limited) is the contract."""

    @staticmethod
    def _ref(q, k, v, w, group=1):
        if group > 1:
            k = jnp.repeat(k, group, axis=2)
            v = jnp.repeat(v, group, axis=2)
        qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
        s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * (q.shape[-1] ** -0.5)
        L = q.shape[1]
        qp = jnp.arange(L)[:, None]
        kp = jnp.arange(L)[None, :]
        s = jnp.where((qp >= kp) & (qp - kp < w), s, -1e30)
        out = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), vt)
        return out.transpose(0, 2, 1, 3)

    @pytest.mark.parametrize("window", [16, 32, 40, 100])
    def test_values_match_reference(self, window):
        from k8s_tpu.parallel.ring_flash import ring_flash_attention_windowed

        mesh = make_mesh(MeshConfig(sp=4, dp=2))
        B, L, H, D = 2, 128, 2, 16  # Lc = 32/rank
        q, k, v = (jax.random.normal(s, (B, L, H, D), jnp.float32) * 0.5
                   for s in jax.random.split(jax.random.PRNGKey(30), 3))
        got = ring_flash_attention_windowed(mesh, q, k, v, window=window,
                                            block_q=16, block_k=16)
        np.testing.assert_allclose(np.asarray(got),
                                   np.asarray(self._ref(q, k, v, window)),
                                   atol=2e-5)

    @pytest.mark.parametrize("window", [16, 40])
    def test_gradients_match_reference(self, window):
        from k8s_tpu.parallel.ring_flash import ring_flash_attention_windowed

        mesh = make_mesh(MeshConfig(sp=4, dp=2))
        B, L, H, D = 2, 128, 2, 16
        q, k, v = (jax.random.normal(s, (B, L, H, D), jnp.float32) * 0.5
                   for s in jax.random.split(jax.random.PRNGKey(31), 3))

        def loss_ring(q, k, v):
            return jnp.sum(jnp.sin(ring_flash_attention_windowed(
                mesh, q, k, v, window=window, block_q=16, block_k=16)))

        def loss_ref(q, k, v):
            return jnp.sum(jnp.sin(self._ref(q, k, v, window)))

        got = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       atol=5e-5)

    def test_gqa_windowed_ring(self):
        from k8s_tpu.parallel.ring_flash import ring_flash_attention_windowed

        mesh = make_mesh(MeshConfig(sp=4, dp=2))
        B, L, H, Hkv, D = 2, 128, 4, 2, 16
        ks = jax.random.split(jax.random.PRNGKey(32), 3)
        q = jax.random.normal(ks[0], (B, L, H, D), jnp.float32) * 0.5
        k = jax.random.normal(ks[1], (B, L, Hkv, D), jnp.float32) * 0.5
        v = jax.random.normal(ks[2], (B, L, Hkv, D), jnp.float32) * 0.5
        got = ring_flash_attention_windowed(mesh, q, k, v, window=40,
                                            block_q=16, block_k=16)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(self._ref(q, k, v, 40, group=2)),
            atol=2e-5)

    def test_model_windowed_ring_path(self):
        """window_size + sp ring composes in the model and matches the
        single-device windowed flash logits."""
        from k8s_tpu.models.transformer import Transformer, TransformerConfig

        mesh = make_mesh(MeshConfig(sp=4, dp=2))
        cfg = TransformerConfig(
            vocab_size=64, hidden=32, ffn_hidden=64, layers=1, heads=2,
            kv_heads=2, max_seq_len=128, dtype=jnp.float32, remat=False,
            use_ring_attention=True, use_flash_attention=True,
            flash_block_q=16, flash_block_k=16, window_size=40,
        )
        tokens = (jnp.arange(2 * 128, dtype=jnp.int32).reshape(2, 128) * 5) % 64
        model = Transformer(cfg)
        params = model.init(jax.random.PRNGKey(0), tokens)
        out_ring = model.apply(params, tokens, mesh=mesh)
        import dataclasses

        cfg_1dev = dataclasses.replace(cfg, use_ring_attention=False)
        out_flash = Transformer(cfg_1dev).apply(params, tokens)
        np.testing.assert_allclose(np.asarray(out_ring),
                                   np.asarray(out_flash), atol=3e-5)

"""Pallas ops: flash attention and fused RMSNorm vs XLA references.

Runs in Pallas interpret mode on the CPU backend (kernels auto-detect), the
same ladder the reference uses for hardware-free tiers (SURVEY.md §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_tpu.ops import flash_attention, rms_norm
from k8s_tpu.parallel.ring_attention import reference_attention


def _rand(key, shape, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(key), shape, dtype)


class TestFlashAttentionForward:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, causal):
        B, L, H, D = 2, 128, 4, 32
        q, k, v = (_rand(i, (B, L, H, D)) for i in range(3))
        out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
        ref = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_uneven_blocks(self):
        # L=96 with preferred block 64 -> picks divisor 48
        B, L, H, D = 1, 96, 2, 16
        q, k, v = (_rand(i, (B, L, H, D)) for i in range(3))
        out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
        ref = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_gqa(self):
        B, L, H, Hkv, D = 1, 64, 8, 2, 16
        q = _rand(0, (B, L, H, D))
        k = _rand(1, (B, L, Hkv, D))
        v = _rand(2, (B, L, Hkv, D))
        out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
        ref = reference_attention(q, jnp.repeat(k, 4, axis=2),
                                  jnp.repeat(v, 4, axis=2), causal=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_causal_cross_length_rejected(self):
        # causal masking assumes 0-aligned self-attention; a kv-cache decode
        # shape (L != Lk) would silently mask the wrong entries
        q = _rand(0, (1, 16, 2, 16))
        k = _rand(1, (1, 64, 2, 16))
        v = _rand(2, (1, 64, 2, 16))
        with pytest.raises(ValueError, match="causal"):
            flash_attention(q, k, v, causal=True)
        flash_attention(q, k, v, causal=False)  # cross-attention still fine

    def test_single_block(self):
        B, L, H, D = 1, 32, 2, 16
        q, k, v = (_rand(i, (B, L, H, D)) for i in range(3))
        out = flash_attention(q, k, v, causal=False)
        ref = reference_attention(q, k, v, causal=False)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_bf16_dtype_preserved(self):
        B, L, H, D = 1, 64, 2, 16
        q, k, v = (_rand(i, (B, L, H, D), jnp.bfloat16) for i in range(3))
        out = flash_attention(q, k, v, causal=True)
        assert out.dtype == jnp.bfloat16
        ref = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            out.astype(jnp.float32), ref.astype(jnp.float32), atol=3e-2)


class TestFlashAttentionBackward:
    @pytest.mark.parametrize("causal", [True, False])
    def test_grads_match_reference(self, causal):
        B, L, H, D = 1, 64, 2, 16
        q, k, v = (_rand(i, (B, L, H, D)) for i in range(3))

        def loss_flash(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, causal=causal,
                                block_q=32, block_k=32) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(reference_attention(q, k, v, causal=causal) ** 2)

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for gf, gr, name in zip(g_flash, g_ref, "qkv"):
            np.testing.assert_allclose(
                gf, gr, atol=5e-4, rtol=5e-4,
                err_msg=f"d{name} mismatch")

    def test_gqa_grads(self):
        B, L, H, Hkv, D = 1, 32, 4, 2, 16
        q = _rand(0, (B, L, H, D))
        k = _rand(1, (B, L, Hkv, D))
        v = _rand(2, (B, L, Hkv, D))

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(reference_attention(
                q, jnp.repeat(k, 2, axis=2), jnp.repeat(v, 2, axis=2),
                causal=True) ** 2)

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        # dk/dv shapes must be the unrepeated [B, L, Hkv, D]
        assert g_flash[1].shape == k.shape
        assert g_flash[2].shape == v.shape
        for gf, gr, name in zip(g_flash, g_ref, "qkv"):
            np.testing.assert_allclose(gf, gr, atol=5e-4, rtol=5e-4,
                                       err_msg=f"d{name} mismatch")

    def test_jit_compatible(self):
        B, L, H, D = 1, 32, 2, 16
        q, k, v = (_rand(i, (B, L, H, D)) for i in range(3))
        f = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))
        out = f(q, k, v)
        assert out.shape == (B, L, H, D)


class TestRMSNorm:
    def test_matches_reference(self):
        x = _rand(0, (4, 96, 64))
        scale = 1.0 + 0.1 * _rand(1, (64,))
        out = rms_norm(x, scale)
        var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
        ref = x * jax.lax.rsqrt(var + 1e-6) * scale
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)

    def test_grads_match_reference(self):
        x = _rand(0, (8, 32))
        scale = 1.0 + 0.1 * _rand(1, (32,))

        def loss_fused(x, s):
            return jnp.sum(rms_norm(x, s) ** 2)

        def loss_ref(x, s):
            var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
            return jnp.sum((x * jax.lax.rsqrt(var + 1e-6) * s) ** 2)

        gx_f, gs_f = jax.grad(loss_fused, argnums=(0, 1))(x, scale)
        gx_r, gs_r = jax.grad(loss_ref, argnums=(0, 1))(x, scale)
        np.testing.assert_allclose(gx_f, gx_r, atol=1e-4, rtol=1e-4)
        np.testing.assert_allclose(gs_f, gs_r, atol=1e-4, rtol=1e-4)

    def test_bf16_promotes_like_plain_path(self):
        # dtype semantics match the unfused RMSNorm module:
        # (bf16 normalized) * (f32 scale) -> f32
        x = _rand(0, (16, 128), jnp.bfloat16)
        scale = jnp.ones((128,), jnp.float32)
        out = rms_norm(x, scale)
        assert out.dtype == jnp.float32
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1,
                       keepdims=True)
        ref = (x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * scale
        np.testing.assert_allclose(out, ref, atol=1e-6)

    def test_large_eps_grads(self):
        # regression: the bwd formula must hold for non-negligible eps
        x = _rand(0, (4, 8))
        scale = 1.0 + 0.1 * _rand(1, (8,))
        eps = 0.5

        def loss_fused(x, s):
            return jnp.sum(rms_norm(x, s, eps=eps) ** 3)

        def loss_ref(x, s):
            var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
            return jnp.sum((x * jax.lax.rsqrt(var + eps) * s) ** 3)

        gx_f, gs_f = jax.grad(loss_fused, argnums=(0, 1))(x, scale)
        gx_r, gs_r = jax.grad(loss_ref, argnums=(0, 1))(x, scale)
        np.testing.assert_allclose(gx_f, gx_r, atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(gs_f, gs_r, atol=1e-5, rtol=1e-5)


class TestTransformerKernelIntegration:
    """Transformer with Pallas kernels on matches the plain XLA path."""

    def test_flash_and_fused_norm_match_plain(self):
        import dataclasses

        from k8s_tpu.models.transformer import Transformer, tiny_test

        cfg_plain = tiny_test()
        cfg_fused = dataclasses.replace(
            cfg_plain, use_flash_attention=True, use_fused_norm=True)
        tokens = jax.random.randint(
            jax.random.PRNGKey(0), (2, 64), 0, cfg_plain.vocab_size)
        params = Transformer(cfg_plain).init(jax.random.PRNGKey(1), tokens)

        logits_plain = Transformer(cfg_plain).apply(params, tokens)
        logits_fused = Transformer(cfg_fused).apply(params, tokens)
        np.testing.assert_allclose(
            logits_plain, logits_fused, atol=2e-3, rtol=2e-3)

    def test_bf16_fused_matches_plain(self):
        import dataclasses

        from k8s_tpu.models.transformer import Transformer, tiny_test

        cfg_plain = dataclasses.replace(tiny_test(), dtype=jnp.bfloat16)
        cfg_fused = dataclasses.replace(
            cfg_plain, use_flash_attention=True, use_fused_norm=True)
        tokens = jax.random.randint(
            jax.random.PRNGKey(0), (2, 32), 0, cfg_plain.vocab_size)
        params = Transformer(cfg_plain).init(jax.random.PRNGKey(1), tokens)

        logits_plain = Transformer(cfg_plain).apply(params, tokens)
        logits_fused = Transformer(cfg_fused).apply(params, tokens)
        assert logits_plain.dtype == logits_fused.dtype
        np.testing.assert_allclose(
            logits_plain, logits_fused, atol=5e-2, rtol=5e-2)

    def test_fused_path_trains(self):
        import dataclasses

        import optax

        from k8s_tpu.models.transformer import Transformer, tiny_test

        cfg = dataclasses.replace(
            tiny_test(), use_flash_attention=True, use_fused_norm=True)
        model = Transformer(cfg)
        tokens = jax.random.randint(
            jax.random.PRNGKey(0), (2, 32), 0, cfg.vocab_size)
        params = model.init(jax.random.PRNGKey(1), tokens)

        def loss_fn(p):
            logits = model.apply(p, tokens[:, :-1])
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, tokens[:, 1:]).mean()

        l0 = loss_fn(params)
        grads = jax.grad(loss_fn)(params)
        sgd = jax.tree.map(lambda p, g: p - 0.5 * g, params, grads)
        l1 = loss_fn(sgd)
        assert jnp.isfinite(l0) and jnp.isfinite(l1)
        assert l1 < l0


class TestFusedLinearCrossEntropy:
    """ops.fused_ce: the chunked head-matmul + online-softmax loss must be
    exact vs the materialized-logits path, for values and both gradients."""

    def _setup(self, T=37, d=16, V=103):
        import jax

        h = jax.random.normal(jax.random.PRNGKey(0), (T, d), jnp.float32)
        emb = jax.random.normal(jax.random.PRNGKey(1), (V, d),
                                jnp.float32) * 0.3
        tg = jax.random.randint(jax.random.PRNGKey(2), (T,), 0, V)
        return h, emb, tg

    def _unfused(self, h, emb, tg):
        from k8s_tpu.models.train import cross_entropy_loss

        logits = jnp.einsum("td,vd->tv", h, emb,
                            preferred_element_type=jnp.float32)
        return cross_entropy_loss(logits, tg)

    def test_loss_and_grads_match_unfused(self):
        import jax

        from k8s_tpu.ops.fused_ce import fused_linear_cross_entropy

        h, emb, tg = self._setup()

        def fused(h, emb, tg):
            return fused_linear_cross_entropy(h, emb, tg, vocab_chunk=32)

        np.testing.assert_allclose(float(fused(h, emb, tg)),
                                   float(self._unfused(h, emb, tg)),
                                   rtol=1e-6)
        gu = jax.grad(self._unfused, argnums=(0, 1))(h, emb, tg)
        gf = jax.grad(fused, argnums=(0, 1))(h, emb, tg)
        for a, b in zip(gf, gu):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6)

    def test_invalid_targets_zero_loss_and_grad(self):
        import jax

        from k8s_tpu.ops.fused_ce import fused_linear_cross_entropy

        h, emb, tg = self._setup()
        tg = tg.at[0].set(-1).at[5].set(emb.shape[0] + 9)

        def fused(h, emb, tg):
            return fused_linear_cross_entropy(h, emb, tg, vocab_chunk=32)

        np.testing.assert_allclose(float(fused(h, emb, tg)),
                                   float(self._unfused(h, emb, tg)),
                                   rtol=1e-6)
        dh = jax.grad(fused)(h, emb, tg)
        # invalid rows get exactly zero hidden gradient
        assert float(jnp.max(jnp.abs(dh[0]))) == 0.0
        assert float(jnp.max(jnp.abs(dh[5]))) == 0.0

    def test_vocab_not_divisible_by_chunk(self):
        from k8s_tpu.ops.fused_ce import fused_linear_cross_entropy

        h, emb, tg = self._setup(V=101)
        for chunk in (7, 101, 128, 4096):
            got = fused_linear_cross_entropy(h, emb, tg, vocab_chunk=chunk)
            np.testing.assert_allclose(float(got),
                                       float(self._unfused(h, emb, tg)),
                                       rtol=1e-6)

    def test_transformer_fused_path_matches_unfused(self):
        import jax

        from k8s_tpu.models import train as train_lib
        from k8s_tpu.models.transformer import Transformer, tiny_test

        model = Transformer(tiny_test())
        toks = jax.random.randint(jax.random.PRNGKey(0), (2, 32), 0, 256)
        params = model.init(jax.random.PRNGKey(1), toks)

        def unfused_loss(params):
            return train_lib.lm_loss(model.apply(params, toks), toks)

        fused_apply = train_lib.make_fused_lm_apply_fn(model, vocab_chunk=64)

        def fused_loss(params):
            return fused_apply(params, toks)

        np.testing.assert_allclose(float(fused_loss(params)),
                                   float(unfused_loss(params)), rtol=1e-5)
        gu = jax.grad(unfused_loss)(params)
        gf = jax.grad(fused_loss)(params)

        def assert_leaf(a, b):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       atol=2e-5)

        jax.tree.map(assert_leaf, gu, gf)

    def test_z_loss_matches_reference(self):
        import jax

        from k8s_tpu.ops.fused_ce import fused_linear_cross_entropy

        h, emb, tg = self._setup(V=67)
        Z = 1e-2

        def ref(h, emb, tg):
            logits = jnp.einsum("td,vd->tv", h, emb,
                                preferred_element_type=jnp.float32)
            lse = jax.nn.logsumexp(logits, axis=-1)
            picked = jnp.take_along_axis(logits, tg[:, None], 1)[:, 0]
            return jnp.mean(lse - picked + Z * lse ** 2)

        def fused(h, emb, tg):
            return fused_linear_cross_entropy(h, emb, tg, vocab_chunk=16,
                                              z_loss=Z)

        np.testing.assert_allclose(float(fused(h, emb, tg)),
                                   float(ref(h, emb, tg)), rtol=1e-5)
        gu = jax.grad(ref, argnums=(0, 1))(h, emb, tg)
        gf = jax.grad(fused, argnums=(0, 1))(h, emb, tg)
        for a, b in zip(gf, gu):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6)

    def test_trains_through_sharded_step(self):
        import jax

        from k8s_tpu.models import train as train_lib
        from k8s_tpu.models.transformer import Transformer, tiny_test
        from k8s_tpu.parallel import MeshConfig, make_mesh
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = make_mesh(MeshConfig(dp=2, fsdp=4))
        model = Transformer(tiny_test())
        toks = jax.random.randint(jax.random.PRNGKey(0), (8, 32), 0, 256)
        params = model.init(jax.random.PRNGKey(1), toks)
        opt = train_lib.default_optimizer(1e-3)
        state = train_lib.init_state(params, opt)
        state, shardings = train_lib.shard_train_state(state, mesh)
        step = train_lib.make_sharded_train_step(
            train_lib.make_fused_lm_apply_fn(model, vocab_chunk=64),
            train_lib.fused_loss_passthrough, opt, mesh, shardings)
        toks_d = jax.device_put(toks, NamedSharding(mesh, P(("dp", "fsdp"))))
        losses = []
        for _ in range(4):
            state, loss = step(state, (toks_d, toks_d))
            losses.append(float(loss))
        assert losses[-1] < losses[0]


class TestSlidingWindowAttention:
    """window= in the flash kernels (Mistral/Gemma-style SWA): each query
    attends its `window` most recent positions.  The kernels' inner grid
    dimension shrinks to the blocks a window can see (out-of-window K/V
    tiles are never DMA'd — O(L*window) compute and traffic); exactness
    vs a masked reference is the contract, including windows that are not
    block-aligned and windows larger than the sequence."""

    @staticmethod
    def _ref(q, k, v, window):
        qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
        s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * (q.shape[-1] ** -0.5)
        L = q.shape[1]
        qpos = jnp.arange(L)[:, None]
        kpos = jnp.arange(L)[None, :]
        keep = (qpos >= kpos) & (qpos - kpos < window)
        s = jnp.where(keep, s, -1e30)
        out = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), vt)
        return out.transpose(0, 2, 1, 3)

    @pytest.mark.parametrize("window", [16, 24, 128, 1000])
    def test_values_match_masked_reference(self, window):
        from k8s_tpu.ops.flash_attention import flash_attention

        B, L, H, D = 2, 128, 2, 16
        q, k, v = (jax.random.normal(s, (B, L, H, D), jnp.float32) * 0.5
                   for s in jax.random.split(jax.random.PRNGKey(20), 3))
        got = flash_attention(q, k, v, causal=True, window=window,
                              block_q=16, block_k=16)
        np.testing.assert_allclose(np.asarray(got),
                                   np.asarray(self._ref(q, k, v, window)),
                                   atol=2e-5)

    @pytest.mark.parametrize("window", [16, 24])
    def test_gradients_match_masked_reference(self, window):
        from k8s_tpu.ops.flash_attention import flash_attention

        B, L, H, D = 1, 64, 2, 16
        q, k, v = (jax.random.normal(s, (B, L, H, D), jnp.float32) * 0.5
                   for s in jax.random.split(jax.random.PRNGKey(21), 3))

        def loss_flash(q, k, v):
            return jnp.sum(jnp.sin(flash_attention(
                q, k, v, causal=True, window=window,
                block_q=16, block_k=16)))

        def loss_ref(q, k, v):
            return jnp.sum(jnp.sin(self._ref(q, k, v, window)))

        got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       atol=5e-5)

    def test_window_larger_than_seq_equals_plain_causal(self):
        from k8s_tpu.ops.flash_attention import flash_attention

        B, L, H, D = 1, 64, 2, 16
        q, k, v = (jax.random.normal(s, (B, L, H, D), jnp.float32)
                   for s in jax.random.split(jax.random.PRNGKey(22), 3))
        plain = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
        windowed = flash_attention(q, k, v, causal=True, window=10 ** 6,
                                   block_q=16, block_k=16)
        np.testing.assert_allclose(np.asarray(windowed), np.asarray(plain),
                                   atol=1e-6)

    def test_window_requires_causal(self):
        from k8s_tpu.ops.flash_attention import flash_attention

        x = jnp.ones((1, 16, 2, 8))
        with pytest.raises(ValueError, match="causal"):
            flash_attention(x, x, x, causal=False, window=8)

    def test_model_window_path_and_guards(self):
        import dataclasses

        from k8s_tpu.models.transformer import Transformer, TransformerConfig

        cfg = TransformerConfig(
            vocab_size=64, hidden=32, ffn_hidden=64, layers=1, heads=2,
            kv_heads=2, max_seq_len=64, dtype=jnp.float32, remat=False,
            use_flash_attention=True, flash_block_q=16, flash_block_k=16,
            window_size=32,
        )
        tokens = (jnp.arange(64, dtype=jnp.int32).reshape(1, 64) * 3) % 64
        model = Transformer(cfg)
        params = model.init(jax.random.PRNGKey(0), tokens)
        out = model.apply(params, tokens)
        assert bool(jnp.all(jnp.isfinite(out)))
        # windowed logits must differ from full-causal logits (the mask
        # is actually applied)
        cfg_full = dataclasses.replace(cfg, window_size=None)
        out_full = Transformer(cfg_full).apply(params, tokens)
        assert not np.allclose(np.asarray(out), np.asarray(out_full))
        # the plain path APPLIES the window too (mask-based; it used to
        # raise) — same convention, so it must agree with the flash path
        cfg_plain = dataclasses.replace(cfg, use_flash_attention=False)
        out_plain = Transformer(cfg_plain).apply(params, tokens)
        np.testing.assert_allclose(
            np.asarray(out_plain), np.asarray(out), rtol=2e-5, atol=2e-5)

    def test_window_rejected_under_ring_and_below_one(self):
        import dataclasses

        from k8s_tpu.models.transformer import Transformer, TransformerConfig
        from k8s_tpu.ops.flash_attention import flash_attention
        from k8s_tpu.parallel.mesh import MeshConfig, make_mesh

        x = jnp.ones((1, 16, 2, 8))
        with pytest.raises(ValueError, match="window must be >= 1"):
            flash_attention(x, x, x, causal=True, window=0)

        # ring + flash + window is SUPPORTED (the windowed ring); the
        # guard fires only where window would be silently ignored:
        # ulysses, and the plain (non-flash) ring
        mesh = make_mesh(MeshConfig(sp=4, dp=2))
        cfg = TransformerConfig(
            vocab_size=64, hidden=32, ffn_hidden=64, layers=1, heads=2,
            kv_heads=2, max_seq_len=64, dtype=jnp.float32, remat=False,
            use_ring_attention=True, use_flash_attention=True,
            flash_block_q=16, flash_block_k=16, window_size=32,
            sp_strategy="ulysses",
        )
        tokens = jnp.zeros((2, 64), jnp.int32)
        cfg_ok = dataclasses.replace(cfg, use_ring_attention=False)
        params = Transformer(cfg_ok).init(jax.random.PRNGKey(0), tokens)
        with pytest.raises(ValueError, match="flash ring"):
            Transformer(cfg).apply(params, tokens, mesh=mesh)
        cfg_plain_ring = dataclasses.replace(
            cfg, sp_strategy="ring", use_flash_attention=False)
        with pytest.raises(ValueError, match="flash ring"):
            Transformer(cfg_plain_ring).apply(params, tokens, mesh=mesh)

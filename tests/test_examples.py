"""Shipped example manifests and chart are loadable, valid, and runnable
(the kubectl-create-f contract the reference e2e harness leans on,
py/test_runner.py:239-276)."""

from __future__ import annotations

import datetime
import os

import pytest

from k8s_tpu.api import manifest, v1alpha1, v1alpha2
from k8s_tpu.api.validation import ValidationError
from k8s_tpu.e2e.local import LocalCluster
from k8s_tpu.harness import chart, tf_job_client

EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples")


def load_one(name):
    jobs = manifest.load_tfjobs_from_file(os.path.join(EXAMPLES, name))
    assert len(jobs) == 1
    return jobs[0]


class TestExampleManifests:
    def test_tf_job_yaml(self):
        job = load_one("tf_job.yaml")
        assert job.api_version == v1alpha1.CRD_API_VERSION
        types = [r.tf_replica_type for r in job.spec.replica_specs]
        assert types == ["MASTER", "WORKER", "PS"]
        assert [r.replicas for r in job.spec.replica_specs] == [1, 1, 2]
        # defaulting filled the port and chief policy
        assert all(r.tf_port == 2222 for r in job.spec.replica_specs)
        assert job.spec.termination_policy.chief.replica_name == "MASTER"

    def test_tf_job_defaults_yaml(self):
        job = load_one("tf_job_defaults.yaml")
        [r] = job.spec.replica_specs
        assert r.tf_replica_type == "MASTER"
        assert r.replicas == 1
        assert r.tf_port == 2222

    def test_tf_job_gpu_yaml(self):
        job = load_one("tf_job_gpu.yaml")
        [r] = job.spec.replica_specs
        limits = r.template["spec"]["containers"][0]["resources"]["limits"]
        assert limits["nvidia.com/gpu"] == 1

    def test_tf_job_tpu_yaml(self):
        job = load_one("tf_job_tpu.yaml")
        assert job.api_version == v1alpha2.CRD_API_VERSION
        assert job.spec.tpu.accelerator_type == "v5litepod-16"
        assert job.spec.tpu.topology == "4x4"
        tpu = job.spec.tf_replica_specs["TPU"]
        assert tpu.replicas == 4
        assert tpu.restart_policy == v1alpha2.RestartPolicyExitCode

    def test_tf_job_multislice_yaml(self):
        job = load_one("tf_job_multislice.yaml")
        assert job.spec.tpu.num_slices == 2
        assert job.spec.tf_replica_specs["TPU"].replicas == 8

    def test_tf_job_serve_yaml(self):
        # the serving manifest: single replica, Never (inference is
        # idempotent — a crash should not loop), decodes from the volume
        # the training job checkpointed to
        job = load_one("tf_job_serve.yaml")
        spec = job.spec.tf_replica_specs["Worker"]
        assert spec.replicas == 1
        assert spec.restart_policy == v1alpha2.RestartPolicyNever
        cmd = spec.template["spec"]["containers"][0]["command"]
        assert any("serve_lm.py" in c for c in cmd)
        assert any(c.startswith("--train_dir=") for c in cmd)

    def test_tf_job_serve_http_yaml(self):
        # the RESIDENT serving manifest: the HTTP server process
        # (k8s_tpu.models.server) with OnFailure restarts and a /healthz
        # readiness probe on the bound port
        job = load_one("tf_job_serve_http.yaml")
        spec = job.spec.tf_replica_specs["Worker"]
        assert spec.replicas == 1
        assert spec.restart_policy == v1alpha2.RestartPolicyOnFailure
        c = spec.template["spec"]["containers"][0]
        assert "k8s_tpu.models.server" in c["command"]
        assert c["readinessProbe"]["httpGet"]["path"] == "/healthz"
        assert any(p.get("containerPort") == 8000 for p in c["ports"])
        # all seven engine knobs surfaced: slots/queue (ISSUE 5), the
        # prefix-reuse retention and sampling-lane routing (ISSUE 6),
        # the speculative-lane routing (ISSUE 9), and the per-request
        # lifecycle recorder + ring bound (ISSUE 12)
        env = {e["name"] for e in c["env"]}
        assert {"K8S_TPU_SERVE_SLOTS", "K8S_TPU_SERVE_QUEUE",
                "K8S_TPU_SERVE_PREFIX_BLOCKS",
                "K8S_TPU_SERVE_BATCH_SAMPLING",
                "K8S_TPU_SERVE_BATCH_SPEC",
                "K8S_TPU_REQUEST_LOG",
                "K8S_TPU_REQUEST_LOG_RING"} <= env
        envv = {e["name"]: e["value"] for e in c["env"]}
        assert envv["K8S_TPU_REQUEST_LOG"] == "1"

    def test_tf_job_serve_router_yaml(self):
        """The front-door example (ISSUE 13): an autoscalable serving
        TFJob (spec.autoscale bounds validate and default) plus its
        router companion Pod document (skipped by the TFJob loader,
        applied by kubectl)."""
        job = load_one("tf_job_serve_router.yaml")
        assert job.api_version == v1alpha2.CRD_API_VERSION
        a = job.spec.autoscale
        assert a is not None
        assert (a.min_replicas, a.max_replicas) == (1, 4)
        assert a.replica_type == "Worker"
        worker = job.spec.tf_replica_specs["Worker"]
        assert worker.replicas == a.min_replicas
        annotations = (worker.template.get("metadata") or {}).get(
            "annotations") or {}
        assert annotations.get("kubeflow.org/fleet-scrape-port") == "8000"
        # the second document is the router companion Pod
        with open(os.path.join(EXAMPLES, "tf_job_serve_router.yaml")) as f:
            docs = list(manifest.load_yaml_documents(f.read()))
        pods = [d for d in docs if d.get("kind") == "Pod"]
        assert len(pods) == 1
        container = pods[0]["spec"]["containers"][0]
        assert "k8s_tpu.cmd.router" in container["command"]
        assert any("--job=default/serve-lm-fleet" == c
                   for c in container["command"])
        probe = container["readinessProbe"]["httpGet"]
        assert probe["path"] == "/healthz"

    def test_tf_job_serve_disagg_yaml(self):
        """The disaggregated serving example (ISSUE 15): one TFJob with
        heterogeneous Prefill/Decode tiers wired for KV migration, plus
        the phase-splitting router companion Pod."""
        job = load_one("tf_job_serve_disagg.yaml")
        assert set(job.spec.tf_replica_specs) == {"Prefill", "Decode"}
        assert job.spec.tf_replica_specs["Prefill"].replicas == 1
        assert job.spec.tf_replica_specs["Decode"].replicas == 2
        for rtype, role in (("Prefill", "prefill"), ("Decode", "decode")):
            spec = job.spec.tf_replica_specs[rtype]
            annotations = (spec.template.get("metadata") or {}).get(
                "annotations") or {}
            assert annotations.get("kubeflow.org/serve-role") == role
            env = {e["name"]: e["value"]
                   for e in spec.template["spec"]["containers"][0]["env"]}
            assert env["K8S_TPU_SERVE_ROLE"] == role
        dec_ann = (job.spec.tf_replica_specs["Decode"].template.get(
            "metadata") or {}).get("annotations") or {}
        assert dec_ann.get("kubeflow.org/kvxfer-port") == "8472"
        with open(os.path.join(EXAMPLES,
                               "tf_job_serve_disagg.yaml")) as f:
            docs = list(manifest.load_yaml_documents(f.read()))
        [pod] = [d for d in docs if d.get("kind") == "Pod"]
        env = {e["name"]: e["value"]
               for e in pod["spec"]["containers"][0]["env"]}
        assert env["K8S_TPU_ROUTER_PHASE_TOKENS"] == "64"

    def test_tpu_smoke_yaml(self):
        job = load_one("tpu_smoke.yaml")
        assert job.spec.tf_replica_specs["TPU"].restart_policy == v1alpha2.RestartPolicyNever

    def test_crd_documents_are_skipped(self):
        for name in ("crd/crd.yaml", "crd/crd-v1alpha2.yaml"):
            assert manifest.load_tfjobs_from_file(os.path.join(EXAMPLES, name)) == []

    def test_load_tfjob_rejects_wrong_kind(self):
        with pytest.raises(ValueError, match="kind"):
            manifest.load_tfjob({"kind": "Pod"})

    def test_invalid_job_fails_validation(self):
        doc = {
            "apiVersion": "kubeflow.org/v1alpha2",
            "kind": "TFJob",
            "metadata": {"name": "bad"},
            "spec": {"tfReplicaSpecs": {"Chief": {"replicas": 2, "template": {
                "spec": {"containers": [{"name": "tensorflow"}]}}}}},
        }
        with pytest.raises(ValidationError, match="Chief"):
            manifest.load_tfjob(doc)


class TestChart:
    def test_render_defaults(self):
        [doc] = chart.render_chart(os.path.join(EXAMPLES, "tf_job_chart"))
        job = manifest.load_tfjob(doc)
        assert job.metadata.name == "chart-job"
        assert job.spec.tf_replica_specs["TPU"].replicas == 4

    def test_render_overrides(self):
        [doc] = chart.render_chart(
            os.path.join(EXAMPLES, "tf_job_chart"),
            {"name": "my-job", "image": "k8s-tpu/custom:1", "replicas": 2},
        )
        job = manifest.load_tfjob(doc)
        assert job.metadata.name == "my-job"
        assert (
            job.spec.tf_replica_specs["TPU"].template["spec"]["containers"][0]["image"]
            == "k8s-tpu/custom:1"
        )
        assert job.spec.tf_replica_specs["TPU"].replicas == 2

    def test_metadata(self):
        meta = chart.chart_metadata(os.path.join(EXAMPLES, "tf_job_chart"))
        assert meta["name"] == "tf-job"

    def test_missing_value_raises(self, tmp_path):
        (tmp_path / "templates").mkdir()
        (tmp_path / "templates" / "x.yaml").write_text("name: ${nope}\n")
        with pytest.raises(chart.ChartError, match="nope"):
            chart.render_chart(str(tmp_path))


class TestExampleRunsEndToEnd:
    def test_tf_job_yaml_runs_on_local_cluster(self):
        """examples/tf_job.yaml submitted verbatim reaches a terminal success
        state (commandless containers: kubelet simulator exits 0, chief state
        decides the job, pkg/trainer/training.go:154-189 semantics)."""
        job = load_one("tf_job.yaml")
        with LocalCluster(version="v1alpha1") as lc:
            created = tf_job_client.create_tf_job(
                lc.clientset, job.to_dict(), version="v1alpha1"
            )
            finished = tf_job_client.wait_for_job(
                lc.clientset,
                created["metadata"]["namespace"],
                created["metadata"]["name"],
                version="v1alpha1",
                timeout=datetime.timedelta(seconds=30),
                polling_interval=datetime.timedelta(milliseconds=50),
            )
        assert finished["status"]["phase"] == "Done"
        assert finished["status"]["state"] == "Succeeded"


def test_notebook_smoke_runs():
    """examples/notebook_smoke.py (reference: examples/gke/test_notebook.py)
    completes against the local cluster + dashboard."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "examples", "notebook_smoke.py")],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "notebook smoke: OK" in out.stdout

"""Flight-recorder tests (ISSUE 7): timeline ordering/bounding/eviction
under concurrent writers, apiserver call-accounting label correctness
(including one-count-per-wire-attempt across transport retries), watch
health through forced 410s, /debug/timeline 404-when-inactive parity with
/debug/traces and /debug/scheduler, event-recorder aggregation/drop
counters, and the churn bench at smoke scale."""

from __future__ import annotations

import json
import socket
import threading
import time
import urllib.request

import pytest

from k8s_tpu import flight
from k8s_tpu.client.clientset import Clientset
from k8s_tpu.client.errors import ApiError
from k8s_tpu.client.fake import FakeCluster
from k8s_tpu.client.gvr import PODS
from k8s_tpu.flight.timeline import TimelineRecorder


# -- timeline ----------------------------------------------------------------


class TestTimeline:
    def _active(self, **kw) -> TimelineRecorder:
        t = TimelineRecorder(**kw)
        t.activate()
        return t

    def test_entries_ordered_and_since_filters(self):
        t = self._active()
        for i in range(5):
            t.record("ns/j", "step", message=f"m{i}")
        entries = t.snapshot("ns/j")
        assert [e["message"] for e in entries] == [f"m{i}" for i in range(5)]
        seqs = [e["seq"] for e in entries]
        assert seqs == sorted(seqs) and len(set(seqs)) == 5
        newer = t.snapshot("ns/j", since=seqs[2])
        assert [e["message"] for e in newer] == ["m3", "m4"]
        assert t.snapshot("ns/j", limit=2) == entries[-2:]

    def test_per_job_ring_bound_evicts_oldest(self):
        t = self._active(max_events_per_job=4)
        for i in range(10):
            t.record("ns/j", "step", message=f"m{i}")
        entries = t.snapshot("ns/j")
        assert [e["message"] for e in entries] == ["m6", "m7", "m8", "m9"]
        assert t.stats()["dropped_events"] == 6
        assert t.stats()["events_total"] == 10

    def test_job_registry_lru_eviction(self):
        t = self._active(max_jobs=2)
        t.record("ns/a", "x")
        t.record("ns/b", "x")
        t.record("ns/a", "y")  # a becomes most recent
        t.record("ns/c", "x")  # evicts b (least recently written)
        assert set(t.jobs()) == {"ns/a", "ns/c"}
        assert t.snapshot("ns/b") == []
        assert t.stats()["evicted_jobs"] == 1

    def test_inactive_recorder_is_a_noop(self):
        t = TimelineRecorder()
        t.record("ns/j", "step")
        assert t.jobs() == []
        t.activate()
        t.record("ns/j", "step")
        assert t.jobs() == ["ns/j"]

    def test_concurrent_writers_keep_order_and_counts(self):
        t = self._active(max_events_per_job=64)
        n_threads, per_thread = 8, 200

        def writer(tid):
            for i in range(per_thread):
                t.record(f"ns/own-{tid}", "step", i=i)
                t.record("ns/shared", "step", tid=tid, i=i)

        threads = [threading.Thread(target=writer, args=(tid,))
                   for tid in range(n_threads)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        stats = t.stats()
        assert stats["events_total"] == n_threads * per_thread * 2
        # per-thread jobs kept their bound; entries stay seq-ordered
        for tid in range(n_threads):
            entries = t.snapshot(f"ns/own-{tid}")
            assert len(entries) == 64
            seqs = [e["seq"] for e in entries]
            assert seqs == sorted(seqs)
            # ring kept the NEWEST 64 of this thread's writes
            assert [e["attrs"]["i"] for e in entries] == list(
                range(per_thread - 64, per_thread))
        shared = t.snapshot("ns/shared")
        assert len(shared) == 64
        seqs = [e["seq"] for e in shared]
        assert seqs == sorted(seqs)


# -- call accounting ---------------------------------------------------------


class TestCallAccounting:
    def test_labels_and_aggregation(self):
        flight.reset_all()
        fc = FakeCluster()
        cs = Clientset(fc)
        cs.pods("ns").create({"metadata": {"name": "p1"}})
        cs.pods("ns").get("p1")
        cs.pods("ns").list()
        with pytest.raises(ApiError):
            cs.pods("ns").get("missing")
        snap = flight.ACCOUNTING.snapshot()
        # wire-parity code labels: a create is a 201 on a real apiserver
        assert snap[("POST", "pods", 201)] == 1
        assert snap[("GET", "pods", 200)] == 1
        assert snap[("LIST", "pods", 200)] == 1
        assert snap[("GET", "pods", 404)] == 1
        assert flight.ACCOUNTING.count(verb="GET", resource="pods") == 2
        assert flight.ACCOUNTING.by_verb_resource()["GET pods"] == 2
        assert flight.ACCOUNTING.duration_stats()["count"] == 4

    def test_composite_fake_calls_count_once(self):
        """patch = get + merge + update inside the fake, but a real
        apiserver saw ONE PATCH — the reentrancy guard keeps it at one."""
        flight.reset_all()
        fc = FakeCluster()
        cs = Clientset(fc)
        cs.pods("ns").create({"metadata": {"name": "p1"}})
        cs.pods("ns").patch("p1", {"status": {"phase": "Running"}})
        by = flight.ACCOUNTING.by_verb_resource()
        assert by == {"POST pods": 1, "PATCH pods": 1}

    def test_account_context_captures_api_error_code(self):
        flight.reset_all()
        with pytest.raises(ApiError):
            with flight.account("GET", "pods"):
                raise ApiError(409, "Conflict", "boom")
        with pytest.raises(ValueError):
            with flight.account("GET", "pods"):
                raise ValueError("no http status here")
        snap = flight.ACCOUNTING.snapshot()
        assert snap[("GET", "pods", 409)] == 1
        assert snap[("GET", "pods", 0)] == 1

    def test_rest_transport_retry_counts_each_attempt(self):
        """One wire attempt = one count: a GET whose first connection dies
        before any response must show up as code-0 AND code-200 entries."""
        from k8s_tpu.client.rest import ClusterConfig, RestClient

        flight.reset_all()
        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(5)
        port = srv.getsockname()[1]
        body = json.dumps({"kind": "Pod", "metadata": {"name": "p"}}).encode()
        resp = (b"HTTP/1.1 200 OK\r\nContent-Type: application/json\r\n"
                + b"Content-Length: %d\r\n\r\n%s" % (len(body), body))

        def serve():
            # first connection: slam shut before answering (transport error)
            c1, _ = srv.accept()
            c1.close()
            # second connection: one proper keep-alive response
            c2, _ = srv.accept()
            c2.recv(65536)
            c2.sendall(resp)
            c2.close()

        t = threading.Thread(target=serve, daemon=True)
        t.start()
        try:
            client = RestClient(ClusterConfig(host=f"http://127.0.0.1:{port}"))
            got = client.get(PODS, "ns", "p")
            assert got["metadata"]["name"] == "p"
        finally:
            srv.close()
        snap = flight.ACCOUNTING.snapshot()
        assert snap[("GET", "pods", 0)] == 1, snap
        assert snap[("GET", "pods", 200)] == 1, snap

    def test_rest_verbs_list_and_watch(self):
        from k8s_tpu.client.rest import _verb_and_resource

        def verb(method, path):
            return _verb_and_resource(method, path)[0]

        assert verb("GET", "/api/v1/namespaces/ns/pods") == "LIST"
        assert verb("GET", "/api/v1/namespaces/ns/pods/p") == "GET"
        assert verb("GET", "/api/v1/namespaces/ns/pods?watch=true") == "WATCH"
        assert verb("POST", "/api/v1/namespaces/ns/pods") == "POST"
        # LIST is decided by path SHAPE: an object legally named like its
        # plural is still a single-object GET, not a phantom LIST
        assert _verb_and_resource(
            "GET", "/api/v1/namespaces/ns/pods/pods") == ("GET", "pods")
        # cluster-scoped + group-scoped shapes
        assert _verb_and_resource("GET", "/api/v1/nodes") == ("LIST", "nodes")
        assert _verb_and_resource("GET", "/api/v1/nodes/n1") == ("GET", "nodes")
        assert _verb_and_resource(
            "GET", "/apis/kubeflow.org/v1alpha2/namespaces/ns/tfjobs"
        ) == ("LIST", "tfjobs")
        assert _verb_and_resource(
            "GET", "/api/v1/namespaces") == ("LIST", "namespaces")
        assert _verb_and_resource(
            "GET", "/api/v1/namespaces/ns") == ("GET", "namespaces")
        # a cluster-scoped object literally named "namespaces" (legal DNS
        # name for a node) is a single-object GET, not LIST namespaces
        assert _verb_and_resource(
            "GET", "/api/v1/nodes/namespaces") == ("GET", "nodes")
        # proxy-fronted apiserver: base path before the api root
        assert _verb_and_resource(
            "GET", "/k8s/clusters/c-abc/api/v1/namespaces/ns/pods"
        ) == ("LIST", "pods")

    def test_rolling_rate_window(self):
        acct = flight.CallAccounting()
        for _ in range(10):
            acct.record("GET", "pods", 200, 0.001)
        # all 10 calls landed within the horizon; a wide window sees them
        assert acct.rate(window_s=60) * 60 >= 9


# -- watch-stream health -----------------------------------------------------


class _DelegatingBackend:
    """FakeCluster wrapper with scriptable watch failures."""

    def __init__(self, inner):
        self.inner = inner
        self.expire_watches = 0  # raise 410 on the next N watch() calls
        self.scripted_watch = None  # one-shot canned watch object

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def watch(self, resource, namespace=None, resource_version=None):
        from k8s_tpu.client import errors

        if self.expire_watches > 0:
            self.expire_watches -= 1
            raise errors.expired("resourceVersion too old (scripted)")
        if self.scripted_watch is not None:
            w, self.scripted_watch = self.scripted_watch, None
            return w
        return self.inner.watch(resource, namespace, resource_version)


class _ScriptedWatch:
    def __init__(self, events):
        self._events = list(events)
        self.stopped = False

    def next(self, timeout=None):
        if self._events:
            return self._events.pop(0)
        self.stopped = True
        return None

    def stop(self):
        self.stopped = True


def _wait_for(pred, timeout=5.0, what="condition"):
    deadline = time.monotonic() + timeout
    while not pred():
        if time.monotonic() > deadline:
            raise AssertionError(f"timed out waiting for {what}")
        time.sleep(0.01)


class TestWatchHealth:
    def test_counters_through_forced_410(self):
        from k8s_tpu.client.informer import SharedInformer

        flight.reset_all()
        backend = _DelegatingBackend(FakeCluster())
        cs = Clientset(backend.inner)
        cs.pods("ns").create({"metadata": {"name": "p0"}})
        inf = SharedInformer(backend, PODS, resync_period=0)
        inf.run()
        try:
            assert inf.wait_for_cache_sync(5)
            assert flight.WATCH.relists(
                resource="pods", reason=flight.RELIST_INITIAL) == 1
            # a live stream exists and its age gauge is exposed
            _wait_for(lambda: "pods" in flight.WATCH.snapshot()["stream_age_s"],
                      what="live stream age")
            # force a 410 on the next watch open: end the current stream
            backend.expire_watches = 1
            with inf._watch_lock:
                inf._active_watch.stop()
            _wait_for(lambda: flight.WATCH.relists(
                resource="pods", reason=flight.RELIST_EXPIRED) == 1,
                what="410 relist")
            # the reflector recovered: restart counted, stream live again
            _wait_for(lambda: flight.WATCH.snapshot()["restarts"].get(
                "pods", 0) >= 1, what="watch restart counter")
            # events flow on the recovered stream
            cs.pods("ns").create({"metadata": {"name": "p1"}})
            _wait_for(lambda: flight.WATCH.snapshot()["events"].get(
                "pods/ADDED", 0) >= 1, what="ADDED event counter")
        finally:
            inf.stop()

    def test_stream_age_survives_a_sibling_informer_teardown(self):
        """Two informers on the SAME resource in one process (leader
        failover, embedded layouts): one reflector ending its stream must
        not pop the sibling's live entry — the age gauge refcounts open
        streams per resource and exposes the oldest."""
        wh = flight.WatchHealth()
        t1 = wh.stream_started("pods")
        time.sleep(0.02)
        t2 = wh.stream_started("pods")
        age_before = wh.labeled()["stream_age_s"]["pods"]
        wh.stream_ended("pods", t2)  # the NEWER sibling goes away
        ages = wh.labeled()["stream_age_s"]
        assert "pods" in ages  # the older live stream still shows
        assert ages["pods"] >= age_before  # and it IS the older one
        wh.stream_ended("pods", t1)
        assert "pods" not in wh.labeled()["stream_age_s"]

    def test_midstream_410_error_frame_counts_as_expired(self):
        from k8s_tpu.client.informer import SharedInformer

        flight.reset_all()
        backend = _DelegatingBackend(FakeCluster())
        backend.scripted_watch = _ScriptedWatch([("ERROR", {"code": 410})])
        inf = SharedInformer(backend, PODS, resync_period=0)
        inf.run()
        try:
            assert inf.wait_for_cache_sync(5)
            # the scripted first watch delivered a mid-stream 410 Status
            # frame; the reflector must relist attributing it to "410"
            _wait_for(lambda: flight.WATCH.relists(
                resource="pods", reason=flight.RELIST_EXPIRED) == 1,
                what="mid-stream 410 relist")
            assert flight.WATCH.snapshot()["events"].get("pods/ERROR") == 1
        finally:
            inf.stop()


# -- /debug/timeline endpoint parity -----------------------------------------


def _get(url):
    try:
        with urllib.request.urlopen(url) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


class TestTimelineEndpoint:
    def test_metrics_server_404_when_inactive_then_serves(self):
        from k8s_tpu.util.metrics_server import MetricsServer

        was_active = flight.TIMELINE.active
        flight.TIMELINE.deactivate()
        srv = MetricsServer(0).start()
        try:
            code, body = _get(
                f"http://127.0.0.1:{srv.port}/debug/timeline")
            assert code == 404
            assert "inactive" in body  # explicit body, not a route typo 404
            flight.TIMELINE.activate()
            flight.TIMELINE.clear()
            flight.timeline("ns/j1", "observed")
            flight.timeline("ns/j1", "condition", reason="TFJobCreated")
            flight.timeline("ns/j2", "observed")
            code, body = _get(
                f"http://127.0.0.1:{srv.port}/debug/timeline?job=ns/j1")
            assert code == 200
            payload = json.loads(body)
            assert payload["job"] == "ns/j1"
            kinds = [e["kind"] for e in payload["events"]]
            assert kinds == ["observed", "condition"]
            seqs = [e["seq"] for e in payload["events"]]
            assert seqs == sorted(seqs)
            # ?since= pagination from the advertised last_seq
            code, body = _get(
                f"http://127.0.0.1:{srv.port}/debug/timeline"
                f"?job=ns/j1&since={payload['last_seq']}")
            assert json.loads(body)["events"] == []
            # summary view lists both jobs + stats
            code, body = _get(
                f"http://127.0.0.1:{srv.port}/debug/timeline")
            summary = json.loads(body)
            assert set(summary["jobs"]) == {"ns/j1", "ns/j2"}
            assert summary["stats"]["jobs"] == 2
        finally:
            srv.stop()
            if was_active:
                flight.TIMELINE.activate()
            else:
                flight.TIMELINE.deactivate()

    def test_dashboard_serves_same_responder(self):
        from k8s_tpu.dashboard.backend import DashboardServer

        was_active = flight.TIMELINE.active
        flight.TIMELINE.deactivate()
        server = DashboardServer(Clientset(FakeCluster()),
                                 host="127.0.0.1", port=0)
        server.start_background()
        try:
            code, body = _get(
                f"http://127.0.0.1:{server.port}/debug/timeline")
            assert code == 404 and "inactive" in body
            flight.TIMELINE.activate()
            flight.TIMELINE.clear()
            flight.timeline("ns/j1", "observed")
            code, body = _get(
                f"http://127.0.0.1:{server.port}/debug/timeline?job=ns/j1")
            assert code == 200
            assert [e["kind"] for e in json.loads(body)["events"]] == [
                "observed"]
        finally:
            server.shutdown()
            if was_active:
                flight.TIMELINE.activate()
            else:
                flight.TIMELINE.deactivate()

    def test_flight_metric_families_exposed(self):
        from k8s_tpu.util import metrics as metrics_mod

        flight.reset_all()
        reg = metrics_mod.Registry()
        metrics_mod.flight_metrics(reg)
        flight.ACCOUNTING.record("GET", "pods", 200, 0.003)
        flight.WATCH.record_relist("pods", flight.RELIST_INITIAL)
        flight.EVENTS.record_recorded()
        text = reg.expose()
        assert ('apiserver_requests_total{verb="GET",resource="pods",'
                'code="200"} 1') in text
        assert 'watch_relists_total{resource="pods",reason="initial"} 1' in text
        assert "events_recorded_total 1" in text
        assert "apiserver_request_duration_seconds_count 1" in text


# -- event-recorder hot path (satellite: aggregation + counters) -------------


class TestEventRecorderAggregation:
    def test_exact_repeats_bump_count_not_new_objects(self):
        from k8s_tpu.client.gvr import EVENTS
        from k8s_tpu.client.record import AsyncEventRecorder

        flight.reset_all()
        fc = FakeCluster()
        rec = AsyncEventRecorder(Clientset(fc), "test-controller")
        involved = {"kind": "TFJob", "apiVersion": "kubeflow.org/v1alpha2",
                    "metadata": {"name": "j1", "namespace": "ns",
                                 "uid": "u1"}}
        try:
            for _ in range(3):
                rec.event(involved, "Normal", "Synced", "same message")
            rec.event(involved, "Normal", "Synced", "different message")
            assert rec.flush(5)
        finally:
            rec.close()
        events = list(fc.objects(EVENTS))
        by_msg = {e["message"]: e for e in events}
        # 3 identical sends -> ONE object with count 3; distinct messages
        # are never merged (the e2e harness parses pod names from them)
        assert len(events) == 2
        assert by_msg["same message"]["count"] == 3
        assert by_msg["different message"]["count"] == 1
        snap = flight.EVENTS.snapshot()
        assert snap["recorded"] == 4
        assert snap["aggregated"] == 2
        assert snap["dropped"] == 0

    def test_overflow_drops_are_counted_never_raised(self):
        from k8s_tpu.client.record import AsyncEventRecorder

        class TinyQueueRecorder(AsyncEventRecorder):
            QUEUE_SIZE = 1

        flight.reset_all()
        fc = FakeCluster()
        fc.create_delay_s = 0.3  # wedge the sink on its first post
        rec = TinyQueueRecorder(Clientset(fc), "test-controller")
        involved = {"kind": "TFJob",
                    "metadata": {"name": "j1", "namespace": "ns"}}
        try:
            for i in range(6):
                rec.event(involved, "Normal", "Spam", f"m{i}")
        finally:
            fc.create_delay_s = 0.0
            rec.close()
        snap = flight.EVENTS.snapshot()
        assert snap["dropped"] >= 1
        assert snap["recorded"] + snap["dropped"] == 6

    def test_events_land_on_the_involved_objects_timeline(self):
        from k8s_tpu.client.record import EventRecorder

        was_active = flight.TIMELINE.active
        flight.TIMELINE.activate()
        flight.TIMELINE.clear()
        try:
            rec = EventRecorder(Clientset(FakeCluster()), "test-controller")
            involved = {"kind": "TFJob",
                        "metadata": {"name": "j1", "namespace": "ns"}}
            rec.eventf(involved, "Warning", "FailedCreate", "boom %d", 7)
            entries = flight.TIMELINE.snapshot("ns/j1")
            assert [e["kind"] for e in entries] == ["event"]
            assert entries[0]["reason"] == "FailedCreate"
            assert entries[0]["message"] == "boom 7"
        finally:
            flight.TIMELINE.clear()
            if not was_active:
                flight.TIMELINE.deactivate()


# -- churn bench (smoke scale; the full 2-5k proof runs via --churn) ---------


class TestChurnBenchSmoke:
    def test_embedded_assertions_pass_at_smoke_scale(self):
        from k8s_tpu.harness.bench_operator import bench_churn

        r = bench_churn(jobs=24, fail_frac=0.25, steady_s=0.5,
                        resync_s=0.3, threadiness=2, timeout_s=60.0)
        assert r["steady_calls_per_sec_flat"] is True
        assert r["steady_half"]["lists"] == 0
        assert r["steady_full"]["lists"] == 0
        assert r["churn_events"] == 6
        assert r["churn_calls_per_event"] <= 40
        assert r["relists"] == {"nodes/initial": 1, "pods/initial": 1,
                                "services/initial": 1, "tfjobs/initial": 1}
        # the artifact carries the verb/resource breakdown + depth stats
        assert "POST pods" in r["apiserver_calls_by_verb_resource"]
        assert r["timeline_stats"]["jobs"] == 24
        # ordered lifecycle for a churned job: observed -> created ->
        # pods created -> running -> gang teardown -> recreate
        kinds = r["sample_timeline_kinds"]
        assert kinds[0] == "observed"
        assert "create_wave" in kinds and "delete_wave" in kinds
        assert kinds.index("delete_wave") > kinds.index("create_wave")

    def test_failed_assertions_still_write_the_artifact(self, tmp_path,
                                                        monkeypatch):
        """A churn regression in the non-gating CI tier must leave the
        measured numbers behind: the artifact is written WITH a failures
        field before the error propagates."""
        import argparse

        from k8s_tpu.harness import bench_operator

        def exploding_bench(**kw):
            err = RuntimeError("churn bench assertions failed:\n  boom")
            err.result = {"steady_full": {"calls_per_sec": 7.5},
                          "failures": ["boom"]}
            raise err

        monkeypatch.setattr(bench_operator, "bench_churn", exploding_bench)
        out = tmp_path / "bench_churn.json"
        args = argparse.Namespace(
            churn_jobs=8, churn_replicas=1, churn_fail_frac=0.25,
            churn_steady=0.5, churn_resync=0.3, churn_threadiness=1,
            churn_out=str(out), timeout=30)
        with pytest.raises(RuntimeError):
            bench_operator.run_churn(args)
        payload = json.loads(out.read_text())
        assert payload["failures"] == ["boom"]
        assert payload["value"] == 7.5

"""Conditions status engine (reference: pkg/controller.v2/controller_status.go).

Semantics kept from the reference:
- conditions CRUD preserves LastTransitionTime when status doesn't change
  (setCondition, controller_status.go:122-150);
- replica statuses are re-counted from pod phases each sync
  (initializeTFReplicaStatuses/updateTFJobReplicaStatuses, :93-119);
- StartTime set when all completion-deciding replicas run, CompletionTime +
  Succeeded when ``replicas - succeeded == 0``, Failed on any failed pod
  (updateStatus, :39-85).

TPU-native extension: the "completion-deciding" replica type is TPU when
present (the SPMD gang), falling back to Worker as in the reference, whose
updateStatus only inspected TFReplicaTypeWorker.
"""

from __future__ import annotations

from k8s_tpu import flight
from k8s_tpu.api.meta import now_rfc3339
from k8s_tpu.api.v1alpha2 import types

# Condition reasons (controller_status.go:27-36)
TFJOB_CREATED_REASON = "TFJobCreated"
TFJOB_SUCCEEDED_REASON = "TFJobSucceeded"
TFJOB_RUNNING_REASON = "TFJobRunning"
TFJOB_FAILED_REASON = "TFJobFailed"
TFJOB_RESTARTING_REASON = "TFJobRestarting"
# activeDeadlineSeconds failures (batch/v1 Job reason); load-bearing in the
# controller: set on the deadline path, matched on the terminal-cleanup path
TFJOB_DEADLINE_EXCEEDED_REASON = "DeadlineExceeded"
# Gang admission (ISSUE 4): Queued-condition reasons.  TFJobQueued — parked
# for capacity; Preempted — evicted by a higher-priority gang and requeued;
# Admitted — the Queued=False transition once the reservation lands.
TFJOB_QUEUED_REASON = "TFJobQueued"
TFJOB_PREEMPTED_REASON = "Preempted"
TFJOB_ADMITTED_REASON = "Admitted"
# Autoscale (ISSUE 13): a replica-count grow whose chip delta does not
# fit parks Queued=True with this reason — the gang keeps running at its
# reserved size (never partially placed) until capacity frees.
TFJOB_SCALE_UP_QUEUED_REASON = "ScaleUpQueued"


def new_condition(cond_type: str, reason: str, message: str) -> types.TFJobCondition:
    now = now_rfc3339()
    return types.TFJobCondition(
        type=cond_type,
        status=types.ConditionTrue,
        reason=reason,
        message=message,
        last_update_time=now,
        last_transition_time=now,
    )


def get_condition(status: types.TFJobStatus, cond_type: str):
    for c in status.conditions:
        if c.type == cond_type:
            return c
    return None


def filter_out_condition(conditions, cond_type: str):
    return [c for c in conditions if c.type != cond_type]


def set_condition(status: types.TFJobStatus, condition: types.TFJobCondition,
                  job: str | None = None) -> None:
    """setCondition with flight-recorder journaling: an ACTUAL transition
    (the no-change early return doesn't count) lands one ``condition``
    entry on ``job``'s lifecycle timeline when the caller passes the
    ``namespace/name`` key.  ``job=None`` keeps the pure-function contract
    for callers without one (tests, v1 compatibility)."""
    current = get_condition(status, condition.type)
    if (
        current is not None
        and current.status == condition.status
        and current.reason == condition.reason
    ):
        return
    if current is not None and current.status == condition.status:
        condition.last_transition_time = current.last_transition_time
    status.conditions = filter_out_condition(status.conditions, condition.type) + [condition]
    if job:
        flight.timeline(job, "condition", reason=condition.reason,
                        message=condition.message, type=condition.type,
                        status=condition.status)


def has_condition(status: types.TFJobStatus, cond_type: str) -> bool:
    c = get_condition(status, cond_type)
    return c is not None and c.status == types.ConditionTrue


def is_finished(status: types.TFJobStatus) -> bool:
    return has_condition(status, types.TFJobSucceeded) or has_condition(
        status, types.TFJobFailed
    )


def initialize_replica_statuses(tfjob: types.TFJob, rtype: str) -> None:
    """controller_status.go:98-105."""
    tfjob.status.tf_replica_statuses[rtype] = types.TFReplicaStatus()


def update_replica_statuses(tfjob: types.TFJob, rtype: str, pod: dict) -> None:
    """controller_status.go:108-119: count one pod's phase."""
    phase = (pod.get("status") or {}).get("phase")
    rs = tfjob.status.tf_replica_statuses[rtype]
    if phase == "Running":
        rs.active += 1
    elif phase == "Succeeded":
        rs.succeeded += 1
    elif phase == "Failed":
        rs.failed += 1


def completion_deciding_type(tfjob: types.TFJob) -> str:
    """TPU gang if present, else Worker (reference hardcoded Worker)."""
    if types.TFReplicaTypeTPU in tfjob.spec.tf_replica_specs:
        return types.TFReplicaTypeTPU
    return types.TFReplicaTypeWorker


def update_status(tfjob: types.TFJob, rtype: str, replicas: int) -> None:
    """updateStatus (controller_status.go:39-85) for one replica type."""
    rs = tfjob.status.tf_replica_statuses[rtype]
    expected = replicas - rs.succeeded
    running = rs.active
    failed = rs.failed
    name = tfjob.metadata.name
    # the ONE job-key definition: timelines written here must land under
    # the same key as those written from controller.py/pod.py
    from k8s_tpu.controller_v2.tpu_config import tfjob_key

    job_key = tfjob_key(tfjob)

    if rtype == completion_deciding_type(tfjob):
        if running == replicas and tfjob.status.start_time is None:
            tfjob.status.start_time = now_rfc3339()
        if running > 0:
            set_condition(
                tfjob.status,
                new_condition(
                    types.TFJobRunning, TFJOB_RUNNING_REASON, f"TFJob {name} is running."
                ),
                job=job_key,
            )
        if expected == 0:
            if tfjob.status.completion_time is None:
                tfjob.status.completion_time = now_rfc3339()
            set_condition(
                tfjob.status,
                new_condition(
                    types.TFJobSucceeded,
                    TFJOB_SUCCEEDED_REASON,
                    f"TFJob {name} is successfully completed.",
                ),
                job=job_key,
            )

    if failed > 0:
        set_condition(
            tfjob.status,
            new_condition(types.TFJobFailed, TFJOB_FAILED_REASON, f"TFJob {name} is failed."),
            job=job_key,
        )

"""Cluster-spec / bootstrap-env generation — the TPU-native replacement for
the TF_CONFIG generator (reference: pkg/controller.v2/controller_tensorflow.go
and controller_helper.go).

The reference emitted one env var, ``TF_CONFIG``, describing a gRPC
parameter-server cluster.  The SPMD world needs a different contract
(SURVEY.md §2.4, §5 "Distributed communication backend"):

- every participating process gets a **global process id** and the address of
  the **coordinator** (process 0) so the launcher can call
  ``jax.distributed.initialize(coordinator, num_processes, process_id)``;
- XLA collectives then run over ICI/DCN with no per-replica service mesh —
  only the coordinator's stable DNS name matters (though per-index headless
  services are still created for harness compatibility);
- slice topology travels as ``TPU_ACCELERATOR_TYPE``/``TPU_TOPOLOGY``, and
  multi-slice jobs get MEGASCALE slice ids for DCN setup.

``TPU_CONFIG`` (and a ``TF_CONFIG`` alias for legacy containers) keeps the
exact TF_CONFIG JSON shape — ``{"cluster": {type: [host:port]}, "task":
{type, index}}`` — so existing tooling and the e2e harness parse it unchanged
(cf. genTFConfigJSONStr, controller_tensorflow.go:63-86).

Everything here is a pure function of the TFJob, unit-testable like
TestClusterSpec (pkg/trainer/training_test.go:119).
"""

from __future__ import annotations

import json
from typing import Optional

from k8s_tpu.api.v1alpha2 import constants, types

# Pod label keys (reference: pkg/controller.v2/controller.go:66-74 and
# controller_helper.go:29-31).
LABEL_GROUP_NAME = "group_name"
LABEL_TFJOB_KEY = "tf_job_key"
LABEL_REPLICA_TYPE = "tf-replica-type"
LABEL_REPLICA_INDEX = "tf-replica-index"

# SPMD participants get JAX process ids, in this deterministic order so
# process 0 (the coordinator / chief) is stable across reconciles.  PS is a
# deleted concept (SURVEY.md §2.4) and Eval runs out-of-band; neither joins
# the jax.distributed world.
# prefill/decode (ISSUE 15) are appended LAST so adding the serving
# tiers never renumbers an existing topology's processes; each tier's
# pods are independent single-host servers, but listing them here
# routes their declared chip limits through the same per-role pricing
# walk every gang uses (chips_for_tfjob).
SPMD_TYPE_ORDER = ("chief", "master", "tpu", "tpu_worker", "worker",
                   "prefill", "decode")


class PortNotFoundError(ValueError):
    """controller_helper.go:36 errPortNotFound."""


def gen_labels(tfjob_key: str) -> dict[str, str]:
    """controller_helper.go:53-58."""
    return {
        LABEL_GROUP_NAME: "kubeflow.org",
        LABEL_TFJOB_KEY: tfjob_key.replace("/", "-"),
    }


def gen_general_name(tfjob_key: str, rtype: str, index) -> str:
    """controller_helper.go:60-63: '<ns>-<name>-<type>-<index>'."""
    return f"{tfjob_key}-{rtype}-{index}".replace("/", "-")


def gen_dns_record(tfjob_key: str, rtype: str, index, namespace: str) -> str:
    """controller_helper.go:65-67: pod DNS via its headless service."""
    return f"{gen_general_name(tfjob_key, rtype, index)}.{namespace}.svc.cluster.local"


def get_port_from_tfjob(tfjob: types.TFJob, rtype: str) -> int:
    """controller_helper.go:84-97: the tfjob-port of the tensorflow container."""
    spec = tfjob.spec.tf_replica_specs[rtype]
    for container in ((spec.template or {}).get("spec") or {}).get("containers") or []:
        if container.get("name") == constants.DEFAULT_CONTAINER_NAME:
            for port in container.get("ports") or []:
                if port.get("name") == constants.DEFAULT_PORT_NAME:
                    return int(port["containerPort"])
    raise PortNotFoundError(f"no {constants.DEFAULT_PORT_NAME} port on {rtype} container")


def tfjob_key(tfjob: types.TFJob) -> str:
    """cache.MetaNamespaceKeyFunc over the job: 'namespace/name'."""
    ns = tfjob.metadata.namespace
    return f"{ns}/{tfjob.metadata.name}" if ns else tfjob.metadata.name


def gen_cluster_spec(tfjob: types.TFJob) -> dict[str, list[str]]:
    """genClusterSpec (controller_tensorflow.go:89-115): map of replica type
    (lowercase) to '<dns>:<port>' lists."""
    key = tfjob_key(tfjob)
    cluster: dict[str, list[str]] = {}
    for rtype, spec in tfjob.spec.tf_replica_specs.items():
        rt = rtype.lower()
        port = get_port_from_tfjob(tfjob, rtype)
        cluster[rt] = [
            f"{gen_dns_record(key, rt, i, tfjob.metadata.namespace)}:{port}"
            for i in range(spec.replicas or 1)
        ]
    return cluster


def spmd_process_table(tfjob: types.TFJob) -> list[tuple[str, int, str]]:
    """Global process numbering for jax.distributed: ordered (rtype_lower,
    index, 'host:port') triples.  Process 0 is the coordinator."""
    key = tfjob_key(tfjob)
    table = []
    by_type = {rt.lower(): spec for rt, spec in tfjob.spec.tf_replica_specs.items()}
    for rt in SPMD_TYPE_ORDER:
        spec = by_type.get(rt)
        if spec is None:
            continue
        orig_rtype = next(r for r in tfjob.spec.tf_replica_specs if r.lower() == rt)
        port = get_port_from_tfjob(tfjob, orig_rtype)
        for i in range(spec.replicas or 1):
            host = f"{gen_dns_record(key, rt, i, tfjob.metadata.namespace)}:{port}"
            table.append((rt, i, host))
    return table


def tpu_chips_per_host(tfjob: types.TFJob, rtype: str) -> int:
    """TPU chips one replica pod of ``rtype`` consumes: the sum of its
    containers' ``cloud-tpus.google.com/*`` resource limits (the same
    limits validation requires on TPU gangs).  0 for CPU-only replicas."""
    spec = tfjob.spec.tf_replica_specs[rtype]
    chips = 0
    for container in ((spec.template or {}).get("spec") or {}).get("containers") or []:
        limits = ((container.get("resources") or {}).get("limits")) or {}
        for key, value in limits.items():
            if key.startswith(constants.TPU_RESOURCE_PREFIX):
                try:
                    chips += int(value)
                except (TypeError, ValueError):
                    continue
    return chips


def chips_for_tfjob(tfjob: types.TFJob) -> int:
    """Whole-job TPU chip demand — the gang-admission unit (ISSUE 4).

    Derived from ``spmd_process_table``: every SPMD participant is one
    slice host, and each host consumes its replica type's declared chip
    limit.  Multislice jobs are already flattened by the table (replicas
    spans all slices), so a 4x v5litepod-256 gang of 256 hosts at 4
    chips/host prices at 1024 chips.  Jobs with no TPU limits anywhere
    (CPU worker/PS topologies) price at 0 and bypass capacity arbitration.
    """
    by_rtype_lower = {rt.lower(): rt for rt in tfjob.spec.tf_replica_specs}
    per_host: dict[str, int] = {}
    total = 0
    for rt, _index, _host in spmd_process_table(tfjob):
        if rt not in per_host:
            per_host[rt] = tpu_chips_per_host(tfjob, by_rtype_lower[rt])
        total += per_host[rt]
    return total


def gen_tpu_config_json(tfjob: types.TFJob, rtype_lower: str, index) -> str:
    """TF_CONFIG-shaped JSON (genTFConfigJSONStr, controller_tensorflow.go:63-86)."""
    config = {
        "cluster": gen_cluster_spec(tfjob),
        "task": {"type": rtype_lower, "index": int(index)},
    }
    return json.dumps(config, sort_keys=True)


def gen_env_vars(tfjob: types.TFJob, rtype_lower: str, index) -> list[dict]:
    """The full env contract injected into a replica pod's containers
    (replaces the TF_CONFIG injection at controller_pod.go:129-147).

    Non-SPMD types (ps/eval) get only the legacy-shaped config vars; SPMD
    participants additionally get the jax.distributed bootstrap and TPU
    topology env consumed by ``k8s_tpu.launcher.bootstrap``.
    """
    index = int(index)
    config_json = gen_tpu_config_json(tfjob, rtype_lower, index)
    env: list[dict] = [
        {"name": constants.ENV_TPU_CONFIG, "value": config_json},
        {"name": "TF_CONFIG", "value": config_json},  # legacy containers
    ]

    table = spmd_process_table(tfjob)
    process_id: Optional[int] = None
    for pid, (rt, i, _host) in enumerate(table):
        if rt == rtype_lower and i == index:
            process_id = pid
            break
    if process_id is None:
        return env  # ps/eval: not a jax.distributed participant

    coordinator = table[0][2]
    same_type_hosts = [h.split(":")[0] for (rt, _i, h) in table if rt == rtype_lower]
    env += [
        {"name": constants.ENV_JAX_COORDINATOR_ADDRESS, "value": coordinator},
        {"name": constants.ENV_JAX_NUM_PROCESSES, "value": str(len(table))},
        {"name": constants.ENV_JAX_PROCESS_ID, "value": str(process_id)},
        {"name": constants.ENV_TPU_WORKER_ID, "value": str(index)},
        {"name": constants.ENV_TPU_WORKER_HOSTNAMES, "value": ",".join(same_type_hosts)},
    ]
    tpu = tfjob.spec.tpu
    if tpu is not None:
        if tpu.accelerator_type:
            env.append(
                {"name": constants.ENV_TPU_ACCELERATOR_TYPE, "value": tpu.accelerator_type}
            )
        if tpu.topology:
            env.append({"name": constants.ENV_TPU_TOPOLOGY, "value": tpu.topology})
        if tpu.num_slices > 1:
            # Proportional partition of same-type workers into slices keeps
            # every slice id in [0, num_slices) even when replicas is not
            # divisible by num_slices.
            replicas = len(same_type_hosts)
            slice_id = min(index * tpu.num_slices // max(replicas, 1), tpu.num_slices - 1)
            env += [
                {"name": constants.ENV_TPU_NUM_SLICES, "value": str(tpu.num_slices)},
                {"name": constants.ENV_TPU_SLICE_ID, "value": str(slice_id)},
            ]
    return env

"""TFJobController v2 (reference: pkg/controller.v2/controller.go).

Stateless reconciler: three informers (TFJobs unstructured, Pods, Services)
feed a rate-limited workqueue; workers sync one job key at a time.  The
expectations cache dedupes creates between a create call and its informer
echo (controller.go:417-436).

Feature restored from v1 that the reference's v2 had not re-grown
(SURVEY.md §1): gang scheduling — a PodDisruptionBudget with
``minAvailable = Σreplicas`` guarding the whole job (pkg/trainer/
training.go:450-511), default-on for jobs with a TPU gang since a partial
slice cannot initialize at all.
"""

from __future__ import annotations

import logging
import os
import threading
from k8s_tpu.analysis import checkedlock
import time

from k8s_tpu import fleet as fleet_mod
from k8s_tpu import flight
from k8s_tpu import router as router_mod
from k8s_tpu import scheduler as scheduler_mod
from k8s_tpu import trace
from k8s_tpu.api import register, validation
from k8s_tpu.api.meta import now_rfc3339
from k8s_tpu.api.v1alpha2 import types
from k8s_tpu.client import errors
from k8s_tpu.client.clientset import Clientset
from k8s_tpu.client.gvr import NODES, PODS, SERVICES, TFJOBS_V1ALPHA2
from k8s_tpu.client.informer import SharedInformerFactory, split_meta_namespace_key
from k8s_tpu.client.record import AsyncEventRecorder, EventRecorder  # noqa: F401 (EventRecorder is part of the module's injection surface)
from k8s_tpu.controller_v2 import pod as pod_mod
from k8s_tpu.controller_v2 import service as service_mod
from k8s_tpu.controller_v2 import status as status_mod
from k8s_tpu.controller_v2 import tpu_config
from k8s_tpu.controller_v2.control import RealPodControl, RealServiceControl
from k8s_tpu.controller_v2.expectations import new_controller_expectations
from k8s_tpu.util import metrics
from k8s_tpu.util.workqueue import new_rate_limiting_queue

log = logging.getLogger(__name__)

CONTROLLER_NAME = "tpu-job-controller-v2"


def cluster_chips_from_env() -> int | None:
    """K8S_TPU_CLUSTER_CHIPS: total TPU chips the gang-admission scheduler
    may reserve.  Unset/garbage -> None (capacity derived from node
    listings, else unlimited); 0 -> explicitly unlimited (admission off)."""
    raw = os.environ.get("K8S_TPU_CLUSTER_CHIPS", "")
    if not raw:
        return None
    try:
        n = int(raw)
    except ValueError:
        return None
    return n if n >= 0 else None


class TFJobController:
    def __init__(
        self,
        clientset: Clientset,
        informer_factory: SharedInformerFactory | None = None,
        enable_gang_scheduling: bool = True,
        pod_control=None,
        service_control=None,
        recorder=None,
        create_concurrency: int | None = None,
        delete_concurrency: int | None = None,
        cluster_chips: int | None = None,
        scheduler=None,
        fleet_scrape: bool | None = None,
        fleet_interval_s: float | None = None,
        autoscale: bool | None = None,
        autoscale_interval_s: float | None = None,
    ):
        self.clientset = clientset
        # async sink: recording is a buffered enqueue, not an API round trip
        # on the reconcile path (client-go EventBroadcaster architecture)
        self.recorder = recorder or AsyncEventRecorder(clientset, CONTROLLER_NAME)
        # create_concurrency: None -> shared env-sized pool
        # (K8S_TPU_CREATE_CONCURRENCY, default 16); 1 -> fully serial (the
        # bench baseline); n -> a dedicated pool this controller owns.
        # delete_concurrency mirrors it for the teardown fan-out
        # (K8S_TPU_DELETE_CONCURRENCY, falling back to the create knob).
        from k8s_tpu.controller_v2 import control as control_mod

        if (create_concurrency is None
                and control_mod.create_concurrency_from_env() == 1):
            # K8S_TPU_CREATE_CONCURRENCY=1 must mean the documented fully
            # serial behavior (inline creates AND serial replica types, for
            # bisecting), not a 1-wide thread pool with concurrent rtypes.
            create_concurrency = 1
        if delete_concurrency is None:
            if control_mod.delete_concurrency_from_env() == 1:
                delete_concurrency = 1  # env-pinned fully serial teardown
            elif create_concurrency == 1:
                # the explicit fully-serial constructor mode (bench baseline,
                # bisecting) covers teardown too
                delete_concurrency = 1
        self._owned_executors: list = []
        create_executor = "shared"
        delete_executor = "shared"
        if pod_control is None or service_control is None:
            # Only build dedicated pools when a Real*Control below will
            # actually submit to them — injected controls (tests) bring
            # their own creation/deletion behavior.
            if create_concurrency is not None:
                create_executor = control_mod.executor_for_concurrency(
                    create_concurrency)
                if create_executor is not None:
                    self._owned_executors.append(create_executor)
            if delete_concurrency is not None:
                delete_executor = control_mod.executor_for_concurrency(
                    delete_concurrency, kind="delete")
                if delete_executor is not None:
                    self._owned_executors.append(delete_executor)
        self.create_concurrency = create_concurrency
        self.delete_concurrency = delete_concurrency
        self.pod_control = pod_control or RealPodControl(
            clientset, self.recorder, executor=create_executor,
            delete_executor=delete_executor)
        self.service_control = service_control or RealServiceControl(
            clientset, self.recorder, executor=create_executor,
            delete_executor=delete_executor)
        self.expectations = new_controller_expectations()
        self.enable_gang_scheduling = enable_gang_scheduling
        # (namespace, pdb-name, job-uid) -> minAvailable last created/verified
        self._pdb_cache: dict = {}
        # job key -> ((uid, replica-count signature), priced chips):
        # the reserved-gang demand-drift check's memo (ISSUE 13)
        self._demand_cache: dict = {}
        self.queue = new_rate_limiting_queue()
        self.metrics = metrics.controller_metrics("v2")
        # Flight recorder (ISSUE 7): activate the per-job lifecycle journal
        # (/debug/timeline serves 404 until a controller does this) and
        # register the apiserver/watch/event metric families so /metrics
        # exports what flight.ACCOUNTING/WATCH/EVENTS have been counting.
        flight.TIMELINE.activate()
        metrics.flight_metrics()
        # Fleet telemetry plane (ISSUE 8): the families are registered
        # unconditionally (HELP/TYPE-only while no plane is active, like
        # any idle family); the plane itself is opt-in — fleet_scrape
        # None defers to K8S_TPU_FLEET_SCRAPE, default off.
        metrics.fleet_metrics()
        # Gang admission & capacity scheduler (ISSUE 4).  cluster_chips:
        # None -> K8S_TPU_CLUSTER_CHIPS, else derive from node allocatable
        # TPU resources per sync, else unlimited (admission off — the
        # compatibility default: the operator behaves exactly as before);
        # 0 -> explicitly unlimited; an injected ``scheduler`` wins (tests).
        if scheduler is not None:
            self.scheduler = scheduler
            self._capacity_pinned = True
        else:
            if cluster_chips is not None and cluster_chips < 0:
                # same contract as the env path: a negative knob is garbage,
                # not a secret admission-off switch (that is 0)
                log.warning("ignoring negative cluster_chips=%d",
                            cluster_chips)
                cluster_chips = None
            if cluster_chips is None:
                cluster_chips = cluster_chips_from_env()
            self.scheduler = scheduler_mod.GangScheduler(
                total_chips=cluster_chips or None)
            self._capacity_pinned = cluster_chips is not None
        scheduler_mod.set_active(self.scheduler)
        # Serializes tfjob.status mutation across concurrent per-replica-type
        # reconcile tasks (one lock per controller: workers sync different
        # jobs, so contention is bounded by the rtype fan-out width).
        self._status_lock = checkedlock.make_lock("controller_v2.status")
        # Per-replica-type fan-out pool: DISTINCT from the create pool — the
        # rtype tasks themselves submit create batches, and nesting both on
        # one saturated executor would deadlock.  Width 4 covers every valid
        # replica-type combination; serial mode (create_concurrency=1) skips
        # it entirely.  Lazily created on the first multi-type sync.
        self._rtype_executor = None
        self._rtype_executor_lock = checkedlock.make_lock("controller_v2.rtype_executor")

        self.service_reconciler = service_mod.ServiceReconciler(
            self.service_control, self.expectations, metrics=self.metrics,
            status_lock=self._status_lock,
        )

        factory = informer_factory or SharedInformerFactory(clientset.backend)
        self.factory = factory
        self.tfjob_informer = factory.informer_for(TFJOBS_V1ALPHA2)
        self.pod_informer = factory.informer_for(PODS)
        self.service_informer = factory.informer_for(SERVICES)
        self.node_informer = factory.informer_for(NODES)
        self.tfjob_lister = factory.lister_for(TFJOBS_V1ALPHA2)
        self.pod_lister = factory.lister_for(PODS)
        self.service_lister = factory.lister_for(SERVICES)
        self.node_lister = factory.lister_for(NODES)

        # Indexers (client-go cache.Indexers): pods/services for one job are
        # point lookups — owned objects by controller uid, plus the (tiny)
        # orphan set per namespace for adoption — instead of an O(all pods
        # in namespace) scan per sync, which was the 200-concurrent-job
        # scale wall (BASELINE.md).
        from k8s_tpu.client.informer import (
            ORPHAN_INDEX,
            OWNER_INDEX,
            index_by_controller_uid,
            index_orphans_by_namespace,
        )

        for informer in (self.pod_informer, self.service_informer):
            informer.store.add_index(OWNER_INDEX, index_by_controller_uid)
            informer.store.add_index(ORPHAN_INDEX, index_orphans_by_namespace)

        # node-condition awareness (SURVEY.md §7: exit-code-only preemption
        # classification is lossy; node taints/Ready conditions disambiguate)
        self.pod_reconciler = pod_mod.PodReconciler(
            self.pod_control, self.expectations, self.recorder,
            node_lister=self.node_lister,
            status_lock=self._status_lock, metrics=self.metrics,
        )

        # Fleet telemetry plane (ISSUE 8): scrape targets resolve from the
        # pod informer's STORE — plain cache reads, so steady-state
        # scraping adds zero apiserver calls (the PR 7 churn property is
        # preserved by construction; bench_operator --fleet asserts it).
        # SLO breaches land a flight-timeline event + a K8s Event through
        # the aggregating recorder via _fleet_breach_sink.
        if fleet_scrape is None:
            fleet_scrape = fleet_mod.scrape_enabled_from_env()
        self.fleet_plane = None
        if fleet_scrape:
            # dedicated store index: per-cycle discovery is a point query
            # over the scrapeable subset, not an O(all pods) scan
            from k8s_tpu.client.informer import (
                FLEET_SCRAPE_INDEX,
                FLEET_SCRAPE_KEY,
                index_fleet_scrape_pods,
            )

            self.pod_informer.store.add_index(FLEET_SCRAPE_INDEX,
                                              index_fleet_scrape_pods)
            self.fleet_plane = fleet_mod.FleetPlane(
                lambda: fleet_mod.targets_from_pods(
                    self.pod_informer.store.by_index(FLEET_SCRAPE_INDEX,
                                                     FLEET_SCRAPE_KEY)),
                interval_s=fleet_interval_s or fleet_mod.interval_from_env(),
                timeout_s=fleet_mod.timeout_from_env(),
                concurrency=fleet_mod.concurrency_from_env(),
                windows=fleet_mod.windows_from_env(),
                slo_rules=fleet_mod.rules_spec_from_env(),
                max_jobs=fleet_mod.max_jobs_from_env(),
            )
            self.fleet_plane.add_sink(self._fleet_breach_sink)
            fleet_mod.set_active(self.fleet_plane)

        # Metric-driven gang autoscaler (ISSUE 13): off by default via
        # K8S_TPU_AUTOSCALE; requires the fleet plane (its rollups are the
        # scaling signals).  Scale-up extends the job's chip reservation
        # through the gang scheduler BEFORE the spec is patched — or parks
        # the expansion Queued, never a partial placement; scale-down
        # drains the victim pods through the active router first.
        if autoscale is None:
            autoscale = router_mod.autoscale_enabled_from_env()
        self.autoscale_loop = None
        if autoscale:
            if self.fleet_plane is None:
                log.warning(
                    "K8S_TPU_AUTOSCALE is set but fleet scraping is off; "
                    "autoscaler disabled (enable K8S_TPU_FLEET_SCRAPE — "
                    "the rollups are its scaling signals)")
            else:
                from k8s_tpu.router.autoscale import (
                    autoscaler_kwargs_from_env,
                )

                self.autoscale_loop = router_mod.AutoscaleLoop(
                    router_mod.Autoscaler(lambda: self.fleet_plane,
                                          **autoscaler_kwargs_from_env()),
                    self._autoscale_jobs, self._autoscale_apply,
                    reserve_fn=self._autoscale_reserve,
                    drain_fn=self._autoscale_drain,
                    undrain_fn=self._autoscale_undrain,
                    event_fn=self._autoscale_event,
                    interval_s=(autoscale_interval_s
                                or router_mod.autoscale_interval_from_env()))

        # seam overridden by tests (controller_test.go updateStatusHandler)
        self.update_status_handler = self._update_tfjob_status

        self._wire_handlers()
        self._workers: list[threading.Thread] = []
        self._stop = threading.Event()

    # -- wiring --------------------------------------------------------------

    def _wire_handlers(self) -> None:
        self.tfjob_informer.add_event_handler(
            on_add=self._add_tfjob,
            on_update=lambda old, new: self.enqueue_key(self._key_of(new)),
            on_delete=self._delete_tfjob,
        )
        add_pod, update_pod, delete_pod = pod_mod.make_pod_event_handlers(self)
        self.pod_informer.add_event_handler(
            on_add=add_pod, on_update=update_pod, on_delete=delete_pod
        )
        add_svc, update_svc, delete_svc = service_mod.make_service_event_handlers(self)
        self.service_informer.add_event_handler(
            on_add=add_svc, on_update=update_svc, on_delete=delete_svc
        )

    @staticmethod
    def _key_of(obj: dict) -> str:
        from k8s_tpu.client.informer import meta_namespace_key

        return meta_namespace_key(obj)

    def _add_tfjob(self, obj: dict) -> None:
        key = self._key_of(obj)
        # timeline head: the job became visible to the control plane (fires
        # again after a relist — entries are cheap and the journal bounded)
        flight.timeline(key, "observed",
                        uid=(obj.get("metadata") or {}).get("uid", ""))
        self.enqueue_key(key)

    def _delete_tfjob(self, obj: dict) -> None:
        key = self._key_of(obj)
        meta = obj.get("metadata") or {}
        self._pdb_cache.pop(
            (meta.get("namespace", ""),
             f"tf-job-pdb-{meta.get('name', '')}", meta.get("uid", "")),
            None,
        )
        # The deleted object's spec may be unavailable (lister-miss path), so
        # sweep every known replica type rather than trusting the payload.
        rtypes = set((obj.get("spec") or {}).get("tfReplicaSpecs") or {})
        rtypes.update(types.VALID_REPLICA_TYPES)
        for rtype in rtypes:
            self.expectations.delete_expectations(
                pod_mod.gen_expectation_pods_key(key, rtype.lower())
            )
            self.expectations.delete_expectations(
                service_mod.gen_expectation_services_key(key, rtype.lower())
            )
        # deleted jobs keep nothing in the capacity scheduler: reservation,
        # queue entry, and preemption marker all go, and freed chips wake
        # the parked jobs that were waiting on them
        self._release_scheduler_key(key)
        self._demand_cache.pop(key, None)
        if self.fleet_plane is not None:
            # drop SLO rule state so a deleted job can't pin a stale
            # breach; its scrape targets vanish with its pods on the
            # next discovery pass
            self.fleet_plane.forget(key)
        if self.autoscale_loop is not None:
            # hysteresis/cooldown/parked state dies with the job
            self.autoscale_loop.autoscaler.forget(key)
        flight.timeline(key, "deleted")

    def enqueue_tfjob(self, tfjob) -> None:
        self.enqueue_key(tpu_config.tfjob_key(tfjob))

    def enqueue_key(self, key: str) -> None:
        self.queue.add(key)

    # -- run loop ------------------------------------------------------------


    def healthy(self) -> bool:
        """Liveness signal for /healthz: healthy before run() starts (a
        standby replica is alive), and, once running, while at least one
        worker thread is still processing the queue."""
        if not self._workers:
            return True
        return any(t.is_alive() for t in self._workers)

    def run(self, threadiness: int = 1, stop_event: threading.Event | None = None) -> None:
        """controller.go:245-284: start informers, wait for sync, run workers.
        Blocks until ``stop_event`` (or internal stop) fires."""
        stop = stop_event or self._stop
        log.info("Starting %s", CONTROLLER_NAME)
        self.factory.start()
        if not self.factory.wait_for_cache_sync(30):
            raise RuntimeError("failed to wait for caches to sync")
        for i in range(threadiness):
            t = threading.Thread(target=self._run_worker, daemon=True, name=f"worker-{i}")
            t.start()
            self._workers.append(t)
        if self.fleet_plane is not None:
            self.fleet_plane.start()
        if self.autoscale_loop is not None:
            self.autoscale_loop.start()
        stop.wait()
        self.shutdown()

    def start(self, threadiness: int = 1) -> None:
        """Non-blocking run (tests, embedding)."""
        self.factory.start()
        if not self.factory.wait_for_cache_sync(30):
            raise RuntimeError("failed to wait for caches to sync")
        for i in range(threadiness):
            t = threading.Thread(target=self._run_worker, daemon=True, name=f"worker-{i}")
            t.start()
            self._workers.append(t)
        if self.fleet_plane is not None:
            self.fleet_plane.start()
        if self.autoscale_loop is not None:
            self.autoscale_loop.start()

    def shutdown(self) -> None:
        self._stop.set()
        if self.autoscale_loop is not None:
            self.autoscale_loop.stop()
        if self.fleet_plane is not None:
            self.fleet_plane.stop()
        self.queue.shut_down()
        self.factory.stop()
        with self._rtype_executor_lock:
            if self._rtype_executor is not None:
                self._rtype_executor.shutdown(wait=False)
                self._rtype_executor = None
        for ex in self._owned_executors:
            ex.shutdown(wait=False)
        close = getattr(self.recorder, "close", None)
        if close:  # drain + terminate the async event sink
            close(timeout=5.0)

    def _run_worker(self) -> None:
        while self._process_next_work_item():
            pass

    def _process_next_work_item(self) -> bool:
        """controller.go:289-321."""
        key, shutdown = self.queue.get()
        if shutdown:
            return False
        # Sampled backlog gauge: one reading per work item keeps the gauge
        # fresh exactly when the queue is moving (an idle queue stays at its
        # last — correct — observation of 0).
        depth = getattr(self.queue, "depth", None)
        self.metrics["workqueue_depth"].labels(self.metrics["generation"]).set(
            depth() if depth is not None else len(self.queue))
        # pop_wait is best-effort (getattr: a custom queue may not track
        # waits); None just means this sync gets no queue_wait span
        pop_wait = getattr(self.queue, "pop_wait", None)
        wait_s = pop_wait(key) if pop_wait is not None else None
        with trace.span("sync_tfjob", job=key) as root:
            if wait_s is not None:
                trace.record_span("queue_wait", wait_s)
            try:
                forget = self.sync_tfjob(key)
                root.set_attribute("forget", forget)
                if forget:
                    self.queue.forget(key)
                else:
                    self.metrics["queue_retries"].labels(self.metrics["generation"]).inc()
                    self.queue.add_rate_limited(key)
            except Exception as e:
                # swallowed here (the worker loop must survive), so the
                # root span is marked by hand — tail sampling keeps it
                root.set_error(e)
                log.exception("error syncing tfjob %s", key)
                self.metrics["queue_retries"].labels(self.metrics["generation"]).inc()
                self.queue.add_rate_limited(key)
            finally:
                self.queue.done(key)
        return True

    # -- sync ----------------------------------------------------------------

    def sync_tfjob(self, key: str) -> bool:
        """syncTFJob (controller.go:336-373): returns True when the job was
        synced to completion of its expectations."""
        start = time.monotonic()
        result = "success"
        try:
            ns, name = split_meta_namespace_key(key)
            obj = self.tfjob_lister.get(ns, name)
            if obj is None:
                log.info("tfjob %s no longer exists", key)
                self._delete_tfjob({"metadata": {"namespace": ns, "name": name},
                                    "spec": {"tfReplicaSpecs": {}}})
                return True
            tfjob = register.tfjob_from_unstructured(obj)

            if not self.satisfied_expectations(tfjob):
                return False

            register.default_tfjob(tfjob)
            # Stash the as-observed status on the sync-local job object (not
            # the controller: workers sync different jobs concurrently).
            tfjob._observed_status = tfjob.status.to_dict()
            # Sync-scoped memo for get_pods_for_tfjob/get_services_for_tfjob:
            # guarantees the claim/adoption scan (plus its can_adopt GET)
            # runs at most once per sync no matter how many callers a sync
            # grows — today each path calls each getter once, so this is a
            # guard for future second callers, not a hot-path save.
            tfjob._sync_cache = {}
            try:
                validation.validate_v1alpha2_tfjob_spec(tfjob.spec)
            except validation.ValidationError as e:
                # Invalid specs fail terminally instead of hot-looping.
                status_mod.set_condition(
                    tfjob.status,
                    status_mod.new_condition(
                        types.TFJobFailed, status_mod.TFJOB_FAILED_REASON, str(e)
                    ),
                    job=key,
                )
                self.update_status_handler(tfjob)
                return True

            self.reconcile_tfjobs(tfjob)
            return True
        except Exception:
            result = "error"
            raise
        finally:
            elapsed = time.monotonic() - start
            gen = self.metrics["generation"]
            self.metrics["sync_duration"].labels(gen).observe(elapsed)
            self.metrics["sync_total"].labels(gen, result).inc()
            log.debug("finished syncing %s (%.3fs)", key, elapsed)

    def satisfied_expectations(self, tfjob) -> bool:
        """All replica types' pod AND service expectations must be satisfied.

        Deliberate fix over the reference (controller.go:417-436 ORs across
        keys): with OR, service ADD echoes arriving before pod echoes let a
        sync proceed against a stale pod lister and double-create the gang.
        """
        key = tpu_config.tfjob_key(tfjob)
        return all(
            self.expectations.satisfied(pod_mod.gen_expectation_pods_key(key, rt.lower()))
            and self.expectations.satisfied(
                service_mod.gen_expectation_services_key(key, rt.lower())
            )
            for rt in tfjob.spec.tf_replica_specs
        )

    def reconcile_tfjobs(self, tfjob) -> None:
        """reconcileTFJobs (controller.go:377-412)."""
        job_key = tpu_config.tfjob_key(tfjob)
        if status_mod.is_finished(tfjob.status):
            # Terminal jobs: optionally clean up pods per cleanPodPolicy
            # (upstream added the field right after this snapshot; the
            # default None keeps pods for log retrieval — the snapshot's
            # behavior); status still refreshed below.  The gang's chip
            # reservation is released first: capacity frees the moment the
            # job is terminal, not when its pods happen to be garbage
            # collected, and the freed chips wake the admission queue.
            self._release_scheduler_key(tpu_config.tfjob_key(tfjob))
            self._clean_up_terminal_pods(tfjob)
            self.update_status_handler(tfjob)
            return

        if self._deadline_exceeded(tfjob):
            # fail the job; the NEXT sync (woken by the status MODIFIED
            # event) takes the terminal path, where cleanPodPolicy runs.
            # Deadline crossings with no cluster events are caught by the
            # periodic resync (the reference's 15-30s backstop cadence).
            status_mod.set_condition(
                tfjob.status,
                status_mod.new_condition(
                    types.TFJobFailed,
                    status_mod.TFJOB_DEADLINE_EXCEEDED_REASON,
                    f"TFJob {tfjob.metadata.name} exceeded its "
                    f"activeDeadlineSeconds="
                    f"{tfjob.spec.active_deadline_seconds}.",
                ),
                job=job_key,
            )
            if tfjob.status.completion_time is None:
                tfjob.status.completion_time = now_rfc3339()
            self.recorder.eventf(
                tfjob.to_dict(), "Warning", "DeadlineExceeded",
                "Job ran for longer than activeDeadlineSeconds=%s",
                tfjob.spec.active_deadline_seconds)
            self.update_status_handler(tfjob)
            return

        if not status_mod.get_condition(tfjob.status, types.TFJobCreated):
            status_mod.set_condition(
                tfjob.status,
                status_mod.new_condition(
                    types.TFJobCreated,
                    status_mod.TFJOB_CREATED_REASON,
                    f"TFJob {tfjob.metadata.name} is created.",
                ),
                job=job_key,
            )

        # Gang admission (ISSUE 4): all-or-nothing — either the whole
        # slice's worth of chips is reserved and reconcile proceeds, or the
        # job parks in Queued with ZERO pods (the half-scheduled-gang
        # deadlock two multislice jobs racing for one pod's chips would
        # otherwise produce).  Runs before any pod/service listing or PDB
        # work: a parked job costs one scheduler lookup per sync.
        if not self._sync_admission(tfjob):
            return

        with trace.span("list_pods"):
            pods = self.get_pods_for_tfjob(tfjob)
        with trace.span("list_services"):
            services = self.get_services_for_tfjob(tfjob)

        if self.enable_gang_scheduling:
            self.sync_pdb(tfjob)

        self._reconcile_replica_types(tfjob, pods, services)

        # parked-scale-up clamp (ISSUE 13): reconcile ran at the
        # reservation-covered size; restore the spec'd count BEFORE the
        # status write, or update() would silently revert the patch
        clamp = getattr(tfjob, "_autoscale_clamp", None)
        if clamp is not None:
            clamp_rtype, clamp_orig = clamp
            tfjob.spec.tf_replica_specs[clamp_rtype].replicas = clamp_orig

        tfjob.status.last_reconcile_time = now_rfc3339()
        self.update_status_handler(tfjob)

    # -- gang admission & capacity scheduling (ISSUE 4) -----------------------

    def _maybe_derive_capacity(self) -> None:
        """No config knob pinned: derive total chips from the node informer's
        allocatable TPU resources, tracking node churn sync-to-sync.  Zero
        TPU-bearing nodes keeps the last known total (an informer hiccup
        must not flip the cluster to unlimited and mass-admit the queue) —
        or unlimited if none were ever seen, the compatibility default."""
        if self._capacity_pinned:
            return
        chips = scheduler_mod.chips_from_nodes(self.node_lister.list())
        if chips > 0:
            self.scheduler.set_total(chips)

    def _sync_admission(self, tfjob) -> bool:
        """The per-sync admission gate: True — the whole gang's chips are
        reserved (or capacity is unlimited) and reconcile proceeds; False —
        the job is parked with a Queued condition, zero pods, and its
        status written."""
        self._maybe_derive_capacity()
        sched = self.scheduler
        if sched.unlimited:
            return True
        key = tpu_config.tfjob_key(tfjob)
        reserved = sched.reserved_chips(key)
        if reserved is not None:
            # Reserved gang: cheap steady-state path UNLESS the spec's
            # demand drifted from the hold (an autoscale replica patch,
            # ISSUE 13) — then the reservation resizes gang-atomically.
            # A grow that does not fit keeps the job at its CURRENT size
            # with a Queued condition (the scale-up parks; the gang is
            # NEVER partially placed and never torn down for growing).
            # The priced demand is memoized per replica-count signature:
            # chips_for_tfjob walks the whole SPMD process table
            # (O(hosts) — 256 iterations for a multislice gang), and
            # the pre-drift fast path deliberately skipped that on
            # every steady sync; the O(#rtypes) signature keeps it
            # skipped until the counts actually change.
            chips = self._priced_demand(tfjob, key)
            if chips == reserved or chips <= 0:
                # demand returned to the reservation (a parked ask was
                # withdrawn / a manual edit reverted): the ScaleUpQueued
                # condition must not outlive the drift — the sync's
                # normal status write persists the flip
                self._clear_scale_up_queued(tfjob, key)
                return True
            decision = sched.resize(key, chips)
            if decision.admitted:
                flight.timeline(key, "resized", chips=chips,
                                was=reserved, reason=decision.reason)
                if decision.reason == "shrunk":
                    # freed chips wake the parked jobs immediately (the
                    # forget() path's contract)
                    for waiting in sched.waiting_keys():
                        self.enqueue_key(waiting)
                self._clear_scale_up_queued(tfjob, key)
                return True
            self._park_scale_up(tfjob, key, chips, reserved, decision)
            # keep servicing the RUNNING gang at its reserved size while
            # the expansion is parked: reconcile proceeds with the
            # scaled type clamped back to the count the reservation
            # covers (restored before the status write — the spec patch
            # must not be silently reverted), so pod repair/restart is
            # never frozen behind a parked scale-up
            return self._clamp_to_reservation(tfjob, reserved)
        chips = tpu_config.chips_for_tfjob(tfjob)
        priority = getattr(tfjob.spec, "priority", 0) or 0
        queue_name = (getattr(tfjob.spec, "queue", None)
                      or types.DEFAULT_SCHEDULING_QUEUE)
        # Reality wins over the ledger: a gang whose pods already run
        # (controller restart) re-adopts its reservation instead of being
        # parked — unless it was deliberately preempted this incarnation.
        running = status_mod.has_condition(tfjob.status, types.TFJobRunning)
        with trace.span("gang_admission", job=key, chips=chips,
                        priority=priority) as sp:
            decision = sched.sync_admit(key, chips, priority, queue_name,
                                        running=running)
            if not decision.admitted and decision.victims:
                decision = self._preempt_victims(
                    tfjob, key, chips, priority, queue_name, decision.victims)
            sp.set_attribute("decision", decision.reason)
            gen = self.metrics["generation"]
            self.metrics["queue_depth"].labels(gen).set(sched.queue_depth())
            if decision.admitted:
                if decision.newly_admitted:
                    self.metrics["admitted_total"].labels(gen).inc()
                    self.metrics["admission_wait"].labels(gen).observe(
                        decision.wait_s)
                    flight.timeline(key, "admitted", reason=decision.reason,
                                    chips=chips, priority=priority,
                                    wait_s=round(decision.wait_s, 3))
                    self._clear_queued_condition(tfjob, decision)
                return True
            self._park_queued(tfjob, key, chips, decision)
            return False

    def _preempt_victims(self, tfjob, key: str, chips: int, priority: int,
                         queue_name: str, victims: list[str]):
        """Seat this higher-priority job by evicting the scheduler-chosen
        victims: the scheduler atomically releases each victim exactly once
        and requeues it at its base priority; the woken victim's OWN next
        sync parks it and tears down its pods through the normal delete
        waves (teardown retries stay with the owner, and a gang already
        mid-teardown is never double-counted — the requeued entry holds no
        reservation and release is idempotent)."""
        with trace.span("preempt_victims", job=key, victims=len(victims)):
            decision = self.scheduler.preempt(key, chips, priority,
                                              queue_name, victims)
            if not decision.victims:
                return decision
            gen = self.metrics["generation"]
            self.metrics["preemptions_total"].labels(gen).inc(
                len(decision.victims))
            flight.timeline(key, "preempted_victims",
                            victims=list(decision.victims), chips=chips)
            for vkey in decision.victims:
                flight.timeline(vkey, "preempted", reason="Preempted",
                                by=key, priority=priority)
                ns, name = split_meta_namespace_key(vkey)
                vobj = self.tfjob_lister.get(ns, name)
                if vobj is not None:
                    self.recorder.eventf(
                        vobj, "Warning", "Preempted",
                        "Gang preempted by higher-priority TFJob %s "
                        "(priority %d); requeued", key, priority)
                self.enqueue_key(vkey)
            self.recorder.eventf(
                tfjob.to_dict(), "Normal", "PreemptedVictims",
                "Preempted %d lower-priority gang(s) to reserve %d chip(s)",
                len(decision.victims), chips)
            return decision

    def _clear_queued_condition(self, tfjob, decision) -> None:
        """A formerly-parked job was admitted: flip Queued to False (keeping
        the condition as history) and record how long it waited."""
        queued = status_mod.get_condition(tfjob.status, types.TFJobQueued)
        if queued is None or queued.status != types.ConditionTrue:
            return
        cond = status_mod.new_condition(
            types.TFJobQueued, status_mod.TFJOB_ADMITTED_REASON,
            f"gang admitted after {decision.wait_s:.1f}s in the queue")
        cond.status = types.ConditionFalse
        with self._status_lock:
            status_mod.set_condition(tfjob.status, cond,
                                     job=tpu_config.tfjob_key(tfjob))
        self.recorder.eventf(
            tfjob.to_dict(), "Normal", "GangAdmitted",
            "Admitted after %.1fs in the admission queue", decision.wait_s)

    def _park_queued(self, tfjob, key: str, chips: int, decision) -> None:
        """Park a job the capacity model cannot seat: Queued=True (with the
        preemption story when that is why), Running flipped False for
        evicted gangs, any remaining pods torn down (all-or-nothing — a
        parked job may not hold chips via leftover pods), status written."""
        preemptor = self.scheduler.preempted_by(key)
        if preemptor:
            reason = status_mod.TFJOB_PREEMPTED_REASON
            message = (f"gang preempted by {preemptor}; requeued waiting "
                       f"for {chips} TPU chip(s)")
        else:
            reason = status_mod.TFJOB_QUEUED_REASON
            message = (f"waiting for {chips} TPU chip(s): "
                       f"{decision.reason}")
        flight.timeline(key, "queued", reason=reason, message=message,
                        chips=chips)
        with self._status_lock:
            status_mod.set_condition(
                tfjob.status,
                status_mod.new_condition(types.TFJobQueued, reason, message),
                job=key)
            running = status_mod.get_condition(tfjob.status, types.TFJobRunning)
            if running is not None and running.status == types.ConditionTrue:
                cond = status_mod.new_condition(
                    types.TFJobRunning, reason,
                    "gang torn down; job is requeued")
                cond.status = types.ConditionFalse
                status_mod.set_condition(tfjob.status, cond, job=key)
        self._teardown_parked_pods(tfjob, key)
        self.update_status_handler(tfjob)

    def _teardown_parked_pods(self, tfjob, key: str) -> int:
        """Delete any pods a parked job still owns (only preemption victims
        ever have some) in bounded delete waves with the job's own
        expectation accounting.  raise_on_error=False: the parked status
        must still be written; failed slots are simply re-listed by the
        next sync of the still-parked job."""
        pods = [p for p in self.get_pods_for_tfjob(tfjob)
                if not (p.get("metadata") or {}).get("deletionTimestamp")]
        if not pods:
            return 0
        from k8s_tpu.controller_v2.control import run_delete_wave

        job_dict = tfjob.to_dict()
        by_type: dict[str, list] = {}
        for p in pods:
            rtype = ((p.get("metadata") or {}).get("labels") or {}).get(
                tpu_config.LABEL_REPLICA_TYPE)
            by_type.setdefault(rtype or "", []).append(p)
        deleted = 0
        for rtype, victims in by_type.items():
            exp_key = (pod_mod.gen_expectation_pods_key(key, rtype)
                       if rtype else None)
            names = [p["metadata"]["name"] for p in victims]
            deleted += run_delete_wave(
                self.expectations, exp_key,
                lambda lo, hi, names=names: self.pod_control.delete_pods_batch(
                    tfjob.metadata.namespace, names[lo:hi], job_dict),
                len(names), self.metrics, "pod",
                lambda i, names=names: f"pod {names[i]} (preemption teardown)",
                initial=getattr(self.pod_control, "delete_width", 1),
                raise_on_error=False,
                job=key,
            )
        if deleted:
            self.recorder.eventf(
                job_dict, "Normal", "PreemptionTeardown",
                "Deleted %d pod(s): gang preempted and requeued", deleted)
        return deleted

    def _priced_demand(self, tfjob, key: str) -> int:
        """chips_for_tfjob memoized per (uid, replica-count signature):
        the signature is O(#rtypes) to build, so steady syncs of a
        running gang skip the O(hosts) process-table walk exactly as
        the pre-ISSUE-13 fast path did."""
        sig = (tfjob.metadata.uid,
               tuple(sorted((rt, spec.replicas or 1)
                            for rt, spec in
                            tfjob.spec.tf_replica_specs.items())))
        cached = self._demand_cache.get(key)
        if cached is not None and cached[0] == sig:
            return cached[1]
        chips = tpu_config.chips_for_tfjob(tfjob)
        self._demand_cache[key] = (sig, chips)
        return chips

    def _park_scale_up(self, tfjob, key: str, chips: int, reserved: int,
                       decision) -> None:
        """A reserved gang's demand grew past available capacity: park
        the EXPANSION (Queued=True, reason ScaleUpQueued) while the gang
        keeps running at its reserved size — zero pods are torn down and
        zero new pods are placed (gang-atomic or nothing, ISSUE 13).
        Reconcile pauses for the job until the resize fits (capacity
        frees) or the spec's demand returns to the reservation; the
        autoscaler's reserve_fn gate makes this a backstop for manual
        ``kubectl``-style replica edits and races, not the normal path."""
        queued = status_mod.get_condition(tfjob.status, types.TFJobQueued)
        message = (f"scale-up to {chips} chip(s) parked: holding "
                   f"{reserved}, {decision.reason}")
        flight.timeline(key, "scale_up_parked", chips=chips,
                        reserved=reserved, reason=decision.reason)
        if queued is not None \
                and queued.status == types.ConditionTrue \
                and queued.reason == status_mod.TFJOB_SCALE_UP_QUEUED_REASON:
            return  # already parked; don't churn status writes
        with self._status_lock:
            status_mod.set_condition(
                tfjob.status,
                status_mod.new_condition(
                    types.TFJobQueued,
                    status_mod.TFJOB_SCALE_UP_QUEUED_REASON, message),
                job=key)
        self.recorder.eventf(
            tfjob.to_dict(), "Warning", "ScaleUpQueued",
            "Replica scale-up needs %d chip(s) (holding %d): %s",
            chips, reserved, decision.reason)
        self.update_status_handler(tfjob)

    def _clamp_to_reservation(self, tfjob, reserved: int) -> bool:
        """Find a replica count for the autoscaled type whose whole-job
        demand equals the chips actually reserved, mutate the SYNC-LOCAL
        spec to it, and stash the original so reconcile_tfjobs restores
        it before any status write.  False when no clamp reproduces the
        reservation (multi-type demand drift: reconcile pauses — the
        conservative pre-clamp behavior)."""
        auto = tfjob.spec.autoscale
        if auto is not None and auto.replica_type:
            candidates = [auto.replica_type]
        else:
            # manual-edit backstop: no declared autoscale type, so try
            # each TPU-bearing type as the one whose count drifted
            candidates = list(tfjob.spec.tf_replica_specs)
        for rtype in candidates:
            rspec = tfjob.spec.tf_replica_specs.get(rtype)
            if rspec is None:
                continue
            original = rspec.replicas or 1
            for r in range(original - 1, 0, -1):
                rspec.replicas = r
                if tpu_config.chips_for_tfjob(tfjob) == reserved:
                    tfjob._autoscale_clamp = (rtype, original)
                    return True
            rspec.replicas = original
        return False

    def _clear_scale_up_queued(self, tfjob, key: str) -> None:
        """A parked expansion finally fit (resize admitted): flip the
        ScaleUpQueued condition to False, keeping it as history."""
        queued = status_mod.get_condition(tfjob.status, types.TFJobQueued)
        if queued is None or queued.status != types.ConditionTrue \
                or queued.reason != status_mod.TFJOB_SCALE_UP_QUEUED_REASON:
            return
        cond = status_mod.new_condition(
            types.TFJobQueued, status_mod.TFJOB_ADMITTED_REASON,
            "parked scale-up admitted; reservation resized")
        cond.status = types.ConditionFalse
        with self._status_lock:
            status_mod.set_condition(tfjob.status, cond, job=key)

    # -- metric-driven gang autoscaler (ISSUE 13) -----------------------------

    def _autoscale_jobs(self):
        """Every autoscalable job's (key, current, min, max) — jobs with
        spec.autoscale bounds, read from the TFJob informer cache (zero
        apiserver calls, the fleet-discovery property)."""
        out = []
        for obj in self.tfjob_lister.list():
            spec = obj.get("spec") or {}
            bounds = spec.get("autoscale") or {}
            lo, hi = bounds.get("minReplicas"), bounds.get("maxReplicas")
            if not lo or not hi:
                continue
            rtype = bounds.get("replicaType") or types.TFReplicaTypeWorker
            rspec = (spec.get("tfReplicaSpecs") or {}).get(rtype)
            if rspec is None:
                continue
            status = obj.get("status") or {}
            if any(c.get("type") in (types.TFJobSucceeded, types.TFJobFailed)
                   and c.get("status") == types.ConditionTrue
                   for c in status.get("conditions") or []):
                continue  # terminal jobs scale nowhere
            meta = obj.get("metadata") or {}
            key = (f"{meta.get('namespace', '')}/{meta.get('name', '')}"
                   if meta.get("namespace") else meta.get("name", ""))
            try:
                out.append((key, int(rspec.get("replicas") or 1),
                            int(lo), int(hi)))
            except (TypeError, ValueError):
                continue  # validation rejects these; don't crash the loop
        return out

    def _autoscale_rtype(self, obj: dict) -> str:
        bounds = (obj.get("spec") or {}).get("autoscale") or {}
        return bounds.get("replicaType") or types.TFReplicaTypeWorker

    def _autoscale_reserve(self, job: str, target: int) -> bool:
        """Extend the job's chip reservation for a scale-up BEFORE the
        spec patch — the gang-atomic gate.  True also when capacity is
        unlimited or the job prices at zero chips (nothing to arbitrate);
        first admission of an unreserved job stays with sync_admit."""
        sched = self.scheduler
        if sched.unlimited:
            return True
        ns, name = split_meta_namespace_key(job)
        obj = self.tfjob_lister.get(ns, name)
        if obj is None:
            return False
        tfjob = register.tfjob_from_unstructured(obj)
        register.default_tfjob(tfjob)
        rtype = self._autoscale_rtype(obj)
        rspec = tfjob.spec.tf_replica_specs.get(rtype)
        if rspec is None:
            return False
        rspec.replicas = target
        chips = tpu_config.chips_for_tfjob(tfjob)
        if chips <= 0:
            return True
        if not sched.is_reserved(job):
            # not admitted yet: the patch is safe — sync_admit arbitrates
            # the whole (larger) gang before any pod exists
            return True
        return sched.resize(job, chips).admitted

    def _autoscale_victims(self, job: str, n_victims: int) -> list[str]:
        """The pods a scale-down will delete: the target replica type's
        highest indices (the reconcile contract — pods at index >=
        replicas are out of range)."""
        ns, name = split_meta_namespace_key(job)
        obj = self.tfjob_lister.get(ns, name)
        if obj is None:
            return []
        rtype = self._autoscale_rtype(obj).lower()
        indexed = []
        from k8s_tpu.client.informer import OWNER_INDEX

        uid = (obj.get("metadata") or {}).get("uid")
        for pod in self.pod_lister.by_index(OWNER_INDEX, uid):
            meta = pod.get("metadata") or {}
            labels = meta.get("labels") or {}
            if labels.get(tpu_config.LABEL_REPLICA_TYPE) != rtype:
                continue
            if meta.get("deletionTimestamp"):
                continue
            try:
                idx = int(labels.get(tpu_config.LABEL_REPLICA_INDEX, ""))
            except ValueError:
                continue
            indexed.append((idx, meta.get("name", "")))
        indexed.sort(reverse=True)
        return [name for _idx, name in indexed[:n_victims]]

    def _annotate_drain(self, job: str, pods: list[str],
                        value: str) -> None:
        """Stamp the router-drain annotation on victim pods — the
        CROSS-PROCESS half of the drain protocol: a router running as a
        companion Pod observes the annotation through its own informer
        cache (fleet discovery carries it) and stops placing onto the
        victims; the in-process router (bench/LocalCluster) is handled
        directly below."""
        ns, _name = split_meta_namespace_key(job)
        for pod in pods:
            try:
                self.clientset.pods(ns).patch(
                    pod, {"metadata": {"annotations": {
                        fleet_mod.discovery.ANNOTATION_ROUTER_DRAIN:
                        value}}})
            except errors.ApiError as e:
                # best-effort: a vanished pod needs no drain
                if not errors.is_not_found(e):
                    log.warning("autoscale: drain-annotating %s/%s "
                                "failed: %s", ns, pod, e)

    def _autoscale_drain(self, job: str, n_victims: int,
                         timeout_s: float = 10.0) -> bool:
        """Route the scale-down victims through the router BEFORE the
        patch that releases their chips: no new placements, in-flight
        requests finish.  The victims are drain-annotated (any
        companion-Pod router picks that up from its pod cache within a
        refresh interval) AND marked directly on the in-process router
        when one is active — only the latter's in-flight counts are
        observable here, so the wait covers it; a remote router gets
        the annotation lead time plus the victim pod's own SIGTERM
        grace."""
        victims = self._autoscale_victims(job, n_victims)
        for pod in victims:
            flight.timeline(job, "autoscale_drain", pod=pod)
        self._annotate_drain(job, victims, "1")
        rt = router_mod.active()
        if rt is None:
            return True
        for pod in victims:
            rt.set_draining(pod, True)
        deadline = time.monotonic() + timeout_s
        drained = True
        for pod in victims:
            while True:
                inflight = rt.backend_inflight(pod)
                if not inflight:  # 0 or unknown (already gone)
                    break
                if time.monotonic() >= deadline:
                    drained = False
                    break
                time.sleep(0.02)
        return drained

    def _autoscale_undrain(self, job: str) -> None:
        """Revert a drain whose spec patch failed: the victims must take
        traffic again instead of sitting refused behind an unshrunk
        spec — both the annotation (remote routers) and the in-process
        flag are cleared."""
        ns, name = split_meta_namespace_key(job)
        obj = self.tfjob_lister.get(ns, name)
        if obj is not None:
            rtype = self._autoscale_rtype(obj).lower()
            from k8s_tpu.client.informer import OWNER_INDEX

            uid = (obj.get("metadata") or {}).get("uid")
            annotated = [
                (p.get("metadata") or {}).get("name", "")
                for p in self.pod_lister.by_index(OWNER_INDEX, uid)
                if ((p.get("metadata") or {}).get("annotations") or {})
                .get(fleet_mod.discovery.ANNOTATION_ROUTER_DRAIN)
                and ((p.get("metadata") or {}).get("labels") or {})
                .get(tpu_config.LABEL_REPLICA_TYPE) == rtype
            ]
            self._annotate_drain(job, [p for p in annotated if p], "0")
        rt = router_mod.active()
        if rt is None:
            return
        for b in rt.backends():
            if b["draining"]:
                rt.set_draining(b["name"], False)

    def _autoscale_apply(self, job: str, target: int) -> bool:
        """Patch the serving TFJob's replica count (JSON merge patch —
        only the one field travels); the normal sync then creates or
        deletes the pods and resizes the reservation."""
        ns, name = split_meta_namespace_key(job)
        obj = self.tfjob_lister.get(ns, name)
        if obj is None:
            return False
        rtype = self._autoscale_rtype(obj)
        try:
            self.clientset.tfjobs_unstructured(
                ns, obj.get("apiVersion", "kubeflow.org/v1alpha2")).patch(
                name,
                {"spec": {"tfReplicaSpecs": {rtype: {"replicas": target}}}})
        except errors.ApiError as e:
            log.warning("autoscale: patching %s to %d replicas failed: %s",
                        job, target, e)
            return False
        flight.timeline(job, "autoscaled", replicas=target, rtype=rtype)
        self.enqueue_key(job)
        return True

    def _autoscale_event(self, job: str, kind: str, message: str) -> None:
        ns, name = split_meta_namespace_key(job)
        involved = self.tfjob_lister.get(ns, name)
        if involved is None:
            return
        etype = "Warning" if kind == "ScaleUpQueued" else "Normal"
        self.recorder.eventf(involved, etype, kind, "%s", message)

    # -- fleet telemetry plane (ISSUE 8) --------------------------------------

    def _fleet_breach_sink(self, job: str, rule, state: dict,
                           breached: bool) -> None:
        """SLO transition → flight-timeline entry + K8s Event (through the
        PR 7 aggregating recorder, so a flapping rule folds into one Event
        with a climbing count instead of an Event storm)."""
        burn_short = state.get("burn_short")
        burn_long = state.get("burn_long")

        def _fmt(v):
            return f"{v:.2f}" if isinstance(v, float) else "n/a"

        flight.timeline(
            job, "slo_breach" if breached else "slo_recovered",
            reason=rule.name,
            message=(f"burn short={_fmt(burn_short)} "
                     f"long={_fmt(burn_long)}"),
            burn_short=burn_short, burn_long=burn_long)
        ns, name = split_meta_namespace_key(job)
        involved = self.tfjob_lister.get(ns, name)
        if involved is None:
            return  # job gone from the cache: the timeline entry stands
        if breached:
            self.recorder.eventf(
                involved, "Warning", "SloBreach",
                "fleet SLO rule %s breached (burn short=%s long=%s)",
                rule.name, _fmt(burn_short), _fmt(burn_long))
        else:
            self.recorder.eventf(
                involved, "Normal", "SloRecovered",
                "fleet SLO rule %s recovered", rule.name)

    def _release_scheduler_key(self, key: str) -> None:
        """Drop every scheduler trace of a terminal/deleted job (reservation,
        queue entry, preemption marker) and, when chips actually freed, wake
        the parked jobs so their next sync can re-ask for capacity."""
        sched = self.scheduler
        if sched.unlimited:
            return
        freed = sched.forget(key)
        self.metrics["queue_depth"].labels(self.metrics["generation"]).set(
            sched.queue_depth())
        if freed:
            # recorded only when chips actually freed: forget() is
            # idempotent, so resyncs of a finished job don't spam the ring
            flight.timeline(key, "released", chips=freed)
            for waiting in sched.waiting_keys():
                self.enqueue_key(waiting)

    def _reconcile_replica_types(self, tfjob, pods, services) -> None:
        """Run the pod+service reconcile pair for every replica type —
        concurrently across types when there is more than one and the
        controller is not pinned serial.  Each type's pair stays ordered
        (pods before services, as the reference does), status mutation is
        serialized by the shared status lock, and the first task error
        re-raises so the sync retries."""
        items = list(tfjob.spec.tf_replica_specs.items())

        def _one(rtype, spec):
            self.pod_reconciler.reconcile(tfjob, pods, rtype, spec)
            self.service_reconciler.reconcile(tfjob, services, rtype, spec)

        executor = None
        if len(items) > 1 and self.create_concurrency != 1:
            executor = self._get_rtype_executor()
        if executor is None:  # single type, pinned serial, or shutting down
            for rtype, spec in items:
                _one(rtype, spec)
            return

        # Each task carries its own copy of the calling context so the
        # per-replica-type spans parent under this sync's root span (a
        # shared Context copy cannot be entered concurrently).
        futures = [
            executor.submit(
                trace.bind_current_context(_one) if trace.enabled() else _one,
                rtype, spec)
            for rtype, spec in items
        ]
        first_error = None
        for (rtype, _spec), f in zip(items, futures):
            try:
                f.result()
            except Exception as e:  # noqa: BLE001 - collected, first re-raised
                if first_error is None:
                    first_error = e
                else:
                    # the sync retry only carries the first error; keep the
                    # rest visible instead of vanishing them
                    log.warning("reconcile of %s also failed: %s", rtype, e)
        if first_error is not None:
            raise first_error

    def _get_rtype_executor(self):
        from concurrent.futures import ThreadPoolExecutor

        with self._rtype_executor_lock:
            # shutdown() nulls the pool under this lock AFTER setting _stop:
            # an in-flight sync racing it must not lazily recreate a pool
            # nobody will ever shut down — it falls back to serial instead.
            if self._stop.is_set():
                return None
            if self._rtype_executor is None:
                self._rtype_executor = ThreadPoolExecutor(
                    max_workers=4, thread_name_prefix="rtype-reconcile")
            return self._rtype_executor

    @staticmethod
    def _deadline_exceeded(tfjob) -> bool:
        """activeDeadlineSeconds: wall clock since StartTime (set when all
        replicas first run, controller_status.go:45-50 semantics)."""
        import datetime

        from k8s_tpu.api.meta import parse_rfc3339

        deadline = tfjob.spec.active_deadline_seconds
        if not deadline:
            return False
        start = parse_rfc3339(tfjob.status.start_time)
        if start is None:
            return False
        elapsed = (datetime.datetime.now(datetime.timezone.utc)
                   - start).total_seconds()
        return elapsed > deadline

    def _clean_up_terminal_pods(self, tfjob) -> None:
        """cleanPodPolicy for finished jobs: "All" deletes the whole gang
        AND its headless services (which otherwise leak forever — nothing
        else ever deletes them while the job object is kept), "Running"
        only pods still running (PS-style replicas that never exit on
        their own), None/"None" keeps everything.  Deletions go through
        the control batch APIs in bounded-concurrency waves with
        expectations accounting, exactly like a gang restart, so the
        informer feedback loop stays consistent."""
        policy = tfjob.spec.clean_pod_policy or types.CleanPodPolicyNone
        if policy == types.CleanPodPolicyNone:
            # batch/v1 Job semantics for wall-clock budgets: a job failed
            # for DeadlineExceeded must actually stop consuming the gang's
            # TPUs, even under the keep-for-logs default — escalate to
            # "Running" (running pods terminated, exited pods kept for
            # logs).  Without this the deadline would mark the job Failed
            # and leave the whole gang training forever.
            failed = status_mod.get_condition(tfjob.status, types.TFJobFailed)
            if (failed is not None and failed.reason ==
                    status_mod.TFJOB_DEADLINE_EXCEEDED_REASON
                    and failed.status == types.ConditionTrue):
                policy = types.CleanPodPolicyRunning
                escalated = True
            else:
                return
        else:
            escalated = False
        pods = self.get_pods_for_tfjob(tfjob)
        key = tpu_config.tfjob_key(tfjob)
        job_dict = tfjob.to_dict()
        by_type: dict[str, list] = {}
        for p in pods:
            phase = (p.get("status") or {}).get("phase")
            if policy == types.CleanPodPolicyRunning and phase != "Running":
                continue
            if (p.get("metadata") or {}).get("deletionTimestamp"):
                continue  # already being deleted
            rtype = ((p.get("metadata") or {}).get("labels") or {}).get(
                tpu_config.LABEL_REPLICA_TYPE)
            by_type.setdefault(rtype or "", []).append(p)
        from k8s_tpu.controller_v2.control import run_delete_wave

        deleted = 0
        for rtype, victims in by_type.items():
            exp_key = (pod_mod.gen_expectation_pods_key(key, rtype)
                       if rtype else None)
            names = [p["metadata"]["name"] for p in victims]
            # raise_on_error=False: the terminal path must still write
            # status this sync; failed slots are unwound inside the wave
            # (no DELETE event will decrement them) and the pods are simply
            # re-listed by the next sync of the still-terminal job.
            deleted += run_delete_wave(
                self.expectations, exp_key,
                lambda lo, hi, names=names: self.pod_control.delete_pods_batch(
                    tfjob.metadata.namespace, names[lo:hi], job_dict),
                len(names), self.metrics, "pod",
                lambda i, names=names: f"pod {names[i]} (cleanPodPolicy)",
                initial=getattr(self.pod_control, "delete_width", 1),
                raise_on_error=False,
                job=key,
            )
        svc_deleted = self._clean_up_terminal_services(tfjob, policy, key,
                                                       job_dict)
        if svc_deleted:
            self.recorder.eventf(
                job_dict, "Normal", "CleanPodPolicy",
                "Deleted %d service(s) of finished TFJob per "
                "cleanPodPolicy=All", svc_deleted)
        if deleted:
            if escalated:
                # the spec never set Running — say why pods vanished under
                # the keep-for-logs default instead of naming a policy the
                # user didn't write
                self.recorder.eventf(
                    job_dict, "Normal", "CleanPodPolicy",
                    "Terminated %d running pod(s): activeDeadlineSeconds "
                    "exceeded (cleanPodPolicy unset; exited pods kept)",
                    deleted)
            else:
                self.recorder.eventf(
                    job_dict, "Normal", "CleanPodPolicy",
                    "Deleted %d pod(s) of finished TFJob per "
                    "cleanPodPolicy=%s", deleted, policy)

    def _clean_up_terminal_services(self, tfjob, policy, key: str,
                                    job_dict: dict) -> int:
        """Under cleanPodPolicy=All a finished job keeps nothing — including
        its per-index headless services, which the old pod-only cleanup
        leaked forever.  Scoped to the explicit "All" policy: "Running"
        (and the DeadlineExceeded escalation to it) keeps exited pods for
        logs, and their DNS names stay resolvable with them."""
        if policy != types.CleanPodPolicyAll:
            return 0
        from k8s_tpu.controller_v2.control import run_delete_wave

        by_type: dict[str, list] = {}
        for s in self.get_services_for_tfjob(tfjob):
            if (s.get("metadata") or {}).get("deletionTimestamp"):
                continue  # already being deleted
            rtype = ((s.get("metadata") or {}).get("labels") or {}).get(
                tpu_config.LABEL_REPLICA_TYPE)
            by_type.setdefault(rtype or "", []).append(s)
        deleted = 0
        for rtype, victims in by_type.items():
            exp_key = (service_mod.gen_expectation_services_key(key, rtype)
                       if rtype else None)
            names = [s["metadata"]["name"] for s in victims]
            deleted += run_delete_wave(
                self.expectations, exp_key,
                lambda lo, hi, names=names:
                    self.service_control.delete_services_batch(
                        tfjob.metadata.namespace, names[lo:hi], job_dict),
                len(names), self.metrics, "service",
                lambda i, names=names: f"service {names[i]} (cleanPodPolicy)",
                initial=getattr(self.service_control, "delete_width", 1),
                raise_on_error=False,
                job=key,
            )
        return deleted

    @staticmethod
    def _status_changed(observed: dict | None, current: dict) -> bool:
        """Ignore last_reconcile_time: writing a bare timestamp would emit a
        MODIFIED event that re-enqueues the job, and the resulting write →
        event → sync → write cycle busy-loops every running job."""
        if observed is None:
            return True
        a = {k: v for k, v in observed.items() if k != "lastReconcileTime"}
        b = {k: v for k, v in current.items() if k != "lastReconcileTime"}
        return a != b

    def _update_tfjob_status(self, tfjob) -> None:
        """updateTFJobStatus (controller_status.go:88-91), writing only when
        the status materially changed since this sync observed it."""
        if not self._status_changed(
            getattr(tfjob, "_observed_status", None), tfjob.status.to_dict()
        ):
            return
        try:
            self.clientset.tfjobs(tfjob.metadata.namespace, tfjob.api_version).update(tfjob)
        except errors.ApiError as e:
            if errors.is_conflict(e):
                # A newer version exists; the enqueued update event resyncs.
                log.info("status update conflict for %s", tfjob.metadata.name)
            else:
                raise

    # -- adoption ------------------------------------------------------------

    def resolve_controller_ref(self, namespace: str, ref: dict):
        """controller.go:441-457.

        Reads the cached object WITHOUT the lister's defensive copy: every
        pod/service event resolves its owner, so this is the hottest read
        in the controller, and its callers only derive the enqueue key /
        expectation key from the result (read-only by contract — the
        mutation seam is sync_tfjob's ``lister.get``)."""
        if ref.get("kind") != "TFJob":
            return None
        key = f"{namespace}/{ref.get('name', '')}" if namespace else ref.get("name", "")
        obj = self.tfjob_informer.store.get_by_key(key)
        if obj is None:
            return None
        tfjob = register.tfjob_from_unstructured(obj)
        if tfjob.metadata.uid != ref.get("uid"):
            return None
        return tfjob

    def _claim_manager_args(self, tfjob):
        key = tpu_config.tfjob_key(tfjob)
        selector = tpu_config.gen_labels(key)

        def can_adopt():
            fresh = self.clientset.tfjobs(
                tfjob.metadata.namespace, tfjob.api_version
            ).get(tfjob.metadata.name)
            if fresh.metadata.uid != tfjob.metadata.uid:
                raise RuntimeError(
                    f"original TFJob {key} is gone: got uid {fresh.metadata.uid}, "
                    f"wanted {tfjob.metadata.uid}"
                )

        return selector, can_adopt

    def _claim_candidates(self, lister, tfjob) -> list[dict]:
        """Owned objects (owner-uid index) + same-namespace orphans (orphan
        index) — the only objects claim_* can possibly keep or adopt.
        Objects owned by OTHER controllers are excluded by construction,
        exactly as the ref manager would skip them after an O(N) scan."""
        from k8s_tpu.client.informer import ORPHAN_INDEX, OWNER_INDEX

        ns = tfjob.metadata.namespace
        # OWNER_INDEX is keyed by uid alone, so filter by namespace here: a
        # cross-namespace object carrying this uid must not be counted as
        # part of the gang.  ORPHAN_INDEX keys ARE namespaces, so its
        # results need no further filtering.
        owned = [
            o for o in lister.by_index(OWNER_INDEX, tfjob.metadata.uid)
            if (o.get("metadata") or {}).get("namespace") == ns
        ]
        return owned + lister.by_index(ORPHAN_INDEX, ns)

    @staticmethod
    def _sync_cached(tfjob, kind: str, compute):
        """Memoize one claim scan on the sync-local job object.  The cache
        only exists while sync_tfjob owns the object (set right after
        conversion), so a stale list can never outlive its sync."""
        cache = getattr(tfjob, "_sync_cache", None)
        if cache is None:
            return compute()
        if kind not in cache:
            cache[kind] = compute()
        return cache[kind]

    def get_pods_for_tfjob(self, tfjob) -> list[dict]:
        """getPodsForTFJob (controller_pod.go:174-210), memoized per sync."""

        def _compute():
            from k8s_tpu.controller_v2.ref_manager import PodControllerRefManager

            selector, can_adopt = self._claim_manager_args(tfjob)
            pods = self._claim_candidates(self.pod_lister, tfjob)
            manager = PodControllerRefManager(
                self.pod_control, tfjob.to_dict(), selector, "TFJob",
                tfjob.api_version, can_adopt,
            )
            return manager.claim_pods(pods)

        return self._sync_cached(tfjob, "pods", _compute)

    def get_services_for_tfjob(self, tfjob) -> list[dict]:
        """getServicesForTFJob (controller_service.go:154-190), memoized per
        sync."""

        def _compute():
            from k8s_tpu.controller_v2.ref_manager import ServiceControllerRefManager

            selector, can_adopt = self._claim_manager_args(tfjob)
            services = self._claim_candidates(self.service_lister, tfjob)
            manager = ServiceControllerRefManager(
                self.service_control, tfjob.to_dict(), selector, "TFJob",
                tfjob.api_version, can_adopt,
            )
            return manager.claim_services(services)

        return self._sync_cached(tfjob, "services", _compute)

    # -- gang scheduling (restored v1 feature; pkg/trainer/training.go:450-511)

    def sync_pdb(self, tfjob) -> None:
        total = sum(
            (spec.replicas or 1) for spec in tfjob.spec.tf_replica_specs.values()
        )
        if total <= 1:
            return
        from k8s_tpu.api import helpers

        key = tpu_config.tfjob_key(tfjob)
        name = f"tf-job-pdb-{tfjob.metadata.name}"
        # Lister-style cache: once this controller has created/verified the
        # job's PDB at this minAvailable, later reconciles skip the GET
        # (measured: 3 PDB GETs per job on the wire bench hot path — the
        # client-go analogue reads its informer cache here, not the API).
        # Invalidated on job deletion; an externally-deleted PDB is restored
        # on the next controller restart or cache miss, matching the
        # reference's informer-backed staleness window.
        cache_key = (tfjob.metadata.namespace, name, tfjob.metadata.uid)
        if self._pdb_cache.get(cache_key) == total:
            return
        pdbs = self.clientset.pdbs(tfjob.metadata.namespace)
        # Optimistic create-first: a cache miss is almost always a NEW job
        # (one per job on the wire bench), so GET-before-create pays a
        # guaranteed 404 round-trip on the hot path; the already-exists
        # fallback below verifies minAvailable for the rare restart/race
        # case, paying one extra (rejected) POST there relative to the old
        # GET-first order.
        pdb = {
            "metadata": {
                "name": name,
                "ownerReferences": [helpers.as_owner(tfjob).to_dict()],
            },
            "spec": {
                "minAvailable": total,
                "selector": {"matchLabels": tpu_config.gen_labels(key)},
            },
        }
        try:
            pdbs.create(pdb)
        except errors.ApiError as e:
            if not errors.is_already_exists(e):
                raise
            # Lost the create race OR a stale PDB from a prior incarnation
            # exists: VERIFY its minAvailable before caching — caching
            # blindly would pin a wrong gang floor until restart.
            existing = pdbs.get(name)
            if (existing.get("spec") or {}).get("minAvailable") != total:
                pdbs.patch(name, {"spec": {"minAvailable": total}})
            self._pdb_cache[cache_key] = total
            return
        self._pdb_cache[cache_key] = total
        self.recorder.eventf(
            tfjob.to_dict(), "Normal", "SuccessfulCreatePdb",
            "Created PDB %s (minAvailable=%d) for gang scheduling", name, total,
        )

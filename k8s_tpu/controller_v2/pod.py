"""Pod reconciliation (reference: pkg/controller.v2/controller_pod.go).

Kept from the reference: the index-label slice pattern (getPodSlices,
controller_pod.go:77-96), expectations bookkeeping before creates
(:99-169), label/env injection, and informer event handlers (:237-322).

TPU-native departures (SURVEY.md §7 "hard parts", designed deliberately):

1. **Whole-gang restart.** A TPU slice is all-or-nothing: jax.distributed
   blocks until every process joins, so the reference's "recreate one failed
   index pod" (controller_pod.go:60-65) would deadlock the survivors against
   a fresh process with no coordinator state.  For SPMD gang types (TPU), any
   retryable pod failure triggers deletion of the *whole* gang, which then
   restarts together under gang scheduling.
2. **Operator-managed restarts.** Gang pods always run with pod-level
   RestartPolicy=Never; Always/OnFailure/ExitCode semantics are implemented
   at the operator level (the reference left ExitCode enforcement TODO at
   controller_pod.go:149).  Kubelet in-place container restarts would rejoin
   a dead jax.distributed world.
3. Exit-code classification (pkg/util/train/train_util.go policy) decides
   retryable vs permanent, with TPU preemption (SIGTERM/143) retryable.
"""

from __future__ import annotations

import copy
import logging
from k8s_tpu.analysis import checkedlock
import time

from k8s_tpu.api.v1alpha2 import types
from k8s_tpu.controller_v2 import status as status_mod
from k8s_tpu.controller_v2 import tpu_config
from k8s_tpu.util import train_util

log = logging.getLogger(__name__)

SPMD_GANG_TYPES = {types.TFReplicaTypeTPU}

# Stamped onto every pod created inside a traced sync: the trace id of the
# sync_tfjob span whose create wave produced it (ISSUE 2 — lets apiserver
# audit entries and kubelet logs be joined back to the operator's span tree).
TRACE_ID_ANNOTATION = "kubeflow.org/trace-id"


def gen_expectation_pods_key(tfjob_key: str, replica_type: str) -> str:
    """controller_pod.go:212-214."""
    return f"{tfjob_key}/{replica_type.lower()}/pods"


def filter_pods_for_replica_type(pods: list[dict], rt_lower: str) -> list[dict]:
    """controller_pod.go:213-231."""
    return [
        p
        for p in pods
        if ((p.get("metadata") or {}).get("labels") or {}).get(
            tpu_config.LABEL_REPLICA_TYPE
        )
        == rt_lower
    ]


def get_pod_slices(pods: list[dict], replicas: int) -> list[list[dict]]:
    """controller_pod.go:77-96: bucket pods by their index label."""
    slices: list[list[dict]] = [[] for _ in range(replicas)]
    for pod in pods:
        labels = (pod.get("metadata") or {}).get("labels") or {}
        if tpu_config.LABEL_REPLICA_INDEX not in labels:
            log.warning("pod %s has no index label", pod.get("metadata", {}).get("name"))
            continue
        try:
            index = int(labels[tpu_config.LABEL_REPLICA_INDEX])
        except ValueError:
            log.warning("bad index label on pod %s", pod.get("metadata", {}).get("name"))
            continue
        if 0 <= index < replicas:
            slices[index].append(pod)
        else:
            log.warning("pod index %d out of range [0,%d)", index, replicas)
    return slices


def tensorflow_exit_code(pod: dict):
    """Exit code of the terminated `tensorflow` container, or None
    (cf. pkg/trainer/replicas.go:326-362 state derivation)."""
    for cs in ((pod.get("status") or {}).get("containerStatuses")) or []:
        if cs.get("name") != "tensorflow":
            continue
        term = (cs.get("state") or {}).get("terminated")
        if term is not None and "exitCode" in term:
            return int(term["exitCode"])
    return None


# Node signals that mean "this machine is going away / gone" rather than
# "the workload crashed".  TPU preemptions and maintenance events surface
# through these before-or-alongside the pod's own failure, and SURVEY.md §7
# calls exit-code-only classification lossy: a preempted worker can die with
# any code (137 OOM-looking, 1, or none at all if the kubelet vanished).
PREEMPTION_TAINT_KEYS = frozenset({
    "cloud.google.com/impending-node-termination",
    "ToBeDeletedByClusterAutoscaler",
    "DeletionCandidateOfClusterAutoscaler",
    "node.kubernetes.io/unreachable",
    "node.kubernetes.io/not-ready",
    "nvidia.com/gpu-preempt",  # parity with accelerator-generic installs
})


def node_indicates_preemption(node: dict) -> bool:
    """True when the node is being reclaimed or lost: a preemption/teardown
    taint, or Ready condition False/Unknown."""
    spec = node.get("spec") or {}
    for taint in spec.get("taints") or []:
        if taint.get("key") in PREEMPTION_TAINT_KEYS:
            return True
    for cond in (node.get("status") or {}).get("conditions") or []:
        if cond.get("type") == "Ready" and cond.get("status") in ("False", "Unknown"):
            return True
    return False


# How recently a pod must have failed for a *missing* node to count as
# preemption evidence.  A node can legitimately vanish long after an
# unrelated pod failure (autoscaler scale-down, reconcile backlog after
# operator downtime); inferring preemption from staleness would reclassify
# a permanently-failing job as retryable and gang-restart it forever.
# Tradeoff: a genuine preemption first reconciled more than this window
# after the pod died (operator down throughout) keeps its exit-code
# verdict.  That is acceptable because preempted pods normally die with
# SIGTERM/143 — retryable under ExitCode policy on its own — so the node
# evidence only matters for the rarer permanent-looking codes, where
# failing closed (no restart loop) is the safer default.
MISSING_NODE_FRESHNESS_SECONDS = 10 * 60.0


def _pod_failure_finished_at(pod: dict) -> float | None:
    """terminated.finishedAt of the ``tensorflow`` container — the same
    container whose exit code drives classification (tensorflow_exit_code
    above); a sidecar killed at node teardown must not make a stale training
    failure look fresh.  POSIX timestamp, or None."""
    for cs in (pod.get("status") or {}).get("containerStatuses") or []:
        if cs.get("name") != "tensorflow":
            continue
        term = (cs.get("state") or {}).get("terminated") or {}
        from k8s_tpu.api.meta import parse_rfc3339

        parsed = parse_rfc3339(term.get("finishedAt"))
        return parsed.timestamp() if parsed is not None else None
    return None


def pod_on_preempted_node(pod: dict, node_lister, *, now: float | None = None) -> bool:
    """Node-condition awareness: look up the pod's node and check for
    preemption/teardown evidence.  ``node_lister`` may be None (no node
    informer — e.g. RBAC without node read), which degrades gracefully to
    exit-code-only classification."""
    if node_lister is None:
        return False
    node_name = (pod.get("spec") or {}).get("nodeName")
    if not node_name:
        return False
    node = node_lister.get("", node_name)
    if node is None:
        # The pod names a node the informer has never seen or that was
        # deleted out from under it.  That is preemption evidence only when
        # the pod's failure is *recent* — the node deletion then plausibly
        # caused the failure.  A stale failure (or one with no finishedAt to
        # date it) whose node later disappeared keeps its exit-code
        # classification; pods that died because the kubelet vanished have
        # no exit code and stay retryable through that path anyway.
        finished = _pod_failure_finished_at(pod)
        if finished is None:
            return False
        now = time.time() if now is None else now
        return (now - finished) <= MISSING_NODE_FRESHNESS_SECONDS
    return node_indicates_preemption(node)


def pod_failed_permanently(pod: dict, restart_policy: str,
                           node_lister=None, *,
                           node_preempted: bool | None = None) -> bool:
    """Under ExitCode policy, a failed pod with a permanent (1-127) code is a
    terminal job failure; other policies treat any failure as restartable
    except Never.  Node evidence overrides the exit code: a pod that died
    because its node is being preempted/reclaimed is always retryable —
    restarting the gang elsewhere is exactly what the job wants.  An
    explicit RestartPolicyNever still wins: the user opted out of restarts
    entirely.  Callers that already classified the node pass the result as
    ``node_preempted`` (one lister lookup per pod, not per question)."""
    if restart_policy == types.RestartPolicyNever:
        return True
    if node_preempted is None:
        node_preempted = pod_on_preempted_node(pod, node_lister)
    if node_preempted:
        return False
    if restart_policy == types.RestartPolicyExitCode:
        code = tensorflow_exit_code(pod)
        if code is None:
            return False  # e.g. node-lost: retryable
        return not train_util.is_retryable_under_exit_code_policy(code)
    # Always / OnFailure restart anything.
    return False


class PodReconciler:
    """reconcilePods + createNewPod bound to a TFJobController's seams."""

    def __init__(self, pod_control, expectations, recorder, node_lister=None,
                 status_lock=None, metrics=None):
        self.pod_control = pod_control
        self.expectations = expectations
        self.recorder = recorder
        # node-condition awareness (optional: None degrades to exit codes)
        self.node_lister = node_lister
        # Serializes tfjob.status mutations when the controller reconciles
        # replica types concurrently: set_condition is read-modify-write on
        # the shared conditions list, and replica counters live in one dict.
        self.status_lock = status_lock or checkedlock.make_lock("podcontrol.status")
        self.metrics = metrics  # optional controller_metrics dict

    def reconcile(
        self, tfjob: types.TFJob, pods: list[dict], rtype: str, spec: types.TFReplicaSpec
    ) -> None:
        """reconcilePods (controller_pod.go:41-74) + gang-restart extension.

        Creation is a single bounded-concurrency wave per replica type: all
        missing indices are collected first, their expectations raised once
        up-front, then created through ``pod_control.create_pods_batch``."""
        from k8s_tpu import trace

        with trace.span("reconcile_pods", rtype=rtype):
            self._reconcile(tfjob, pods, rtype, spec)

    def _reconcile(
        self, tfjob: types.TFJob, pods: list[dict], rtype: str, spec: types.TFReplicaSpec
    ) -> None:
        rt = rtype.lower()
        pods = filter_pods_for_replica_type(pods, rt)
        replicas = spec.replicas or 1

        with self.status_lock:
            status_mod.initialize_replica_statuses(tfjob, rtype)

        restarting = False
        if rtype in SPMD_GANG_TYPES:
            restarting = self._maybe_restart_gang(tfjob, pods, rtype, spec)

        if not restarting:
            # scale-down (ISSUE 13): pods whose index fell out of
            # [0, replicas) after an autoscale replica patch are torn
            # down in one bounded wave — without this the gang never
            # actually shrinks and the freed chips are a ledger fiction
            extra = self._out_of_range_pods(pods, replicas)
            if extra:
                self._delete_pods_wave(
                    tfjob, rt, extra, self._job_snapshot(tfjob),
                    reason="scale-down")
            slices = get_pod_slices(pods, replicas)
            missing: list[int] = []
            for index, pod_slice in enumerate(slices):
                if len(pod_slice) > 1:
                    log.warning("too many pods for %s %d", rt, index)
                elif len(pod_slice) == 0:
                    missing.append(index)
                elif self._maybe_restart_pod(tfjob, pod_slice[0], rtype, spec):
                    restarting = True
                else:
                    with self.status_lock:
                        status_mod.update_replica_statuses(tfjob, rtype, pod_slice[0])
            if missing:
                self._create_pods_wave(tfjob, rt, missing, spec)

        with self.status_lock:
            status_mod.update_status(tfjob, rtype, replicas)

    def _maybe_restart_pod(
        self, tfjob: types.TFJob, pod: dict, rtype: str, spec: types.TFReplicaSpec
    ) -> bool:
        """Operator-level ExitCode restart for non-gang replicas: a failed pod
        with a retryable (128-255) exit code is deleted so the missing-index
        logic recreates it next sync (enforcement of the contract the
        reference left TODO at controller_pod.go:149).  Returns True when the
        pod was torn down (caller must not count it into the failed status)."""
        if rtype in SPMD_GANG_TYPES:
            return False  # gang path handles SPMD types
        if spec.restart_policy != types.RestartPolicyExitCode:
            return False  # Always/OnFailure restart in-place via kubelet
        if (pod.get("status") or {}).get("phase") != "Failed":
            return False
        preempted = pod_on_preempted_node(pod, self.node_lister)
        if pod_failed_permanently(pod, spec.restart_policy,
                                  node_preempted=preempted):
            return False
        job_dict = self._job_snapshot(tfjob)
        if preempted:
            self.recorder.eventf(
                job_dict, "Normal", "TPUPreempted",
                "Pod %s lost to node preemption/teardown; restarting",
                pod["metadata"]["name"],
            )
        name = pod["metadata"]["name"]
        log.info("restarting pod %s (retryable exit code)", name)
        with self.status_lock:
            status_mod.set_condition(
                tfjob.status,
                status_mod.new_condition(
                    types.TFJobRestarting,
                    status_mod.TFJOB_RESTARTING_REASON,
                    f"pod {name} exited retryably and is restarting",
                ),
                job=tpu_config.tfjob_key(tfjob),
            )
        # Single-pod restart batches trivially: a 1-slot wave buys the shared
        # expectation-unwind, NotFound-as-success, span, and metrics contract
        # for free (run_delete_wave — the invariant the old inline
        # try/except hand-rolled).
        self._delete_pods_wave(tfjob, rtype, [name], job_dict,
                               reason="retryable-exit restart")
        return True

    # -- gang restart --------------------------------------------------------

    def _maybe_restart_gang(
        self, tfjob: types.TFJob, pods: list[dict], rtype: str, spec: types.TFReplicaSpec
    ) -> bool:
        """If any gang pod failed retryably, tear down the whole gang so it
        restarts together.  Returns True when a restart is in progress (the
        caller must not create replacement pods this sync)."""
        failed = [p for p in pods if (p.get("status") or {}).get("phase") == "Failed"]
        if not failed:
            return False
        policy = spec.restart_policy or types.RestartPolicyAlways
        # one node classification per pod, shared by both questions below
        preempted_flags = [pod_on_preempted_node(p, self.node_lister)
                           for p in failed]
        if any(pod_failed_permanently(p, policy, node_preempted=pre)
               for p, pre in zip(failed, preempted_flags)):
            return False  # permanent: let update_status mark the job Failed
        job_dict = self._job_snapshot(tfjob)
        preempted = [p for p, pre in zip(failed, preempted_flags) if pre]
        if preempted:
            self.recorder.eventf(
                job_dict, "Normal", "TPUPreempted",
                "%d gang pod(s) lost to node preemption/teardown",
                len(preempted),
            )
        key = tpu_config.tfjob_key(tfjob)
        log.info(
            "gang restart for %s %s: %d failed pod(s), tearing down %d pod(s)",
            key, rtype, len(failed), len(pods),
        )
        with self.status_lock:
            status_mod.set_condition(
                tfjob.status,
                status_mod.new_condition(
                    types.TFJobRestarting,
                    status_mod.TFJOB_RESTARTING_REASON,
                    f"gang {rtype} restarting: {len(failed)} pod(s) failed retryably",
                ),
                job=key,
            )
        self.recorder.eventf(
            job_dict, "Normal", "GangRestart",
            "Restarting whole %s gang (%d pods) after retryable failure", rtype, len(pods),
        )
        # The hot path: kill-to-re-running is what chaos measures, and a
        # serial teardown of a 256-replica slice gang is O(N x RTT) of pure
        # idle-TPU time.  One bounded-concurrency wave instead — failed and
        # never-submitted slots are unwound by the shared helper, the
        # already-deleted pods' DELETE events stay counted.
        self._delete_pods_wave(
            tfjob, rtype, [p["metadata"]["name"] for p in pods], job_dict,
            reason="gang restart")
        return True

    @staticmethod
    def _out_of_range_pods(pods: list[dict], replicas: int) -> list[str]:
        """Names of live pods with an index >= replicas (the scale-down
        victims; already-terminating pods are skipped)."""
        out: list[str] = []
        for pod in pods:
            meta = pod.get("metadata") or {}
            if meta.get("deletionTimestamp"):
                continue
            try:
                index = int((meta.get("labels") or {}).get(
                    tpu_config.LABEL_REPLICA_INDEX, ""))
            except ValueError:
                continue
            if index >= replicas:
                out.append(meta.get("name", ""))
        return [n for n in out if n]

    def _delete_pods_wave(
        self, tfjob: types.TFJob, rtype: str, names: list[str],
        job_dict: dict, reason: str,
    ) -> None:
        """Tear down ``names`` in one bounded-concurrency wave (contract:
        control.run_delete_wave — deletion expectations raised up-front,
        per-slot unwind on failure, NotFound counts as deleted, first real
        error re-raised so the sync retries)."""
        from k8s_tpu.controller_v2.control import run_delete_wave

        key = tpu_config.tfjob_key(tfjob)
        run_delete_wave(
            self.expectations, gen_expectation_pods_key(key, rtype),
            lambda lo, hi: self.pod_control.delete_pods_batch(
                tfjob.metadata.namespace, names[lo:hi], job_dict),
            len(names), self.metrics, "pod",
            lambda i: f"pod {names[i]} ({reason} of {key})",
            initial=getattr(self.pod_control, "delete_width", 1),
            job=key,
        )

    # -- creation ------------------------------------------------------------

    def _build_pod_template(
        self, tfjob: types.TFJob, rt: str, index: int, spec: types.TFReplicaSpec
    ) -> dict:
        """createNewPod's template assembly (controller_pod.go:99-169),
        separated from the create so a wave can prepare every template —
        including the fallible port/env generation — before any expectation
        is raised."""
        key = tpu_config.tfjob_key(tfjob)

        labels = tpu_config.gen_labels(key)
        labels[tpu_config.LABEL_REPLICA_TYPE] = rt
        labels[tpu_config.LABEL_REPLICA_INDEX] = str(index)

        template = copy.deepcopy(spec.template or {})
        meta = template.setdefault("metadata", {})
        meta.setdefault("labels", {}).update(labels)
        from k8s_tpu import trace

        trace_id = trace.current_trace_id()
        if trace_id:
            # join key for apiserver audit / kubelet logs: which sync's
            # create wave produced this pod
            meta.setdefault("annotations", {})[TRACE_ID_ANNOTATION] = trace_id
        # Pod identity lives in the labels (reference behavior); the name is
        # generated so recreated gang members never collide.
        meta.pop("name", None)
        meta["generateName"] = tpu_config.gen_general_name(key, rt, index) + "-"

        env_vars = tpu_config.gen_env_vars(tfjob, rt, index)
        for container in template.setdefault("spec", {}).setdefault("containers", []):
            container.setdefault("env", []).extend(copy.deepcopy(env_vars))

        pod_spec = template["spec"]
        rtype_canonical = next(
            (r for r in tfjob.spec.tf_replica_specs if r.lower() == rt), rt
        )
        if rtype_canonical in SPMD_GANG_TYPES:
            # Departure #2: gang pods never restart in place.
            pod_spec["restartPolicy"] = "Never"
        elif spec.restart_policy and spec.restart_policy != types.RestartPolicyExitCode:
            # controller_pod.go:150-152.
            pod_spec["restartPolicy"] = spec.restart_policy
        else:
            pod_spec.setdefault("restartPolicy", "Never")
        return template

    def _create_new_pod(
        self, tfjob: types.TFJob, rt: str, index: int, spec: types.TFReplicaSpec
    ) -> None:
        """Single-pod compatibility shim over the wave path."""
        self._create_pods_wave(tfjob, rt, [index], spec)

    def _create_pods_wave(
        self, tfjob: types.TFJob, rt: str, indices: list[int], spec: types.TFReplicaSpec
    ) -> None:
        """Create every missing replica of one type in a bounded-concurrency
        wave (contract: control.run_create_wave — expectations raised once
        up-front, per-slot unwind on failure, first real error re-raised).
        Failed creates are simply observed-as-missing next sync — the
        successful slots' informer ADDs are already in flight, so nothing is
        ever double-created."""
        key = tpu_config.tfjob_key(tfjob)

        from k8s_tpu.api import helpers
        from k8s_tpu.controller_v2.control import run_create_wave

        controller_ref = helpers.as_owner(tfjob)
        # Everything fallible (port lookup, env generation, the job-dict
        # snapshot) happens BEFORE the expectations are raised: a raise after
        # expect_creations with no create would leak them and wedge retries.
        templates = [
            self._build_pod_template(tfjob, rt, index, spec) for index in indices
        ]
        job_dict = self._job_snapshot(tfjob)
        run_create_wave(
            self.expectations, gen_expectation_pods_key(key, rt),
            lambda lo, hi: self.pod_control.create_pods_batch(
                tfjob.metadata.namespace, templates[lo:hi], job_dict,
                controller_ref),
            len(templates), self.metrics, "pod",
            lambda i: f"pod for {key} {rt}/{indices[i]}",
            initial=getattr(self.pod_control, "create_width", 1),
            job=key,
        )

    def _job_snapshot(self, tfjob: types.TFJob) -> dict:
        """tfjob.to_dict() under the status lock: concurrent replica-type
        tasks mutate tfjob.status under it, and a dict resized mid-iteration
        makes an unlocked to_dict() raise RuntimeError."""
        with self.status_lock:
            return tfjob.to_dict()


# -- informer event handlers (controller_pod.go:237-322) ----------------------


def make_pod_event_handlers(controller):
    """Bind addPod/updatePod/deletePod to a TFJobController."""

    def add_pod(pod: dict) -> None:
        meta = pod.get("metadata") or {}
        if meta.get("deletionTimestamp"):
            return
        from k8s_tpu.api.meta import get_controller_of

        ref = get_controller_of(meta)
        if ref is None:
            return  # orphan: no one is waiting for it
        tfjob = controller.resolve_controller_ref(meta.get("namespace", ""), ref)
        if tfjob is None:
            return
        labels = meta.get("labels") or {}
        rtype = labels.get(tpu_config.LABEL_REPLICA_TYPE)
        if rtype is None:
            return
        key = tpu_config.tfjob_key(tfjob)
        controller.expectations.creation_observed(gen_expectation_pods_key(key, rtype))
        controller.enqueue_tfjob(tfjob)

    def update_pod(old: dict, cur: dict) -> None:
        if (old.get("metadata") or {}).get("resourceVersion") == (
            cur.get("metadata") or {}
        ).get("resourceVersion"):
            return  # resync echo
        from k8s_tpu.api.meta import get_controller_of

        cur_meta = cur.get("metadata") or {}
        old_ref = get_controller_of(old.get("metadata") or {})
        cur_ref = get_controller_of(cur_meta)
        if old_ref != cur_ref and old_ref is not None:
            tfjob = controller.resolve_controller_ref(cur_meta.get("namespace", ""), old_ref)
            if tfjob is not None:
                controller.enqueue_tfjob(tfjob)
        if cur_ref is not None:
            tfjob = controller.resolve_controller_ref(cur_meta.get("namespace", ""), cur_ref)
            if tfjob is not None:
                controller.enqueue_tfjob(tfjob)

    def delete_pod(pod: dict) -> None:
        """Implemented (reference left this TODO at controller_pod.go:320):
        observe gang-restart deletions and wake the job."""
        meta = pod.get("metadata") or {}
        from k8s_tpu.api.meta import get_controller_of

        ref = get_controller_of(meta)
        if ref is None:
            return
        tfjob = controller.resolve_controller_ref(meta.get("namespace", ""), ref)
        if tfjob is None:
            return
        rtype = (meta.get("labels") or {}).get(tpu_config.LABEL_REPLICA_TYPE)
        if rtype:
            key = tpu_config.tfjob_key(tfjob)
            controller.expectations.deletion_observed(gen_expectation_pods_key(key, rtype))
        controller.enqueue_tfjob(tfjob)

    return add_pod, update_pod, delete_pod

"""Controller-ref adoption/orphaning (reference: upstream
PodControllerRefManager + pkg/controller.v2/service_ref_manager.go:31-120).

``claim(objects)`` walks listed objects and for each decides:
- owned by us (controllerRef.uid matches): keep, unless the selector no
  longer matches — then release (strip the controllerRef via patch);
- owned by someone else: skip;
- orphan matching our selector: adopt (patch in our controllerRef), unless
  the controller is being deleted.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional

from k8s_tpu.api.meta import OwnerReference, get_controller_of
from k8s_tpu.client import errors
from k8s_tpu.client.selectors import labels_match

log = logging.getLogger(__name__)


class ControllerRefManager:
    def __init__(
        self,
        controller_obj: dict,
        selector: dict[str, str],
        controller_kind: str,
        api_version: str,
        can_adopt: Optional[Callable[[], None]] = None,
    ):
        self.controller = controller_obj
        self.selector = selector
        self.controller_kind = controller_kind
        self.api_version = api_version
        self._can_adopt = can_adopt
        self._can_adopt_err: Optional[Exception] = None
        self._can_adopt_checked = False

    @property
    def _meta(self) -> dict:
        return self.controller.get("metadata") or {}

    def _check_can_adopt(self) -> None:
        """Once-per-claim recheck that the controller still exists and is not
        being deleted (RecheckDeletionTimestamp, controller_pod.go:196-208)."""
        if not self._can_adopt_checked:
            self._can_adopt_checked = True
            if self._can_adopt is not None:
                try:
                    self._can_adopt()
                except Exception as e:  # noqa: BLE001
                    self._can_adopt_err = e
        if self._can_adopt_err is not None:
            raise self._can_adopt_err
        if self._meta.get("deletionTimestamp"):
            raise RuntimeError(
                f"{self.controller_kind} {self._meta.get('namespace')}/"
                f"{self._meta.get('name')} has just been deleted"
            )

    def _controller_ref(self) -> OwnerReference:
        return OwnerReference(
            api_version=self.api_version,
            kind=self.controller_kind,
            name=self._meta.get("name", ""),
            uid=self._meta.get("uid", ""),
            controller=True,
            block_owner_deletion=True,
        )

    def claim(self, objects: list[dict], adopt_fn, release_fn) -> list[dict]:
        claimed = []
        for obj in objects:
            ref = get_controller_of(obj.get("metadata") or {})
            matches = labels_match(obj, self.selector)
            if ref is not None:
                if ref.get("uid") != self._meta.get("uid"):
                    continue  # owned by someone else
                if matches:
                    claimed.append(obj)
                    continue
                # Owned but selector no longer matches: release unless the
                # owner is being deleted.
                if self._meta.get("deletionTimestamp"):
                    continue
                try:
                    release_fn(obj)
                except errors.ApiError as e:
                    if not errors.is_not_found(e):
                        raise
                continue
            # Orphan
            if self._meta.get("deletionTimestamp") or not matches:
                continue
            if (obj.get("metadata") or {}).get("deletionTimestamp"):
                continue
            try:
                self._check_can_adopt()
                adopt_fn(obj)
            except errors.ApiError as e:
                if errors.is_not_found(e):
                    continue
                raise
            except RuntimeError:
                continue  # controller being deleted: don't adopt
            claimed.append(obj)
        return claimed


class PodControllerRefManager(ControllerRefManager):
    def __init__(self, pod_control, controller_obj, selector, controller_kind,
                 api_version, can_adopt=None):
        super().__init__(controller_obj, selector, controller_kind, api_version, can_adopt)
        self.pod_control = pod_control

    def claim_pods(self, pods: list[dict]) -> list[dict]:
        ref = self._controller_ref().to_dict()

        def adopt(pod):
            # strategic merge on ownerReferences (merge key: uid): OUR ref
            # is added/updated, other owners survive — replacing the list
            # wholesale would silently drop them (pod_control.go adoption
            # patch semantics)
            self.pod_control.patch_pod(
                pod["metadata"].get("namespace", ""),
                pod["metadata"]["name"],
                {"metadata": {"ownerReferences": [ref]}},
            )

        def release(pod):
            # delete ONLY our ownerReference via the $patch delete
            # directive, exactly like client-go's release patch
            self.pod_control.patch_pod(
                pod["metadata"].get("namespace", ""),
                pod["metadata"]["name"],
                {"metadata": {"ownerReferences": [
                    {"$patch": "delete", "uid": ref["uid"]}]}},
            )

        return self.claim(pods, adopt, release)


class ServiceControllerRefManager(ControllerRefManager):
    """service_ref_manager.go:31-120."""

    def __init__(self, service_control, controller_obj, selector, controller_kind,
                 api_version, can_adopt=None):
        super().__init__(controller_obj, selector, controller_kind, api_version, can_adopt)
        self.service_control = service_control

    def claim_services(self, services: list[dict]) -> list[dict]:
        ref = self._controller_ref().to_dict()

        def adopt(svc):
            self.service_control.patch_service(
                svc["metadata"].get("namespace", ""),
                svc["metadata"]["name"],
                {"metadata": {"ownerReferences": [ref]}},
            )

        def release(svc):
            self.service_control.patch_service(
                svc["metadata"].get("namespace", ""),
                svc["metadata"]["name"],
                {"metadata": {"ownerReferences": [
                    {"$patch": "delete", "uid": ref["uid"]}]}},
            )

        return self.claim(services, adopt, release)

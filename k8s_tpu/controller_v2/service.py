"""Service reconciliation (reference: pkg/controller.v2/controller_service.go).

Per-index headless services give every replica a stable DNS name.  In the
SPMD world only the coordinator (process 0) strictly needs one, but the e2e
harness counts per-replica service events (py/test_runner.py:301-332), so the
reference's one-service-per-index contract is preserved.
"""

from __future__ import annotations

import logging
from k8s_tpu.analysis import checkedlock

from k8s_tpu.api.v1alpha2 import types
from k8s_tpu.controller_v2 import tpu_config

log = logging.getLogger(__name__)


def gen_expectation_services_key(tfjob_key: str, replica_type: str) -> str:
    """controller_service.go:225-227."""
    return f"{tfjob_key}/{replica_type.lower()}/services"


def filter_services_for_replica_type(services: list[dict], rt_lower: str) -> list[dict]:
    """controller_service.go:200-219."""
    return [
        s
        for s in services
        if ((s.get("metadata") or {}).get("labels") or {}).get(
            tpu_config.LABEL_REPLICA_TYPE
        )
        == rt_lower
    ]


def get_service_slices(services: list[dict], replicas: int) -> list[list[dict]]:
    """controller_service.go:67-89: bucket services by index label."""
    slices: list[list[dict]] = [[] for _ in range(replicas)]
    for svc in services:
        labels = (svc.get("metadata") or {}).get("labels") or {}
        if tpu_config.LABEL_REPLICA_INDEX not in labels:
            log.warning("service %s has no index label", svc.get("metadata", {}).get("name"))
            continue
        try:
            index = int(labels[tpu_config.LABEL_REPLICA_INDEX])
        except ValueError:
            continue
        if 0 <= index < replicas:
            slices[index].append(svc)
        else:
            log.warning("service index %d out of range [0,%d)", index, replicas)
    return slices


class ServiceReconciler:
    """reconcileServices + createNewService bound to controller seams."""

    def __init__(self, service_control, expectations, metrics=None,
                 status_lock=None):
        self.service_control = service_control
        self.expectations = expectations
        self.metrics = metrics  # optional controller_metrics dict
        # Shared with PodReconciler: tfjob.status is mutated under it by
        # concurrent replica-type tasks, so the job-dict snapshot below must
        # hold it too (an unlocked to_dict() can crash mid-iteration).
        self.status_lock = status_lock or checkedlock.make_lock("servicecontrol.status")

    def reconcile(
        self,
        tfjob: types.TFJob,
        services: list[dict],
        rtype: str,
        spec: types.TFReplicaSpec,
    ) -> None:
        """controller_service.go:35-64, with creation batched into one
        bounded-concurrency wave per replica type (see pod.py counterpart)."""
        from k8s_tpu import trace

        with trace.span("reconcile_services", rtype=rtype):
            self._reconcile(tfjob, services, rtype, spec)

    def _reconcile(
        self,
        tfjob: types.TFJob,
        services: list[dict],
        rtype: str,
        spec: types.TFReplicaSpec,
    ) -> None:
        rt = rtype.lower()
        services = filter_services_for_replica_type(services, rt)
        replicas = spec.replicas or 1
        # scale-down (ISSUE 13): drop the headless services of indices
        # that fell out of range, symmetric with the pod reconciler —
        # an autoscaled job must not leak one DNS name per past peak
        extra = self._out_of_range_services(services, replicas)
        if extra:
            self._delete_services_wave(tfjob, rt, extra)
        missing: list[int] = []
        for index, svc_slice in enumerate(get_service_slices(services, replicas)):
            if len(svc_slice) > 1:
                log.warning("too many services for %s %d", rt, index)
            elif len(svc_slice) == 0:
                missing.append(index)
        if missing:
            self._create_services_wave(tfjob, rtype, missing, spec)

    @staticmethod
    def _out_of_range_services(services: list[dict], replicas: int
                               ) -> list[str]:
        """Names of live services with an index >= replicas."""
        out: list[str] = []
        for svc in services:
            meta = svc.get("metadata") or {}
            if meta.get("deletionTimestamp"):
                continue
            try:
                index = int((meta.get("labels") or {}).get(
                    tpu_config.LABEL_REPLICA_INDEX, ""))
            except ValueError:
                continue
            if index >= replicas:
                out.append(meta.get("name", ""))
        return [n for n in out if n]

    def _delete_services_wave(self, tfjob: types.TFJob, rt: str,
                              names: list[str]) -> None:
        """Tear down ``names`` in one bounded wave (the pod counterpart's
        contract: expectations up-front, per-slot unwind, NotFound counts
        as deleted)."""
        from k8s_tpu.controller_v2.control import run_delete_wave

        key = tpu_config.tfjob_key(tfjob)
        with self.status_lock:
            job_dict = tfjob.to_dict()
        run_delete_wave(
            self.expectations, gen_expectation_services_key(key, rt),
            lambda lo, hi: self.service_control.delete_services_batch(
                tfjob.metadata.namespace, names[lo:hi], job_dict),
            len(names), self.metrics, "service",
            lambda i: f"service {names[i]} (scale-down of {key})",
            initial=getattr(self.service_control, "delete_width", 1),
            job=key,
        )

    def _build_service(self, tfjob: types.TFJob, rtype: str, index: int) -> dict:
        """createNewService's object assembly (controller_service.go:91-149):
        headless service selecting exactly one replica index.  The fallible
        port lookup lives here so a wave fails before raising expectations."""
        key = tpu_config.tfjob_key(tfjob)
        rt = rtype.lower()
        labels = tpu_config.gen_labels(key)
        labels[tpu_config.LABEL_REPLICA_TYPE] = rt
        labels[tpu_config.LABEL_REPLICA_INDEX] = str(index)
        name = tpu_config.gen_general_name(key, rt, index)
        port = tpu_config.get_port_from_tfjob(tfjob, rtype)
        return {
            "metadata": {"name": name, "labels": dict(labels)},
            "spec": {
                "clusterIP": "None",
                "selector": dict(labels),
                "ports": [{"name": name[-63:], "port": port}],
            },
        }

    def _create_new_service(
        self, tfjob: types.TFJob, rtype: str, index: int, spec: types.TFReplicaSpec
    ) -> None:
        """Single-service compatibility shim over the wave path."""
        self._create_services_wave(tfjob, rtype, [index], spec)

    def _create_services_wave(
        self, tfjob: types.TFJob, rtype: str, indices: list[int],
        spec: types.TFReplicaSpec,
    ) -> None:
        """One bounded-concurrency create per missing index via the shared
        wave contract (control.run_create_wave — expectations raised once
        up-front, per-slot unwind on failure, first real error re-raised)."""
        key = tpu_config.tfjob_key(tfjob)
        rt = rtype.lower()

        from k8s_tpu.api import helpers
        from k8s_tpu.controller_v2.control import run_create_wave

        controller_ref = helpers.as_owner(tfjob)
        # All fallible prep (port lookup, the job-dict snapshot) happens
        # before any expectation is raised (a raise afterwards would leak
        # it — see pod.py counterpart).
        service_objs = [self._build_service(tfjob, rtype, i) for i in indices]
        with self.status_lock:
            job_dict = tfjob.to_dict()
        run_create_wave(
            self.expectations, gen_expectation_services_key(key, rt),
            lambda lo, hi: self.service_control.create_services_batch(
                tfjob.metadata.namespace, service_objs[lo:hi], job_dict,
                controller_ref),
            len(service_objs), self.metrics, "service",
            lambda i: f"service {service_objs[i]['metadata']['name']}",
            initial=getattr(self.service_control, "create_width", 1),
            job=key,
        )


def make_service_event_handlers(controller):
    """addService/updateService/deleteService (controller_service.go:229-265;
    update/delete were TODO in the reference — implemented here)."""

    def add_service(svc: dict) -> None:
        meta = svc.get("metadata") or {}
        if meta.get("deletionTimestamp"):
            return
        from k8s_tpu.api.meta import get_controller_of

        ref = get_controller_of(meta)
        if ref is None:
            return
        tfjob = controller.resolve_controller_ref(meta.get("namespace", ""), ref)
        if tfjob is None:
            return
        rtype = (meta.get("labels") or {}).get(tpu_config.LABEL_REPLICA_TYPE)
        if rtype is None:
            return
        key = tpu_config.tfjob_key(tfjob)
        controller.expectations.creation_observed(gen_expectation_services_key(key, rtype))
        controller.enqueue_tfjob(tfjob)

    def update_service(old: dict, cur: dict) -> None:
        if (old.get("metadata") or {}).get("resourceVersion") == (
            cur.get("metadata") or {}
        ).get("resourceVersion"):
            return
        from k8s_tpu.api.meta import get_controller_of

        meta = cur.get("metadata") or {}
        ref = get_controller_of(meta)
        if ref is not None:
            tfjob = controller.resolve_controller_ref(meta.get("namespace", ""), ref)
            if tfjob is not None:
                controller.enqueue_tfjob(tfjob)

    def delete_service(svc: dict) -> None:
        """Observe teardown-wave deletions (symmetric with the pod DELETE
        handler): the terminal-cleanup service wave raises deletion
        expectations, and this DELETE echo is what decrements them."""
        meta = svc.get("metadata") or {}
        from k8s_tpu.api.meta import get_controller_of

        ref = get_controller_of(meta)
        if ref is None:
            return
        tfjob = controller.resolve_controller_ref(meta.get("namespace", ""), ref)
        if tfjob is None:
            return
        rtype = (meta.get("labels") or {}).get(tpu_config.LABEL_REPLICA_TYPE)
        if rtype:
            key = tpu_config.tfjob_key(tfjob)
            controller.expectations.deletion_observed(
                gen_expectation_services_key(key, rtype))
        controller.enqueue_tfjob(tfjob)

    return add_service, update_service, delete_service

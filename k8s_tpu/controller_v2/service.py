"""Service reconciliation (reference: pkg/controller.v2/controller_service.go).

Per-index headless services give every replica a stable DNS name.  In the
SPMD world only the coordinator (process 0) strictly needs one, but the e2e
harness counts per-replica service events (py/test_runner.py:301-332), so the
reference's one-service-per-index contract is preserved.
"""

from __future__ import annotations

import logging

from k8s_tpu.api.v1alpha2 import types
from k8s_tpu.controller_v2 import tpu_config

log = logging.getLogger(__name__)


def gen_expectation_services_key(tfjob_key: str, replica_type: str) -> str:
    """controller_service.go:225-227."""
    return f"{tfjob_key}/{replica_type.lower()}/services"


def filter_services_for_replica_type(services: list[dict], rt_lower: str) -> list[dict]:
    """controller_service.go:200-219."""
    return [
        s
        for s in services
        if ((s.get("metadata") or {}).get("labels") or {}).get(
            tpu_config.LABEL_REPLICA_TYPE
        )
        == rt_lower
    ]


def get_service_slices(services: list[dict], replicas: int) -> list[list[dict]]:
    """controller_service.go:67-89: bucket services by index label."""
    slices: list[list[dict]] = [[] for _ in range(replicas)]
    for svc in services:
        labels = (svc.get("metadata") or {}).get("labels") or {}
        if tpu_config.LABEL_REPLICA_INDEX not in labels:
            log.warning("service %s has no index label", svc.get("metadata", {}).get("name"))
            continue
        try:
            index = int(labels[tpu_config.LABEL_REPLICA_INDEX])
        except ValueError:
            continue
        if 0 <= index < replicas:
            slices[index].append(svc)
        else:
            log.warning("service index %d out of range [0,%d)", index, replicas)
    return slices


class ServiceReconciler:
    """reconcileServices + createNewService bound to controller seams."""

    def __init__(self, service_control, expectations):
        self.service_control = service_control
        self.expectations = expectations

    def reconcile(
        self,
        tfjob: types.TFJob,
        services: list[dict],
        rtype: str,
        spec: types.TFReplicaSpec,
    ) -> None:
        """controller_service.go:35-64."""
        rt = rtype.lower()
        services = filter_services_for_replica_type(services, rt)
        replicas = spec.replicas or 1
        for index, svc_slice in enumerate(get_service_slices(services, replicas)):
            if len(svc_slice) > 1:
                log.warning("too many services for %s %d", rt, index)
            elif len(svc_slice) == 0:
                self._create_new_service(tfjob, rtype, index, spec)

    def _create_new_service(
        self, tfjob: types.TFJob, rtype: str, index: int, spec: types.TFReplicaSpec
    ) -> None:
        """createNewService (controller_service.go:91-149): headless service
        selecting exactly one replica index."""
        key = tpu_config.tfjob_key(tfjob)
        rt = rtype.lower()

        from k8s_tpu.api import helpers

        controller_ref = helpers.as_owner(tfjob)
        labels = tpu_config.gen_labels(key)
        labels[tpu_config.LABEL_REPLICA_TYPE] = rt
        labels[tpu_config.LABEL_REPLICA_INDEX] = str(index)

        name = tpu_config.gen_general_name(key, rt, index)
        # Fallible port lookup happens before the expectation is raised (a
        # raise afterwards would leak it — see pod.py counterpart).
        port = tpu_config.get_port_from_tfjob(tfjob, rtype)
        self.expectations.expect_creations(gen_expectation_services_key(key, rt), 1)
        service = {
            "metadata": {"name": name, "labels": dict(labels)},
            "spec": {
                "clusterIP": "None",
                "selector": dict(labels),
                "ports": [{"name": name[-63:], "port": port}],
            },
        }
        try:
            self.service_control.create_services_with_controller_ref(
                tfjob.metadata.namespace, service, tfjob.to_dict(), controller_ref
            )
        except Exception as e:
            # Unwind the expectation on a failed create (no ADD event will
            # decrement it); AlreadyExists just means the cache was stale.
            self.expectations.creation_observed(gen_expectation_services_key(key, rt))
            from k8s_tpu.client import errors as api_errors

            if isinstance(e, api_errors.ApiError) and api_errors.is_already_exists(e):
                log.info("service %s already exists", name)
                return
            raise


def make_service_event_handlers(controller):
    """addService/updateService/deleteService (controller_service.go:229-265;
    update/delete were TODO in the reference — implemented here)."""

    def add_service(svc: dict) -> None:
        meta = svc.get("metadata") or {}
        if meta.get("deletionTimestamp"):
            return
        from k8s_tpu.api.meta import get_controller_of

        ref = get_controller_of(meta)
        if ref is None:
            return
        tfjob = controller.resolve_controller_ref(meta.get("namespace", ""), ref)
        if tfjob is None:
            return
        rtype = (meta.get("labels") or {}).get(tpu_config.LABEL_REPLICA_TYPE)
        if rtype is None:
            return
        key = tpu_config.tfjob_key(tfjob)
        controller.expectations.creation_observed(gen_expectation_services_key(key, rtype))
        controller.enqueue_tfjob(tfjob)

    def update_service(old: dict, cur: dict) -> None:
        if (old.get("metadata") or {}).get("resourceVersion") == (
            cur.get("metadata") or {}
        ).get("resourceVersion"):
            return
        from k8s_tpu.api.meta import get_controller_of

        meta = cur.get("metadata") or {}
        ref = get_controller_of(meta)
        if ref is not None:
            tfjob = controller.resolve_controller_ref(meta.get("namespace", ""), ref)
            if tfjob is not None:
                controller.enqueue_tfjob(tfjob)

    def delete_service(svc: dict) -> None:
        meta = svc.get("metadata") or {}
        from k8s_tpu.api.meta import get_controller_of

        ref = get_controller_of(meta)
        if ref is None:
            return
        tfjob = controller.resolve_controller_ref(meta.get("namespace", ""), ref)
        if tfjob is not None:
            controller.enqueue_tfjob(tfjob)

    return add_service, update_service, delete_service

"""Pod/Service control seams (reference: upstream PodControl +
pkg/controller.v2/service_control.go).

These exist as interfaces *specifically because* they are the fake points for
the controller test tier (controller_test.go:65-66): tests swap in
``FakePodControl``/``FakeServiceControl`` to capture creates/deletes without
an apiserver.  The real implementations validate the controller ref, create
via the clientset, and record K8s events (service_control.go:69-115).
"""

from __future__ import annotations

import copy
import logging
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor

from k8s_tpu.api.meta import OwnerReference
from k8s_tpu.client.clientset import Clientset

log = logging.getLogger(__name__)

FAILED_CREATE_POD_REASON = "FailedCreate"
SUCCESSFUL_CREATE_POD_REASON = "SuccessfulCreate"
FAILED_DELETE_POD_REASON = "FailedDelete"
SUCCESSFUL_DELETE_POD_REASON = "SuccessfulDelete"

# -- bounded-concurrency creation layer ---------------------------------------
#
# A TFJob on a TPU pod slice means 64-256 worker pods, and one blocking API
# round trip per pod makes first-sync latency O(replicas x RTT).  The batch
# APIs below fan a creation wave out over a shared ThreadPoolExecutor so the
# sync loop scales O(replicas / concurrency) instead.  The apiserver is the
# explicit sizing target: client-go defaults to 5 qps/10 burst per client but
# tolerates far more in-flight mutations; 16 matches the priority-and-fairness
# per-client seat budget magnitude without approaching storm territory.

DEFAULT_CREATE_CONCURRENCY = 16

_shared_executor: ThreadPoolExecutor | None = None
_shared_executor_lock = threading.Lock()


def create_concurrency_from_env() -> int:
    """K8S_TPU_CREATE_CONCURRENCY, defaulting to DEFAULT_CREATE_CONCURRENCY;
    values < 1 (or garbage) fall back to the default."""
    raw = os.environ.get("K8S_TPU_CREATE_CONCURRENCY", "")
    try:
        n = int(raw)
    except ValueError:
        n = 0
    return n if n >= 1 else DEFAULT_CREATE_CONCURRENCY


def shared_create_executor() -> ThreadPoolExecutor:
    """The process-wide creation pool, sized once from the environment.
    Shared across controls/controllers: total in-flight creates against the
    apiserver stay bounded no matter how many jobs sync concurrently."""
    global _shared_executor
    with _shared_executor_lock:
        if _shared_executor is None:
            _shared_executor = ThreadPoolExecutor(
                max_workers=create_concurrency_from_env(),
                thread_name_prefix="create-fanout",
            )
        return _shared_executor


def executor_for_concurrency(concurrency: int | None):
    """Map a requested create concurrency to an executor:

    - ``None``  -> the shared env-sized pool (production default);
    - ``1``     -> ``None`` (inline serial; no thread hop for the degenerate
      case, and the serial baseline the bench compares against);
    - ``n > 1`` -> a dedicated pool the caller owns (must ``shutdown()``).
    """
    if concurrency is None:
        return shared_create_executor()
    if concurrency <= 1:
        return None
    return ThreadPoolExecutor(max_workers=concurrency,
                              thread_name_prefix="create-fanout")


class _BatchCreateMixin:
    """Batch-create plumbing shared by the real and fake controls.

    ``_run_create_batch`` runs one callable per object through the control's
    executor (or inline when serial) and returns ``[(created, exc), ...]``
    aligned with the input order — partial failures are per-slot data, never
    an exception, so callers can unwind exactly the expectations whose
    creates failed while the successful creates' informer ADDs are already
    in flight."""

    _create_executor = None  # None -> inline serial

    @property
    def create_width(self) -> int:
        """Effective in-flight create concurrency: the slow-start initial
        chunk size (a wedged job's per-sync failure storm is bounded by the
        pool width, while a wave no larger than the pool stays one round)."""
        ex = self._create_executor
        return getattr(ex, "_max_workers", 1) if ex is not None else 1

    def _run_create_batch(self, calls):
        results: list[tuple[dict | None, Exception | None]]
        if self._create_executor is None or len(calls) <= 1:
            results = []
            for call in calls:
                try:
                    results.append((call(), None))
                except Exception as e:  # noqa: BLE001 - per-slot failure data
                    results.append((None, e))
            return results

        def _one(call):
            try:
                return (call(), None)
            except Exception as e:  # noqa: BLE001
                return (None, e)

        # Carry the wave span onto the pool threads: each slot gets its own
        # Context copy, so the REST-call spans it opens parent under the
        # create-batch span instead of starting orphan traces.
        from k8s_tpu import trace

        tracing = trace.enabled()
        futures = []
        tail: list[tuple[dict | None, Exception | None]] = []
        for call in calls:
            try:
                futures.append(self._create_executor.submit(
                    trace.bind_current_context(_one) if tracing else _one,
                    call))
            except RuntimeError as e:
                # Executor shut down mid-wave: the unsubmitted slots become
                # per-slot failures so the caller unwinds exactly their
                # expectations — a wholesale raise here would also unwind the
                # already-submitted slots, whose informer ADDs are coming.
                tail.append((None, e))
        return [f.result() for f in futures] + tail


def run_create_wave(expectations, exp_key: str, submit_range, count: int,
                    metrics, kind: str, describe, initial: int = 1) -> None:
    """The creation-wave contract shared by the pod/service reconcilers:
    raise ``count`` expectations up-front, submit creates in slow-start
    chunks of ``initial``, 2x, 4x, ... (client-go's slowStartBatch: a chunk
    containing any failure stops further submission, so a hard apiserver
    rejection costs O(pool-width) calls per retry sync instead of
    re-storming all N through the shared pool; callers pass the control's
    ``create_width`` so a wave no larger than the pool stays one round),
    unwind the expectations of failed and never-submitted
    slots (no informer ADD will ever decrement them), tolerate AlreadyExists
    as a stale-cache signal, and re-raise the first real error so the sync
    retries.  ``submit_range(lo, hi)`` must create slots [lo, hi) and return
    per-slot ``(created, exc)`` pairs, never raise wholesale — see
    ``_run_create_batch``.  Callers must finish ALL fallible prep — template
    assembly, port/env generation, the job-dict snapshot — before calling:
    nothing between ``expect_creations`` and the submits may raise, or the
    expectations leak and the job wedges until the TTL.  ``describe(i)``
    names slot i for logs."""
    from k8s_tpu import trace

    # One span per wave (create_pods_batch / create_services_batch); the
    # per-slot REST-call spans nest under it via the executor's context
    # binding.  An error re-raised out of the wave marks the span failed.
    with trace.span(f"create_{kind}s_batch", kind=kind, count=count):
        _run_wave(expectations, exp_key, submit_range, count, metrics,
                  kind, describe, initial)


def _run_wave(expectations, exp_key: str, submit_range, count: int,
              metrics, kind: str, describe, initial: int) -> None:
    expectations.expect_creations(exp_key, count)
    t0 = time.monotonic()
    results: list[tuple[dict | None, Exception | None]] = []
    try:
        chunk = max(1, initial)
        while len(results) < count:
            lo = len(results)
            part = submit_range(lo, min(lo + chunk, count))
            results.extend(part)
            # Only REAL errors stop the wave: AlreadyExists is a stale
            # informer cache telling us the object is fine — the remaining
            # replicas must still be created in this sync, as the old
            # per-object path did.
            if any(exc is not None and not _is_already_exists(exc)
                   for _, exc in part):
                break
            chunk *= 2
    finally:
        # Slots never submitted (slow-start aborted, or a contract-violating
        # wholesale raise from submit_range): no create happened for them,
        # so no informer ADD will ever decrement their expectations.
        for _ in range(count - len(results)):
            expectations.creation_observed(exp_key)
    record_batch_metrics(metrics, kind, results, time.monotonic() - t0)
    first_error: Exception | None = None
    for i, (_created, exc) in enumerate(results):
        if exc is None:
            continue
        expectations.creation_observed(exp_key)
        if _is_already_exists(exc):
            log.info("%s already exists", describe(i))
            continue
        log.warning("create failed for %s: %s", describe(i), exc)
        if first_error is None:
            first_error = exc
    if first_error is not None:
        raise first_error


def _is_already_exists(exc) -> bool:
    """The one definition of the stale-cache 409 signal: AlreadyExists means
    the object is fine and the sync proceeds — the wave-abort decision, the
    per-slot unwind, and the metrics classification must all agree on it."""
    from k8s_tpu.client import errors as api_errors

    return (isinstance(exc, api_errors.ApiError)
            and api_errors.is_already_exists(exc))


def record_batch_metrics(metrics, kind: str, results, elapsed: float) -> None:
    """Account one create wave into a controller_metrics dict (no-op when the
    reconciler runs without metrics, e.g. bare unit-test wiring)."""
    if not metrics:
        return
    gen = metrics["generation"]
    metrics["create_batch_duration"].labels(gen, kind).observe(elapsed)
    by_result = {"success": 0, "already_exists": 0, "error": 0}
    for _, exc in results:
        if exc is None:
            by_result["success"] += 1
        elif _is_already_exists(exc):
            by_result["already_exists"] += 1
        else:
            by_result["error"] += 1
    for result, n in by_result.items():
        if n:
            metrics["creates_total"].labels(gen, kind, result).inc(n)


def _validate_controller_ref(ref: OwnerReference) -> None:
    """RealPodControl.createPods validation (upstream pod_control semantics)."""
    if ref is None:
        raise ValueError("controllerRef is required")
    if not ref.api_version or not ref.kind or not ref.name or not ref.uid:
        raise ValueError(f"controllerRef is incomplete: {ref}")
    if not ref.controller:
        raise ValueError("controllerRef.controller must be true")


def _pod_from_template(template: dict, controller_ref: OwnerReference) -> dict:
    pod = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": copy.deepcopy(template.get("metadata") or {}),
        "spec": copy.deepcopy(template.get("spec") or {}),
    }
    pod["metadata"]["ownerReferences"] = [controller_ref.to_dict()]
    return pod


class RealPodControl(_BatchCreateMixin):
    def __init__(self, clientset: Clientset, recorder, executor="shared"):
        self.clientset = clientset
        self.recorder = recorder
        # executor: "shared" (default) -> process-wide pool; None -> serial;
        # or any ThreadPoolExecutor-alike the caller owns (bench/tests).
        self._create_executor = (
            shared_create_executor() if executor == "shared" else executor
        )

    def create_pods_batch(
        self, namespace: str, templates: list[dict], controller_obj: dict,
        controller_ref: OwnerReference,
    ) -> list[tuple[dict | None, Exception | None]]:
        """Fan out one create per template with bounded concurrency.
        Returns (created, exc) per slot, input-ordered."""
        return self._run_create_batch([
            (lambda t=t: self.create_pods_with_controller_ref(
                namespace, t, controller_obj, controller_ref))
            for t in templates
        ])

    def create_pods_with_controller_ref(
        self, namespace: str, template: dict, controller_obj: dict, controller_ref: OwnerReference
    ) -> dict:
        _validate_controller_ref(controller_ref)
        pod = _pod_from_template(template, controller_ref)
        try:
            created = self.clientset.pods(namespace).create(pod)
        except Exception as e:
            self.recorder.eventf(
                controller_obj, "Warning", FAILED_CREATE_POD_REASON,
                "Error creating: %s", e,
            )
            raise
        self.recorder.eventf(
            controller_obj, "Normal", SUCCESSFUL_CREATE_POD_REASON,
            "Created pod: %s", created["metadata"]["name"],
        )
        return created

    def delete_pod(self, namespace: str, name: str, controller_obj: dict) -> None:
        try:
            self.clientset.pods(namespace).delete(name)
        except Exception as e:
            self.recorder.eventf(
                controller_obj, "Warning", FAILED_DELETE_POD_REASON,
                "Error deleting: %s", e,
            )
            raise
        self.recorder.eventf(
            controller_obj, "Normal", SUCCESSFUL_DELETE_POD_REASON,
            "Deleted pod: %s", name,
        )

    def patch_pod(self, namespace: str, name: str, patch: dict) -> None:
        # strategic, not JSON merge: client-go's PodControl sends
        # types.StrategicMergePatchType (controller_pod.go:99-169), so
        # ownerReferences/containers/env lists merge by key on the wire
        self.clientset.pods(namespace).patch(name, patch,
                                             patch_type="strategic")


class RealServiceControl(_BatchCreateMixin):
    """service_control.go:69-115."""

    def __init__(self, clientset: Clientset, recorder, executor="shared"):
        self.clientset = clientset
        self.recorder = recorder
        self._create_executor = (
            shared_create_executor() if executor == "shared" else executor
        )

    def create_services_batch(
        self, namespace: str, services: list[dict], controller_obj: dict,
        controller_ref: OwnerReference,
    ) -> list[tuple[dict | None, Exception | None]]:
        """Fan out one create per service with bounded concurrency.
        Returns (created, exc) per slot, input-ordered."""
        return self._run_create_batch([
            (lambda s=s: self.create_services_with_controller_ref(
                namespace, s, controller_obj, controller_ref))
            for s in services
        ])

    def create_services_with_controller_ref(
        self, namespace: str, service: dict, controller_obj: dict, controller_ref: OwnerReference
    ) -> dict:
        _validate_controller_ref(controller_ref)
        svc = copy.deepcopy(service)
        svc.setdefault("apiVersion", "v1")
        svc.setdefault("kind", "Service")
        svc.setdefault("metadata", {})["ownerReferences"] = [controller_ref.to_dict()]
        try:
            created = self.clientset.services(namespace).create(svc)
        except Exception as e:
            self.recorder.eventf(
                controller_obj, "Warning", FAILED_CREATE_POD_REASON,
                "Error creating: %s", e,
            )
            raise
        self.recorder.eventf(
            controller_obj, "Normal", SUCCESSFUL_CREATE_POD_REASON,
            "Created service: %s", created["metadata"]["name"],
        )
        return created

    def delete_service(self, namespace: str, name: str, controller_obj: dict) -> None:
        try:
            self.clientset.services(namespace).delete(name)
        except Exception as e:
            self.recorder.eventf(
                controller_obj, "Warning", FAILED_DELETE_POD_REASON,
                "Error deleting: %s", e,
            )
            raise
        self.recorder.eventf(
            controller_obj, "Normal", SUCCESSFUL_DELETE_POD_REASON,
            "Deleted service: %s", name,
        )

    def patch_service(self, namespace: str, name: str, patch: dict) -> None:
        # strategic for the same reason as RealPodControl.patch_pod
        self.clientset.services(namespace).patch(name, patch,
                                                 patch_type="strategic")


class FakePodControl(_BatchCreateMixin):
    """controller.FakePodControl: captures templates/deletions for asserts.

    Thread-safe: the concurrent creators (create_pods_batch, the per-replica-
    type reconcile fan-out) hit one fake from many threads, so every capture
    list append and ``clear()`` runs under a lock.  Batch creates stay inline
    serial by default (``_create_executor = None``) so per-test capture order
    is deterministic; the thread-safety matters because the *controller* may
    call the fake from concurrent reconcile tasks."""

    def __init__(self):
        self._lock = threading.Lock()
        self.templates: list[dict] = []
        self.controller_refs: list[OwnerReference] = []
        self.delete_pod_names: list[str] = []
        self.patches: list[dict] = []
        self.create_error: Exception | None = None
        self.delete_error: Exception | None = None

    def create_pods_with_controller_ref(self, namespace, template, controller_obj, controller_ref):
        _validate_controller_ref(controller_ref)
        if self.create_error is not None:
            raise self.create_error
        captured = copy.deepcopy(template)
        with self._lock:
            self.templates.append(captured)
            self.controller_refs.append(controller_ref)
        return _pod_from_template(template, controller_ref)

    def create_pods_batch(self, namespace, templates, controller_obj, controller_ref):
        return self._run_create_batch([
            (lambda t=t: self.create_pods_with_controller_ref(
                namespace, t, controller_obj, controller_ref))
            for t in templates
        ])

    def delete_pod(self, namespace, name, controller_obj):
        if self.delete_error is not None:
            raise self.delete_error
        with self._lock:
            self.delete_pod_names.append(name)

    def patch_pod(self, namespace, name, patch):
        with self._lock:
            self.patches.append(patch)

    def clear(self):
        with self._lock:
            self.templates = []
            self.controller_refs = []
            self.delete_pod_names = []
            self.patches = []
            self.create_error = None
            self.delete_error = None


class FakeServiceControl(_BatchCreateMixin):
    """service_control.go:117-175.  Thread-safe for the same reason as
    FakePodControl."""

    def __init__(self):
        self._lock = threading.Lock()
        self.services: list[dict] = []
        self.controller_refs: list[OwnerReference] = []
        self.delete_service_names: list[str] = []
        self.patches: list[dict] = []
        self.create_error: Exception | None = None

    def create_services_with_controller_ref(self, namespace, service, controller_obj, controller_ref):
        _validate_controller_ref(controller_ref)
        if self.create_error is not None:
            raise self.create_error
        captured = copy.deepcopy(service)
        with self._lock:
            self.services.append(captured)
            self.controller_refs.append(controller_ref)
        return copy.deepcopy(service)

    def create_services_batch(self, namespace, services, controller_obj, controller_ref):
        return self._run_create_batch([
            (lambda s=s: self.create_services_with_controller_ref(
                namespace, s, controller_obj, controller_ref))
            for s in services
        ])

    def delete_service(self, namespace, name, controller_obj):
        with self._lock:
            self.delete_service_names.append(name)

    def patch_service(self, namespace, name, patch):
        with self._lock:
            self.patches.append(patch)

    def clear(self):
        with self._lock:
            self.services = []
            self.controller_refs = []
            self.delete_service_names = []
            self.patches = []
            self.create_error = None

"""Pod/Service control seams (reference: upstream PodControl +
pkg/controller.v2/service_control.go).

These exist as interfaces *specifically because* they are the fake points for
the controller test tier (controller_test.go:65-66): tests swap in
``FakePodControl``/``FakeServiceControl`` to capture creates/deletes without
an apiserver.  The real implementations validate the controller ref, create
via the clientset, and record K8s events (service_control.go:69-115).
"""

from __future__ import annotations

import copy
import logging
import os
from k8s_tpu.analysis import checkedlock
import time
from concurrent.futures import ThreadPoolExecutor

from k8s_tpu.api.meta import OwnerReference
from k8s_tpu.client.clientset import Clientset

log = logging.getLogger(__name__)

FAILED_CREATE_POD_REASON = "FailedCreate"
SUCCESSFUL_CREATE_POD_REASON = "SuccessfulCreate"
FAILED_DELETE_POD_REASON = "FailedDelete"
SUCCESSFUL_DELETE_POD_REASON = "SuccessfulDelete"

# -- bounded-concurrency creation layer ---------------------------------------
#
# A TFJob on a TPU pod slice means 64-256 worker pods, and one blocking API
# round trip per pod makes first-sync latency O(replicas x RTT).  The batch
# APIs below fan a creation wave out over a shared ThreadPoolExecutor so the
# sync loop scales O(replicas / concurrency) instead.  The apiserver is the
# explicit sizing target: client-go defaults to 5 qps/10 burst per client but
# tolerates far more in-flight mutations; 16 matches the priority-and-fairness
# per-client seat budget magnitude without approaching storm territory.

DEFAULT_CREATE_CONCURRENCY = 16
# Teardown mirrors creation: the gang restart after a retryable failure or
# TPU preemption is delete-all-then-recreate-all, so the delete fan-out gets
# the same default width and the same apiserver-budget rationale.
DEFAULT_DELETE_CONCURRENCY = 16

_shared_executor: ThreadPoolExecutor | None = None
_shared_delete_executor: ThreadPoolExecutor | None = None
_shared_executor_lock = checkedlock.make_lock("control.shared_executor")


def _concurrency_env(var: str) -> int:
    """Parse one concurrency env var; 0 means unset/garbage/sub-1."""
    raw = os.environ.get(var, "")
    try:
        n = int(raw)
    except ValueError:
        n = 0
    return n if n >= 1 else 0


def create_concurrency_from_env() -> int:
    """K8S_TPU_CREATE_CONCURRENCY, defaulting to DEFAULT_CREATE_CONCURRENCY;
    values < 1 (or garbage) fall back to the default."""
    return (_concurrency_env("K8S_TPU_CREATE_CONCURRENCY")
            or DEFAULT_CREATE_CONCURRENCY)


def delete_concurrency_from_env() -> int:
    """K8S_TPU_DELETE_CONCURRENCY, falling back to K8S_TPU_CREATE_CONCURRENCY
    (one knob tunes both fan-outs — including the documented ``=1`` fully
    serial bisect mode), then to DEFAULT_DELETE_CONCURRENCY."""
    return (_concurrency_env("K8S_TPU_DELETE_CONCURRENCY")
            or _concurrency_env("K8S_TPU_CREATE_CONCURRENCY")
            or DEFAULT_DELETE_CONCURRENCY)


def shared_create_executor() -> ThreadPoolExecutor:
    """The process-wide creation pool, sized once from the environment.
    Shared across controls/controllers: total in-flight creates against the
    apiserver stay bounded no matter how many jobs sync concurrently."""
    global _shared_executor
    with _shared_executor_lock:
        if _shared_executor is None:
            _shared_executor = ThreadPoolExecutor(
                max_workers=create_concurrency_from_env(),
                thread_name_prefix="create-fanout",
            )
        return _shared_executor


def shared_delete_executor() -> ThreadPoolExecutor:
    """The process-wide deletion pool — DISTINCT from the create pool so a
    256-replica teardown wave can't starve another job's creation wave (and
    vice versa); each side keeps its own bounded apiserver budget."""
    global _shared_delete_executor
    with _shared_executor_lock:
        if _shared_delete_executor is None:
            _shared_delete_executor = ThreadPoolExecutor(
                max_workers=delete_concurrency_from_env(),
                thread_name_prefix="delete-fanout",
            )
        return _shared_delete_executor


def executor_for_concurrency(concurrency: int | None, kind: str = "create"):
    """Map a requested create/delete concurrency to an executor:

    - ``None``  -> the shared env-sized pool (production default);
    - ``1``     -> ``None`` (inline serial; no thread hop for the degenerate
      case, and the serial baseline the bench compares against);
    - ``n > 1`` -> a dedicated pool the caller owns (must ``shutdown()``).
    """
    if concurrency is None:
        return (shared_create_executor() if kind == "create"
                else shared_delete_executor())
    if concurrency <= 1:
        return None
    return ThreadPoolExecutor(max_workers=concurrency,
                              thread_name_prefix=f"{kind}-fanout")


def _run_batch(calls, executor):
    """Run one callable per slot through ``executor`` (inline when None or a
    single slot) and return ``[(result, exc), ...]`` aligned with the input
    order — partial failures are per-slot data, never a wholesale raise, so
    callers can unwind exactly the expectations whose calls failed while the
    successful calls' informer echoes are already in flight."""
    results: list[tuple[dict | None, Exception | None]]
    if executor is None or len(calls) <= 1:
        results = []
        for call in calls:
            try:
                results.append((call(), None))
            except Exception as e:  # noqa: BLE001 - per-slot failure data
                results.append((None, e))
        return results

    def _one(call):
        try:
            return (call(), None)
        except Exception as e:  # noqa: BLE001
            return (None, e)

    # Carry the wave span onto the pool threads: each slot gets its own
    # Context copy, so the REST-call spans it opens parent under the
    # batch span instead of starting orphan traces.
    from k8s_tpu import trace

    tracing = trace.enabled()
    futures = []
    tail: list[tuple[dict | None, Exception | None]] = []
    for call in calls:
        try:
            futures.append(executor.submit(
                trace.bind_current_context(_one) if tracing else _one,
                call))
        except RuntimeError as e:
            # Executor shut down mid-wave: the unsubmitted slots become
            # per-slot failures so the caller unwinds exactly their
            # expectations — a wholesale raise here would also unwind the
            # already-submitted slots, whose informer echoes are coming.
            tail.append((None, e))
    return [f.result() for f in futures] + tail


class _BatchCreateMixin:
    """Batch-create plumbing shared by the real and fake controls.

    ``_run_create_batch`` runs one callable per object through the control's
    executor (or inline when serial) and returns ``[(created, exc), ...]``
    aligned with the input order — partial failures are per-slot data, never
    an exception, so callers can unwind exactly the expectations whose
    creates failed while the successful creates' informer ADDs are already
    in flight."""

    _create_executor = None  # None -> inline serial

    @property
    def create_width(self) -> int:
        """Effective in-flight create concurrency: the slow-start initial
        chunk size (a wedged job's per-sync failure storm is bounded by the
        pool width, while a wave no larger than the pool stays one round)."""
        ex = self._create_executor
        return getattr(ex, "_max_workers", 1) if ex is not None else 1

    def _run_create_batch(self, calls):
        return _run_batch(calls, self._create_executor)


class _BatchDeleteMixin:
    """Batch-delete plumbing shared by the real and fake controls — the
    teardown mirror of ``_BatchCreateMixin``, backed by the separate delete
    pool so restart waves and creation waves can't starve each other."""

    _delete_executor = None  # None -> inline serial

    @property
    def delete_width(self) -> int:
        """Effective in-flight delete concurrency: the slow-start initial
        chunk size for teardown waves (same contract as create_width)."""
        ex = self._delete_executor
        return getattr(ex, "_max_workers", 1) if ex is not None else 1

    def _run_delete_batch(self, calls):
        return _run_batch(calls, self._delete_executor)


def run_create_wave(expectations, exp_key: str, submit_range, count: int,
                    metrics, kind: str, describe, initial: int = 1,
                    job: str | None = None) -> None:
    """The creation-wave contract shared by the pod/service reconcilers:
    raise ``count`` expectations up-front, submit creates in slow-start
    chunks of ``initial``, 2x, 4x, ... (client-go's slowStartBatch: a chunk
    containing any failure stops further submission, so a hard apiserver
    rejection costs O(pool-width) calls per retry sync instead of
    re-storming all N through the shared pool; callers pass the control's
    ``create_width`` so a wave no larger than the pool stays one round),
    unwind the expectations of failed and never-submitted
    slots (no informer ADD will ever decrement them), tolerate AlreadyExists
    as a stale-cache signal, and re-raise the first real error so the sync
    retries.  ``submit_range(lo, hi)`` must create slots [lo, hi) and return
    per-slot ``(created, exc)`` pairs, never raise wholesale — see
    ``_run_create_batch``.  Callers must finish ALL fallible prep — template
    assembly, port/env generation, the job-dict snapshot — before calling:
    nothing between ``expect_creations`` and the submits may raise, or the
    expectations leak and the job wedges until the TTL.  ``describe(i)``
    names slot i for logs."""
    from k8s_tpu import trace

    # One span per wave (create_pods_batch / create_services_batch); the
    # per-slot REST-call spans nest under it via the executor's context
    # binding.  An error re-raised out of the wave marks the span failed.
    with trace.span(f"create_{kind}s_batch", kind=kind, count=count):
        _run_wave(expectations, exp_key, submit_range, count, metrics,
                  kind, describe, initial, job)


def _slow_start_submit(submit_range, count: int, initial: int, is_benign,
                       out: list) -> None:
    """client-go's slowStartBatch, shared by the create and delete waves:
    submit in chunks of ``initial``, 2x, 4x, ...; a chunk containing any
    non-benign failure stops further submission (a hard apiserver rejection
    costs O(pool-width) calls per retry sync, never a re-storm of all N).
    Appends per-slot results to ``out`` in place so a contract-violating
    wholesale raise from ``submit_range`` still leaves the already-submitted
    slots visible to the caller's unwind accounting."""
    chunk = max(1, initial)
    while len(out) < count:
        lo = len(out)
        part = submit_range(lo, min(lo + chunk, count))
        out.extend(part)
        if any(exc is not None and not is_benign(exc) for _, exc in part):
            break
        chunk *= 2


def _run_wave(expectations, exp_key: str, submit_range, count: int,
              metrics, kind: str, describe, initial: int,
              job: str | None = None) -> None:
    expectations.expect_creations(exp_key, count)
    t0 = time.monotonic()
    results: list[tuple[dict | None, Exception | None]] = []
    try:
        # Only REAL errors stop the wave: AlreadyExists is a stale informer
        # cache telling us the object is fine — the remaining replicas must
        # still be created in this sync, as the old per-object path did.
        _slow_start_submit(submit_range, count, initial, _is_already_exists,
                           results)
    finally:
        # Slots never submitted (slow-start aborted, or a contract-violating
        # wholesale raise from submit_range): no create happened for them,
        # so no informer ADD will ever decrement their expectations.
        for _ in range(count - len(results)):
            expectations.creation_observed(exp_key)
    record_batch_metrics(metrics, kind, results, time.monotonic() - t0)
    _timeline_wave(job, "create_wave", kind, count, results)
    first_error: Exception | None = None
    for i, (_created, exc) in enumerate(results):
        if exc is None:
            continue
        expectations.creation_observed(exp_key)
        if _is_already_exists(exc):
            log.info("%s already exists", describe(i))
            continue
        log.warning("create failed for %s: %s", describe(i), exc)
        if first_error is None:
            first_error = exc
    if first_error is not None:
        raise first_error


def _timeline_wave(job: str | None, wave: str, kind: str, count: int,
                   results) -> None:
    """One flight-recorder timeline entry per create/delete wave (ISSUE 7):
    the "pods created"/"pods deleted" markers of a job's lifecycle, with
    the per-slot outcome tallies.  ``job=None`` (bare unit-test wiring)
    records nothing."""
    if not job:
        return
    from k8s_tpu import flight

    ok = sum(1 for _r, exc in results if exc is None)
    flight.timeline(job, wave, resource=kind, count=count, ok=ok,
                    errors=len(results) - ok,
                    unsubmitted=count - len(results))


def _is_already_exists(exc) -> bool:
    """The one definition of the stale-cache 409 signal: AlreadyExists means
    the object is fine and the sync proceeds — the wave-abort decision, the
    per-slot unwind, and the metrics classification must all agree on it."""
    from k8s_tpu.client import errors as api_errors

    return (isinstance(exc, api_errors.ApiError)
            and api_errors.is_already_exists(exc))


def record_batch_metrics(metrics, kind: str, results, elapsed: float) -> None:
    """Account one create wave into a controller_metrics dict (no-op when the
    reconciler runs without metrics, e.g. bare unit-test wiring)."""
    if not metrics:
        return
    gen = metrics["generation"]
    metrics["create_batch_duration"].labels(gen, kind).observe(elapsed)
    by_result = {"success": 0, "already_exists": 0, "error": 0}
    for _, exc in results:
        if exc is None:
            by_result["success"] += 1
        elif _is_already_exists(exc):
            by_result["already_exists"] += 1
        else:
            by_result["error"] += 1
    for result, n in by_result.items():
        if n:
            metrics["creates_total"].labels(gen, kind, result).inc(n)


# -- bounded-concurrency deletion layer ----------------------------------------
#
# Every deletion path is the prerequisite for a gang restart: on TPU pod
# slices the whole gang restarts together whenever one host fails, so
# kill-to-re-running latency is pure idle-TPU time.  The wave contract below
# is deliberately symmetric with run_create_wave; the asymmetries are the
# delete-specific semantics (NotFound is success, and some callers — terminal
# cleanup — swallow errors instead of retrying the sync).


def unwind_delete_expectations(expectations, exp_key: str | None,
                               count: int) -> None:
    """The one deletion-unwind helper: a failed or never-submitted delete
    produced no apiserver deletion, so no informer DELETE event will ever
    decrement its expectation — it must be observed by hand or the job
    wedges on satisfied_expectations until the TTL.  ``exp_key`` may be
    None (cleanup of rtype-less pods keeps no expectations).  One bulk
    lower instead of ``count`` observed calls: an aborted 256-slot wave is
    one lock acquisition, and both implementations (Python and native)
    no-op identically on a missing record."""
    if not exp_key or count <= 0:
        return
    expectations.raise_expectations(exp_key, 0, -count)


def run_delete_wave(expectations, exp_key: str | None, submit_range,
                    count: int, metrics, kind: str, describe,
                    initial: int = 1, raise_on_error: bool = True,
                    job: str | None = None) -> int:
    """The teardown-wave contract shared by gang restart, single-pod restart,
    and terminal cleanup: raise ``count`` deletion expectations up-front,
    submit deletes in slow-start chunks of ``initial``, 2x, 4x, ... (a hard
    apiserver rejection costs O(pool-width) calls per retry sync), unwind the
    expectations of failed and never-submitted slots via
    ``unwind_delete_expectations``, and treat NotFound as success — the
    object is already gone, and its informer DELETE event is (or was) in
    flight; the NotFound slot's expectation is unwound exactly like
    client-go's DeletionObserved-on-error, so a racing external delete never
    wedges the job.  ``submit_range(lo, hi)`` must delete slots [lo, hi) and
    return per-slot ``(result, exc)`` pairs, never raise wholesale.  Returns
    the number of objects now gone (successes + NotFounds); the first real
    error re-raises when ``raise_on_error`` (restart paths retry the sync)
    and is swallowed-after-logging otherwise (terminal cleanup must still
    write status)."""
    from k8s_tpu import trace

    with trace.span(f"delete_{kind}s_batch", kind=kind, count=count):
        return _run_delete_wave(expectations, exp_key, submit_range, count,
                                metrics, kind, describe, initial,
                                raise_on_error, job)


def _run_delete_wave(expectations, exp_key, submit_range, count, metrics,
                     kind, describe, initial, raise_on_error,
                     job: str | None = None) -> int:
    if exp_key:
        expectations.expect_deletions(exp_key, count)
    t0 = time.monotonic()
    results: list[tuple[dict | None, Exception | None]] = []
    try:
        # Only REAL errors stop the wave: NotFound means the object is
        # already gone (chaos kill, GC cascade, a prior sync's delete) —
        # the remaining slots must still be deleted in this sync.
        _slow_start_submit(submit_range, count, initial, _is_not_found,
                           results)
    finally:
        # Slots never submitted (slow-start aborted, or a contract-violating
        # wholesale raise from submit_range): nothing was deleted for them.
        unwind_delete_expectations(expectations, exp_key,
                                   count - len(results))
    record_delete_batch_metrics(metrics, kind, results,
                                time.monotonic() - t0)
    _timeline_wave(job, "delete_wave", kind, count, results)
    first_error: Exception | None = None
    gone = 0
    for i, (_result, exc) in enumerate(results):
        if exc is None:
            gone += 1
            continue
        unwind_delete_expectations(expectations, exp_key, 1)
        if _is_not_found(exc):
            gone += 1
            log.info("%s already deleted", describe(i))
            continue
        log.warning("delete failed for %s: %s", describe(i), exc)
        if first_error is None:
            first_error = exc
    if first_error is not None and raise_on_error:
        raise first_error
    return gone


def _is_not_found(exc) -> bool:
    """The one definition of the already-gone 404 signal: the wave-abort
    decision, the per-slot unwind, and the metrics classification must all
    agree on it (mirror of _is_already_exists on the create side)."""
    from k8s_tpu.client import errors as api_errors

    return (isinstance(exc, api_errors.ApiError)
            and api_errors.is_not_found(exc))


def record_delete_batch_metrics(metrics, kind: str, results,
                                elapsed: float) -> None:
    """Account one delete wave into a controller_metrics dict (no-op when
    the caller runs without metrics, e.g. bare unit-test wiring)."""
    if not metrics or "deletes_total" not in metrics:
        return
    gen = metrics["generation"]
    metrics["delete_batch_duration"].labels(gen, kind).observe(elapsed)
    by_result = {"success": 0, "not_found": 0, "error": 0}
    for _, exc in results:
        if exc is None:
            by_result["success"] += 1
        elif _is_not_found(exc):
            by_result["not_found"] += 1
        else:
            by_result["error"] += 1
    for result, n in by_result.items():
        if n:
            metrics["deletes_total"].labels(gen, kind, result).inc(n)


def _validate_controller_ref(ref: OwnerReference) -> None:
    """RealPodControl.createPods validation (upstream pod_control semantics)."""
    if ref is None:
        raise ValueError("controllerRef is required")
    if not ref.api_version or not ref.kind or not ref.name or not ref.uid:
        raise ValueError(f"controllerRef is incomplete: {ref}")
    if not ref.controller:
        raise ValueError("controllerRef.controller must be true")


def _pod_from_template(template: dict, controller_ref: OwnerReference) -> dict:
    pod = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": copy.deepcopy(template.get("metadata") or {}),
        "spec": copy.deepcopy(template.get("spec") or {}),
    }
    pod["metadata"]["ownerReferences"] = [controller_ref.to_dict()]
    return pod


class RealPodControl(_BatchCreateMixin, _BatchDeleteMixin):
    def __init__(self, clientset: Clientset, recorder, executor="shared",
                 delete_executor="shared"):
        self.clientset = clientset
        self.recorder = recorder
        # executor / delete_executor: "shared" (default) -> process-wide
        # pool; None -> serial; or any ThreadPoolExecutor-alike the caller
        # owns (bench/tests).
        self._create_executor = (
            shared_create_executor() if executor == "shared" else executor
        )
        self._delete_executor = (
            shared_delete_executor() if delete_executor == "shared"
            else delete_executor
        )

    def create_pods_batch(
        self, namespace: str, templates: list[dict], controller_obj: dict,
        controller_ref: OwnerReference,
    ) -> list[tuple[dict | None, Exception | None]]:
        """Fan out one create per template with bounded concurrency.
        Returns (created, exc) per slot, input-ordered."""
        return self._run_create_batch([
            (lambda t=t: self.create_pods_with_controller_ref(
                namespace, t, controller_obj, controller_ref))
            for t in templates
        ])

    def create_pods_with_controller_ref(
        self, namespace: str, template: dict, controller_obj: dict, controller_ref: OwnerReference
    ) -> dict:
        _validate_controller_ref(controller_ref)
        pod = _pod_from_template(template, controller_ref)
        try:
            created = self.clientset.pods(namespace).create(pod)
        except Exception as e:
            self.recorder.eventf(
                controller_obj, "Warning", FAILED_CREATE_POD_REASON,
                "Error creating: %s", e,
            )
            raise
        self.recorder.eventf(
            controller_obj, "Normal", SUCCESSFUL_CREATE_POD_REASON,
            "Created pod: %s", created["metadata"]["name"],
        )
        return created

    def delete_pods_batch(
        self, namespace: str, names: list[str], controller_obj: dict,
    ) -> list[tuple[dict | None, Exception | None]]:
        """Fan out one delete per name with bounded concurrency.
        Returns (result, exc) per slot, input-ordered (result is always
        None for deletes; only exc carries information)."""
        return self._run_delete_batch([
            (lambda n=n: self.delete_pod(namespace, n, controller_obj))
            for n in names
        ])

    def delete_pod(self, namespace: str, name: str, controller_obj: dict) -> None:
        try:
            self.clientset.pods(namespace).delete(name)
        except Exception as e:
            self.recorder.eventf(
                controller_obj, "Warning", FAILED_DELETE_POD_REASON,
                "Error deleting: %s", e,
            )
            raise
        self.recorder.eventf(
            controller_obj, "Normal", SUCCESSFUL_DELETE_POD_REASON,
            "Deleted pod: %s", name,
        )

    def patch_pod(self, namespace: str, name: str, patch: dict) -> None:
        # strategic, not JSON merge: client-go's PodControl sends
        # types.StrategicMergePatchType (controller_pod.go:99-169), so
        # ownerReferences/containers/env lists merge by key on the wire
        self.clientset.pods(namespace).patch(name, patch,
                                             patch_type="strategic")


class RealServiceControl(_BatchCreateMixin, _BatchDeleteMixin):
    """service_control.go:69-115."""

    def __init__(self, clientset: Clientset, recorder, executor="shared",
                 delete_executor="shared"):
        self.clientset = clientset
        self.recorder = recorder
        self._create_executor = (
            shared_create_executor() if executor == "shared" else executor
        )
        self._delete_executor = (
            shared_delete_executor() if delete_executor == "shared"
            else delete_executor
        )

    def create_services_batch(
        self, namespace: str, services: list[dict], controller_obj: dict,
        controller_ref: OwnerReference,
    ) -> list[tuple[dict | None, Exception | None]]:
        """Fan out one create per service with bounded concurrency.
        Returns (created, exc) per slot, input-ordered."""
        return self._run_create_batch([
            (lambda s=s: self.create_services_with_controller_ref(
                namespace, s, controller_obj, controller_ref))
            for s in services
        ])

    def create_services_with_controller_ref(
        self, namespace: str, service: dict, controller_obj: dict, controller_ref: OwnerReference
    ) -> dict:
        _validate_controller_ref(controller_ref)
        svc = copy.deepcopy(service)
        svc.setdefault("apiVersion", "v1")
        svc.setdefault("kind", "Service")
        svc.setdefault("metadata", {})["ownerReferences"] = [controller_ref.to_dict()]
        try:
            created = self.clientset.services(namespace).create(svc)
        except Exception as e:
            self.recorder.eventf(
                controller_obj, "Warning", FAILED_CREATE_POD_REASON,
                "Error creating: %s", e,
            )
            raise
        self.recorder.eventf(
            controller_obj, "Normal", SUCCESSFUL_CREATE_POD_REASON,
            "Created service: %s", created["metadata"]["name"],
        )
        return created

    def delete_services_batch(
        self, namespace: str, names: list[str], controller_obj: dict,
    ) -> list[tuple[dict | None, Exception | None]]:
        """Fan out one delete per name with bounded concurrency.
        Returns (result, exc) per slot, input-ordered."""
        return self._run_delete_batch([
            (lambda n=n: self.delete_service(namespace, n, controller_obj))
            for n in names
        ])

    def delete_service(self, namespace: str, name: str, controller_obj: dict) -> None:
        try:
            self.clientset.services(namespace).delete(name)
        except Exception as e:
            self.recorder.eventf(
                controller_obj, "Warning", FAILED_DELETE_POD_REASON,
                "Error deleting: %s", e,
            )
            raise
        self.recorder.eventf(
            controller_obj, "Normal", SUCCESSFUL_DELETE_POD_REASON,
            "Deleted service: %s", name,
        )

    def patch_service(self, namespace: str, name: str, patch: dict) -> None:
        # strategic for the same reason as RealPodControl.patch_pod
        self.clientset.services(namespace).patch(name, patch,
                                                 patch_type="strategic")


class FakePodControl(_BatchCreateMixin, _BatchDeleteMixin):
    """controller.FakePodControl: captures templates/deletions for asserts.

    Thread-safe: the concurrent creators AND deleters (create_pods_batch,
    delete_pods_batch, the per-replica-type reconcile fan-out) hit one fake
    from many threads, so every capture list append and ``clear()`` runs
    under a lock.  Batch creates/deletes stay inline serial by default
    (``_create_executor = _delete_executor = None``) so per-test capture
    order is deterministic; the thread-safety matters because the
    *controller* may call the fake from concurrent reconcile tasks."""

    def __init__(self):
        self._lock = checkedlock.make_lock("control.fake_pod")
        self.templates: list[dict] = []
        self.controller_refs: list[OwnerReference] = []
        self.delete_pod_names: list[str] = []
        self.patches: list[dict] = []
        self.create_error: Exception | None = None
        self.delete_error: Exception | None = None

    def create_pods_with_controller_ref(self, namespace, template, controller_obj, controller_ref):
        _validate_controller_ref(controller_ref)
        captured = copy.deepcopy(template)
        with self._lock:
            # error injection is cleared under the lock (clear()), so the
            # racing reconcile threads must read it there too
            if self.create_error is not None:
                raise self.create_error
            self.templates.append(captured)
            self.controller_refs.append(controller_ref)
        return _pod_from_template(template, controller_ref)

    def create_pods_batch(self, namespace, templates, controller_obj, controller_ref):
        return self._run_create_batch([
            (lambda t=t: self.create_pods_with_controller_ref(
                namespace, t, controller_obj, controller_ref))
            for t in templates
        ])

    def delete_pods_batch(self, namespace, names, controller_obj):
        return self._run_delete_batch([
            (lambda n=n: self.delete_pod(namespace, n, controller_obj))
            for n in names
        ])

    def delete_pod(self, namespace, name, controller_obj):
        with self._lock:
            if self.delete_error is not None:
                raise self.delete_error
            self.delete_pod_names.append(name)

    def patch_pod(self, namespace, name, patch):
        with self._lock:
            self.patches.append(patch)

    def clear(self):
        with self._lock:
            self.templates = []
            self.controller_refs = []
            self.delete_pod_names = []
            self.patches = []
            self.create_error = None
            self.delete_error = None


class FakeServiceControl(_BatchCreateMixin, _BatchDeleteMixin):
    """service_control.go:117-175.  Thread-safe for the same reason as
    FakePodControl, and carries the same ``delete_error`` injection seam —
    the service teardown wave (terminal cleanup under cleanPodPolicy=All)
    needs failure tests exactly like the pod side."""

    def __init__(self):
        self._lock = checkedlock.make_lock("control.fake_service")
        self.services: list[dict] = []
        self.controller_refs: list[OwnerReference] = []
        self.delete_service_names: list[str] = []
        self.patches: list[dict] = []
        self.create_error: Exception | None = None
        self.delete_error: Exception | None = None

    def create_services_with_controller_ref(self, namespace, service, controller_obj, controller_ref):
        _validate_controller_ref(controller_ref)
        captured = copy.deepcopy(service)
        with self._lock:
            if self.create_error is not None:
                raise self.create_error
            self.services.append(captured)
            self.controller_refs.append(controller_ref)
        return copy.deepcopy(service)

    def create_services_batch(self, namespace, services, controller_obj, controller_ref):
        return self._run_create_batch([
            (lambda s=s: self.create_services_with_controller_ref(
                namespace, s, controller_obj, controller_ref))
            for s in services
        ])

    def delete_services_batch(self, namespace, names, controller_obj):
        return self._run_delete_batch([
            (lambda n=n: self.delete_service(namespace, n, controller_obj))
            for n in names
        ])

    def delete_service(self, namespace, name, controller_obj):
        with self._lock:
            if self.delete_error is not None:
                raise self.delete_error
            self.delete_service_names.append(name)

    def patch_service(self, namespace, name, patch):
        with self._lock:
            self.patches.append(patch)

    def clear(self):
        with self._lock:
            self.services = []
            self.controller_refs = []
            self.delete_service_names = []
            self.patches = []
            self.create_error = None
            self.delete_error = None

"""Pod/Service control seams (reference: upstream PodControl +
pkg/controller.v2/service_control.go).

These exist as interfaces *specifically because* they are the fake points for
the controller test tier (controller_test.go:65-66): tests swap in
``FakePodControl``/``FakeServiceControl`` to capture creates/deletes without
an apiserver.  The real implementations validate the controller ref, create
via the clientset, and record K8s events (service_control.go:69-115).
"""

from __future__ import annotations

import copy
import logging

from k8s_tpu.api.meta import OwnerReference
from k8s_tpu.client.clientset import Clientset

log = logging.getLogger(__name__)

FAILED_CREATE_POD_REASON = "FailedCreate"
SUCCESSFUL_CREATE_POD_REASON = "SuccessfulCreate"
FAILED_DELETE_POD_REASON = "FailedDelete"
SUCCESSFUL_DELETE_POD_REASON = "SuccessfulDelete"


def _validate_controller_ref(ref: OwnerReference) -> None:
    """RealPodControl.createPods validation (upstream pod_control semantics)."""
    if ref is None:
        raise ValueError("controllerRef is required")
    if not ref.api_version or not ref.kind or not ref.name or not ref.uid:
        raise ValueError(f"controllerRef is incomplete: {ref}")
    if not ref.controller:
        raise ValueError("controllerRef.controller must be true")


def _pod_from_template(template: dict, controller_ref: OwnerReference) -> dict:
    pod = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": copy.deepcopy(template.get("metadata") or {}),
        "spec": copy.deepcopy(template.get("spec") or {}),
    }
    pod["metadata"]["ownerReferences"] = [controller_ref.to_dict()]
    return pod


class RealPodControl:
    def __init__(self, clientset: Clientset, recorder):
        self.clientset = clientset
        self.recorder = recorder

    def create_pods_with_controller_ref(
        self, namespace: str, template: dict, controller_obj: dict, controller_ref: OwnerReference
    ) -> dict:
        _validate_controller_ref(controller_ref)
        pod = _pod_from_template(template, controller_ref)
        try:
            created = self.clientset.pods(namespace).create(pod)
        except Exception as e:
            self.recorder.eventf(
                controller_obj, "Warning", FAILED_CREATE_POD_REASON,
                "Error creating: %s", e,
            )
            raise
        self.recorder.eventf(
            controller_obj, "Normal", SUCCESSFUL_CREATE_POD_REASON,
            "Created pod: %s", created["metadata"]["name"],
        )
        return created

    def delete_pod(self, namespace: str, name: str, controller_obj: dict) -> None:
        try:
            self.clientset.pods(namespace).delete(name)
        except Exception as e:
            self.recorder.eventf(
                controller_obj, "Warning", FAILED_DELETE_POD_REASON,
                "Error deleting: %s", e,
            )
            raise
        self.recorder.eventf(
            controller_obj, "Normal", SUCCESSFUL_DELETE_POD_REASON,
            "Deleted pod: %s", name,
        )

    def patch_pod(self, namespace: str, name: str, patch: dict) -> None:
        # strategic, not JSON merge: client-go's PodControl sends
        # types.StrategicMergePatchType (controller_pod.go:99-169), so
        # ownerReferences/containers/env lists merge by key on the wire
        self.clientset.pods(namespace).patch(name, patch,
                                             patch_type="strategic")


class RealServiceControl:
    """service_control.go:69-115."""

    def __init__(self, clientset: Clientset, recorder):
        self.clientset = clientset
        self.recorder = recorder

    def create_services_with_controller_ref(
        self, namespace: str, service: dict, controller_obj: dict, controller_ref: OwnerReference
    ) -> dict:
        _validate_controller_ref(controller_ref)
        svc = copy.deepcopy(service)
        svc.setdefault("apiVersion", "v1")
        svc.setdefault("kind", "Service")
        svc.setdefault("metadata", {})["ownerReferences"] = [controller_ref.to_dict()]
        try:
            created = self.clientset.services(namespace).create(svc)
        except Exception as e:
            self.recorder.eventf(
                controller_obj, "Warning", FAILED_CREATE_POD_REASON,
                "Error creating: %s", e,
            )
            raise
        self.recorder.eventf(
            controller_obj, "Normal", SUCCESSFUL_CREATE_POD_REASON,
            "Created service: %s", created["metadata"]["name"],
        )
        return created

    def delete_service(self, namespace: str, name: str, controller_obj: dict) -> None:
        try:
            self.clientset.services(namespace).delete(name)
        except Exception as e:
            self.recorder.eventf(
                controller_obj, "Warning", FAILED_DELETE_POD_REASON,
                "Error deleting: %s", e,
            )
            raise
        self.recorder.eventf(
            controller_obj, "Normal", SUCCESSFUL_DELETE_POD_REASON,
            "Deleted service: %s", name,
        )

    def patch_service(self, namespace: str, name: str, patch: dict) -> None:
        # strategic for the same reason as RealPodControl.patch_pod
        self.clientset.services(namespace).patch(name, patch,
                                                 patch_type="strategic")


class FakePodControl:
    """controller.FakePodControl: captures templates/deletions for asserts."""

    def __init__(self):
        self.templates: list[dict] = []
        self.controller_refs: list[OwnerReference] = []
        self.delete_pod_names: list[str] = []
        self.patches: list[dict] = []
        self.create_error: Exception | None = None
        self.delete_error: Exception | None = None

    def create_pods_with_controller_ref(self, namespace, template, controller_obj, controller_ref):
        _validate_controller_ref(controller_ref)
        if self.create_error is not None:
            raise self.create_error
        self.templates.append(copy.deepcopy(template))
        self.controller_refs.append(controller_ref)
        return _pod_from_template(template, controller_ref)

    def delete_pod(self, namespace, name, controller_obj):
        if self.delete_error is not None:
            raise self.delete_error
        self.delete_pod_names.append(name)

    def patch_pod(self, namespace, name, patch):
        self.patches.append(patch)

    def clear(self):
        self.__init__()


class FakeServiceControl:
    """service_control.go:117-175."""

    def __init__(self):
        self.services: list[dict] = []
        self.controller_refs: list[OwnerReference] = []
        self.delete_service_names: list[str] = []
        self.patches: list[dict] = []
        self.create_error: Exception | None = None

    def create_services_with_controller_ref(self, namespace, service, controller_obj, controller_ref):
        _validate_controller_ref(controller_ref)
        if self.create_error is not None:
            raise self.create_error
        self.services.append(copy.deepcopy(service))
        self.controller_refs.append(controller_ref)
        return copy.deepcopy(service)

    def delete_service(self, namespace, name, controller_obj):
        self.delete_service_names.append(name)

    def patch_service(self, namespace, name, patch):
        self.patches.append(patch)

    def clear(self):
        self.__init__()

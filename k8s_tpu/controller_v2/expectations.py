"""ControllerExpectations (k8s.io/kubernetes/pkg/controller expectations,
consumed at pkg/controller.v2/controller.go:417-436 and controller_pod.go:99).

Expectations are a TTL cache of in-flight creates/deletes per controller key,
preventing a reconcile from re-creating pods whose informer ADD events have
not arrived yet.  ``satisfied(key)`` is the gate before a full reconcile
(controller.go:417): true when the record is fulfilled, expired, or absent.
"""

from __future__ import annotations

from k8s_tpu.analysis import checkedlock
import time

EXPECTATION_TTL_SECONDS = 5 * 60.0  # ExpectationsTimeout in upstream


class _Expectation:
    __slots__ = ("adds", "dels", "timestamp")

    def __init__(self, adds: int = 0, dels: int = 0):
        self.adds = adds
        self.dels = dels
        self.timestamp = time.monotonic()

    def fulfilled(self) -> bool:
        return self.adds <= 0 and self.dels <= 0

    def expired(self) -> bool:
        return time.monotonic() - self.timestamp > EXPECTATION_TTL_SECONDS


class ControllerExpectations:
    def __init__(self):
        self._lock = checkedlock.make_lock("expectations")
        self._store: dict[str, _Expectation] = {}

    def expect_creations(self, key: str, count: int) -> None:
        """Record ``count`` expected creates.  Unlike upstream's replace
        semantics, pending un-expired expectations accumulate: the reconcilers
        call this once per object in a burst (createNewPod pattern,
        controller_pod.go:110), and replacing the record would let a single
        observed ADD satisfy the whole burst, re-opening the duplicate-create
        race the cache exists to prevent."""
        with self._lock:
            exp = self._store.get(key)
            if exp is not None and not exp.expired() and (exp.adds > 0 or exp.dels > 0):
                exp.adds += count
            else:
                self._store[key] = _Expectation(adds=count)

    def expect_deletions(self, key: str, count: int) -> None:
        with self._lock:
            exp = self._store.get(key)
            if exp is not None and not exp.expired() and (exp.adds > 0 or exp.dels > 0):
                exp.dels += count
            else:
                self._store[key] = _Expectation(dels=count)

    def creation_observed(self, key: str) -> None:
        self._lower(key, add_delta=-1)

    def deletion_observed(self, key: str) -> None:
        self._lower(key, del_delta=-1)

    def _lower(self, key: str, add_delta: int = 0, del_delta: int = 0) -> None:
        with self._lock:
            exp = self._store.get(key)
            if exp is not None:
                exp.adds += add_delta
                exp.dels += del_delta

    def raise_expectations(self, key: str, adds: int, dels: int) -> None:
        with self._lock:
            exp = self._store.get(key)
            if exp is not None:
                exp.adds += adds
                exp.dels += dels

    def satisfied(self, key: str) -> bool:
        with self._lock:
            exp = self._store.get(key)
            if exp is None:
                return True  # new controller: needs a sync
            return exp.fulfilled() or exp.expired()

    def delete_expectations(self, key: str) -> None:
        with self._lock:
            self._store.pop(key, None)


def new_controller_expectations():
    """Factory seam mirroring workqueue.new_rate_limiting_queue: native TTL
    cache when the compiled runtime is available, else this module's.
    Selection policy is shared — k8s_tpu.native.select."""
    from k8s_tpu import native

    def _native():
        from k8s_tpu.native.runtime import NativeControllerExpectations

        return NativeControllerExpectations()

    return native.select(_native, ControllerExpectations)

"""Controller v2: stateless informer/expectations reconciler
(reference: pkg/controller.v2/)."""

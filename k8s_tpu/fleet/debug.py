"""/debug/fleet responder (mirror of trace.debug_traces_response,
scheduler.debug_scheduler_response, and flight.debug_timeline_response —
ONE implementation shared by the metrics server and the dashboard
backend, so both speak the same contract).

Routes:

- ``/debug/fleet``                 — plane summary (jobs, targets,
  staleness, scrape counters, SLO rules + breach flags)
- ``/debug/fleet?job=<ns/name>``   — that job's windowed rollups
  (counter rates, gauge stats, histogram p50/p99), targets, SLO state,
  and its recent events
- ``?since=<seq>``                 — only events newer than seq
  (incremental polling; the response echoes ``last_seq`` back)
- ``?n=<limit>``                   — most recent N events

404 with an explicit body while no fleet plane is active (the v2
controller starts one when fleet scraping is enabled) — the same
contract as every other /debug route.
"""

from __future__ import annotations

import json
from urllib.parse import parse_qs


def debug_fleet_response(plane, query: str = "") -> tuple[int, str, str]:
    """(status_code, body, content_type) for GET /debug/fleet."""
    if plane is None or not plane.active:
        return (404,
                "fleet telemetry inactive (enable K8S_TPU_FLEET_SCRAPE so "
                "the v2 controller starts the scrape plane)\n",
                "text/plain")
    params = parse_qs(query or "")

    def _int_param(name: str):
        raw = (params.get(name) or [None])[0]
        if raw is None:
            return None
        try:
            return int(raw)
        except ValueError:
            return None

    job = (params.get("job") or [None])[0]
    since = _int_param("since")
    limit = _int_param("n")
    if job:
        events = plane.events(since=since, job=job)
        if limit is not None and limit >= 0:
            events = events[-limit:] if limit else []
        body = json.dumps({
            "job": job,
            "rollup": plane.rollup(job),
            "slo": plane.slo.state(job),
            "targets": [t for t in plane.stats.targets()
                        if t.get("job") == job],
            "events": events,
            # empty incremental polls echo the caller's since (the
            # /debug/timeline contract: a last_seq of 0 would make the
            # next ?since=0 poll re-download the ring)
            "last_seq": events[-1]["seq"] if events else (since or 0),
        }, indent=2, default=str)
        return 200, body + "\n", "application/json"
    body = json.dumps(plane.summary(), indent=2, sort_keys=True, default=str)
    return 200, body + "\n", "application/json"

"""Bounded-concurrency scrape loop with per-target deadlines.

One cycle: resolve targets (a pure read over the informer cache via the
plane's ``targets_fn``), fan the HTTP GETs over a fixed thread pool,
parse each body through :mod:`k8s_tpu.fleet.parser`, and feed the
aggregator.  Failures are *tracked, never raised* — a dead pod makes
its target stale and its job's staleness gauge climb; it cannot stall
the loop or the other targets.

Self-observability (the ``fleet_scrape_*`` families the metrics module
proxies): per-(job, outcome) scrape counts, a scrape-duration
histogram, per-target last-success/failure state, and per-job
staleness.  Intervals are jittered (±``jitter_frac``) so a fleet of
operators scraping the same pods doesn't phase-lock.
"""

from __future__ import annotations

import threading
from k8s_tpu.analysis import checkedlock
import time
import urllib.request
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor, wait

DEFAULT_INTERVAL_S = 10.0
DEFAULT_TIMEOUT_S = 2.0
DEFAULT_CONCURRENCY = 8
DEFAULT_JITTER_FRAC = 0.1

# scrape-duration histogram bounds (seconds): scrapes are LAN-fast or
# broken, so the resolution clusters low with a tail for sick targets
DURATION_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                    1.0, 2.5, 5.0)

OUTCOME_OK = "ok"
OUTCOME_HTTP_ERROR = "http_error"
OUTCOME_TIMEOUT = "timeout"
OUTCOME_PARSE_ERROR = "parse_error"
OUTCOME_ERROR = "error"


def default_fetch(url: str, timeout_s: float) -> str:
    """GET one exposition body (the production fetch seam; benches and
    tests inject their own)."""
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        if resp.status != 200:
            raise OSError(f"scrape got HTTP {resp.status}")
        return resp.read().decode("utf-8", "replace")


class ScrapeStats:
    """Thread-safe scrape self-observability state.  Per-job scrape
    counters are LRU-bounded by job (``max_count_jobs``): under the
    repo's 2-5k-job churn regime a long-lived operator must not
    accumulate a ``fleet_scrape_total`` label set (and the memory behind
    it) for every job that ever existed — the least recently *scraped*
    job's counters are evicted, the same bounded-everything contract as
    the aggregator's job LRU and the plane's event ring.  (Prometheus
    treats the resulting counter reset like any target restart.)"""

    MAX_COUNT_JOBS = 1024

    def __init__(self, max_count_jobs: int = MAX_COUNT_JOBS):
        self._lock = checkedlock.make_lock("fleet.scrape_stats")
        self.max_count_jobs = max_count_jobs
        # job -> {outcome: n}; OrderedDict gives LRU-by-scrape
        self._counts: "OrderedDict[str, dict]" = OrderedDict()
        self._duration_counts = [0] * len(DURATION_BUCKETS)
        self._duration_sum = 0.0
        self._duration_n = 0
        self._targets: dict[str, dict] = {}  # target key -> status dict
        self.cycles = 0
        self.last_cycle_s = 0.0

    def record(self, target, outcome: str, duration_s: float,
               error: str = "", now: float | None = None) -> None:
        now = time.time() if now is None else now
        with self._lock:
            per_job = self._counts.get(target.job)
            if per_job is None:
                per_job = self._counts[target.job] = {}
                if len(self._counts) > self.max_count_jobs:
                    self._counts.popitem(last=False)
            else:
                self._counts.move_to_end(target.job)
            per_job[outcome] = per_job.get(outcome, 0) + 1
            self._duration_sum += duration_s
            self._duration_n += 1
            for i, bound in enumerate(DURATION_BUCKETS):
                if duration_s <= bound:
                    self._duration_counts[i] += 1
                    break
            st = self._targets.setdefault(target.key(), {
                "job": target.job, "pod": target.pod,
                "last_success": None, "consecutive_failures": 0,
            })
            st["url"] = target.url
            st["last_attempt"] = now
            st["last_outcome"] = outcome
            if outcome == OUTCOME_OK:
                st["last_success"] = now
                st["consecutive_failures"] = 0
                st.pop("last_error", None)
            else:
                st["consecutive_failures"] += 1
                st["last_error"] = error

    def prune(self, live_keys: set) -> None:
        """Drop state for targets discovery no longer returns (deleted or
        scaled-down pods must not hold staleness forever)."""
        with self._lock:
            for key in [k for k in self._targets if k not in live_keys]:
                del self._targets[key]

    def counts(self) -> dict[tuple, int]:
        """Flat ``{(job, outcome): n}`` view (the metric/label shape)."""
        with self._lock:
            return {(job, outcome): n
                    for job, per_job in self._counts.items()
                    for outcome, n in per_job.items()}

    def forget(self, job: str) -> None:
        """Drop a deleted job's scrape counters (cardinality hygiene —
        the plane forwards controller-observed job deletions here)."""
        with self._lock:
            self._counts.pop(job, None)

    def duration_samples(self) -> tuple:
        """(bounds, per-bucket counts, sum, count) — the ProxyMetric
        histogram shape ``util/metrics.flight_metrics`` also uses."""
        with self._lock:
            return (DURATION_BUCKETS, list(self._duration_counts),
                    self._duration_sum, self._duration_n)

    def targets(self) -> list[dict]:
        with self._lock:
            return [dict(v) for v in self._targets.values()]

    def staleness(self, now: float | None = None) -> dict[str, float]:
        """Per-job staleness: seconds since the *least recently
        successful* target of the job (the straggler defines the job's
        freshness — an aggregate missing one pod is not fresh)."""
        now = time.time() if now is None else now
        out: dict[str, float] = {}
        with self._lock:
            for st in self._targets.values():
                last = st.get("last_success")
                age = (now - last) if last is not None else float("inf")
                job = st["job"]
                if job not in out or age > out[job]:
                    out[job] = age
        return out

    def target_count(self) -> dict[str, int]:
        with self._lock:
            counts: dict[str, int] = {}
            for st in self._targets.values():
                counts[st["job"]] = counts.get(st["job"], 0) + 1
            return counts


class ScrapeLoop:
    """The cycle driver.  ``scrape_once`` is synchronous (tests and the
    bench call it directly for determinism); ``start`` runs it on a
    daemon thread at jittered intervals until ``stop``."""

    def __init__(self, targets_fn, aggregator, *, stats: ScrapeStats,
                 interval_s: float = DEFAULT_INTERVAL_S,
                 timeout_s: float = DEFAULT_TIMEOUT_S,
                 concurrency: int = DEFAULT_CONCURRENCY,
                 jitter_frac: float = DEFAULT_JITTER_FRAC,
                 fetch=None, on_cycle=None, on_failure=None):
        if interval_s <= 0 or timeout_s <= 0 or concurrency < 1:
            raise ValueError("scrape loop needs positive interval/timeout "
                             "and >= 1 concurrency")
        self.targets_fn = targets_fn
        self.aggregator = aggregator
        self.stats = stats
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self.concurrency = int(concurrency)
        self.jitter_frac = float(jitter_frac)
        self.fetch = fetch or default_fetch
        self.on_cycle = on_cycle      # called (targets, now) after each cycle
        self.on_failure = on_failure  # called (target, outcome, error)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._pool: ThreadPoolExecutor | None = None
        self._pool_lock = checkedlock.make_lock("fleet.scrape_pool")
        # targets currently submitted to the pool: a cycle never
        # re-enqueues a target whose previous scrape is still running,
        # so a mass outage (every fetch riding its deadline) cannot grow
        # the executor queue without bound cycle over cycle
        self._inflight: set = set()
        self._inflight_lock = checkedlock.make_lock("fleet.scrape_inflight")

    def _get_pool(self) -> ThreadPoolExecutor:
        with self._pool_lock:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=self.concurrency,
                    thread_name_prefix="fleet-scrape")
            return self._pool

    def _scrape_target(self, target, now_fn) -> None:
        from k8s_tpu.fleet import parser

        t0 = time.monotonic()
        outcome, error = OUTCOME_OK, ""
        try:
            body = self.fetch(target.url, self.timeout_s)
            families = parser.parse_exposition(body)
            self.aggregator.ingest(target.job, target.pod, families, now_fn())
        except parser.ParseError as e:
            outcome, error = OUTCOME_PARSE_ERROR, str(e)
        except TimeoutError as e:
            outcome, error = OUTCOME_TIMEOUT, str(e) or "timed out"
        except OSError as e:
            # urllib timeouts surface as socket.timeout (an OSError) or
            # URLError wrapping one; classify by message so the staleness
            # story distinguishes slow from refused
            msg = str(e)
            outcome = OUTCOME_TIMEOUT if "timed out" in msg \
                else OUTCOME_HTTP_ERROR
            error = msg
        except Exception as e:  # noqa: BLE001 - tracked, never raised
            outcome, error = OUTCOME_ERROR, f"{type(e).__name__}: {e}"
        finally:
            with self._inflight_lock:
                self._inflight.discard(target.key())
        self.stats.record(target, outcome, time.monotonic() - t0, error)
        if outcome != OUTCOME_OK and self.on_failure is not None:
            try:
                self.on_failure(target, outcome, error)
            except Exception:  # noqa: BLE001 - hook failure must not kill the scrape thread
                # ISSUE 11 first-audit fix: this swallow was silent — a
                # raising failure hook is the SLO/burn-rate wiring
                # breaking, which is itself an alertable condition
                import logging

                logging.getLogger(__name__).exception(
                    "fleet: on_failure hook raised for %s (%s)",
                    target.key(), outcome)

    def scrape_once(self, now: float | None = None) -> int:
        """One full cycle: discover, fan out, wait (bounded by the
        per-target timeout + slack), aggregate, evaluate.  Returns the
        number of targets scraped."""
        t_cycle = time.monotonic()
        now = time.time() if now is None else now
        targets = list(self.targets_fn() or ())
        self.stats.prune({t.key() for t in targets})
        if targets:
            pool = self._get_pool()
            # skip targets whose previous scrape is still in flight
            # (mass-outage cycles must not stack duplicate fetches)
            with self._inflight_lock:
                fresh = [t for t in targets
                         if t.key() not in self._inflight]
                self._inflight.update(t.key() for t in fresh)
            futures = [pool.submit(self._scrape_target, t, lambda: now)
                       for t in fresh]
            # budget for the WHOLE fan-out: with targets >> concurrency
            # the pool legitimately needs batches * deadline of wall
            # clock (every fetch has its own deadline inside); 2x slack
            # covers resolver stalls the socket timeout doesn't
            batches = -(-max(len(fresh), 1) // self.concurrency)
            wait(futures, timeout=batches * self.timeout_s * 2 + 5.0)
        self.aggregator.cycle_done(now, stale_after_s=self.interval_s * 3)
        if self.on_cycle is not None:
            try:
                self.on_cycle(targets, now)
            except Exception:  # noqa: BLE001 - evaluation must not kill the loop
                import logging

                logging.getLogger(__name__).exception("fleet cycle hook")
        self.stats.cycles += 1
        self.stats.last_cycle_s = time.monotonic() - t_cycle
        return len(targets)

    def _run(self) -> None:
        import random

        while not self._stop.is_set():
            try:
                self.scrape_once()
            except Exception:  # noqa: BLE001 - the loop must survive anything
                import logging

                logging.getLogger(__name__).exception("fleet scrape cycle")
            jitter = 1.0 + random.uniform(-self.jitter_frac, self.jitter_frac)
            self._stop.wait(self.interval_s * jitter)

    def start(self) -> "ScrapeLoop":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="fleet-scrape-loop")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.timeout_s * 2 + 5.0)
            self._thread = None
        with self._pool_lock:
            if self._pool is not None:
                self._pool.shutdown(wait=False)
                self._pool = None

"""Fleet telemetry plane (ISSUE 8): per-pod scrape loop, per-job
aggregation, and SLO burn-rate tracking.

The read-side half of ROADMAP item 2: the router/autoscaler needs to
know what the serving fleet is doing *right now* — aggregate tokens/s,
queue depth, batch occupancy, whether the p99 SLO is burning — derived
from the ``serve_*`` metrics every serving pod already exports (PR 5/6)
without a single extra apiserver call (discovery reads the informer
cache; PR 7's zero-steady-LIST property is preserved by construction).

Mirrors the ``trace.TRACER`` / ``scheduler.set_active`` /
``flight.TIMELINE`` pattern: one process-global *active plane* registry
so the metrics server and dashboard serve ``/debug/fleet`` without a
controller reference, 404-with-explicit-body while inactive.

This package is stdlib-only by policy (``harness/py_checks.py`` gates
it like ``trace/``, ``scheduler/``, and ``flight/``): it runs a scrape
thread inside the operator process and is read by two HTTP servers; all
informer/TFJob knowledge stays with its callers.
"""

from __future__ import annotations

import os
from typing import Optional

from k8s_tpu.fleet.aggregate import (  # noqa: F401 (public surface)
    FleetAggregator,
    fraction_above,
    quantile_from_buckets,
)
from k8s_tpu.fleet.debug import debug_fleet_response  # noqa: F401
from k8s_tpu.fleet.discovery import (  # noqa: F401
    ANNOTATION_ROUTER_DRAIN,
    ANNOTATION_SCRAPE_PORT,
    ENV_SCRAPE_PORT,
    ScrapeTarget,
    scrape_port,
    targets_from_pods,
)
from k8s_tpu.fleet.parser import (  # noqa: F401
    Family,
    ParseError,
    histogram_points,
    parse_exposition,
    render,
)
from k8s_tpu.fleet.plane import DEFAULT_WINDOWS, FleetPlane  # noqa: F401
from k8s_tpu.fleet.scrape import (  # noqa: F401
    DEFAULT_INTERVAL_S,
    ScrapeLoop,
    ScrapeStats,
)
from k8s_tpu.fleet.slo import (  # noqa: F401
    DEFAULT_RULES_SPEC,
    SloEvaluator,
    SloRule,
    parse_rules,
)

# -- env knobs ----------------------------------------------------------------

ENV_SCRAPE_ENABLE = "K8S_TPU_FLEET_SCRAPE"
ENV_INTERVAL = "K8S_TPU_FLEET_INTERVAL_S"
ENV_TIMEOUT = "K8S_TPU_FLEET_TIMEOUT_S"
ENV_CONCURRENCY = "K8S_TPU_FLEET_CONCURRENCY"
ENV_SLO_RULES = "K8S_TPU_FLEET_SLO"
ENV_WINDOWS = "K8S_TPU_FLEET_WINDOWS"
ENV_MAX_JOBS = "K8S_TPU_FLEET_MAX_JOBS"


def scrape_enabled_from_env() -> bool:
    """K8S_TPU_FLEET_SCRAPE: truthy enables the controller's fleet plane
    (default off — the compatibility default; /debug/fleet then 404s)."""
    return os.environ.get(ENV_SCRAPE_ENABLE, "").lower() in ("1", "true",
                                                             "on", "yes")


def _float_from_env(name: str, default: float) -> float:
    try:
        v = float(os.environ.get(name, ""))
    except ValueError:
        return default
    return v if v > 0 else default


def _int_from_env(name: str, default: int) -> int:
    try:
        v = int(os.environ.get(name, ""))
    except ValueError:
        return default
    return v if v > 0 else default


def interval_from_env() -> float:
    return _float_from_env(ENV_INTERVAL, DEFAULT_INTERVAL_S)


def timeout_from_env() -> float:
    from k8s_tpu.fleet.scrape import DEFAULT_TIMEOUT_S

    return _float_from_env(ENV_TIMEOUT, DEFAULT_TIMEOUT_S)


def concurrency_from_env() -> int:
    from k8s_tpu.fleet.scrape import DEFAULT_CONCURRENCY

    return _int_from_env(ENV_CONCURRENCY, DEFAULT_CONCURRENCY)


def max_jobs_from_env() -> int:
    from k8s_tpu.fleet.aggregate import DEFAULT_MAX_JOBS

    return _int_from_env(ENV_MAX_JOBS, DEFAULT_MAX_JOBS)


def rules_spec_from_env() -> str:
    return os.environ.get(ENV_SLO_RULES, "") or DEFAULT_RULES_SPEC


def windows_from_env() -> tuple:
    """K8S_TPU_FLEET_WINDOWS: "short,long" seconds for the SLO /
    aggregation windows (default 30,300).  Garbage or a non-increasing
    pair falls back to the default."""
    raw = os.environ.get(ENV_WINDOWS, "")
    parts = [p.strip() for p in raw.split(",") if p.strip()]
    if len(parts) == 2:
        try:
            short, long_ = float(parts[0]), float(parts[1])
        except ValueError:
            return DEFAULT_WINDOWS
        if 0 < short < long_:
            return (short, long_)
    return DEFAULT_WINDOWS


# -- process-global active plane (trace.TRACER / scheduler pattern) -----------

_ACTIVE: Optional[FleetPlane] = None


def set_active(plane: Optional[FleetPlane]) -> None:
    global _ACTIVE
    _ACTIVE = plane


def active() -> Optional[FleetPlane]:
    return _ACTIVE


def debug_response(query: str = "") -> tuple[int, str, str]:
    """The /debug/fleet endpoint body for the active plane."""
    return debug_fleet_response(_ACTIVE, query)

"""Scrape-target discovery from the informer's pod cache.

The fleet plane never talks to the apiserver: the controller hands it a
``targets_fn`` that reads the pod informer's *store* (plain dicts, the
same zero-steady-LIST substrate every sync uses — PR 7's churn bench
property is preserved by construction).  This module is the pure
function from those cached pod dicts to scrape targets; it imports
nothing from the client layer.

A pod is scrape-discoverable when it is Running, not terminating, and
declares a scrape port — either the ``kubeflow.org/fleet-scrape-port``
annotation (what ``genjob --serve`` stamps) or a
``K8S_TPU_FLEET_SCRAPE_PORT`` container env var.  The target address
prefers the annotation host override (benches / exotic networks), then
``status.podIP``, then the pod's per-index headless-service DNS name
(the service the controller already created for it — no extra lookup
needed, the name is derivable from the labels on the pod).
"""

from __future__ import annotations

# Annotation keys (pod template metadata → every pod of the job).
ANNOTATION_SCRAPE_PORT = "kubeflow.org/fleet-scrape-port"
ANNOTATION_SCRAPE_PATH = "kubeflow.org/fleet-scrape-path"
ANNOTATION_SCRAPE_HOST = "kubeflow.org/fleet-scrape-host"
ANNOTATION_SCRAPE = "kubeflow.org/fleet-scrape"  # "false" opts a pod out
# relative serving capacity (ISSUE 14): the router's weighted hash ring
# plants keyspace points proportional to this — a 4-chip tensor-parallel
# serving pod next to 1-chip pods declares 4.0 and receives ~4x the
# affine placements.  Absent/garbage = 1.0; must be > 0.
ANNOTATION_SERVE_WEIGHT = "kubeflow.org/fleet-serve-weight"
# Router drain protocol (ISSUE 13): the operator's autoscaler annotates
# a scale-down victim POD (not the template) truthy before patching the
# replica count; any router whose discovery feeds from the pod cache
# marks that backend draining — no new placements, in-flight requests
# finish — before the pod itself is deleted.  A falsy value un-drains.
ANNOTATION_ROUTER_DRAIN = "kubeflow.org/router-drain"
# Disaggregated serving tiers (ISSUE 15): role marks a pod as a
# prefill- or decode-tier member (absent = collapsed single-role pod),
# and kvxfer-port is the decode pod's KV block-transfer listener — the
# router derives the ``kv_dest`` long requests follow their blocks to
# (host is the pod's scrape host, port this annotation).
ANNOTATION_SERVE_ROLE = "kubeflow.org/serve-role"
ANNOTATION_KVXFER_PORT = "kubeflow.org/kvxfer-port"

# Env var fallback carried by serving containers (genjob --serve).
ENV_SCRAPE_PORT = "K8S_TPU_FLEET_SCRAPE_PORT"

# Same label keys as controller_v2/tpu_config.py — literal by design:
# this package may not import controller modules (stdlib-only gate), and
# the label contract is pinned by tests on both sides.
_LABEL_REPLICA_TYPE = "tf-replica-type"
_LABEL_REPLICA_INDEX = "tf-replica-index"
_LABEL_TFJOB_KEY = "tf_job_key"


class ScrapeTarget:
    """One scrapeable pod: its owning job key (``namespace/name``), pod
    identity, the URL to GET, and the router-drain flag (None = no
    annotation; the router leaves its local drain state alone)."""

    __slots__ = ("job", "namespace", "job_name", "pod", "index", "url",
                 "draining", "weight", "role", "kvxfer")

    def __init__(self, job: str, namespace: str, job_name: str, pod: str,
                 index: str, url: str, draining=None,
                 weight: float = 1.0, role: str = "",
                 kvxfer=None):
        self.job = job
        self.namespace = namespace
        self.job_name = job_name
        self.pod = pod
        self.index = index
        self.url = url
        self.draining = draining
        self.weight = weight
        # disaggregated tier membership + the pod's kv-transfer address
        # ("host:port", decode-tier pods only) — ISSUE 15
        self.role = role
        self.kvxfer = kvxfer

    def key(self) -> str:
        return f"{self.job}:{self.pod}"

    def to_dict(self) -> dict:
        return {"job": self.job, "pod": self.pod, "index": self.index,
                "url": self.url}

    def __repr__(self):
        return f"ScrapeTarget({self.job}:{self.pod} -> {self.url})"


def _controller_owner(meta: dict):
    for ref in meta.get("ownerReferences") or []:
        if ref.get("controller") and ref.get("kind") == "TFJob":
            return ref
    return None


def scrape_port(pod: dict) -> int | None:
    """The pod's declared fleet scrape port (annotation first, then the
    container env), or None when the pod is not scrape-discoverable.
    Public: the informer layer's fleet-scrape index keys off this same
    predicate, so "indexed" and "discoverable" cannot drift apart."""
    meta = pod.get("metadata") or {}
    annotations = meta.get("annotations") or {}
    raw = annotations.get(ANNOTATION_SCRAPE_PORT)
    if raw is None:
        for container in ((pod.get("spec") or {}).get("containers")) or []:
            for env in container.get("env") or []:
                if env.get("name") == ENV_SCRAPE_PORT:
                    raw = env.get("value")
                    break
            if raw is not None:
                break
    if raw is None:
        return None
    try:
        port = int(raw)
    except (TypeError, ValueError):
        return None
    return port if 0 < port < 65536 else None


def _dns_host(meta: dict) -> str | None:
    """The pod's per-index headless-service DNS name, rebuilt from the
    labels the controller stamped (tpu_config.gen_general_name contract:
    ``<ns>-<name>-<rtype>-<index>.<ns>.svc.cluster.local``)."""
    labels = meta.get("labels") or {}
    job_key = labels.get(_LABEL_TFJOB_KEY)
    rtype = labels.get(_LABEL_REPLICA_TYPE)
    index = labels.get(_LABEL_REPLICA_INDEX)
    ns = meta.get("namespace", "")
    if not (job_key and rtype and index is not None and ns):
        return None
    return f"{job_key}-{rtype}-{index}.{ns}.svc.cluster.local"


def targets_from_pods(pods: list[dict]) -> list[ScrapeTarget]:
    """Resolve the scrapeable subset of the cached pods.

    Pure function over store dicts — safe to call per scrape cycle, no
    copies made, nothing mutated (the informer's read-only contract)."""
    targets: list[ScrapeTarget] = []
    for pod in pods:
        meta = pod.get("metadata") or {}
        if meta.get("deletionTimestamp"):
            continue
        if (pod.get("status") or {}).get("phase") != "Running":
            continue
        annotations = meta.get("annotations") or {}
        if annotations.get(ANNOTATION_SCRAPE, "").lower() in ("false", "0"):
            continue
        port = scrape_port(pod)
        if port is None:
            continue
        ref = _controller_owner(meta)
        if ref is None:
            continue
        ns = meta.get("namespace", "")
        job_name = ref.get("name", "")
        host = (annotations.get(ANNOTATION_SCRAPE_HOST)
                or (pod.get("status") or {}).get("podIP")
                or _dns_host(meta))
        if not host:
            continue
        path = annotations.get(ANNOTATION_SCRAPE_PATH) or "/metrics"
        if not path.startswith("/"):
            path = "/" + path
        drain_raw = annotations.get(ANNOTATION_ROUTER_DRAIN)
        draining = (None if drain_raw is None
                    else drain_raw.lower() in ("1", "true", "yes", "on"))
        try:
            weight = float(annotations.get(ANNOTATION_SERVE_WEIGHT, 1.0))
        except (TypeError, ValueError):
            weight = 1.0  # garbage annotation: default share, not a crash
        if weight <= 0:
            weight = 1.0
        role = str(annotations.get(ANNOTATION_SERVE_ROLE, "")
                   ).strip().lower()
        if role not in ("prefill", "decode"):
            role = ""  # garbage annotation: collapsed pod, not a crash
        kvxfer = None
        raw_kv = annotations.get(ANNOTATION_KVXFER_PORT)
        if raw_kv is not None:
            try:
                kv_port = int(raw_kv)
            except (TypeError, ValueError):
                kv_port = 0
            if 0 < kv_port < 65536:
                kvxfer = f"{host}:{kv_port}"
        targets.append(ScrapeTarget(
            job=f"{ns}/{job_name}" if ns else job_name,
            namespace=ns,
            job_name=job_name,
            pod=meta.get("name", ""),
            index=(meta.get("labels") or {}).get(_LABEL_REPLICA_INDEX, ""),
            url=f"http://{host}:{port}{path}",
            draining=draining,
            weight=weight,
            role=role,
            kvxfer=kvxfer,
        ))
    return targets

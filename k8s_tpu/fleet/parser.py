"""Prometheus text-exposition (0.0.4) parser + renderer.

The scrape loop's wire format is exactly what ``util/metrics.py`` (and
any real Prometheus client) emits: ``# HELP`` / ``# TYPE`` comments
followed by sample lines ``name{label="value",...} 1.5``.  The parser
groups samples into *families* keyed by base name — histogram
``_bucket`` / ``_sum`` / ``_count`` suffix lines fold under the
histogram's declared name — and validates the histogram contract
(``le`` bounds present, numerically ordered, ``+Inf`` last and equal to
``_count``) so a malformed exporter fails the scrape instead of
corrupting fleet quantiles.

``render`` is the exact inverse; ``tests/test_fleet.py`` pins the
round trip over every family ``util/metrics.py`` exposes, so this
parser and that exposition format cannot drift apart silently.

Stdlib-only (``harness/py_checks.py`` gates the whole package).
"""

from __future__ import annotations

_INF = float("inf")

# sample-name suffixes that belong to a declared histogram family
_HISTOGRAM_SUFFIXES = ("_bucket", "_sum", "_count")


class ParseError(ValueError):
    """Malformed exposition text (carries the offending line number)."""

    def __init__(self, message: str, lineno: int = 0):
        super().__init__(f"line {lineno}: {message}" if lineno else message)
        self.lineno = lineno


class Family:
    """One metric family: name, kind (counter/gauge/histogram/untyped),
    help text, and its samples as ``(sample_name, labels_dict, value)``
    triples in arrival order (``sample_name`` differs from ``name`` only
    for histogram suffix lines)."""

    __slots__ = ("name", "kind", "help", "samples", "_points")

    def __init__(self, name: str, kind: str = "untyped", help_text: str = ""):
        self.name = name
        self.kind = kind
        self.help = help_text
        self.samples: list[tuple[str, dict, float]] = []
        # histogram_points memo: parse-time validation and the
        # aggregator's ingest read the same decomposition; families are
        # immutable after parsing, so computing it twice per scrape of
        # every histogram (once under the aggregator lock) is pure waste
        self._points = None

    def values(self) -> dict:
        """``{labels_tuple: value}`` for non-suffixed samples (counters
        and gauges; histogram families use :func:`histogram_points`)."""
        out = {}
        for sname, labels, value in self.samples:
            if sname == self.name:
                out[tuple(sorted(labels.items()))] = value
        return out

    def __repr__(self):  # debugging aid only
        return f"Family({self.name!r}, {self.kind!r}, {len(self.samples)} samples)"


def _parse_value(raw: str, lineno: int) -> float:
    raw = raw.strip()
    if raw == "+Inf":
        return _INF
    if raw == "-Inf":
        return -_INF
    try:
        return float(raw)
    except ValueError:
        raise ParseError(f"bad sample value {raw!r}", lineno) from None


def _unescape(raw: str) -> str:
    out = []
    i = 0
    while i < len(raw):
        c = raw[i]
        if c == "\\" and i + 1 < len(raw):
            nxt = raw[i + 1]
            if nxt == "n":
                out.append("\n")
            elif nxt in ("\\", '"'):
                out.append(nxt)
            else:  # unknown escape: keep verbatim (prometheus behavior)
                out.append(c)
                out.append(nxt)
            i += 2
            continue
        out.append(c)
        i += 1
    return "".join(out)


def _parse_labels(raw: str, lineno: int) -> dict:
    """``name="value",...`` (the text between ``{`` and ``}``)."""
    labels: dict[str, str] = {}
    i, n = 0, len(raw)
    while i < n:
        eq = raw.find("=", i)
        if eq < 0:
            raise ParseError(f"bad label pair in {raw!r}", lineno)
        key = raw[i:eq].strip().lstrip(",").strip()
        if not key:
            raise ParseError(f"empty label name in {raw!r}", lineno)
        j = eq + 1
        while j < n and raw[j] in " \t":
            j += 1
        if j >= n or raw[j] != '"':
            raise ParseError(f"unquoted label value in {raw!r}", lineno)
        j += 1
        buf = []
        while j < n:
            c = raw[j]
            if c == "\\" and j + 1 < n:
                buf.append(c)
                buf.append(raw[j + 1])
                j += 2
                continue
            if c == '"':
                break
            buf.append(c)
            j += 1
        if j >= n:
            raise ParseError(f"unterminated label value in {raw!r}", lineno)
        labels[key] = _unescape("".join(buf))
        i = j + 1
    return labels


def _base_name(sample_name: str, families: dict) -> str:
    """Fold histogram suffix lines under their declared family."""
    for suffix in _HISTOGRAM_SUFFIXES:
        if sample_name.endswith(suffix):
            base = sample_name[: -len(suffix)]
            fam = families.get(base)
            if fam is not None and fam.kind == "histogram":
                return base
    return sample_name


def parse_exposition(text: str) -> dict[str, Family]:
    """Parse one exposition body into ``{family_name: Family}``.

    Families appear in declaration order (dicts preserve insertion);
    a sample line with no preceding ``# TYPE`` gets an ``untyped``
    family.  Raises :class:`ParseError` on malformed lines — a scrape
    of a broken exporter must count as a failed scrape.
    """
    families: dict[str, Family] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            # "# HELP name text..." / "# TYPE name kind"
            if len(parts) >= 3 and parts[1] in ("HELP", "TYPE"):
                name = parts[2]
                fam = families.get(name)
                if fam is None:
                    fam = families[name] = Family(name)
                if parts[1] == "TYPE":
                    if len(parts) < 4:
                        raise ParseError("TYPE line without a kind", lineno)
                    fam.kind = parts[3].strip()
                else:
                    fam.help = parts[3] if len(parts) > 3 else ""
            continue  # other comments are ignored per the format spec
        # sample line: name[{labels}] value [timestamp]
        brace = line.find("{")
        if brace >= 0:
            close = line.rfind("}")
            if close < brace:
                raise ParseError(f"unbalanced braces in {line!r}", lineno)
            sample_name = line[:brace].strip()
            labels = _parse_labels(line[brace + 1:close], lineno)
            rest = line[close + 1:].strip()
        else:
            fields = line.split()
            if len(fields) < 2:
                raise ParseError(f"sample line without a value: {line!r}",
                                 lineno)
            sample_name, rest = fields[0], " ".join(fields[1:])
            labels = {}
        if not sample_name:
            raise ParseError(f"sample line without a name: {line!r}", lineno)
        fields = rest.split()
        if not fields:  # e.g. 'foo{a="b"}' — labels but no value
            raise ParseError(f"sample line without a value: {line!r}",
                             lineno)
        value = _parse_value(fields[0], lineno)  # optional timestamp dropped
        base = _base_name(sample_name, families)
        fam = families.get(base)
        if fam is None:
            fam = families[base] = Family(base)
        fam.samples.append((sample_name, labels, value))
    _fold_stray_histogram_suffixes(families)
    for fam in families.values():
        if fam.kind == "histogram":
            histogram_points(fam)  # validates le ordering / +Inf contract
    return families


def _fold_stray_histogram_suffixes(families: dict) -> None:
    """Samples emitted BEFORE their family's ``# TYPE ... histogram``
    line land in untyped ``<name>_bucket``/``_sum``/``_count`` families
    (``_base_name`` can only fold suffixes under an already-declared
    histogram).  Fold them back once the declaration is known — without
    this, an out-of-order exporter's histogram data would be silently
    dropped AND skip the +Inf/_count validation."""
    for name, fam in list(families.items()):
        if fam.kind != "histogram":
            continue
        for suffix in _HISTOGRAM_SUFFIXES:
            stray = families.get(name + suffix)
            if stray is None or stray.kind != "untyped" or stray.help:
                continue  # a real (declared) family, not a stray
            fam.samples.extend(stray.samples)
            del families[name + suffix]


def histogram_points(family: Family) -> dict:
    """Per-labelset histogram decomposition with contract validation.

    Returns ``{labels_tuple: {"buckets": [(le, cumulative_count), ...],
    "sum": float, "count": float}}`` where ``labels_tuple`` excludes the
    ``le`` label and buckets are sorted by bound.  Raises
    :class:`ParseError` when bucket counts are not monotonically
    non-decreasing with ``le``, when ``+Inf`` is missing, or when the
    ``+Inf`` bucket disagrees with ``_count``.
    """
    if family.kind != "histogram":
        raise ParseError(f"{family.name} is {family.kind}, not histogram")
    if family._points is not None:
        return family._points
    points: dict[tuple, dict] = {}

    def _point(labels: dict) -> dict:
        key = tuple(sorted((k, v) for k, v in labels.items() if k != "le"))
        return points.setdefault(key, {"buckets": [], "sum": None,
                                       "count": None})

    for sname, labels, value in family.samples:
        if sname == family.name + "_bucket":
            if "le" not in labels:
                raise ParseError(
                    f"{family.name}_bucket sample without an le label")
            le = _parse_value(labels["le"], 0)
            _point(labels)["buckets"].append((le, value))
        elif sname == family.name + "_sum":
            _point(labels)["sum"] = value
        elif sname == family.name + "_count":
            _point(labels)["count"] = value
    for key, point in points.items():
        buckets = sorted(point["buckets"])
        point["buckets"] = buckets
        if not buckets or buckets[-1][0] != _INF:
            raise ParseError(
                f"{family.name}{dict(key)}: histogram without a +Inf bucket")
        last = -1.0
        for le, cum in buckets:
            if cum < last:
                raise ParseError(
                    f"{family.name}{dict(key)}: bucket counts decrease "
                    f"at le={le!r} ({cum} < {last})")
            last = cum
        if point["count"] is not None and buckets[-1][1] != point["count"]:
            raise ParseError(
                f"{family.name}{dict(key)}: +Inf bucket "
                f"{buckets[-1][1]} != _count {point['count']}")
    family._points = points
    return points


def _format_value(v: float) -> str:
    if v == _INF:
        return "+Inf"
    if v == -_INF:
        return "-Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


def _escape(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def render(families: dict[str, Family]) -> str:
    """The inverse of :func:`parse_exposition` (modulo float formatting):
    used by the round-trip regression test and the bench's fake serving
    pods."""
    lines: list[str] = []
    for fam in families.values():
        lines.append(f"# HELP {fam.name} {fam.help}")
        lines.append(f"# TYPE {fam.name} {fam.kind}")
        for sname, labels, value in fam.samples:
            if labels:
                pairs = ",".join(f'{k}="{_escape(v)}"'
                                 for k, v in labels.items())
                lines.append(f"{sname}{{{pairs}}} {_format_value(value)}")
            else:
                lines.append(f"{sname} {_format_value(value)}")
    return "\n".join(lines) + "\n" if lines else ""

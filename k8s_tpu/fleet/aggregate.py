"""Per-job fleet aggregation over scraped samples.

Every scrape cycle feeds parsed families per (job, pod) in here; the
aggregator keeps **bounded time-series rings** so any "tokens/s over the
last 30s/5m" question is a pure read over memory — no apiserver, no
re-scrape, no unbounded growth:

- **counters**: per (job, family, labelset, pod) ring of ``(t, value)``
  cumulative samples → windowed rates as the sum of per-pod positive
  deltas over the window (a pod restart resets its counter; negative
  deltas are treated as a reset, counting the post-reset value);
- **gauges**: per-pod latest values → fleet max / mean, plus a ring of
  per-cycle fleet maxima so SLO rules can ask for a *windowed* bound;
- **histograms**: per-pod rings of cumulative bucket snapshots →
  windowed per-pod bucket deltas merged across the fleet, with p50/p99
  estimated by linear interpolation inside the winning bucket (the
  standard Prometheus ``histogram_quantile`` estimate).

Bounds: rings hold ``max_samples`` points (sized by the plane from the
long window / scrape interval), jobs are LRU-evicted past ``max_jobs``,
and only families matching ``family_prefixes`` are retained at all —
an exporter with 10k ad-hoc families cannot balloon the plane.
"""

from __future__ import annotations

import logging
from k8s_tpu.analysis import checkedlock
from collections import OrderedDict, deque

log = logging.getLogger(__name__)

_INF = float("inf")

DEFAULT_MAX_SAMPLES = 512
# sized ABOVE the repo's proven 2-5k-job churn regime: when live
# scrapeable jobs exceed this bound, each cycle rotates jobs through
# LRU eviction and their windows never fill (K8S_TPU_FLEET_MAX_JOBS
# raises it; the footprint is rings-per-family per job, small)
DEFAULT_MAX_JOBS = 8192
DEFAULT_FAMILY_PREFIXES = ("serve_",)


def _window_slice(ring: deque, now: float, window_s: float):
    """(oldest_in_window, newest) from a ring of (t, payload) tuples, or
    None when fewer than two points fall inside the window."""
    if len(ring) < 2:
        return None
    newest = ring[-1]
    oldest = None
    cutoff = now - window_s
    for point in ring:
        if point[0] >= cutoff:
            oldest = point
            break
    if oldest is None or oldest is newest or newest[0] <= oldest[0]:
        return None
    return oldest, newest


def _counter_rate(ring: deque, now: float, window_s: float) -> float | None:
    """Positive-delta rate over the window, reset-aware: a decrease means
    the pod restarted, and the post-reset value is the delta since then."""
    if len(ring) < 2:
        return None
    cutoff = now - window_s
    points = [p for p in ring if p[0] >= cutoff]
    if len(points) < 2:
        return None
    delta = 0.0
    prev = points[0][1]
    for _t, v in points[1:]:
        delta += (v - prev) if v >= prev else v
        prev = v
    span = points[-1][0] - points[0][0]
    return delta / span if span > 0 else None


def _merge_bucket_deltas(per_pod: list[tuple[dict, dict]]) -> dict:
    """Sum per-pod windowed bucket deltas: each item is (old_point,
    new_point) with ``{"buckets": [(le, cum)], "count": n}`` shapes.
    Returns ``{"buckets": [(le, cum_delta)], "count": total}`` — still
    cumulative in ``le`` (each pod's new−old difference of cumulative
    counts preserves monotonicity), so the result is quantile-ready."""
    merged: dict[float, float] = {}
    total = 0.0
    for old, new in per_pod:
        old_by_le = dict(old["buckets"])
        for le, cum in new["buckets"]:
            delta = cum - old_by_le.get(le, 0.0)
            if delta < 0:  # pod restart: take the post-reset cumulative
                delta = cum
            merged[le] = merged.get(le, 0.0) + delta
        new_count = new.get("count") or (new["buckets"][-1][1]
                                         if new["buckets"] else 0.0)
        old_count = old.get("count") or (old["buckets"][-1][1]
                                         if old["buckets"] else 0.0)
        dcount = new_count - old_count
        total += dcount if dcount >= 0 else new_count
    return {"buckets": sorted(merged.items()), "count": total}


def quantile_from_buckets(buckets: list[tuple[float, float]],
                          q: float) -> float | None:
    """Prometheus-style histogram_quantile over CUMULATIVE (le, count)
    pairs: linear interpolation inside the winning bucket; the +Inf
    bucket answers with the highest finite bound."""
    if not buckets:
        return None
    total = buckets[-1][1]
    if total <= 0:
        return None
    rank = q * total
    prev_le, prev_cum = 0.0, 0.0
    for le, cum in buckets:
        if cum >= rank:
            if le == _INF:
                # beyond the last finite bound: report that bound (the
                # Prometheus convention — the estimate is a floor)
                return prev_le if prev_le > 0 else None
            if cum == prev_cum:
                return le
            return prev_le + (le - prev_le) * (rank - prev_cum) / (cum - prev_cum)
        prev_le, prev_cum = le, cum
    return buckets[-1][0] if buckets[-1][0] != _INF else prev_le


def fraction_above(buckets: list[tuple[float, float]],
                   threshold: float) -> float | None:
    """Fraction of observations above ``threshold``, from cumulative
    (le, count) pairs — the SLO "bad fraction".  Uses the smallest
    FINITE bound >= threshold (conservative: observations between the
    threshold and that bound count as good).  A threshold above every
    finite bound counts the +Inf tail as bad — an unbounded observation
    is not provably under ANY finite bound, and an SLO set past the
    exporter's top bucket must not silently neuter the rule."""
    if not buckets:
        return None
    total = buckets[-1][1]
    if total <= 0:
        return None
    below = None
    for le, cum in buckets:
        if le != _INF and le >= threshold:
            below = cum
            break
    if below is None:
        finite = [cum for le, cum in buckets if le != _INF]
        below = finite[-1] if finite else 0.0
    return max(0.0, (total - below) / total)


class FleetAggregator:
    """Thread-safe per-job rollup state (one instance per fleet plane)."""

    def __init__(self, max_samples: int = DEFAULT_MAX_SAMPLES,
                 max_jobs: int = DEFAULT_MAX_JOBS,
                 family_prefixes: tuple = DEFAULT_FAMILY_PREFIXES):
        if max_samples < 2 or max_jobs < 1:
            raise ValueError("aggregator bounds must be >= 2 samples / 1 job")
        self.max_samples = max_samples
        self.max_jobs = max_jobs
        self.family_prefixes = tuple(family_prefixes)
        self._lock = checkedlock.make_lock("fleet.aggregate")
        # job -> {"counters": {(family, labels): {pod: ring}},
        #         "gauges":   {family: ({pod: (t, value)}, max_ring)},
        #         "hist":     {family: {pod: ring-of-points}}}
        self._jobs: "OrderedDict[str, dict]" = OrderedDict()
        # histogram families dropped mid-ingest (malformed bucket tables
        # that got past the parser): observable, not silently swallowed —
        # a fleet plane that quietly stops aggregating latency rots every
        # SLO burn rule downstream
        self.hist_drops = 0

    def _keep(self, name: str) -> bool:
        if not self.family_prefixes:
            return True
        return any(name.startswith(p) for p in self.family_prefixes)

    def _job_state(self, job: str) -> dict:
        state = self._jobs.get(job)
        if state is None:
            state = {"counters": {}, "gauges": {}, "hist": {}}
            self._jobs[job] = state
            if len(self._jobs) > self.max_jobs:
                self._jobs.popitem(last=False)
        else:
            self._jobs.move_to_end(job)
        return state

    def ingest(self, job: str, pod: str, families: dict, now: float) -> None:
        """Fold one pod's parsed scrape into the job's rings.
        ``families`` is the parser's ``{name: Family}`` output."""
        from k8s_tpu.fleet.parser import histogram_points

        with self._lock:
            state = self._job_state(job)
            for name, fam in families.items():
                if not self._keep(name):
                    continue
                if fam.kind == "counter":
                    for labels_key, value in fam.values().items():
                        series = state["counters"].setdefault(
                            (name, labels_key), {})
                        ring = series.get(pod)
                        if ring is None:
                            ring = series[pod] = deque(maxlen=self.max_samples)
                        ring.append((now, value))
                elif fam.kind == "gauge":
                    for labels_key, value in fam.values().items():
                        latest, max_ring = state["gauges"].setdefault(
                            (name, labels_key),
                            ({}, deque(maxlen=self.max_samples)))
                        latest[pod] = (now, value)
                elif fam.kind == "histogram":
                    try:
                        points = histogram_points(fam)
                    except Exception as e:  # noqa: BLE001 - one bad family must not drop the scrape
                        # ISSUE 11 first-audit fix: this swallow was
                        # silent — a malformed bucket table now counts
                        # and logs instead of vanishing
                        self.hist_drops += 1
                        log.warning(
                            "fleet: dropping histogram family %r from "
                            "%s/%s: %s", name, job, pod, e)
                        continue
                    for labels_key, point in points.items():
                        series = state["hist"].setdefault(
                            (name, labels_key), {})
                        ring = series.get(pod)
                        if ring is None:
                            ring = series[pod] = deque(maxlen=self.max_samples)
                        ring.append((now, point))

    def cycle_done(self, now: float, stale_after_s: float) -> None:
        """End-of-cycle bookkeeping: append per-cycle fleet maxima to the
        gauge rings (the windowed-gauge substrate) and drop pods whose
        series went stale (scaled-down / deleted pods must not pin old
        gauge readings into the fleet max forever)."""
        cutoff = now - stale_after_s
        with self._lock:
            for state in self._jobs.values():
                for _key, (latest, cycle_ring) in state["gauges"].items():
                    for pod in [p for p, (t, _v) in latest.items()
                                if t < cutoff]:
                        del latest[pod]
                    if latest:
                        values = [v for _t, v in latest.values()]
                        # (t, fleet max, fleet mean): both reducers need
                        # a windowed history, or multi-window SLO rules
                        # on a gauge would be vacuous
                        cycle_ring.append(
                            (now, max(values),
                             sum(values) / len(values)))
                for series in list(state["counters"].values()) \
                        + list(state["hist"].values()):
                    for pod in [p for p, ring in series.items()
                                if ring and ring[-1][0] < cutoff]:
                        del series[pod]

    # -- pure reads ----------------------------------------------------------

    def jobs(self) -> list[str]:
        with self._lock:
            return list(self._jobs)

    def forget(self, job: str) -> None:
        """Drop a deleted job's rings.  Without this the job would live
        in ``jobs()`` until LRU eviction — and the SLO evaluator, which
        builds its job list from there, would recreate the deleted job's
        rule state from the stale in-window samples and re-fire a breach
        that no longer exists."""
        with self._lock:
            self._jobs.pop(job, None)

    def counter_rate(self, job: str, family: str, window_s: float,
                     now: float, labels: tuple = ()) -> float | None:
        """Fleet rate: sum of per-pod reset-aware rates over the window."""
        with self._lock:
            state = self._jobs.get(job)
            if state is None:
                return None
            series = state["counters"].get((family, tuple(labels)))
            if not series:
                return None
            rates = [r for r in
                     (_counter_rate(ring, now, window_s)
                      for ring in series.values())
                     if r is not None]
        return sum(rates) if rates else None

    def gauge_stats(self, job: str, family: str,
                    labels: tuple = ()) -> dict | None:
        """Latest per-pod readings → fleet max/mean/sum."""
        with self._lock:
            state = self._jobs.get(job)
            if state is None:
                return None
            entry = state["gauges"].get((family, tuple(labels)))
            if entry is None or not entry[0]:
                return None
            values = [v for _t, v in entry[0].values()]
        return {"max": max(values), "mean": sum(values) / len(values),
                "sum": sum(values), "pods": len(values)}

    def pod_gauge_latest(self, job: str, family: str,
                         labels: tuple = ()) -> dict | None:
        """Latest per-POD readings of one gauge family — ``{pod: value}``
        (ISSUE 13: the router's least-outstanding fallback tie-breaks on
        per-target ``serve_queue_depth``, which the per-job merge above
        erases).  None when the job/family is unknown; a pure read."""
        with self._lock:
            state = self._jobs.get(job)
            if state is None:
                return None
            entry = state["gauges"].get((family, tuple(labels)))
            if entry is None or not entry[0]:
                return None
            return {pod: v for pod, (_t, v) in entry[0].items()}

    def gauge_window_mean(self, job: str, family: str, window_s: float,
                          now: float, of: str = "max",
                          labels: tuple = ()) -> float | None:
        """Windowed mean of the per-cycle fleet **max** (``of="max"`` —
        "was the worst pod's queue depth above X, sustained?") or fleet
        **mean** (``of="mean"``).  Both SLO gauge reducers read here so
        short and long windows genuinely differ."""
        idx = 1 if of == "max" else 2
        with self._lock:
            state = self._jobs.get(job)
            if state is None:
                return None
            entry = state["gauges"].get((family, tuple(labels)))
            if entry is None:
                return None
            cutoff = now - window_s
            points = [p[idx] for p in entry[1] if p[0] >= cutoff]
        return sum(points) / len(points) if points else None


    def histogram_window(self, job: str, family: str, window_s: float,
                         now: float, labels: tuple = ()) -> dict | None:
        """Fleet-merged windowed histogram: ``{"buckets": [(le, cum)],
        "count": n}`` with per-pod deltas over the window summed, then
        accumulated back to cumulative form for quantile estimation."""
        with self._lock:
            state = self._jobs.get(job)
            if state is None:
                return None
            series = state["hist"].get((family, tuple(labels)))
            if not series:
                return None
            per_pod = []
            for ring in series.values():
                sl = _window_slice(ring, now, window_s)
                if sl is None:
                    continue
                per_pod.append((sl[0][1], sl[1][1]))
        if not per_pod:
            return None
        # per-le deltas of CUMULATIVE counts are themselves cumulative in
        # le (new−old preserves monotonicity), so the merge is directly
        # quantile-ready — re-accumulating would double-count
        return _merge_bucket_deltas(per_pod)

    def quantile(self, job: str, family: str, q: float, window_s: float,
                 now: float, labels: tuple = ()) -> float | None:
        win = self.histogram_window(job, family, window_s, now, labels)
        if win is None:
            return None
        return quantile_from_buckets(win["buckets"], q)

    def rollup(self, job: str, now: float,
               windows: tuple = (30.0, 300.0)) -> dict:
        """The /debug/fleet per-job payload: every retained family's
        windowed rates / gauge stats / quantiles.  A pure read."""
        with self._lock:
            state = self._jobs.get(job)
            if state is None:
                return {}
            counter_keys = list(state["counters"])
            gauge_keys = list(state["gauges"])
            hist_keys = list(state["hist"])
        out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
        for family, labels in counter_keys:
            entry: dict = {}
            for w in windows:
                rate = self.counter_rate(job, family, w, now, labels)
                if rate is not None:
                    entry[f"rate_{int(w)}s"] = round(rate, 4)
            if entry:
                out["counters"][_display(family, labels)] = entry
        for family, labels in gauge_keys:
            stats = self.gauge_stats(job, family, labels)
            if stats:
                stats = {k: (round(v, 4) if isinstance(v, float) else v)
                         for k, v in stats.items()}
                out["gauges"][_display(family, labels)] = stats
        for family, labels in hist_keys:
            entry = {}
            for w in windows:
                win = self.histogram_window(job, family, w, now, labels)
                if win is None:
                    continue
                for q in (0.5, 0.99):
                    val = quantile_from_buckets(win["buckets"], q)
                    if val is not None:
                        entry[f"p{int(q * 100)}_{int(w)}s"] = round(val, 6)
                entry[f"count_{int(w)}s"] = win["count"]
            if entry:
                out["histograms"][_display(family, labels)] = entry
        return out


def _display(family: str, labels: tuple) -> str:
    if not labels:
        return family
    pairs = ",".join(f'{k}="{v}"' for k, v in labels)
    return f"{family}{{{pairs}}}"

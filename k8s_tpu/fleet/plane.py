"""FleetPlane: the composed telemetry plane one controller owns.

Discovery (``targets_fn`` over the informer cache) → scrape loop →
aggregator → SLO evaluator, plus a bounded, sequence-numbered event
ring (scrape failures, SLO transitions) that gives ``/debug/fleet`` the
same ``?since=`` incremental-poll contract as ``/debug/timeline``.

The plane starts *inactive*; ``/debug/fleet`` answers 404 with an
explicit body until a controller (or bench) activates one — exactly the
``/debug/traces`` / ``/debug/scheduler`` / ``/debug/timeline``
contract.  External consumers (ROADMAP item 2's router/autoscaler) read
``rollup()`` / ``slo.state()`` — pure in-memory reads.
"""

from __future__ import annotations

import itertools
from k8s_tpu.analysis import checkedlock
import time
from collections import deque

from k8s_tpu.fleet.aggregate import (
    DEFAULT_FAMILY_PREFIXES,
    FleetAggregator,
)
from k8s_tpu.fleet.scrape import (
    DEFAULT_CONCURRENCY,
    DEFAULT_INTERVAL_S,
    DEFAULT_TIMEOUT_S,
    OUTCOME_OK,
    ScrapeLoop,
    ScrapeStats,
)
from k8s_tpu.fleet.slo import DEFAULT_RULES_SPEC, SloEvaluator, parse_rules

DEFAULT_WINDOWS = (30.0, 300.0)
EVENT_RING_SIZE = 512


class FleetPlane:
    """One fleet telemetry plane (scraper + aggregator + SLO rules)."""

    def __init__(self, targets_fn, *,
                 interval_s: float = DEFAULT_INTERVAL_S,
                 timeout_s: float = DEFAULT_TIMEOUT_S,
                 concurrency: int = DEFAULT_CONCURRENCY,
                 windows: tuple = DEFAULT_WINDOWS,
                 slo_rules: str | list = DEFAULT_RULES_SPEC,
                 family_prefixes: tuple = DEFAULT_FAMILY_PREFIXES,
                 max_jobs: int | None = None,
                 fetch=None, url_override=None):
        # ring depth ~ the long window at this cadence (+ slack), bounded
        # so a 1s interval with a 5m window cannot grow unbounded
        max_samples = max(8, min(4096, int(windows[-1] / interval_s) + 8))
        self.windows = tuple(float(w) for w in windows)
        self.interval_s = float(interval_s)
        from k8s_tpu.fleet.aggregate import DEFAULT_MAX_JOBS

        self.aggregator = FleetAggregator(max_samples=max_samples,
                                          max_jobs=max_jobs
                                          or DEFAULT_MAX_JOBS,
                                          family_prefixes=family_prefixes)
        rules = (parse_rules(slo_rules) if isinstance(slo_rules, str)
                 else list(slo_rules))
        self.slo = SloEvaluator(rules, self.aggregator, windows=self.windows)
        self.stats = ScrapeStats()
        self._url_override = url_override
        self._targets_fn = targets_fn
        self.loop = ScrapeLoop(
            self._resolved_targets, self.aggregator, stats=self.stats,
            interval_s=interval_s, timeout_s=timeout_s,
            concurrency=concurrency, fetch=fetch,
            on_cycle=self._on_cycle, on_failure=self._on_failure)
        self._sinks: list = [self._event_ring_sink]
        self._active = False
        self._started_at: float | None = None
        self._lock = checkedlock.make_lock("fleet.plane")
        self._seq = itertools.count(1)
        self._events: deque = deque(maxlen=EVENT_RING_SIZE)

    # -- wiring ---------------------------------------------------------------

    @property
    def url_override(self):
        return self._url_override

    @url_override.setter
    def url_override(self, fn) -> None:
        """Benches/tests rewrite target URLs (fake serving pods listen on
        loopback ports, not pod DNS); discovery itself stays untouched so
        the zero-apiserver-call property is still what's measured."""
        self._url_override = fn

    def _resolved_targets(self):
        targets = list(self._targets_fn() or ())
        override = self._url_override
        if override is not None:
            for t in targets:
                url = override(t)
                if url:
                    t.url = url
        return targets

    def add_sink(self, sink) -> None:
        """``sink(job, rule, state, breached)`` on every SLO transition
        (the controller hangs the timeline event + K8s Event here)."""
        self._sinks.append(sink)

    # -- lifecycle ------------------------------------------------------------

    @property
    def active(self) -> bool:
        return self._active

    def start(self) -> "FleetPlane":
        self._active = True
        self._started_at = time.time()
        self.loop.start()
        return self

    def stop(self) -> None:
        self.loop.stop()
        self._active = False

    def scrape_once(self, now: float | None = None) -> int:
        """Synchronous single cycle (tests/benches); activates the plane
        so debug surfaces serve what it gathered."""
        self._active = True
        if self._started_at is None:
            self._started_at = time.time()
        return self.loop.scrape_once(now)

    def forget(self, job: str) -> None:
        """Drop a deleted job's rule state, scrape counters, AND
        aggregation rings — leaving the rings would let the next cycle
        recreate the rule state from stale samples and re-fire a breach
        for a job that no longer exists."""
        self.slo.forget(job)
        self.stats.forget(job)
        self.aggregator.forget(job)

    # -- cycle hooks ----------------------------------------------------------

    def _on_cycle(self, targets, now: float) -> None:
        jobs = sorted({t.job for t in targets} | set(self.aggregator.jobs()))
        self.slo.evaluate(jobs, now, sinks=tuple(self._sinks))

    def _on_failure(self, target, outcome: str, error: str) -> None:
        self._record_event("scrape_failure", target.job, pod=target.pod,
                           outcome=outcome, error=error[:200])

    def _event_ring_sink(self, job: str, rule, state: dict,
                         breached: bool) -> None:
        self._record_event(
            "slo_breach" if breached else "slo_recovered", job,
            rule=rule.name,
            burn_short=_round(state.get("burn_short")),
            burn_long=_round(state.get("burn_long")))

    def _record_event(self, kind: str, job: str, **attrs) -> None:
        entry = {"ts": time.time(), "kind": kind, "job": job}
        entry.update({k: v for k, v in attrs.items() if v is not None})
        with self._lock:
            entry["seq"] = next(self._seq)
            self._events.append(entry)

    # -- reads ----------------------------------------------------------------

    def events(self, since: int | None = None,
               job: str | None = None) -> list[dict]:
        with self._lock:
            entries = list(self._events)
        if job:
            entries = [e for e in entries if e["job"] == job]
        if since is not None:
            entries = [e for e in entries if e["seq"] > since]
        return entries

    def rollup(self, job: str, now: float | None = None) -> dict:
        return self.aggregator.rollup(job, time.time() if now is None else now,
                                      windows=self.windows)

    def burn_rates(self) -> dict[tuple, float]:
        """(job, rule) -> current short-window burn (the
        ``fleet_slo_burn_rate`` gauge samples)."""
        out = {}
        for s in self.slo.state():
            burn = s.get("burn_short")
            if burn is not None:
                out[(s["job"], s["rule"])] = burn
        return out

    def summary(self, now: float | None = None) -> dict:
        now = time.time() if now is None else now
        staleness = self.stats.staleness(now)
        return {
            "active": self._active,
            "started_at": self._started_at,
            "interval_s": self.interval_s,
            "windows_s": list(self.windows),
            "cycles": self.stats.cycles,
            "last_cycle_s": round(self.stats.last_cycle_s, 4),
            "jobs": {
                job: {
                    "targets": count,
                    "staleness_s": (round(staleness[job], 3)
                                    if staleness.get(job, float("inf"))
                                    != float("inf") else None),
                    "slo_breached": self.slo.breached(job),
                }
                for job, count in sorted(self.stats.target_count().items())
            },
            "rules": [r.to_dict() for r in self.slo.rules],
            "scrapes": {
                f"{job}:{outcome}": n
                for (job, outcome), n in sorted(self.stats.counts().items())
            },
        }


def _round(v):
    return round(v, 4) if isinstance(v, float) else v


# re-exported so plane consumers need one import
__all__ = ["FleetPlane", "OUTCOME_OK", "DEFAULT_WINDOWS"]

"""Multi-window SLO burn-rate rules over the fleet aggregates.

A rule names a family, a reducer, and a bound, in a compact spec string
(the ``K8S_TPU_FLEET_SLO`` knob / docs syntax):

    serve_request_duration_seconds:p99<0.5,serve_queue_depth:max<48

Two reducer shapes:

- **quantile rules** (``p50``/``p90``/``p99`` on a histogram family):
  the *burn rate* over a window is the fraction of observations above
  the bound divided by the error budget the quantile allows (``p99 <
  0.5s`` budgets 1% of requests above 0.5s; 3% slow ⇒ burn 3.0).
- **gauge rules** (``max``/``mean`` on a gauge family): burn is the
  windowed mean of the per-cycle fleet max (or mean) over the bound
  (queue depth sustained at 2x its bound ⇒ burn 2.0).

Breach needs burn ≥ 1 in **both** windows (default 30s/5m): the short
window makes detection fast, the long window keeps a transient spike
from flapping the rule — the standard SRE multi-window construction.
State transitions (ok → breached and back) fire the plane's sinks,
which is where the controller hangs the flight-timeline event and the
K8s Event; the current burn is exported as the ``fleet_slo_burn_rate``
gauge either way.
"""

from __future__ import annotations

from k8s_tpu.analysis import checkedlock

_QUANTILE_REDUCERS = {"p50": 0.50, "p90": 0.90, "p99": 0.99}
_GAUGE_REDUCERS = ("max", "mean")

DEFAULT_RULES_SPEC = ("serve_request_duration_seconds:p99<0.5,"
                      "serve_queue_depth:max<48")


class RuleError(ValueError):
    """Malformed SLO rule spec."""


class SloRule:
    """One parsed rule: ``<family>:<reducer><op><bound>`` (op is ``<``)."""

    __slots__ = ("family", "reducer", "bound", "name")

    def __init__(self, family: str, reducer: str, bound: float):
        if reducer not in _QUANTILE_REDUCERS and reducer not in _GAUGE_REDUCERS:
            raise RuleError(f"unknown reducer {reducer!r} (expected one of "
                            f"{sorted(_QUANTILE_REDUCERS)} + "
                            f"{list(_GAUGE_REDUCERS)})")
        if bound <= 0:
            raise RuleError(f"rule bound must be > 0, got {bound}")
        self.family = family
        self.reducer = reducer
        self.bound = bound
        self.name = f"{family}:{reducer}<{_trim(bound)}"

    @property
    def quantile(self) -> float | None:
        return _QUANTILE_REDUCERS.get(self.reducer)

    def to_dict(self) -> dict:
        return {"name": self.name, "family": self.family,
                "reducer": self.reducer, "bound": self.bound}


def _trim(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else repr(float(v))


def parse_rules(spec: str) -> list[SloRule]:
    """Parse the comma-separated rule spec; raises :class:`RuleError` on
    malformed entries (a silently-dropped SLO rule is an outage that
    never pages)."""
    rules = []
    for chunk in (spec or "").split(","):
        chunk = chunk.strip()
        if not chunk:
            continue
        if ":" not in chunk or "<" not in chunk:
            raise RuleError(f"bad rule {chunk!r} "
                            "(expected family:reducer<bound)")
        family, _, rest = chunk.partition(":")
        reducer, _, bound_raw = rest.partition("<")
        try:
            bound = float(bound_raw)
        except ValueError:
            raise RuleError(f"bad bound {bound_raw!r} in {chunk!r}") from None
        rules.append(SloRule(family.strip(), reducer.strip(), bound))
    return rules


class SloEvaluator:
    """Evaluates every rule against every known job once per scrape
    cycle and tracks breach state per (job, rule)."""

    def __init__(self, rules: list[SloRule], aggregator,
                 windows: tuple = (30.0, 300.0)):
        if len(windows) != 2 or windows[0] >= windows[1]:
            raise RuleError("windows must be (short, long) with short < long")
        self.rules = list(rules)
        self.aggregator = aggregator
        self.windows = tuple(float(w) for w in windows)
        self._lock = checkedlock.make_lock("fleet.slo")
        # (job, rule.name) -> state dict
        self._state: dict[tuple, dict] = {}
        self.breaches_total: dict[tuple, int] = {}

    def _burn(self, job: str, rule: SloRule, window_s: float,
              now: float) -> float | None:
        from k8s_tpu.fleet.aggregate import fraction_above

        agg = self.aggregator
        q = rule.quantile
        if q is not None:
            win = agg.histogram_window(job, rule.family, window_s, now)
            if win is None or win["count"] <= 0:
                return None
            bad = fraction_above(win["buckets"], rule.bound)
            if bad is None:
                return None
            budget = 1.0 - q
            return bad / budget if budget > 0 else None
        # both gauge reducers are WINDOWED (mean of the per-cycle fleet
        # max or fleet mean): an instantaneous read would make the two
        # windows identical and the multi-window construction vacuous
        value = agg.gauge_window_mean(job, rule.family, window_s, now,
                                      of=rule.reducer)
        if value is None:
            return None
        return value / rule.bound

    def evaluate(self, jobs: list[str], now: float, sinks=()) -> None:
        """One evaluation pass over the CURRENT job set; calls
        ``sink(job, rule, state, breached)`` on every ok↔breached
        transition.  Sinks run outside the lock and are fail-soft (a
        broken sink cannot stall the scrape loop).

        Two non-obvious rules keep churn honest: a **data gap** (no
        samples in either window — scrape outage, aggregator ring
        eviction) holds the last state instead of flipping a breached
        job to "recovered" (absence of evidence is not recovery); and
        state for jobs absent from ``jobs`` is **pruned** (the plane
        passes targets ∪ aggregator jobs, so a vanished job's rule
        state cannot accumulate past the aggregator's own LRU bound)."""
        short_w, long_w = self.windows
        transitions = []
        job_set = set(jobs)
        for job in jobs:
            for rule in self.rules:
                burn_short = self._burn(job, rule, short_w, now)
                burn_long = self._burn(job, rule, long_w, now)
                no_data = burn_short is None and burn_long is None
                full_data = (burn_short is not None
                             and burn_long is not None)
                breached = (full_data and burn_short >= 1.0
                            and burn_long >= 1.0)
                key = (job, rule.name)
                with self._lock:
                    state = self._state.get(key)
                    if state is None:
                        if no_data:
                            continue  # nothing known: no state to hold
                        state = self._state[key] = {
                            "job": job, "rule": rule.name,
                            "breached": False, "since": None,
                        }
                    state["burn_short"] = burn_short
                    state["burn_long"] = burn_long
                    state["checked_at"] = now
                    if not full_data:
                        # total OR partial gap (e.g. the short window
                        # emptied mid-outage while the long still holds
                        # old samples): neither breach nor recovery is
                        # affirmable — hold the last verdict.  A breach
                        # needs full data by construction, and flipping
                        # a breached rule to "recovered" because its
                        # pods stopped answering would page-resolve the
                        # very outage that caused the breach.
                        continue
                    if breached != state["breached"]:
                        state["breached"] = breached
                        state["since"] = now if breached else None
                        if breached:
                            self.breaches_total[key] = \
                                self.breaches_total.get(key, 0) + 1
                        transitions.append((job, rule, dict(state), breached))
        with self._lock:
            for key in [k for k in self._state if k[0] not in job_set]:
                del self._state[key]
            for key in [k for k in self.breaches_total
                        if k[0] not in job_set]:
                del self.breaches_total[key]
        for job, rule, state, breached in transitions:
            for sink in sinks:
                try:
                    sink(job, rule, state, breached)
                except Exception:  # noqa: BLE001 - sinks are best-effort
                    import logging

                    logging.getLogger(__name__).exception(
                        "SLO sink failed for %s %s", job, rule.name)

    def state(self, job: str | None = None) -> list[dict]:
        """Current per-(job, rule) burn/breach snapshot (a pure read)."""
        with self._lock:
            out = [dict(s) for k, s in self._state.items()
                   if job is None or k[0] == job]
        return sorted(out, key=lambda s: (s["job"], s["rule"]))

    def breaches(self) -> dict[tuple, int]:
        """(job, rule) -> lifetime breach-transition count (the
        ``fleet_slo_breaches_total`` samples)."""
        with self._lock:
            return dict(self.breaches_total)

    def breached(self, job: str) -> bool:
        with self._lock:
            return any(s["breached"] for k, s in self._state.items()
                       if k[0] == job)

    def forget(self, job: str) -> None:
        """Drop a deleted job's rule state (no stale breach pinning)."""
        with self._lock:
            for key in [k for k in self._state if k[0] == job]:
                del self._state[key]
            for key in [k for k in self.breaches_total if k[0] == job]:
                del self.breaches_total[key]

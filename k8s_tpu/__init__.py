"""k8s_tpu — a TPU-native training-job operator and SPMD launcher stack.

A ground-up rebuild of the capabilities of the kubeflow/tf-operator snapshot
(reference layer map in SURVEY.md §1): a ``TFJob`` custom resource plus
controllers that reconcile distributed training jobs on Kubernetes — redesigned
for Cloud TPU pod slices.  The TF1 parameter-server/gRPC world (TF_CONFIG env,
per-replica headless services) is replaced by a JAX/XLA multi-host SPMD model:
the operator provisions gang-scheduled slice workers, injects
``JAX_COORDINATOR_ADDRESS``/``TPU_WORKER_ID`` bootstrap env, and the in-pod
launcher brings up ``jax.distributed`` + a device mesh with XLA collectives
over ICI/DCN.

Layout (cf. SURVEY.md §2 component inventory):

- ``k8s_tpu.api``            — CRD schema: types, defaults, validation, helpers
                               (reference: pkg/apis/tensorflow/)
- ``k8s_tpu.client``         — REST client, typed clientset, informers, listers
                               and in-memory fakes (reference: pkg/client/)
- ``k8s_tpu.controller``     — v1 "trainer" reconciler: stateful TrainingJob
                               state machine (reference: pkg/controller, pkg/trainer)
- ``k8s_tpu.controller_v2``  — v2 stateless informer/expectations reconciler
                               (reference: pkg/controller.v2/)
- ``k8s_tpu.util``           — workqueue, exit-code policy, leader election,
                               signals (reference: pkg/util/)
- ``k8s_tpu.launcher``       — in-pod runtime: env → jax.distributed → Mesh
                               (replaces the TF_CONFIG/tf.train.Server contract)
- ``k8s_tpu.parallel``       — mesh axes, sharding rules, ring attention,
                               collective helpers (dp/fsdp/tp/sp/ep)
- ``k8s_tpu.models``         — workloads: ResNet-50, dist-mnist, transformer
                               (reference: examples/tf_sample, test/e2e/dist-mnist)
- ``k8s_tpu.ops``            — Pallas TPU kernels for hot ops
- ``k8s_tpu.cmd``            — operator entrypoints (reference: cmd/)
- ``k8s_tpu.dashboard``      — REST API + SPA (reference: dashboard/)
- ``k8s_tpu.harness``        — CI/test/release harness (reference: py/)
"""

from k8s_tpu.version import __version__  # noqa: F401

"""Shared AST-walker utilities.

Used by the concurrency analyzer (:mod:`k8s_tpu.analysis.static`) and by
the in-tree linter (:mod:`k8s_tpu.harness.pylint_lite`) — one copy of the
noqa parser, the scope-bounded walker, and the dotted-name resolver
instead of a private reimplementation per tool.
"""

from __future__ import annotations

import ast
import os

#: directories never descended into when walking a source tree
EXCLUDE_DIRS = {".git", "__pycache__", ".eggs", "build", "vendor",
                "node_modules"}

_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                ast.Lambda)


def iter_py_files(src_dir: str):
    """Yield every ``.py`` path under ``src_dir``, sorted per directory,
    skipping :data:`EXCLUDE_DIRS`."""
    for root, dirs, files in os.walk(src_dir):
        dirs[:] = [d for d in dirs if d not in EXCLUDE_DIRS]
        for name in sorted(files):
            if name.endswith(".py"):
                yield os.path.join(root, name)


def noqa_lines(source: str) -> dict[int, set[str] | None]:
    """Parse ``# noqa`` comments: line -> None (blanket) or a set of
    lower-cased codes (``# noqa: CODE1, CODE2`` — trailing prose after a
    code token is tolerated)."""
    out: dict[int, set[str] | None] = {}
    for i, line in enumerate(source.splitlines(), 1):
        if "# noqa" not in line:
            continue
        _, _, tail = line.partition("# noqa")
        tail = tail.strip()
        if tail.startswith(":"):
            codes = set()
            for chunk in tail[1:].split(","):
                tok = chunk.strip().split()
                if not tok:
                    continue
                codes.add(tok[0].lower())
            out[i] = codes
        else:
            out[i] = None
    return out


def own_scope_nodes(fn: ast.AST):
    """Walk a function's own body, stopping at nested function / class /
    lambda scopes (their bodies belong to a different runtime context)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, _SCOPE_NODES):
            stack.extend(ast.iter_child_nodes(n))


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None (calls,
    subscripts, and literals break the chain)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def line_comments(source: str, marker: str) -> dict[int, str]:
    """Map line number -> trailing text for lines carrying a
    ``# <marker>:`` comment (e.g. ``# guarded-by: _lock`` or
    ``# lock-ok: reason``).  The text after the colon is stripped."""
    tag = f"# {marker}:"
    out: dict[int, str] = {}
    for i, line in enumerate(source.splitlines(), 1):
        if tag in line:
            _, _, tail = line.partition(tag)
            out[i] = tail.strip()
    return out

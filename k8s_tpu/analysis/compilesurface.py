"""Static compile-surface analysis for the serving stack (ISSUE 11).

The engine's throughput story rests on a hand-maintained compile
discipline — bucketed prefill programs, power-of-two fused decode
widths, (W, sampling) spec triples — and on keeping host-device syncs
out of the step loop.  This pass *enforces* that discipline the way
:mod:`k8s_tpu.analysis.static` enforces lock discipline: four AST
sub-passes over the tree, gated in the ``py_checks`` lint tier.

- **jit-surface** (``jit-per-call`` / ``jit-in-loop``): every
  ``jax.jit``/``pjit`` construction site is classified.  OK classes:
  module/import time, ``__init__`` construction, an
  ``functools.lru_cache``-decorated builder, a function carrying the
  memoizing *program-table* idiom (a mapping read — ``self.X.get`` /
  ``in self.X`` — plus a store to the same table; the engine's
  ``_prefill_fns`` copy-on-write rebind is the model), or a *factory*
  whose jit escapes through a ``return`` / returned closure.  A jit
  constructed per plain call, or any jit (or factory call) inside a
  ``for``/``while`` body, is a finding: a fresh program per request is
  exactly the recompile tax the engine exists to avoid.
- **uncovered-traced-branch**: for each resolvable
  ``jax.jit(target, static_argnums=..., static_argnames=...)`` wrapper
  (bound methods drop ``self``), Python ``if``/``while``/``for``/
  ternary tests inside the target (and its nested scopes, with
  shadowing respected) must not branch on a parameter that is *traced*
  — only on statics, locals, closure constants, or ``.shape``-class
  attributes (trace-time constants).  Branching on a traced argument
  either fails at trace time or silently bakes one path per value.
- **host-sync** (``host-sync-hot-loop`` / ``host-sync-under-lock``):
  ``.item()`` / ``block_until_ready`` / ``jax.device_get`` /
  ``np.asarray``-family calls (plus ``int()``/``float()`` over a call
  result) reached transitively from a hot root (a function named in
  ``HOT_ROOT_NAMES``, default the engine's ``_loop``, or annotated
  ``# hot-root: reason``) or while a known lock is held — composed
  with the ISSUE-10 lock model (``with self._lock:`` regions plus the
  underscore-helper entry-context inference).  Deliberate syncs carry
  ``# sync-ok: <reason>``.
- **swallowed-exception**: bare ``except:`` and
  ``except Exception/BaseException:`` handlers whose whole body is
  ``pass``/``continue``/``...`` anywhere under ``k8s_tpu/`` — silent
  swallows rot into unobservable failures; deliberate ones carry
  ``# except-ok: <reason>``.

Annotations suppress on their own line or up to two lines above the
finding (the ``static.py`` contract); everything else goes through the
reason-mandatory allowlist (``compile_allowlist.txt``, same
stale-entries-fail loader as ``allowlist.txt``).
"""

from __future__ import annotations

import ast
import os

from k8s_tpu.analysis import astutil
from k8s_tpu.analysis import static as _static

Finding = _static.Finding
AllowlistError = _static.AllowlistError
load_allowlist = _static.load_allowlist

#: last dotted component of a call that constructs an XLA program
JIT_CALL_NAMES = {"jit", "pjit"}
#: decorators that memoize a builder's return value
LRU_DECORATORS = {"lru_cache", "cache"}
#: attribute accesses on a traced value that are trace-time constants
SHAPE_ATTRS = {"shape", "ndim", "dtype", "size"}
#: functions whose transitive callees are "hot" (the engine step loop)
HOT_ROOT_NAMES = ("_loop",)
#: swallowing handlers are only flagged for these (or bare) types
BROAD_EXCEPTIONS = {"Exception", "BaseException"}

# dotted call names that force a device->host sync
_SYNC_DOTTED = {
    "np.asarray": "np.asarray", "numpy.asarray": "np.asarray",
    "onp.asarray": "np.asarray",
    "np.array": "np.array", "numpy.array": "np.array",
    "onp.array": "np.array",
    "jax.device_get": "jax.device_get", "device_get": "jax.device_get",
}

_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                ast.Lambda)
_LOOP_NODES = (ast.For, ast.AsyncFor, ast.While)
_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


class CompileReport:
    """Findings plus the classified inventory (jit sites, resolved jit
    wrappers, hot functions) — the JSON artifact's payload."""

    def __init__(self):
        self.findings: list[Finding] = []
        self.suppressed: list[dict] = []
        self.jit_sites: list[dict] = []
        self.wrappers: list[dict] = []
        self.hot_functions: list[dict] = []
        self.module_count = 0
        self.allowlist_unused: list[dict] = []

    @property
    def ok(self) -> bool:
        return not self.findings

    def as_dict(self) -> dict:
        return {
            "ok": self.ok,
            "modules": self.module_count,
            "jit_sites": self.jit_sites,
            "wrappers": self.wrappers,
            "hot_functions": self.hot_functions,
            "findings": [f.as_dict() for f in self.findings],
            "suppressed": self.suppressed,
            "allowlist_unused": self.allowlist_unused,
        }


# --- shared helpers ----------------------------------------------------------


def _last_comp(name: str | None) -> str | None:
    return name.rsplit(".", 1)[-1] if name else None


def _note(notes: dict[int, str], line: int) -> str | None:
    """An annotation suppresses findings on its own line or (comments
    usually precede the statement) up to two lines below it — the
    ``static._Module.note`` contract."""
    for ln in (line, line - 1, line - 2):
        if ln in notes:
            return notes[ln]
    return None


def _is_jit_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and _last_comp(astutil.dotted_name(node.func)) in JIT_CALL_NAMES)


def _memo_attr(node: ast.AST) -> str | None:
    """``self.X`` / bare ``X`` spelled as a memo-table receiver."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id in ("self", "cls"):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


# --- per-function facts (jit-surface pass) -----------------------------------


class _FnFacts:
    def __init__(self, node: ast.AST, qualname: str):
        self.node = node
        self.qualname = qualname
        self.name = getattr(node, "name", "<module>")
        self.is_init = self.name in ("__init__", "__post_init__")
        self.is_lru = any(
            _last_comp(astutil.dotted_name(
                d.func if isinstance(d, ast.Call) else d)) in LRU_DECORATORS
            for d in getattr(node, "decorator_list", []))
        self.memo = False
        # (lineno, bound_name|None, in_loop, returned_direct)
        self.jit_sites: list[tuple[int, str | None, bool, bool]] = []
        # (lineno, callee_name) for every plain call, with loop context
        self.calls_in_loops: list[tuple[int, str]] = []
        self.returned_names: set[str] = set()
        self.nested_free: set[str] = set()
        self.is_factory = False


def _collect_fn_facts(fn: ast.AST, qualname: str) -> _FnFacts:
    facts = _FnFacts(fn, qualname)
    memo_read: set[str] = set()
    memo_store: set[str] = set()

    def scan(node: ast.AST, in_loop: bool):
        if isinstance(node, _FUNC_NODES) and node is not fn:
            # a nested def: its decorators run in THIS scope (a
            # @jax.jit-decorated nested def is a jit site here), its
            # body's free names mark closure escape
            for d in node.decorator_list:
                if _is_jit_call(d) or _last_comp(
                        astutil.dotted_name(d)) in JIT_CALL_NAMES:
                    facts.jit_sites.append(
                        (node.lineno, node.name, in_loop, False))
            bound = {a.arg for a in node.args.posonlyargs + node.args.args
                     + node.args.kwonlyargs}
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name):
                    if isinstance(sub.ctx, ast.Load) and sub.id not in bound:
                        facts.nested_free.add(sub.id)
            return
        if isinstance(node, (ast.ClassDef, ast.Lambda)):
            if isinstance(node, ast.Lambda):
                for sub in ast.walk(node.body):
                    if isinstance(sub, ast.Name) and \
                            isinstance(sub.ctx, ast.Load):
                        facts.nested_free.add(sub.id)
            return
        nxt = in_loop or isinstance(node, _LOOP_NODES)
        if isinstance(node, ast.Return) and node.value is not None:
            if _is_jit_call(node.value):
                facts.jit_sites.append((node.value.lineno, None, in_loop,
                                        True))
                # the jit's operands still need scanning (nested calls)
            if isinstance(node.value, ast.Name):
                facts.returned_names.add(node.value.id)
        if isinstance(node, ast.Assign) and _is_jit_call(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    facts.jit_sites.append((node.value.lineno, t.id,
                                            in_loop, False))
                    break
            else:
                facts.jit_sites.append((node.value.lineno, None, in_loop,
                                        False))
        elif _is_jit_call(node) and not _inside_recorded(facts, node):
            facts.jit_sites.append((node.lineno, None, in_loop, False))
        if isinstance(node, ast.Call):
            # memo reads: self.X.get(...) / X.get(...)
            if isinstance(node.func, ast.Attribute):
                attr = _memo_attr(node.func.value)
                if attr is not None:
                    if node.func.attr == "get":
                        memo_read.add(attr)
                    elif node.func.attr == "setdefault":
                        memo_read.add(attr)
                        memo_store.add(attr)
            if nxt or in_loop:
                callee = astutil.dotted_name(node.func)
                if callee:
                    facts.calls_in_loops.append((node.lineno, callee))
        if isinstance(node, ast.Compare) and \
                any(isinstance(op, (ast.In, ast.NotIn)) for op in node.ops):
            for cmp_ in node.comparators:
                attr = _memo_attr(cmp_)
                if attr is not None:
                    memo_read.add(attr)
        if isinstance(node, ast.Assign):
            for t in node.targets:
                tgt = t
                while isinstance(tgt, ast.Subscript):
                    inner = _memo_attr(tgt.value)
                    if inner is not None:
                        memo_store.add(inner)
                    tgt = tgt.value
                attr = _memo_attr(t)
                if attr is not None and isinstance(node.value, ast.Dict):
                    # copy-on-write rebind: self.X = {**self.X, k: v}
                    for k, v in zip(node.value.keys, node.value.values):
                        if k is None and _memo_attr(v) == attr:
                            memo_store.add(attr)
        for child in ast.iter_child_nodes(node):
            scan(child, nxt)

    for stmt in ast.iter_child_nodes(fn):
        scan(stmt, False)
    facts.memo = bool(memo_read & memo_store)
    facts.is_factory = any(
        (bound is not None and (bound in facts.returned_names
                                or bound in facts.nested_free)) or direct
        for _ln, bound, _loop, direct in facts.jit_sites)
    return facts


def _inside_recorded(facts: _FnFacts, node: ast.Call) -> bool:
    """Avoid double-recording a jit already captured at its statement."""
    return any(ln == node.lineno for ln, _b, _l, _d in facts.jit_sites)


# --- jit-surface pass --------------------------------------------------------


def _iter_functions(tree: ast.Module):
    """Yield (qualname, node) for every function at every nesting depth
    (methods as ``Class.method``, nested defs as ``outer.<locals>.inner``)."""
    def rec(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNC_NODES):
                qual = f"{prefix}{child.name}"
                yield qual, child
                yield from rec(child, f"{qual}.<locals>.")
            elif isinstance(child, ast.ClassDef):
                yield from rec(child, f"{prefix}{child.name}.")
            elif not isinstance(child, ast.Lambda):
                yield from rec(child, prefix)

    yield from rec(tree, "")


def _jit_pass(mod_tree: ast.Module, source: str, rel: str,
              report: CompileReport):
    jit_ok = astutil.line_comments(source, "jit-ok")
    note = _note

    all_facts: dict[str, _FnFacts] = {
        qual: _collect_fn_facts(node, qual)
        for qual, node in _iter_functions(mod_tree)}

    # module-scope jit sites (top-level assigns / decorated defs) are
    # import-time programs: classified ok, recorded for the inventory
    for node in astutil.own_scope_nodes(mod_tree):
        if isinstance(node, ast.Assign) and _is_jit_call(node.value):
            report.jit_sites.append({
                "path": rel, "line": node.value.lineno,
                "scope": "<module>", "class": "import-time"})
        elif isinstance(node, _FUNC_NODES):
            for d in node.decorator_list:
                if _is_jit_call(d) or _last_comp(
                        astutil.dotted_name(d)) in JIT_CALL_NAMES:
                    report.jit_sites.append({
                        "path": rel, "line": node.lineno,
                        "scope": "<module>", "class": "import-time"})

    # memoized/lru builders RETURN a jit too, but calling them per
    # request is the point — only unmemoized factories are loop hazards
    factory_names = {f.name for f in all_facts.values()
                     if f.is_factory and not (f.memo or f.is_lru)}

    for qual, facts in all_facts.items():
        if facts.is_init:
            cls = "construction-time"
        elif facts.is_lru:
            cls = "memoized-builder"
        elif facts.memo:
            cls = "program-table"
        elif facts.is_factory:
            cls = "factory"
        else:
            cls = None
        for line, bound, in_loop, direct in facts.jit_sites:
            qualifier = f"{qual}:{bound or '<jit>'}"
            site_cls = cls
            code = None
            if in_loop and cls not in ("construction-time",
                                       "memoized-builder"):
                code, site_cls = "jit-in-loop", "hazard"
            elif cls is None and not (bound is not None and (
                    bound in facts.returned_names
                    or bound in facts.nested_free)) and not direct:
                code, site_cls = "jit-per-call", "hazard"
            elif cls is None:
                site_cls = "factory"
            report.jit_sites.append({
                "path": rel, "line": line, "scope": qual,
                "class": site_cls})
            if code is None:
                continue
            reason = note(jit_ok, line)
            if reason:
                report.suppressed.append({
                    "code": code, "path": rel, "lineno": line,
                    "reason": reason, "qualifier": qualifier})
                continue
            msg = ("jax.jit constructed inside a loop body"
                   if code == "jit-in-loop" else
                   "jax.jit constructed per call (no memoizing "
                   "program-table, lru_cache, or factory-return idiom)")
            report.findings.append(Finding(
                code, rel, line, f"{msg} in {qual} — a fresh XLA program "
                "per invocation", qualifier=qualifier))
        # calls to known jit factories from inside a loop compile a
        # fresh program per iteration just the same
        if facts.is_lru or facts.memo or facts.is_init:
            continue
        for line, callee in facts.calls_in_loops:
            last = _last_comp(callee)
            if last in factory_names:
                qualifier = f"{qual}:{last}"
                reason = note(jit_ok, line)
                if reason:
                    report.suppressed.append({
                        "code": "jit-in-loop", "path": rel, "lineno": line,
                        "reason": reason, "qualifier": qualifier})
                    continue
                report.findings.append(Finding(
                    "jit-in-loop", rel, line,
                    f"jit factory {last}() called inside a loop body in "
                    f"{qual} — a fresh XLA program per iteration",
                    qualifier=qualifier))


# --- uncovered-traced-branch pass --------------------------------------------


def _static_names(call: ast.Call, params: list[str]) -> tuple[set, bool]:
    """(static param names, parsed_ok) from a jit call's keywords."""
    static: set[str] = set()
    ok = True

    def ints(node):
        nonlocal ok
        if isinstance(node, ast.Constant) and isinstance(node.value, int):
            return [node.value]
        if isinstance(node, (ast.Tuple, ast.List)):
            out = []
            for e in node.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    out.append(e.value)
                else:
                    ok = False
            return out
        ok = False
        return []

    def strs(node):
        nonlocal ok
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return [node.value]
        if isinstance(node, (ast.Tuple, ast.List)):
            out = []
            for e in node.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    out.append(e.value)
                else:
                    ok = False
            return out
        ok = False
        return []

    for kw in call.keywords:
        if kw.arg == "static_argnums":
            for i in ints(kw.value):
                if 0 <= i < len(params):
                    static.add(params[i])
        elif kw.arg == "static_argnames":
            static.update(strs(kw.value))
    return static, ok


def _params_of(fn: ast.AST, drop_self: bool) -> list[str]:
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args]
    if drop_self and names and names[0] in ("self", "cls"):
        names = names[1:]
    return names


def _branch_hits(expr: ast.AST, watched: set[str]) -> list[str]:
    hits: list[str] = []

    def rec(node):
        if isinstance(node, ast.Attribute) and node.attr in SHAPE_ATTRS:
            return  # x.shape / x.ndim / x.dtype are trace-time constants
        if isinstance(node, ast.Compare) and \
                all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops) \
                and all(isinstance(c, ast.Constant) and c.value is None
                        for c in node.comparators):
            return  # `x is None`: None is a static pytree, not a tracer
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load) \
                and node.id in watched:
            hits.append(node.id)
        for child in ast.iter_child_nodes(node):
            rec(child)

    rec(expr)
    return hits


def _check_traced_branches(target: ast.AST, watched: set[str],
                           out: list[tuple[int, str]]):
    """Collect (lineno, param) for branches on watched names, descending
    into nested scopes with Python's name-shadowing rules."""
    def assigned_names(fn):
        names = {a.arg for a in fn.args.posonlyargs + fn.args.args
                 + fn.args.kwonlyargs}
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Name) and isinstance(
                    sub.ctx, (ast.Store, ast.Del)):
                names.add(sub.id)
        return names

    def rec(node, watched):
        if isinstance(node, _FUNC_NODES):
            inner = watched - assigned_names(node)
            for stmt in node.body:
                rec(stmt, inner)
            return
        if isinstance(node, ast.Lambda):
            inner = watched - {a.arg for a in node.args.args}
            rec(node.body, inner)
            return
        if isinstance(node, ast.ClassDef):
            return
        test = None
        if isinstance(node, (ast.If, ast.While, ast.IfExp)):
            test = node.test
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            test = node.iter
        if test is not None:
            for name in _branch_hits(test, watched):
                out.append((test.lineno, name))
        for child in ast.iter_child_nodes(node):
            rec(child, watched)

    for stmt in target.body:
        rec(stmt, watched)


def _traced_branch_pass(mod_tree: ast.Module, source: str, rel: str,
                        report: CompileReport):
    traced_ok = astutil.line_comments(source, "traced-ok")

    # index every def by (enclosing-class, name) and (enclosing-func, name)
    class_methods: dict[str, dict[str, ast.AST]] = {}
    module_funcs: dict[str, ast.AST] = {}
    for node in astutil.own_scope_nodes(mod_tree):
        if isinstance(node, ast.ClassDef):
            class_methods[node.name] = {
                m.name: m for m in node.body if isinstance(m, _FUNC_NODES)}
        elif isinstance(node, _FUNC_NODES):
            module_funcs[node.name] = node

    def resolve(call: ast.Call, cls_name: str | None,
                local_defs: dict[str, ast.AST]):
        """(target_def, drop_self, target_qual) or (None, ..)."""
        if not call.args:
            return None, False, None
        arg0 = call.args[0]
        if isinstance(arg0, ast.Attribute) and \
                isinstance(arg0.value, ast.Name) and \
                arg0.value.id in ("self", "cls") and cls_name:
            m = class_methods.get(cls_name, {}).get(arg0.attr)
            if m is not None:
                return m, True, f"{cls_name}.{arg0.attr}"
        if isinstance(arg0, ast.Name):
            tgt = local_defs.get(arg0.id) or module_funcs.get(arg0.id)
            if tgt is not None:
                return tgt, False, arg0.id
        return None, False, None

    def visit_scope(scope: ast.AST, cls_name: str | None, qual: str):
        local_defs = {n.name: n for n in astutil.own_scope_nodes(scope)
                      if isinstance(n, _FUNC_NODES)}
        # decorator form: @jax.jit def f — the def itself is the target
        for node in astutil.own_scope_nodes(scope):
            if isinstance(node, _FUNC_NODES):
                for d in node.decorator_list:
                    call = d if isinstance(d, ast.Call) else None
                    is_jit = _is_jit_call(d) or _last_comp(
                        astutil.dotted_name(d)) in JIT_CALL_NAMES
                    if not is_jit:
                        continue
                    static = set()
                    parsed = True
                    params = _params_of(node, drop_self=False)
                    if call is not None:
                        static, parsed = _static_names(call, params)
                    check(node, params, static, parsed,
                          f"{qual}{node.name}", node.lineno)
            if isinstance(node, ast.Call) and _is_jit_call(node):
                tgt, drop_self, tqual = resolve(node, cls_name, local_defs)
                if tgt is None:
                    report.wrappers.append({
                        "path": rel, "line": node.lineno,
                        "target": None, "resolved": False})
                    continue
                params = _params_of(tgt, drop_self=drop_self)
                static, parsed = _static_names(node, params)
                check(tgt, params, static, parsed, tqual, node.lineno)

    def check(target, params, static, parsed, tqual, wrapper_line):
        watched = set(params) - static
        report.wrappers.append({
            "path": rel, "line": wrapper_line, "target": tqual,
            "resolved": True, "params": params,
            "static": sorted(static), "statics_parsed": parsed})
        hits: list[tuple[int, str]] = []
        _check_traced_branches(target, watched, hits)
        for line, name in hits:
            qualifier = f"{tqual}:{name}"
            reason = _note(traced_ok, line)
            if reason:
                report.suppressed.append({
                    "code": "uncovered-traced-branch", "path": rel,
                    "lineno": line, "reason": reason,
                    "qualifier": qualifier})
                continue
            report.findings.append(Finding(
                "uncovered-traced-branch", rel, line,
                f"Python branch on traced argument {name!r} in {tqual} "
                f"(jit wrapper at line {wrapper_line} has no covering "
                "static_argnums/static_argnames entry)",
                qualifier=qualifier))

    # walk every scope that can contain a jit wrapper construction
    visit_scope(mod_tree, None, "")
    for node in astutil.own_scope_nodes(mod_tree):
        if isinstance(node, ast.ClassDef):
            for m in node.body:
                if isinstance(m, _FUNC_NODES):
                    visit_scope(m, node.name, f"{node.name}.")
        elif isinstance(node, _FUNC_NODES):
            visit_scope(node, None, f"{node.name}.")


# --- host-sync pass ----------------------------------------------------------


def _sync_desc(node: ast.Call) -> str | None:
    func = node.func
    dotted = astutil.dotted_name(func)
    if dotted in _SYNC_DOTTED:
        return _SYNC_DOTTED[dotted]
    if isinstance(func, ast.Attribute):
        if func.attr == "item" and not node.args and not node.keywords:
            return ".item()"
        if func.attr == "block_until_ready":
            return ".block_until_ready()"
    if isinstance(func, ast.Name) and func.id in ("float", "int") \
            and len(node.args) == 1:
        arg = node.args[0]
        inner_calls = [n for n in ast.walk(arg) if isinstance(n, ast.Call)]
        if inner_calls and not any(_sync_desc(c) for c in inner_calls):
            return f"{func.id}(<call>)"
    return None


class _SyncVisitor(_static._FnVisitor):
    """The ISSUE-10 lock-tracking walker, extended to record host-sync
    descriptors with the locks held at each site."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.syncs: list[tuple[str, tuple, int]] = []

    def visit_Call(self, node):
        desc = _sync_desc(node)
        if desc is not None:
            self.syncs.append((desc, tuple(self.held), node.lineno))
        super().visit_Call(node)


def _hot_set(mod: "_static._Module", hot_roots: tuple,
             hot_notes: dict[int, str]) -> dict[str, str]:
    """qualname -> root it is reached from, via same-module call BFS."""
    roots: dict[str, str] = {}
    for qual, s in mod.summaries.items():
        if s.name in hot_roots:
            roots[qual] = qual
    for node in ast.walk(mod.tree):
        # the annotation rides its own line above (or on) the def line —
        # the same two-line window every other marker gets
        if isinstance(node, _FUNC_NODES) and \
                _note(hot_notes, node.lineno) is not None:
            for qual, s in mod.summaries.items():
                if s.qualname.endswith(node.name) and \
                        qual.split(".")[-1] == node.name:
                    roots.setdefault(qual, qual)
    hot = dict(roots)
    frontier = list(roots)
    while frontier:
        qual = frontier.pop()
        s = mod.summaries.get(qual)
        if s is None:
            continue
        for kind, target, _held, _line in s.calls:
            callee = mod._resolve_callee(s, kind, target)
            if callee is not None and callee not in hot:
                hot[callee] = hot[qual]
                frontier.append(callee)
    return hot


def _sync_pass(mod: "_static._Module", rel: str, report: CompileReport,
               hot_roots: tuple):
    sync_ok = astutil.line_comments(mod.source, "sync-ok")
    hot_notes = astutil.line_comments(mod.source, "hot-root")
    hot = _hot_set(mod, hot_roots, hot_notes)
    for qual, root in sorted(hot.items()):
        report.hot_functions.append({"path": rel, "function": qual,
                                     "root": root})

    for qual, s in mod.summaries.items():
        node = None
        if s.cls is not None:
            node = mod.classes[s.cls]["methods"].get(s.name)
            locks = mod.classes[s.cls]["locks"]
            methods = set(mod.classes[s.cls]["methods"])
            prefix = f"{s.cls}."
        else:
            node = mod.module_funcs.get(s.name)
            locks, methods, prefix = {}, set(), ""
        if node is None:
            continue
        summary = _static._FnSummary(qual, s.name, s.cls)
        v = _SyncVisitor(summary, locks, mod.module_locks, methods,
                         set(mod.module_funcs), prefix)
        for stmt in node.body:
            v.visit(stmt)
        for desc, held, line in v.syncs:
            eff = frozenset(held) | s.entry_held
            if eff:
                code = "host-sync-under-lock"
                ctx = "while holding " + ", ".join(sorted(eff))
            elif qual in hot:
                code = "host-sync-hot-loop"
                ctx = f"in the hot path of {hot[qual]}"
            else:
                continue
            qualifier = f"{qual}:{desc}"
            reason = mod.note(sync_ok, line)
            if reason:
                report.suppressed.append({
                    "code": code, "path": rel, "lineno": line,
                    "reason": reason, "qualifier": qualifier})
                continue
            report.findings.append(Finding(
                code, rel, line,
                f"host-device sync {desc} {ctx} in {qual}",
                qualifier=qualifier))


# --- swallowed-exception pass ------------------------------------------------


def _swallows(handler: ast.ExceptHandler) -> bool:
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                     ast.Constant):
            continue  # docstring / bare ... placeholder
        return False
    return True


def _except_pass(mod_tree: ast.Module, source: str, rel: str,
                 report: CompileReport):
    except_ok = astutil.line_comments(source, "except-ok")

    def rec(node, qual):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNC_NODES):
                rec(child, f"{qual}.{child.name}" if qual else child.name)
                continue
            if isinstance(child, ast.ClassDef):
                rec(child, f"{qual}.{child.name}" if qual else child.name)
                continue
            if isinstance(child, ast.ExceptHandler):
                ename = None if child.type is None else _last_comp(
                    astutil.dotted_name(child.type))
                broad = child.type is None or ename in BROAD_EXCEPTIONS
                if broad and _swallows(child):
                    label = ename or "bare"
                    qualifier = f"{qual or '<module>'}:{label}"
                    reason = _note(except_ok, child.lineno)
                    if reason:
                        report.suppressed.append({
                            "code": "swallowed-exception", "path": rel,
                            "lineno": child.lineno, "reason": reason,
                            "qualifier": qualifier})
                    else:
                        report.findings.append(Finding(
                            "swallowed-exception", rel, child.lineno,
                            f"broad '{'except:' if ename is None else f'except {ename}:'}' "
                            f"handler swallows silently in "
                            f"{qual or '<module>'} (body is only "
                            "pass/continue)", qualifier=qualifier))
            rec(child, qual)

    rec(mod_tree, "")


# --- entry points ------------------------------------------------------------


def analyze_tree(root: str, allowlist_path: str | None = None,
                 rel_base: str | None = None,
                 compile_scope: str = "models",
                 hot_roots: tuple = HOT_ROOT_NAMES) -> CompileReport:
    """All four passes over ``root`` (the ``k8s_tpu`` package dir).

    The jit-surface / traced-branch / host-sync passes run over modules
    under ``root/<compile_scope>/`` (the jitted serving stack); the
    swallowed-exception pass runs over the whole tree."""
    entries = load_allowlist(allowlist_path) if allowlist_path else []
    base = rel_base or os.path.dirname(os.path.abspath(root))
    scope_dir = os.path.join(os.path.abspath(root), compile_scope) + os.sep
    report = CompileReport()
    for path in astutil.iter_py_files(root):
        rel = os.path.relpath(os.path.abspath(path), base).replace(
            os.sep, "/")
        try:
            with open(path, "rb") as f:
                source = f.read().decode("utf-8", "replace")
            tree = ast.parse(source, path)
        except SyntaxError:
            continue  # the lint syntax layer owns this failure
        report.module_count += 1
        _except_pass(tree, source, rel, report)
        if os.path.abspath(path).startswith(scope_dir):
            _jit_pass(tree, source, rel, report)
            _traced_branch_pass(tree, source, rel, report)
            mod = _static._Module(path, rel, source, tree)
            _sync_pass(mod, rel, report, hot_roots)
    _static._apply_allowlist(report, entries)
    report.findings.sort(key=lambda f: (f.path, f.lineno, f.code))
    return report


def analyze_source(source: str, relpath: str = "mod.py",
                   hot_roots: tuple = HOT_ROOT_NAMES) -> CompileReport:
    """Single-module entry point for tests/fixtures: runs all four
    passes (no allowlist)."""
    report = CompileReport()
    tree = ast.parse(source, relpath)
    report.module_count = 1
    _except_pass(tree, source, relpath, report)
    _jit_pass(tree, source, relpath, report)
    _traced_branch_pass(tree, source, relpath, report)
    mod = _static._Module(relpath, relpath, source, tree)
    _sync_pass(mod, relpath, report, hot_roots)
    report.findings.sort(key=lambda f: (f.path, f.lineno, f.code))
    return report

"""Runtime deadlock-and-race detector: checked Lock/RLock/Condition.

Drop-in factories for the control plane's hot-path locks::

    self._lock = checkedlock.make_lock("engine.slots")
    self._cond = checkedlock.make_condition("workqueue.cond")

With ``K8S_TPU_LOCK_CHECK`` unset (the default) the factories return raw
``threading`` primitives — zero instrumentation, zero overhead.  With
``K8S_TPU_LOCK_CHECK=1`` every acquisition updates a process-global
acquisition DAG (per lock *instance*, so two queues of the same class are
two nodes and an ABBA interleave across instances is caught):

- acquiring B while holding A adds the edge A->B; if a path B->...->A
  already exists the acquire RAISES :class:`LockOrderViolation` carrying
  this thread's stack AND the stack captured when the reverse path's
  first edge was formed — the two halves of the potential deadlock.
- re-acquiring a non-reentrant checked Lock on the same thread raises
  immediately (the undetectable-until-production self-deadlock).
- a daemon watchdog scans held locks and records (never raises) a
  violation with the holder's live stack once a lock has been held
  longer than ``K8S_TPU_LOCK_MAX_HOLD_S`` (default 30s).
- contention (acquire had to block) and max-hold-time are counted per
  lock name and exported by :func:`audit_snapshot` /
  :func:`write_audit` — the ``lock_audit.json`` artifact the bench tier
  emits.

The wrappers interoperate with ``threading.Condition`` (they provide
``_release_save`` / ``_acquire_restore`` / ``_is_owned``), so
``make_condition`` is a Condition over a checked RLock and a
``cond.wait()`` correctly *removes* the lock from the thread's held set
for the duration of the wait.
"""

from __future__ import annotations

import collections
import os
import sys
import threading
import time
import traceback
import weakref

DEFAULT_MAX_HOLD_S = 30.0
WATCHDOG_HITS_MAX = 256     # recorded held-too-long violations kept

_registry_lock = threading.Lock()   # leaf lock: guards the graph/stats only
_edges: dict[int, dict[int, dict]] = {}     # id(a) -> id(b) -> witness
_nodes: dict[int, str] = {}                 # id -> name (live checked locks)
_stats: dict[str, dict] = {}                # name -> counters
_watchdog_hits: list[dict] = []
_cycle_hits = 0
_watchdog_thread: threading.Thread | None = None
_watchdog_hook = None       # test seam: called with each violation dict
_tls = threading.local()    # .held: list of [lock, depth, t_acquire, tracked]


def _registry_acquire(blocking: bool = True) -> bool:
    """Take the process-global registry lock for bookkeeping; False means
    the caller must skip (best-effort) instead.

    Signal-safety: a SIGTERM handler (signals.py runs shutdown callbacks
    on the interrupted thread) may call into checked locks while THIS
    thread's interrupted frame is inside a registry critical section —
    blocking on the non-reentrant registry lock there would self-deadlock
    the process for the whole grace window.  A thread-local in-registry
    flag set for the duration of every critical section (including while
    blocked acquiring it) lets the re-entered frame detect that and skip
    bookkeeping; order checking and stats are best-effort in handler
    context, the inner lock semantics are not."""
    if getattr(_tls, "in_registry", False):
        return False
    _tls.in_registry = True
    if _registry_lock.acquire(blocking):
        return True
    _tls.in_registry = False
    return False


def _registry_release() -> None:
    _registry_lock.release()
    _tls.in_registry = False


class LockOrderViolation(RuntimeError):
    """Acquisition would close a cycle in the lock-order DAG."""


def _stat_locked(name: str) -> dict:
    """The per-name counter row, (re)seeded on demand — reset() may have
    dropped it while the lock instance stayed alive, and a KeyError in
    release() would leak the inner lock locked forever."""
    return _stats.setdefault(name, {
        "acquisitions": 0, "contention": 0, "max_hold_s": 0.0,
        "total_hold_s": 0.0, "live": 0})


def enabled() -> bool:
    return os.environ.get("K8S_TPU_LOCK_CHECK") == "1"


def max_hold_s() -> float:
    try:
        return float(os.environ.get("K8S_TPU_LOCK_MAX_HOLD_S", ""))
    except ValueError:
        return DEFAULT_MAX_HOLD_S


# --- factories ---------------------------------------------------------------


def make_lock(name: str | None = None):
    """A ``threading.Lock`` (checking off) or a checked non-reentrant
    lock (checking on)."""
    if not enabled():
        return threading.Lock()
    return _CheckedLock(threading.Lock(), name or _callsite(), False)


def make_rlock(name: str | None = None):
    if not enabled():
        return threading.RLock()
    return _CheckedLock(threading.RLock(), name or _callsite(), True)


def make_condition(name: str | None = None):
    """A Condition whose underlying lock participates in checking."""
    if not enabled():
        return threading.Condition()
    return threading.Condition(
        _CheckedLock(threading.RLock(), name or _callsite(), True))


def _callsite() -> str:
    f = sys._getframe(2)
    mod = f.f_globals.get("__name__", "?")
    return f"{mod}:{f.f_lineno}"


# --- the wrapper -------------------------------------------------------------


def _held() -> list:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


class _CheckedLock:
    __slots__ = ("_inner", "name", "reentrant", "__weakref__")

    def __init__(self, inner, name: str, reentrant: bool):
        self._inner = inner
        self.name = name
        self.reentrant = reentrant
        if _registry_acquire():
            try:
                _drain_pending_locked()
                _nodes[id(self)] = name
                _stat_locked(name)["live"] += 1
            finally:
                _registry_release()
        else:
            # created from a frame that re-entered the registry (signal
            # handler): queue the registration like a deferred forget
            _pending_ops.append(("reg", id(self), name))
        # prune this instance's node/edges when it is collected so the
        # per-instance graph stays bounded under object churn
        weakref.finalize(self, _forget_node, id(self), name)
        _ensure_watchdog()

    # -- core protocol

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        held = _held()
        for entry in held:
            if entry[0] is self:
                if self.reentrant:
                    ok = self._inner.acquire(blocking, timeout)
                    if ok:
                        entry[1] += 1
                    return ok
                if not blocking:
                    # raw-Lock contract: trylock on a held lock returns
                    # False, whoever holds it — checkpoint._save_now's
                    # SIGTERM handler relies on exactly that to SKIP the
                    # final save when it interrupted the interval save
                    # mid-hold; raising here would throw into the
                    # interrupted frame instead
                    return False
                raise LockOrderViolation(
                    f"self-deadlock: thread {threading.current_thread().name}"
                    f" re-acquiring non-reentrant lock {self.name!r}\n"
                    + "".join(traceback.format_stack()))
        if not blocking:
            # signal-safe path: a trylock (checkpoint _save_now's SIGTERM
            # handler) must never wait on the registry lock — the
            # interrupted thread may be inside a bookkeeping critical
            # section, and blocking here would self-deadlock the process
            # for the whole grace window.  Order checking only matters for
            # waits, so it is skipped; stats are best-effort.
            if not self._inner.acquire(False):
                if _registry_acquire(False):
                    try:
                        _stat_locked(self.name)["contention"] += 1
                    finally:
                        _registry_release()
                return False
            t0 = time.monotonic()
            tracked = _registry_acquire(False)
            if tracked:
                try:
                    me = threading.current_thread()
                    _stat_locked(self.name)["acquisitions"] += 1
                    _live_holds[(me.ident, id(self))] = (self.name, me.name,
                                                         t0)
                finally:
                    _registry_release()
            held.append([self, 1, t0, tracked])
            return True
        self._check_order(held)
        if self._inner.acquire(False):
            got = True
        else:
            if _registry_acquire():
                try:
                    _stat_locked(self.name)["contention"] += 1
                finally:
                    _registry_release()
            got = self._inner.acquire(True, timeout)
        if not got:
            return False
        t0 = time.monotonic()
        me = threading.current_thread()
        tracked = _registry_acquire()
        if tracked:
            try:
                _stat_locked(self.name)["acquisitions"] += 1
                _live_holds[(me.ident, id(self))] = (self.name, me.name, t0)
            finally:
                _registry_release()
        held.append([self, 1, t0, tracked])
        return True

    def _end_hold(self, entry: list) -> None:
        """Hold-time stat + live-hold unwind shared by release() and
        _release_save().  An untracked (signal-handler) hold has no
        registry state to unwind; a re-entered registry skips best-effort
        (worst case: one stale _live_holds row until this thread's next
        tracked release, which the watchdog may RECORD — never raise —
        as a long hold)."""
        dt = time.monotonic() - entry[2]
        if entry[3] and _registry_acquire():
            try:
                st = _stat_locked(self.name)
                st["total_hold_s"] += dt
                if dt > st["max_hold_s"]:
                    st["max_hold_s"] = dt
                _live_holds.pop((threading.get_ident(), id(self)), None)
            finally:
                _registry_release()

    def release(self):
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is self:
                held[i][1] -= 1
                if held[i][1] == 0:
                    entry = held[i]
                    del held[i]
                    self._end_hold(entry)
                break
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked() if hasattr(self._inner, "locked") \
            else self._inner._is_owned()

    # -- Condition interop: wait() must drop the lock from the held set

    def _release_save(self):
        held = _held()
        depth = 0
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is self:
                depth = held[i][1]
                entry = held[i]
                del held[i]
                self._end_hold(entry)
                break
        if hasattr(self._inner, "_release_save"):
            return (self._inner._release_save(), depth)
        self._inner.release()
        return (None, depth)

    def _acquire_restore(self, state):
        inner_state, depth = state
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(inner_state)
        else:
            self._inner.acquire()
        t0 = time.monotonic()
        me = threading.current_thread()
        tracked = _registry_acquire()
        if tracked:
            try:
                _live_holds[(me.ident, id(self))] = (self.name, me.name, t0)
            finally:
                _registry_release()
        _held().append([self, depth or 1, t0, tracked])

    def _is_owned(self):
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        return any(e[0] is self for e in _held())

    # -- ordering

    def _check_order(self, held: list):
        """Add edges held->self; raise if any would close a cycle.

        Stack formatting (traceback.format_stack reads source files
        through linecache — disk I/O) happens OUTSIDE the registry
        critical section: witnesses for new edges are inserted with a
        placeholder and filled in after release (a concurrent cycle
        report racing the fill-in sees the placeholder at worst), so no
        thread ever serializes the process-wide lock bookkeeping behind
        file reads."""
        global _cycle_hits
        if not held:
            return
        me = id(self)
        cycle = None
        new_witnesses: list[dict] = []
        if not _registry_acquire():
            return  # re-entered from a signal handler: best-effort skip
        try:
            # cycle test first: does a path me -> ... -> any held exist?
            held_ids = {id(e[0]) for e in held}
            path = _find_path(me, held_ids)
            if path is not None:
                _cycle_hits += 1
                first_edge = _edges[path[0]][path[1]]
                cycle = ([_nodes.get(n, "?") for n in path],
                         [_nodes.get(i, "?") for i in held_ids],
                         dict(first_edge))
            else:
                for entry in held:
                    a = id(entry[0])
                    tgt = _edges.setdefault(a, {})
                    if me not in tgt:
                        w = tgt[me] = {
                            "from_name": entry[0].name, "to_name": self.name,
                            "thread": threading.current_thread().name,
                            "stack": "<stack pending>", "count": 1}
                        new_witnesses.append(w)
                    else:
                        tgt[me]["count"] += 1
        finally:
            _registry_release()
        if cycle is not None:
            names, held_names, other = cycle
            raise LockOrderViolation(
                "lock-order cycle: acquiring "
                f"{self.name!r} while holding "
                f"{held_names} would close "
                f"the cycle {' -> '.join(names + [self.name])}\n"
                "--- this thread "
                f"({threading.current_thread().name}) ---\n"
                + "".join(traceback.format_stack())
                + f"--- reverse edge {other['from_name']} -> "
                f"{other['to_name']} first formed by thread "
                f"{other['thread']} ---\n" + other["stack"])
        if new_witnesses:
            # one format per batch of new edges; GIL-atomic store
            stack_text = "".join(traceback.format_stack())
            for w in new_witnesses:
                w["stack"] = stack_text


def _find_path(src: int, targets: set[int]) -> list[int] | None:
    """DFS in the edge graph from src to any of targets; returns the node
    path or None.  Caller holds the registry lock."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        for nxt in _edges.get(node, ()):
            if nxt in targets:
                return path + [nxt]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


# finalize callbacks run synchronously wherever GC fires — possibly on a
# thread that is INSIDE a _registry_lock critical section (allocation under
# the lock can trigger a cyclic-GC pass that collects a cycle-trapped
# checked lock).  Blocking on the non-reentrant registry lock there would
# self-deadlock the detector, so forgets (and signal-context
# registrations) are queued and drained IN ORDER by whoever can take the
# lock without waiting — FIFO matters: id() of a collected lock can be
# reused, so its forget must land before the successor's registration.
_pending_ops: collections.deque[tuple[str, int, str]] = collections.deque()


def _forget_node(node_id: int, name: str):
    _pending_ops.append(("forget", node_id, name))  # deque.append: GIL-atomic
    _drain_pending()


def _drain_pending():
    if not _registry_acquire(False):
        return  # holder (or the next forget/audit) drains the queue
    try:
        _drain_pending_locked()
    finally:
        _registry_release()


def _drain_pending_locked():
    while _pending_ops:
        op, node_id, name = _pending_ops.popleft()
        if op == "reg":
            _nodes[node_id] = name
            _stat_locked(name)["live"] += 1
            continue
        _nodes.pop(node_id, None)
        _edges.pop(node_id, None)
        for tgt in _edges.values():
            tgt.pop(node_id, None)
        st = _stats.get(name)
        if st is not None:
            st["live"] -= 1


# --- watchdog ----------------------------------------------------------------


def _ensure_watchdog():
    global _watchdog_thread
    t = None
    if not _registry_acquire():
        return  # signal-context factory call: the next one starts it
    try:
        if _watchdog_thread is None or not _watchdog_thread.is_alive():
            t = threading.Thread(target=_watchdog_loop, daemon=True,
                                 name="checkedlock-watchdog")
            _watchdog_thread = t
    finally:
        _registry_release()
    if t is not None:
        t.start()


def _watchdog_loop():
    reported: set[tuple[int, float]] = set()
    while True:
        threshold = max_hold_s()
        time.sleep(min(max(threshold / 4.0, 0.01), 1.0))
        now = time.monotonic()
        frames = None
        with _registry_lock:
            snapshots = list(_long_holds(now, threshold))
            live_keys = {(lock_id, t0)
                         for (_, lock_id), (_, _, t0) in _live_holds.items()}
        # a (lock, t_acquire) key can't recur once the hold ends, so
        # pruning against the live set both bounds `reported` in a
        # long-lived soak and keeps the dedup exact
        reported &= live_keys
        for lock_name, tid, tname, held_s, key in snapshots:
            if key in reported:
                continue
            reported.add(key)
            if frames is None:
                frames = sys._current_frames()
            stack = "".join(traceback.format_stack(frames[tid])) \
                if tid in frames else "<thread gone>"
            hit = {"lock": lock_name, "thread": tname, "held_s": held_s,
                   "stack": stack}
            with _registry_lock:
                _watchdog_hits.append(hit)
                if len(_watchdog_hits) > WATCHDOG_HITS_MAX:
                    # keep the most recent hits; each retains a multi-KB
                    # stack, and a recurring long hold in a soak run must
                    # not grow the process without bound
                    del _watchdog_hits[0]
            hook = _watchdog_hook
            if hook is not None:
                try:
                    hook(hit)
                # except-ok: a broken test hook must not kill the watchdog
                except Exception:
                    pass
            print(f"[checkedlock] WATCHDOG: {lock_name!r} held "
                  f"{held_s:.2f}s by {tname}\n{stack}", file=sys.stderr)


# the watchdog needs (thread, lock, t_acquire) for every live hold; the
# held stacks are thread-local, so acquire() also mirrors them here
_live_holds: dict[tuple[int, int], tuple[str, str, float]] = {}


def _long_holds(now: float, threshold: float):
    for (tid, lock_id), (lock_name, tname, t0) in list(_live_holds.items()):
        held_s = now - t0
        if held_s > threshold:
            yield lock_name, tid, tname, held_s, (lock_id, t0)


# --- audit -------------------------------------------------------------------


def audit_snapshot() -> dict:
    """The ``lock_audit.json`` payload: per-name stats, the acquisition
    graph aggregated by name, and recorded violations."""
    if not _registry_acquire():
        # re-entered from a handler frame that holds the registry
        return {"enabled": enabled(), "reentered": True}
    try:
        _drain_pending_locked()
        by_name: dict[tuple[str, str], int] = {}
        for a, targets in _edges.items():
            for b, w in targets.items():
                key = (w["from_name"], w["to_name"])
                by_name[key] = by_name.get(key, 0) + w["count"]
        return {
            "enabled": enabled(),
            "locks": {name: {k: (round(v, 6) if isinstance(v, float) else v)
                             for k, v in st.items()}
                      for name, st in sorted(_stats.items())},
            "edges": [{"from": a, "to": b, "count": n}
                      for (a, b), n in sorted(by_name.items())],
            "watchdog_violations": [
                {k: (round(v, 3) if isinstance(v, float) else v)
                 for k, v in hit.items() if k != "stack"}
                for hit in _watchdog_hits],
            "cycle_violations": _cycle_hits,
        }
    finally:
        _registry_release()


def write_audit(path: str) -> dict:
    import json

    snap = audit_snapshot()
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(snap, f, indent=1, sort_keys=True)
    return snap


def reset() -> None:
    """Test seam: drop the global graph, stats, and violation records."""
    global _cycle_hits
    if not _registry_acquire():
        return  # signal-context re-entry: nothing sane to reset here
    try:
        _pending_ops.clear()
        _edges.clear()
        _nodes.clear()
        _stats.clear()
        _watchdog_hits.clear()
        _live_holds.clear()
        _cycle_hits = 0
    finally:
        _registry_release()

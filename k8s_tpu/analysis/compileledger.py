"""Runtime XLA compile ledger with per-seam budgets (ISSUE 11).

The runtime half of the compile-surface auditor: a process-global
:class:`CompileLedger` that records every XLA compile — fingerprint
(function name, abstract arg shapes/dtypes, static-arg values), wall
time, and originating stack — attributed to the *seam* (jit entry
point) that triggered it, and raises :class:`CompileBudgetExceeded`
when a seam compiles more distinct programs than its declared budget
(the engine declares its expected inventory: one prefill program per
bucket, one decode program per (fused width, sampling) pair, one spec
program per (draft_k, sampling) pair, a whole-generation table bound).

Activation mirrors ``trace``/``scheduler``/``flight``/``fleet``:
``K8S_TPU_COMPILE_LEDGER=1`` plus the :func:`set_active`/:func:`active`
process-global registry; a zero-overhead no-op when unset (consumers
check ``active() is None`` and use their raw jit functions).

Compile *detection* has two sources, in preference order:

1. a ``jax.monitoring`` event-duration listener on the backend-compile
   event — the consumer passes the ``jax.monitoring`` module into
   :func:`ensure_listener` so this module stays **stdlib-only** (the
   ``py_checks`` gate on ``k8s_tpu.analysis`` holds; the jax import
   lives with the jax-importing caller).  The listener is installed
   once per process (jax offers no per-listener removal) and
   dispatches to the wrap context / active ledger at event time.
2. wrapping ``jax.jit`` returns: :meth:`CompileLedger.wrap` falls back
   to the jitted function's ``_cache_size()`` delta when no listener
   event arrived (older jax, or a non-jit callable under test).

Served at ``/debug/compiles`` on the metrics server, the dashboard
backend, and the serving pod's HTTP server (the shared-responder /
404-parity pattern), and exported as the ``compile_audit.json`` bench
artifact.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import traceback
from collections import deque
from collections.abc import Mapping
from typing import Callable, Optional
from urllib.parse import parse_qs

from k8s_tpu.analysis import checkedlock

ENV_ENABLE = "K8S_TPU_COMPILE_LEDGER"

#: the jax.monitoring event one XLA backend compile records
COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

#: default budget for the engine's (draft_k, sampling) spec seam — the
#: draft width is client-chosen, so a flood of distinct values is
#: exactly the compile-surface DoS a budget should catch
DEFAULT_SPEC_BUDGET = 8

#: recent compile events kept for /debug/compiles (per ledger)
EVENTS_MAX = 512

#: stack frames kept per fingerprint witness
STACK_FRAMES = 10


def enabled_from_env() -> bool:
    """K8S_TPU_COMPILE_LEDGER: truthy activates the ledger (default off
    — the zero-overhead compatibility default)."""
    return os.environ.get(ENV_ENABLE, "").lower() in ("1", "true", "on",
                                                      "yes")


class CompileBudgetExceeded(RuntimeError):
    """A seam compiled more distinct XLA programs than it declared."""

    def __init__(self, seam_name: str, budget: int, count: int,
                 fingerprint: str, stack: Optional[str]):
        msg = (f"compile budget exceeded for seam {seam_name!r}: "
               f"{count} distinct programs > budget {budget}; offending "
               f"fingerprint: {fingerprint}")
        if stack:
            msg += f"\ncompiled from:\n{stack}"
        super().__init__(msg)
        self.seam_name = seam_name
        self.budget = budget
        self.count = count
        self.fingerprint = fingerprint
        self.stack = stack


class _Seam:
    """One declared jit entry point: its budget and the distinct
    program fingerprints observed compiling through it.  Mutated only
    under the owning ledger's lock."""

    def __init__(self, name: str, budget: Optional[int], note: str):
        self.name = name
        self.budget = budget
        self.note = note
        # fingerprint -> {count, duration_s, stack}
        self.fingerprints: dict[str, dict] = {}
        self.compiles = 0

    def snapshot(self) -> dict:
        programs = len(self.fingerprints)
        return {"seam": self.name, "budget": self.budget,
                "programs": programs, "compiles": self.compiles,
                "over_budget": self.budget is not None
                and programs > self.budget}


# thread-local wrap context: a pending-durations list the monitoring
# listener appends to while a wrapped call is on this thread's stack
_tls = threading.local()


def caller_stack(skip: int = 2) -> str:
    """The originating stack, trimmed of this module's and jax's own
    frames — what a human needs to find the recompiling call site.
    Public: seams that record by hand (the server's whole-generation
    accounting) attach the same witness the wrap path does."""
    frames = traceback.extract_stack()[:-skip]
    keep = [f for f in frames
            if "/jax/" not in f.filename.replace(os.sep, "/")
            and "/jaxlib/" not in f.filename.replace(os.sep, "/")
            and not f.filename.endswith("compileledger.py")]
    return "".join(traceback.format_list(keep[-STACK_FRAMES:])).rstrip()


_caller_stack = caller_stack


def _spec(x) -> str:
    """Abstract-value summary of one argument: shape/dtype for arrays,
    recursive structure for pytrees, the bare type otherwise — the
    shape identity that decides whether jit retraces."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return f"{dtype}[{','.join(str(d) for d in shape)}]"
    if isinstance(x, Mapping):
        inner = ",".join(f"{k}:{_spec(v)}" for k, v in
                         sorted(x.items(), key=lambda kv: str(kv[0])))
        return _digest("{" + inner + "}")
    if isinstance(x, (list, tuple)):
        return _digest("(" + ",".join(_spec(v) for v in x) + ")")
    if x is None or isinstance(x, (bool, int, float, complex, str)):
        return type(x).__name__
    return type(x).__name__


def _digest(s: str) -> str:
    """Large pytree specs collapse to a stable digest so fingerprints
    stay greppable (identical trees -> identical digest)."""
    if len(s) <= 48:
        return s
    return f"tree#{hashlib.md5(s.encode()).hexdigest()[:10]}"


def _static_repr(v) -> str:
    r = repr(v)
    return r if len(r) <= 48 else r[:45] + "..."


def fingerprint(name: str, args: tuple, kwargs: dict,
                static_argnums: tuple = (), static_argnames: tuple = (),
                context: tuple = ()) -> str:
    """The program identity of one call: traced args by abstract
    shape/dtype, static args by VALUE (they select the program), plus
    any caller-supplied context pairs."""
    statics = set(static_argnums)
    parts = []
    for i, a in enumerate(args):
        parts.append(_static_repr(a) if i in statics else _spec(a))
    for k in sorted(kwargs):
        v = kwargs[k]
        parts.append(f"{k}={_static_repr(v) if k in static_argnames else _spec(v)}")
    tail = "".join(f"; {k}={_static_repr(v)}" for k, v in context)
    return f"{name}({', '.join(parts)}{tail})"


class CompileLedger:
    """Thread-safe record of every observed XLA compile, grouped by
    seam and fingerprint, with per-seam budget enforcement."""

    def __init__(self, events_max: int = EVENTS_MAX):
        self._lock = checkedlock.make_lock("compileledger.registry")
        self._seams: list[_Seam] = []
        self._events: deque[dict] = deque(maxlen=events_max)
        self._unattributed: Optional[_Seam] = None
        self.created_at = time.time()

    # -- declaration --------------------------------------------------

    def declare(self, name: str, budget: Optional[int], note: str = "",
                singleton: bool = False) -> _Seam:
        """Declare a seam and its program budget (None = tracked,
        unbudgeted).  ``singleton=True`` returns the existing seam of
        that name (module-level seams like the whole-generation table);
        the default creates a fresh instance per declaration (each
        engine owns its own seam handles, so two engines in one
        process don't pool their budgets)."""
        with self._lock:
            if singleton:
                for s in self._seams:
                    if s.name == name:
                        return s
            seam = _Seam(name, budget, note)
            self._seams.append(seam)
            return seam

    def _unattributed_seam(self) -> _Seam:
        with self._lock:
            if self._unattributed is None:
                seam = _Seam("(unattributed)", None,
                             "compiles observed outside any wrapped seam "
                             "(warmup, eager dispatch, exclusive-lane "
                             "programs not yet wrapped)")
                self._seams.append(seam)
                self._unattributed = seam
            return self._unattributed

    # -- recording ----------------------------------------------------

    def record(self, seam: _Seam, fp: str, duration_s: float,
               stack: Optional[str] = None) -> None:
        """One observed compile.  Raises :class:`CompileBudgetExceeded`
        (after recording — the ledger never loses the evidence) when
        the seam's distinct-program count passes its budget."""
        over = None
        with self._lock:
            info = seam.fingerprints.get(fp)
            if info is None:
                info = seam.fingerprints[fp] = {
                    "count": 0, "duration_s": 0.0, "stack": None}
            info["count"] += 1
            info["duration_s"] = round(info["duration_s"] + duration_s, 6)
            if stack:
                info["stack"] = stack
            seam.compiles += 1
            self._events.append({
                "ts": round(time.time(), 3), "seam": seam.name,
                "fingerprint": fp, "duration_s": round(duration_s, 6)})
            if seam.budget is not None and \
                    len(seam.fingerprints) > seam.budget:
                over = (seam.name, seam.budget, len(seam.fingerprints))
        if over is not None:
            raise CompileBudgetExceeded(over[0], over[1], over[2], fp,
                                        stack)

    # -- the jit wrap -------------------------------------------------

    def wrap(self, fn: Callable, seam: _Seam, *, name: str | None = None,
             static_argnums: tuple = (), static_argnames: tuple = (),
             context: Optional[dict] = None) -> Callable:
        """Wrap a jitted callable so every compile it triggers lands in
        the ledger under ``seam`` with a full fingerprint.  Detection:
        monitoring-listener events drained from the wrap context when
        the listener is installed, else the jitted function's
        ``_cache_size()`` delta."""
        label = name or getattr(fn, "__name__", "<jit>")
        ctx_items = tuple(sorted((context or {}).items()))
        statics = tuple(static_argnums)
        static_names = tuple(static_argnames)
        cache_size = getattr(fn, "_cache_size", None)

        def wrapped(*args, **kwargs):
            # the fingerprint walks every arg pytree (params, pool...) —
            # compute it LAZILY, only when a compile was detected: the
            # steady-state per-step cost of the wrap must stay at a tls
            # swap + a cache-size read, or the ledger taxes the very hot
            # loop it audits
            pending: list[float] = []
            prev = getattr(_tls, "pending", None)
            _tls.pending = pending
            before = None
            if cache_size is not None:
                try:
                    before = cache_size()
                except Exception:  # noqa: BLE001 - diagnostic seam only
                    before = None
            t0 = time.perf_counter()
            try:
                out = fn(*args, **kwargs)
            finally:
                _tls.pending = prev
            if pending:
                fp = fingerprint(label, args, kwargs, statics,
                                 static_names, ctx_items)
                stack = _caller_stack()
                for dur in pending:
                    self.record(seam, fp, dur, stack)
            elif before is not None:
                try:
                    after = cache_size()
                except Exception:  # noqa: BLE001
                    after = before
                if after > before:
                    fp = fingerprint(label, args, kwargs, statics,
                                     static_names, ctx_items)
                    self.record(seam, fp, time.perf_counter() - t0,
                                _caller_stack())
            return out

        wrapped.__wrapped__ = fn
        wrapped.__name__ = f"ledgered_{label}"
        return wrapped

    # -- reads --------------------------------------------------------

    def seams(self) -> list[dict]:
        with self._lock:
            return [s.snapshot() for s in self._seams]

    def seam_programs(self, name: str) -> int:
        """Distinct programs across every seam instance of ``name``."""
        with self._lock:
            return sum(len(s.fingerprints) for s in self._seams
                       if s.name == name)

    def seam_audit(self, seams: list) -> dict:
        """One consumer's seam handles as an audit payload: snapshots
        plus the over-budget subset — what ``Engine.compile_audit()``
        returns and the bench phases assert on."""
        with self._lock:
            snaps = [s.snapshot() for s in seams]
        return {"seams": snaps,
                "programs": sum(s["programs"] for s in snaps),
                "compiles": sum(s["compiles"] for s in snaps),
                "over_budget": [s["seam"] for s in snaps
                                if s["over_budget"]]}

    def as_dict(self, stacks: bool = True) -> dict:
        """The compile_audit.json payload: per-seam budgets and
        per-fingerprint counts/durations/stacks plus the recent-event
        ring."""
        with self._lock:
            seams = []
            for s in self._seams:
                fps = []
                for fp, info in sorted(s.fingerprints.items()):
                    row = {"fingerprint": fp, "count": info["count"],
                           "duration_s": info["duration_s"]}
                    if stacks and info["stack"]:
                        row["stack"] = info["stack"]
                    fps.append(row)
                seams.append({**s.snapshot(), "note": s.note,
                              "fingerprints": fps})
            return {
                "enabled": True,
                "seams": seams,
                "total_compiles": sum(s.compiles for s in self._seams),
                "total_programs": sum(len(s.fingerprints)
                                      for s in self._seams),
                "over_budget": [s.name for s in self._seams
                                if s.budget is not None
                                and len(s.fingerprints) > s.budget],
                "events": list(self._events),
            }


# -- process-global active ledger (trace.TRACER / fleet pattern) --------------

_ACTIVE: Optional[CompileLedger] = None


def set_active(ledger: Optional[CompileLedger]) -> None:
    global _ACTIVE
    _ACTIVE = ledger


def active() -> Optional[CompileLedger]:
    return _ACTIVE


def maybe_active() -> Optional[CompileLedger]:
    """The active ledger, auto-created on first use when
    ``K8S_TPU_COMPILE_LEDGER`` is set — the activation seam consumers
    (the engine, the exclusive decode lane) call at construction."""
    global _ACTIVE
    if _ACTIVE is None and enabled_from_env():
        _ACTIVE = CompileLedger()
    return _ACTIVE


# -- the jax.monitoring listener ----------------------------------------------

_listener_state = {"installed": False}


def _on_event(event: str, duration_secs: float, **kwargs) -> None:
    """One backend compile happened on this thread.  Inside a wrapped
    call: park the duration for the wrapper to attribute (and to raise
    budget violations OUTSIDE jax's compilation machinery).  Outside:
    record unattributed against the active ledger, never raising."""
    del kwargs
    if event != COMPILE_EVENT:
        return
    pending = getattr(_tls, "pending", None)
    if pending is not None:
        pending.append(duration_secs)
        return
    ledger = _ACTIVE
    if ledger is None:
        return
    ledger.record(ledger._unattributed_seam(), "(unattributed)",
                  duration_secs, _caller_stack())


def ensure_listener(monitoring) -> bool:
    """Install the compile-event listener once per process.  The caller
    passes the ``jax.monitoring`` module — this module never imports
    jax, so the ``k8s_tpu.analysis`` stdlib-only gate holds.  Returns
    True when a listener is (now) installed."""
    if _listener_state["installed"]:
        return True
    if monitoring is None:
        return False
    try:
        monitoring.register_event_duration_secs_listener(_on_event)
    except Exception:  # noqa: BLE001 - older jax: wrap fallback covers it
        return False
    _listener_state["installed"] = True
    return True


def listener_installed() -> bool:
    return _listener_state["installed"]


# -- /debug/compiles ----------------------------------------------------------


def debug_compiles_response(query: str = "") -> tuple[int, str, str]:
    """(status, body, content-type) for GET /debug/compiles — the ONE
    responder the metrics server, the dashboard backend, and the
    serving pod's HTTP server all route to (404 with an explicit body
    while no ledger is active, like every other /debug route)."""
    ledger = _ACTIVE
    if ledger is None:
        return (404,
                "compile ledger inactive (set K8S_TPU_COMPILE_LEDGER=1 so "
                "the engine/decode seams record XLA compiles)\n",
                "text/plain")
    params = parse_qs(query or "")
    seam_filter = (params.get("seam") or [None])[0]
    raw_n = (params.get("n") or [None])[0]
    try:
        limit = int(raw_n) if raw_n is not None else None
    except ValueError:
        limit = None
    # ?stacks=0 drops the per-fingerprint origin stacks (the payload-cap
    # knob docs/observability.md documents); default includes them.
    # parse_qs drops blank-valued keys, so a bare "?stacks" reads as the
    # default too — the VALUE decides, never key presence.
    raw_stacks = (params.get("stacks") or ["1"])[0]
    payload = ledger.as_dict(
        stacks=raw_stacks.lower() not in ("0", "false", "no", "off"))
    if seam_filter:
        payload["seams"] = [s for s in payload["seams"]
                            if s["seam"] == seam_filter]
    if limit is not None and limit >= 0:
        payload["events"] = payload["events"][-limit:] if limit else []
        for s in payload["seams"]:
            s["fingerprints"] = s["fingerprints"][:limit]
    body = json.dumps(payload, indent=2, sort_keys=True)
    return 200, body + "\n", "application/json"


def write_audit(path: str) -> dict:
    """Write the active ledger's audit JSON artifact (compile_audit.json
    from the bench tier); returns the payload ({} when inactive)."""
    ledger = _ACTIVE
    payload = ledger.as_dict() if ledger is not None else {
        "enabled": False, "seams": [], "total_compiles": 0,
        "total_programs": 0, "over_budget": [], "events": []}
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
    return payload

"""Static concurrency analysis: lock-order, guarded-by, blocking-under-lock.

RacerD-style lock-consistency checking scoped to this repo's idioms
(``with self._lock:`` regions, ``_locked``-suffixed helpers, Conditions,
module-level locks).  Three passes over every module of the tree:

- **lock-order**: every acquisition site feeds an interprocedural
  acquisition-order graph (per class/module lock identities); a cycle is
  a potential deadlock and fails the build with the witness site of every
  edge on the cycle.
- **guarded-by**: a field written under one of its class's locks in at
  least one non-``__init__`` method (or annotated ``# guarded-by: _lock``
  on its ``__init__`` assignment) is *guarded*; any read/write/mutation of
  it outside a region holding one of its guard locks is a violation unless
  annotated ``# unguarded-ok: reason`` or allowlisted.
- **blocking-under-lock**: sleep / Thread.join / Future.result /
  Event.wait / urlopen / subprocess / apiserver client verbs reached
  (directly or through same-module calls) while a lock is held.  Waiting
  on the *sole held* Condition is exempt — ``wait()`` releases it.

Interprocedural approximation: underscore-named methods/functions inherit
the intersection of locks held at their intra-class (intra-module) call
sites as an entry context — this is what makes the ``_admit_locked``
helper idiom analyzable without annotations.  Public names get an empty
entry context (any caller may call them unlocked).

Allowlist file (one audited survivor per line, reason mandatory)::

    <check> <repo-relative-file> <qualifier> -- <reason>

``qualifier`` is ``Class.field`` for guarded-by, ``Qualname:desc`` for
blocking-under-lock, and ``lockA->lockB`` for lock-order edges.  Unused
entries fail the run (stale allowlists rot into blanket exemptions).
"""

from __future__ import annotations

import ast
import os

from k8s_tpu.analysis import astutil

# --- lock model --------------------------------------------------------------

# constructor names that make an attribute/global a lock; value is the
# lock kind ("lock" = non-reentrant, "rlock"/"cond" = reentrant).  Matched
# against the LAST component of the called dotted name, so any receiver
# spelling — `threading.Lock`, `checkedlock.make_lock`, or an aliased
# `_checkedlock.make_lock` (rest.py) — resolves the same
LOCK_CTORS = {
    "Lock": "lock", "RLock": "rlock", "Condition": "cond",
    "make_lock": "lock", "make_rlock": "rlock", "make_condition": "cond",
}

# clientset resource accessors: `.pods(ns).create(...)` is an apiserver call
_CLIENT_ACCESSORS = {"pods", "services", "events", "endpoints", "configmaps",
                     "namespaces", "pdbs", "crds", "tfjobs",
                     "tfjobs_unstructured"}
_CLIENT_VERBS = {"create", "get", "list", "update", "patch", "delete",
                 "delete_collection", "watch"}

# pod/service-control fan-out methods (controller_v2/control.py surface)
_CONTROL_PREFIXES = ("create_pod", "delete_pod", "patch_pod",
                     "create_service", "delete_service", "patch_service")

# fully-dotted callables that block
_BLOCKING_DOTTED = {
    "time.sleep": "time.sleep",
    "sleep": "time.sleep",
    "urllib.request.urlopen": "urllib.request.urlopen",
    "urlopen": "urllib.request.urlopen",
    "socket.create_connection": "socket.create_connection",
    "select.select": "select.select",
    "subprocess.run": "subprocess.run",
    "subprocess.call": "subprocess.call",
    "subprocess.check_call": "subprocess.check_call",
    "subprocess.check_output": "subprocess.check_output",
}

# attribute method calls that mutate their receiver in place (used to
# classify `self._counts.pop(...)` as a write to `_counts`)
_MUTATORS = {"append", "appendleft", "add", "pop", "popitem", "popleft",
             "clear", "update", "extend", "remove", "discard", "insert",
             "setdefault", "move_to_end", "sort", "reverse", "rotate"}


class Finding:
    def __init__(self, code: str, path: str, lineno: int, message: str,
                 qualifier: str = ""):
        self.code = code
        self.path = path
        self.lineno = lineno
        self.message = message
        self.qualifier = qualifier  # the allowlist matching key

    def __str__(self):
        return f"{self.path}:{self.lineno}: {self.code}: {self.message}"

    def as_dict(self) -> dict:
        return {"code": self.code, "path": self.path, "lineno": self.lineno,
                "qualifier": self.qualifier, "message": self.message}


class AllowlistError(ValueError):
    pass


def load_allowlist(path: str) -> list[dict]:
    """Parse the allowlist; every entry must carry a ``-- reason``."""
    entries = []
    with open(path, encoding="utf-8") as f:
        for i, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            head, sep, reason = line.partition("--")
            reason = reason.strip()
            if not sep or not reason:
                raise AllowlistError(
                    f"{path}:{i}: allowlist entry without a '-- reason' "
                    f"justification: {line!r}")
            # split(None, 2): the qualifier is everything after the file
            # and may itself contain spaces (blocking-under-lock emits
            # e.g. 'C.sync:apiserver .pods().create'); strip the
            # whitespace maxsplit leaves before the '--'
            parts = [p.strip() for p in head.split(None, 2)]
            if len(parts) != 3:
                raise AllowlistError(
                    f"{path}:{i}: expected '<check> <file> <qualifier> -- "
                    f"<reason>', got {line!r}")
            entries.append({"check": parts[0], "file": parts[1],
                            "qualifier": parts[2], "reason": reason,
                            "line": i, "used": False})
    return entries


# --- per-function extraction -------------------------------------------------


class _FnSummary:
    """Everything one function contributes to the module-level analysis."""

    def __init__(self, qualname: str, name: str, cls: str | None):
        self.qualname = qualname
        self.name = name
        self.cls = cls
        # (lock_id, held_tuple, lineno) for each `with <lock>:` entry
        self.acquires: list[tuple[str, tuple, int]] = []
        # (attr, "read"|"write", held_tuple, lineno)
        self.accesses: list[tuple[str, str, tuple, int]] = []
        # (kind "method"|"func", target, held_tuple, lineno)
        self.calls: list[tuple[str, str, tuple, int]] = []
        # (desc, held_tuple, lineno, receiver_lock_or_None)
        self.blocking: list[tuple[str, tuple, int, str | None]] = []
        self.entry_held: frozenset = frozenset()


class _FnVisitor(ast.NodeVisitor):
    """Walks one function body tracking the stack of held known locks.

    Nested function/class/lambda bodies are skipped — they run in a
    different context, and are summarized separately with an empty entry
    context."""

    def __init__(self, summary: _FnSummary, class_locks: dict[str, str],
                 module_locks: dict[str, str], class_methods: set[str],
                 module_funcs: set[str], lock_prefix: str):
        self.s = summary
        self.class_locks = class_locks      # attr -> kind
        self.module_locks = module_locks    # global name -> kind
        self.class_methods = class_methods
        self.module_funcs = module_funcs
        self.lock_prefix = lock_prefix      # "Class." or "" for lock ids
        self.held: list[str] = []

    # -- lock resolution

    def _resolve_lock(self, node: ast.AST) -> str | None:
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id in ("self", "cls")
                and node.attr in self.class_locks):
            return self.lock_prefix + node.attr
        if isinstance(node, ast.Name) and node.id in self.module_locks:
            return node.id
        return None

    # -- traversal

    def visit_FunctionDef(self, node):  # nested scope: separate summary
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass

    def visit_With(self, node):
        pushed = 0
        for item in node.items:
            self.visit(item.context_expr)
            lock = self._resolve_lock(item.context_expr)
            if lock is not None:
                self.s.acquires.append((lock, tuple(self.held),
                                        item.context_expr.lineno))
                self.held.append(lock)
                pushed += 1
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(pushed):
            self.held.pop()

    visit_AsyncWith = visit_With

    # -- field accesses

    def _record_self_attr(self, attr: str, kind: str, lineno: int):
        self.s.accesses.append((attr, kind, tuple(self.held), lineno))

    def _write_target(self, target: ast.AST):
        """Record assignment/deletion targets rooted at self.X as writes
        (``self.X = ...``, ``self.X[k] = ...``, ``del self.X[k]``); the
        target is still visited afterwards so subscript indexes and the
        inner ``self.X`` load are traversed normally."""
        node = target
        while isinstance(node, ast.Subscript):
            node = node.value
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            self._record_self_attr(node.attr, "write", target.lineno)

    def visit_Assign(self, node):
        for t in node.targets:
            self._write_target(t)
            self.visit(t)
        self.visit(node.value)

    def visit_AugAssign(self, node):
        self._write_target(node.target)
        self.visit(node.value)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._write_target(node.target)
            self.visit(node.value)

    def visit_Delete(self, node):
        for t in node.targets:
            self._write_target(t)
            self.visit(t)

    def visit_Attribute(self, node):
        if (isinstance(node.value, ast.Name) and node.value.id == "self"
                and isinstance(node.ctx, ast.Load)):
            self._record_self_attr(node.attr, "read", node.lineno)
        self.generic_visit(node)

    # -- calls

    def visit_Call(self, node):
        func = node.func
        handled = False
        if isinstance(func, ast.Attribute):
            recv = func.value
            # self.X.mutator(...): a write to field X
            if (isinstance(recv, ast.Attribute)
                    and isinstance(recv.value, ast.Name)
                    and recv.value.id == "self"
                    and func.attr in _MUTATORS):
                self._record_self_attr(recv.attr, "write", node.lineno)
            # self.method(...): intra-class call
            if (isinstance(recv, ast.Name) and recv.id == "self"
                    and func.attr in self.class_methods):
                self.s.calls.append(("method", func.attr, tuple(self.held),
                                     node.lineno))
                handled = True
        elif isinstance(func, ast.Name) and func.id in self.module_funcs:
            self.s.calls.append(("func", func.id, tuple(self.held),
                                 node.lineno))
            handled = True
        if not handled:
            desc, recv_lock = self._blocking_desc(node)
            if desc is not None:
                self.s.blocking.append((desc, tuple(self.held), node.lineno,
                                        recv_lock))
        for child in ast.iter_child_nodes(node):
            self.visit(child)

    def _blocking_desc(self, node: ast.Call):
        """(description, receiver_lock_or_None) when the call blocks."""
        func = node.func
        dotted = astutil.dotted_name(func)
        if dotted is not None and dotted in _BLOCKING_DOTTED:
            return _BLOCKING_DOTTED[dotted], None
        if not isinstance(func, ast.Attribute):
            return None, None
        attr = func.attr
        recv = func.value
        if attr in ("wait", "wait_for"):
            lock = self._resolve_lock(recv)
            return f"{astutil.dotted_name(recv) or '<expr>'}.{attr}", lock
        if attr == "result" and len(node.args) <= 1:
            return "Future.result", None
        if attr == "join" and not isinstance(recv, ast.Constant):
            # str.join always takes a positional iterable; Thread.join
            # takes nothing or a timeout keyword
            kw = {k.arg for k in node.keywords}
            if not node.args and kw <= {"timeout"}:
                return "Thread.join", None
        if attr in _CLIENT_VERBS and isinstance(recv, ast.Call) and \
                isinstance(recv.func, ast.Attribute) and \
                recv.func.attr in _CLIENT_ACCESSORS:
            return f"apiserver .{recv.func.attr}().{attr}", None
        if any(attr.startswith(p) for p in _CONTROL_PREFIXES):
            return f"podcontrol.{attr}", None
        return None, None


# --- per-module analysis -----------------------------------------------------


def _lock_ctor_kind(value: ast.AST) -> str | None:
    for n in ast.walk(value):
        if isinstance(n, ast.Call):
            name = astutil.dotted_name(n.func)
            if name and name.rsplit(".", 1)[-1] in LOCK_CTORS:
                return LOCK_CTORS[name.rsplit(".", 1)[-1]]
    return None


class _Module:
    def __init__(self, path: str, relpath: str, source: str,
                 tree: ast.Module):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.tree = tree
        self.guard_notes = astutil.line_comments(source, "guarded-by")
        self.unguarded_ok = astutil.line_comments(source, "unguarded-ok")
        self.lock_ok = astutil.line_comments(source, "lock-ok")
        self.source_lines = source.count("\n") + 1
        self.module_locks: dict[str, str] = {}
        self.module_funcs: dict[str, ast.AST] = {}
        self.classes: dict[str, dict] = {}
        self.summaries: dict[str, _FnSummary] = {}
        self._collect()
        self._summarize()
        self._entry_contexts()

    def note(self, notes: dict[int, str], line: int) -> str | None:
        """An annotation suppresses findings on its own line or (comments
        usually precede the statement) up to two lines below it."""
        for ln in (line, line - 1, line - 2):
            if ln in notes:
                return notes[ln]
        return None

    # -- collection

    def _collect(self):
        # own_scope_nodes, not tree.body: module-level locks/functions may
        # sit inside top-level if/try/with blocks (rest.py creates
        # _wire_profile_lock under `if WIRE_PROFILE_ENABLED:`) and must
        # still be visible to all three passes; class and function bodies
        # stay separate scopes, collected as units below
        for node in astutil.own_scope_nodes(self.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                kind = _lock_ctor_kind(node.value)
                if kind:
                    self.module_locks[node.targets[0].id] = kind
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.module_funcs[node.name] = node
            elif isinstance(node, ast.ClassDef):
                self._collect_class(node)

    def _collect_class(self, cls: ast.ClassDef):
        locks: dict[str, str] = {}
        annotations: dict[str, str] = {}  # field -> guard lock attr
        methods: dict[str, ast.AST] = {}
        for node in cls.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                methods[node.name] = node
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign):
                        for t in sub.targets:
                            if (isinstance(t, ast.Attribute)
                                    and isinstance(t.value, ast.Name)
                                    and t.value.id == "self"):
                                kind = _lock_ctor_kind(sub.value)
                                if kind:
                                    locks.setdefault(t.attr, kind)
                                note = self.guard_notes.get(sub.lineno)
                                if note:
                                    annotations[t.attr] = (
                                        note[5:] if note.startswith("self.")
                                        else note)
        self.classes[cls.name] = {"node": cls, "locks": locks,
                                  "methods": methods,
                                  "annotations": annotations}

    # -- summaries

    def _summarize(self):
        for name, node in self.module_funcs.items():
            s = _FnSummary(name, name, None)
            v = _FnVisitor(s, {}, self.module_locks, set(),
                           set(self.module_funcs), "")
            for stmt in node.body:
                v.visit(stmt)
            self.summaries[name] = s
        for cname, info in self.classes.items():
            for mname, node in info["methods"].items():
                qual = f"{cname}.{mname}"
                s = _FnSummary(qual, mname, cname)
                v = _FnVisitor(s, info["locks"], self.module_locks,
                               set(info["methods"]),
                               set(self.module_funcs), f"{cname}.")
                for stmt in node.body:
                    v.visit(stmt)
                self.summaries[qual] = s

    def _resolve_callee(self, caller: _FnSummary, kind: str,
                        target: str) -> str | None:
        if kind == "method" and caller.cls is not None:
            qual = f"{caller.cls}.{target}"
            return qual if qual in self.summaries else None
        if kind == "func":
            return target if target in self.summaries else None
        return None

    def _entry_contexts(self):
        """Private helpers inherit the intersection of locks held at their
        intra-module call sites.  Fixpoint, capped."""
        sites: dict[str, list[tuple[str, tuple]]] = {}
        for qual, s in self.summaries.items():
            for kind, target, held, _lineno in s.calls:
                callee = self._resolve_callee(s, kind, target)
                if callee is not None:
                    sites.setdefault(callee, []).append((qual, held))
        for _ in range(10):
            changed = False
            for qual, s in self.summaries.items():
                if not s.name.startswith("_") or s.name.startswith("__"):
                    continue  # public or dunder: callable from anywhere
                call_sites = sites.get(qual)
                if not call_sites:
                    continue
                ctxs = [frozenset(held) | self.summaries[caller].entry_held
                        for caller, held in call_sites]
                new = frozenset.intersection(*ctxs) if ctxs else frozenset()
                if new != s.entry_held:
                    s.entry_held = new
                    changed = True
            if not changed:
                break


# --- report ------------------------------------------------------------------


class Report:
    def __init__(self):
        self.findings: list[Finding] = []
        self.suppressed: list[dict] = []
        self.edges: dict[tuple[str, str], dict] = {}
        self.lock_count = 0
        self.module_count = 0
        self.allowlist_unused: list[dict] = []

    @property
    def ok(self) -> bool:
        return not self.findings

    def as_dict(self) -> dict:
        return {
            "ok": self.ok,
            "modules": self.module_count,
            "locks": self.lock_count,
            "edges": [
                {"from": a, "to": b, "path": w["path"],
                 "line": w["line"], "via": w["via"]}
                for (a, b), w in sorted(self.edges.items())],
            "findings": [f.as_dict() for f in self.findings],
            "suppressed": self.suppressed,
            "allowlist_unused": self.allowlist_unused,
        }


def _module_lock_id(relpath: str, lock: str) -> str:
    return f"{relpath}::{lock}"


def _analyze_module(mod: _Module, report: Report):
    rel = mod.relpath
    summaries = mod.summaries

    # transitive lock-acquisition sets per function, with one witness chain
    acq: dict[str, dict[str, list]] = {
        q: {lock: [(rel, line, q)]
            for lock, _held, line in s.acquires}
        for q, s in summaries.items()}
    # transitive blocking descriptors, with witness chain + receiver lock
    blk: dict[str, dict[str, tuple[list, str | None]]] = {}
    for q, s in summaries.items():
        blk[q] = {}
        for desc, _held, line, recv_lock in s.blocking:
            blk[q].setdefault(desc, ([(rel, line, q)], recv_lock))
    for _ in range(10):
        changed = False
        for q, s in summaries.items():
            for kind, target, _held, line in s.calls:
                callee = mod._resolve_callee(s, kind, target)
                if callee is None:
                    continue
                for lock, chain in acq.get(callee, {}).items():
                    if lock not in acq[q]:
                        acq[q][lock] = [(rel, line, q)] + chain
                        changed = True
                for desc, (chain, recv_lock) in blk.get(callee, {}).items():
                    if desc not in blk[q]:
                        blk[q][desc] = ([(rel, line, q)] + chain, recv_lock)
                        changed = True
        if not changed:
            break

    def lock_key(lock: str) -> str:
        return _module_lock_id(rel, lock)

    def add_edge(a: str, b: str, witness: dict):
        key = (lock_key(a), lock_key(b))
        report.edges.setdefault(key, witness)

    kinds = dict(mod.module_locks)
    for cname, info in mod.classes.items():
        for attr, kind in info["locks"].items():
            kinds[f"{cname}.{attr}"] = kind
    report.lock_count += len(kinds)

    # -- lock-order edges
    for q, s in summaries.items():
        eff_entry = s.entry_held
        for lock, held, line in s.acquires:
            for h in frozenset(held) | eff_entry:
                if h == lock:
                    if kinds.get(lock) == "lock":
                        report.findings.append(Finding(
                            "lock-order-cycle", rel, line,
                            f"nested re-acquisition of non-reentrant lock "
                            f"{lock} in {q} (self-deadlock)",
                            qualifier=f"{lock}->{lock}"))
                    continue
                add_edge(h, lock, {"path": rel, "line": line,
                                   "via": q})
        for kind, target, held, line in s.calls:
            callee = mod._resolve_callee(s, kind, target)
            if callee is None:
                continue
            eff = frozenset(held) | eff_entry
            for lock, chain in acq.get(callee, {}).items():
                for h in eff:
                    if h == lock:
                        continue
                    via = " -> ".join(hop[2] for hop in
                                      [(rel, line, q)] + chain)
                    add_edge(h, lock, {"path": rel, "line": line,
                                       "via": via})

    # -- blocking-under-lock
    for q, s in summaries.items():
        eff_entry = s.entry_held

        def _flag(desc, eff_held, line, recv_lock, via=None):
            hazard = set(eff_held)
            if recv_lock is not None:
                hazard.discard(recv_lock)  # cond.wait releases its own lock
            if not hazard:
                return
            note = mod.note(mod.lock_ok, line)
            if note:
                report.suppressed.append({
                    "code": "blocking-under-lock", "path": rel,
                    "lineno": line, "reason": note,
                    "qualifier": f"{q}:{desc}"})
                return
            held_s = ", ".join(sorted(hazard))
            msg = f"blocking call {desc} while holding {held_s}"
            if via:
                msg += f" (via {via})"
            report.findings.append(Finding(
                "blocking-under-lock", rel, line, msg,
                qualifier=f"{q}:{desc}"))

        for desc, held, line, recv_lock in s.blocking:
            _flag(desc, frozenset(held) | eff_entry, line, recv_lock)
        for kind, target, held, line in s.calls:
            callee = mod._resolve_callee(s, kind, target)
            if callee is None:
                continue
            eff = frozenset(held) | eff_entry
            if not eff:
                continue
            for desc, (chain, recv_lock) in blk.get(callee, {}).items():
                via = " -> ".join(hop[2] for hop in chain)
                _flag(desc, eff, line, recv_lock, via=via)

    # -- guarded-by
    for cname, info in mod.classes.items():
        class_lock_ids = {f"{cname}.{a}" for a in info["locks"]}
        if not class_lock_ids:
            continue
        guards: dict[str, set[str]] = {}   # field -> guard lock ids
        for attr, lockname in info["annotations"].items():
            guards.setdefault(attr, set()).add(f"{cname}.{lockname}")
        accesses: list[tuple[str, str, str, frozenset, int]] = []
        for mname in info["methods"]:
            s = summaries[f"{cname}.{mname}"]
            for attr, kind, held, line in s.accesses:
                if attr in info["locks"]:
                    continue
                eff = frozenset(held) | s.entry_held
                accesses.append((mname, attr, kind, eff, line))
                if kind == "write" and mname not in ("__init__",
                                                     "__post_init__"):
                    under = eff & class_lock_ids
                    if under:
                        guards.setdefault(attr, set()).update(under)
        for mname, attr, kind, eff, line in accesses:
            if attr not in guards:
                continue
            if mname in ("__init__", "__post_init__"):
                continue
            if eff & guards[attr]:
                continue
            note = mod.note(mod.unguarded_ok, line)
            if note:
                report.suppressed.append({
                    "code": "guarded-by", "path": rel, "lineno": line,
                    "reason": note,
                    "qualifier": f"{cname}.{attr}"})
                continue
            guard_s = ", ".join(sorted(guards[attr]))
            report.findings.append(Finding(
                "guarded-by", rel, line,
                f"{kind} of {cname}.{attr} in {mname}() outside its guard "
                f"lock ({guard_s})",
                qualifier=f"{cname}.{attr}"))


def _detect_cycles(report: Report):
    """DFS over the global edge set; every cycle found becomes a finding
    carrying the witness site of each edge on it."""
    graph: dict[str, list[str]] = {}
    for (a, b) in report.edges:
        graph.setdefault(a, []).append(b)
        graph.setdefault(b, [])
    color: dict[str, int] = {}
    stack: list[str] = []
    cycles: list[list[str]] = []
    seen_cycles: set[frozenset] = set()

    def dfs(node: str):
        color[node] = 1
        stack.append(node)
        for nxt in graph[node]:
            if color.get(nxt, 0) == 0:
                dfs(nxt)
            elif color.get(nxt) == 1:
                cyc = stack[stack.index(nxt):] + [nxt]
                key = frozenset(cyc)
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    cycles.append(cyc)
        stack.pop()
        color[node] = 2

    for node in sorted(graph):
        if color.get(node, 0) == 0:
            dfs(node)

    for cyc in cycles:
        edges = list(zip(cyc, cyc[1:]))
        witness_lines = []
        for a, b in edges:
            w = report.edges[(a, b)]
            witness_lines.append(
                f"{a} -> {b} at {w['path']}:{w['line']} (via {w['via']})")
        first = report.edges[edges[0]]
        a_short = cyc[0].split("::")[-1]
        report.findings.append(Finding(
            "lock-order-cycle", first["path"], first["line"],
            "potential deadlock: acquisition-order cycle "
            + " -> ".join(c.split("::")[-1] for c in cyc)
            + "; witnesses: " + "; ".join(witness_lines),
            qualifier=f"{a_short}->{cyc[1].split('::')[-1]}"))


def _apply_allowlist(report: Report, entries: list[dict]):
    kept = []
    for f in report.findings:
        hit = None
        for e in entries:
            if (e["check"] == f.code and e["file"] == f.path
                    and e["qualifier"] == f.qualifier):
                hit = e
                break
        if hit is not None:
            hit["used"] = True
            report.suppressed.append({
                "code": f.code, "path": f.path, "lineno": f.lineno,
                "qualifier": f.qualifier, "reason": hit["reason"]})
        else:
            kept.append(f)
    report.findings = kept
    for e in entries:
        if not e["used"]:
            report.allowlist_unused.append(e)
            report.findings.append(Finding(
                "stale-allowlist", e["file"], e["line"],
                f"allowlist entry never matched a finding: {e['check']} "
                f"{e['file']} {e['qualifier']} — delete it or fix the "
                f"qualifier", qualifier=e["qualifier"]))


def analyze_tree(root: str, allowlist_path: str | None = None,
                 rel_base: str | None = None) -> Report:
    """Run all three passes over every module under ``root``.

    ``rel_base`` anchors the repo-relative paths findings/allowlists use
    (defaults to ``root``'s parent so paths read ``k8s_tpu/...``)."""
    entries = load_allowlist(allowlist_path) if allowlist_path else []
    base = rel_base or os.path.dirname(os.path.abspath(root))
    report = Report()
    for path in astutil.iter_py_files(root):
        rel = os.path.relpath(os.path.abspath(path), base).replace(
            os.sep, "/")
        try:
            with open(path, "rb") as f:
                source = f.read().decode("utf-8", "replace")
            tree = ast.parse(source, path)
        except SyntaxError:
            continue  # the lint syntax layer owns this failure
        mod = _Module(path, rel, source, tree)
        report.module_count += 1
        _analyze_module(mod, report)
    _detect_cycles(report)
    _apply_allowlist(report, entries)
    report.findings.sort(key=lambda f: (f.path, f.lineno, f.code))
    return report


def analyze_source(source: str, relpath: str = "mod.py") -> Report:
    """Single-module entry point for tests/fixtures."""
    report = Report()
    tree = ast.parse(source, relpath)
    mod = _Module(relpath, relpath, source, tree)
    report.module_count = 1
    _analyze_module(mod, report)
    _detect_cycles(report)
    report.findings.sort(key=lambda f: (f.path, f.lineno, f.code))
    return report

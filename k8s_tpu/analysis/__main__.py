"""CLI: run the static analyzers over a tree.

    python -m k8s_tpu.analysis [--check concurrency|compile-surface|all]
                               [--root k8s_tpu] [--allowlist ...]
                               [--compile-allowlist ...] [--json out]

Exit 0 when clean (after allowlists), 1 when findings remain.  The lint
CI tier invokes the same passes through :mod:`k8s_tpu.harness.py_checks`.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_ALLOWLIST = os.path.join(
    REPO_ROOT, "k8s_tpu", "analysis", "allowlist.txt")
DEFAULT_COMPILE_ALLOWLIST = os.path.join(
    REPO_ROOT, "k8s_tpu", "analysis", "compile_allowlist.txt")


def _resolve(path: str | None) -> str | None:
    if path in (None, "none"):
        return None
    return path if os.path.exists(path) else None


def _dump(report_dict: dict, path: str | None) -> None:
    if not path:
        return
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(report_dict, f, indent=1, sort_keys=True)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--check",
                   choices=["concurrency", "compile-surface", "all"],
                   default="all")
    p.add_argument("--root", default=os.path.join(REPO_ROOT, "k8s_tpu"))
    p.add_argument("--allowlist", default=DEFAULT_ALLOWLIST,
                   help="concurrency audited-exemption file; "
                   "'none' disables")
    p.add_argument("--compile-allowlist",
                   default=DEFAULT_COMPILE_ALLOWLIST,
                   help="compile-surface audited-exemption file; "
                   "'none' disables")
    p.add_argument("--json", default=None,
                   help="write the full report JSON here (one combined "
                   "object keyed by check)")
    args = p.parse_args(argv)

    ok = True
    combined: dict[str, dict] = {}
    if args.check in ("concurrency", "all"):
        from k8s_tpu.analysis import static

        report = static.analyze_tree(
            args.root, allowlist_path=_resolve(args.allowlist))
        combined["concurrency"] = report.as_dict()
        for f in report.findings:
            print(str(f), file=sys.stderr)
        print(f"[analysis] {report.module_count} modules, "
              f"{report.lock_count} locks, {len(report.edges)} order "
              f"edges, {len(report.findings)} findings, "
              f"{len(report.suppressed)} suppressed")
        ok = report.ok and ok
    if args.check in ("compile-surface", "all"):
        from k8s_tpu.analysis import compilesurface

        report = compilesurface.analyze_tree(
            args.root, allowlist_path=_resolve(args.compile_allowlist))
        combined["compile_surface"] = report.as_dict()
        for f in report.findings:
            print(str(f), file=sys.stderr)
        print(f"[compile-surface] {report.module_count} modules, "
              f"{len(report.jit_sites)} jit sites, "
              f"{len(report.wrappers)} wrappers, "
              f"{len(report.hot_functions)} hot functions, "
              f"{len(report.findings)} findings, "
              f"{len(report.suppressed)} suppressed")
        ok = report.ok and ok
    _dump(combined, args.json)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())

"""CLI: run the static concurrency analyzer over a tree.

    python -m k8s_tpu.analysis [--root k8s_tpu] [--allowlist ...] [--json out]

Exit 0 when clean (after allowlist), 1 when findings remain.  The lint CI
tier invokes the same entry through :mod:`k8s_tpu.harness.py_checks`.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from k8s_tpu.analysis import static

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_ALLOWLIST = os.path.join(
    REPO_ROOT, "k8s_tpu", "analysis", "allowlist.txt")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--root", default=os.path.join(REPO_ROOT, "k8s_tpu"))
    p.add_argument("--allowlist", default=DEFAULT_ALLOWLIST,
                   help="audited-exemption file; 'none' disables")
    p.add_argument("--json", default=None,
                   help="write the full report JSON here")
    args = p.parse_args(argv)
    allowlist = None if args.allowlist == "none" else (
        args.allowlist if os.path.exists(args.allowlist) else None)
    report = static.analyze_tree(args.root, allowlist_path=allowlist)
    if args.json:
        os.makedirs(os.path.dirname(os.path.abspath(args.json)),
                    exist_ok=True)
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(report.as_dict(), f, indent=1, sort_keys=True)
    for f in report.findings:
        print(str(f), file=sys.stderr)
    print(f"[analysis] {report.module_count} modules, {report.lock_count} "
          f"locks, {len(report.edges)} order edges, "
          f"{len(report.findings)} findings, "
          f"{len(report.suppressed)} suppressed")
    return 0 if report.ok else 1


if __name__ == "__main__":
    raise SystemExit(main())

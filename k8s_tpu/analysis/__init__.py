"""Concurrency auditor for the control plane (ISSUE 10).

Two halves, both stdlib-only:

- :mod:`k8s_tpu.analysis.static` — an AST pass over the whole ``k8s_tpu``
  tree that builds an interprocedural lock acquisition-order graph per
  module (failing on cycles with witness paths), enforces guarded-by
  discipline on fields written under a lock, and flags blocking calls
  (sleep/join/Future.result/apiserver client verbs/...) made while a lock
  is held.  Wired into the gating ``lint`` tier by
  :mod:`k8s_tpu.harness.py_checks`.
- :mod:`k8s_tpu.analysis.checkedlock` — a drop-in
  Lock/RLock/Condition factory that, under ``K8S_TPU_LOCK_CHECK=1``,
  records the process-global acquisition DAG live, raises on cycle
  formation with both threads' stacks, runs a held-too-long watchdog,
  and emits a ``lock_audit.json`` artifact.  Zero overhead when off
  (the factories return raw ``threading`` primitives).

See docs/static_analysis.md for annotation and allowlist syntax.

No eager submodule imports here: ~25 hot-path modules import
``checkedlock`` at startup, and they must not drag the whole static
analyzer (CI-only machinery) into every operator/bench process —
consumers import ``k8s_tpu.analysis.static`` / ``.checkedlock``
directly.
"""

"""Concurrency + compile-surface auditors for the tree (ISSUEs 10, 11).

Four halves, all stdlib-only:

- :mod:`k8s_tpu.analysis.static` — an AST pass over the whole ``k8s_tpu``
  tree that builds an interprocedural lock acquisition-order graph per
  module (failing on cycles with witness paths), enforces guarded-by
  discipline on fields written under a lock, and flags blocking calls
  (sleep/join/Future.result/apiserver client verbs/...) made while a lock
  is held.  Wired into the gating ``lint`` tier by
  :mod:`k8s_tpu.harness.py_checks`.
- :mod:`k8s_tpu.analysis.checkedlock` — a drop-in
  Lock/RLock/Condition factory that, under ``K8S_TPU_LOCK_CHECK=1``,
  records the process-global acquisition DAG live, raises on cycle
  formation with both threads' stacks, runs a held-too-long watchdog,
  and emits a ``lock_audit.json`` artifact.  Zero overhead when off
  (the factories return raw ``threading`` primitives).
- :mod:`k8s_tpu.analysis.compilesurface` — the static compile-surface
  pass (ISSUE 11): per-request ``jax.jit`` constructions without a
  memoizing program-table idiom, Python branches on traced arguments
  lacking a covering ``static_argnums`` entry, host-device sync points
  reached from the engine's step loop or under a lock, and swallowing
  broad exception handlers.  Same lint tier, same reason-mandatory
  stale-entries-fail allowlist contract (``compile_allowlist.txt``).
- :mod:`k8s_tpu.analysis.compileledger` — the runtime XLA compile
  ledger (``K8S_TPU_COMPILE_LEDGER=1``, ``set_active``/``active()``
  registry): every compile recorded with fingerprint + wall time +
  stack via a ``jax.monitoring`` listener (the consumer passes the
  module in, so this package never imports jax) or the wrapped jit's
  cache-size delta; seams declare compile budgets and a recompile past
  budget raises ``CompileBudgetExceeded``.  ``/debug/compiles`` on the
  metrics server, dashboard, and serving pod; ``compile_audit.json``
  from the bench tier.

See docs/static_analysis.md for annotation and allowlist syntax.

No eager submodule imports here: ~25 hot-path modules import
``checkedlock`` at startup, and they must not drag the whole static
analyzer (CI-only machinery) into every operator/bench process —
consumers import ``k8s_tpu.analysis.static`` / ``.checkedlock`` /
``.compilesurface`` / ``.compileledger`` directly.
"""

"""Utility layer (reference: pkg/util/)."""

"""General helpers (reference: pkg/util/util.go).

- ``pformat`` — pretty-print any object as indented JSON for log lines
  (pkg/util/util.go:33-48).
- ``rand_string`` — DNS-safe random lowercase string used as a job RuntimeId
  (pkg/util/util.go:59-66).
- ``get_namespace`` — operator namespace from env (pkg/util/util.go:27-31).
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import string

# Env var naming kept from the reference (pkg/util/util.go:29,
# pkg/apis/tensorflow/v1alpha2/constants.go:19) so existing deployment
# manifests keep working.
ENV_KUBEFLOW_NAMESPACE = "KUBEFLOW_NAMESPACE"

_DNS_SAFE = string.ascii_lowercase  # no digits first-char hazards, DNS-1035 safe


def _jsonable(obj):
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return dataclasses.asdict(obj)
    if hasattr(obj, "to_dict"):
        return obj.to_dict()
    return str(obj)


def pformat(obj) -> str:
    """Pretty-format an object as indented JSON (pkg/util/util.go:33-48)."""
    try:
        return json.dumps(obj, indent=2, sort_keys=True, default=_jsonable)
    except (TypeError, ValueError):
        return repr(obj)


def quantile_nearest(sorted_vals, q: float) -> float:
    """Nearest-rank quantile of an ascending-sorted sequence (0.0 for
    empty input) — THE one implementation the bench harnesses and the
    request recorder share, so their percentiles cannot silently
    diverge."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[int(idx)]


def rand_string(n: int, rng: random.Random | None = None) -> str:
    """Random lowercase ascii string of length ``n`` (pkg/util/util.go:59-66).

    Used for job RuntimeIds that end up in pod/service DNS names, hence
    restricted to DNS-safe lowercase letters.
    """
    r = rng or random
    return "".join(r.choice(_DNS_SAFE) for _ in range(n))


def get_namespace(default: str = "default") -> str:
    """Operator namespace from KUBEFLOW_NAMESPACE env (pkg/util/util.go:27-31)."""
    return os.environ.get(ENV_KUBEFLOW_NAMESPACE) or default

"""/debug index: one responder listing the live debug endpoints with
their active/inactive state, shared by the metrics server and the
dashboard backend (replacing the guess-the-URL experience — every
``/debug/*`` route 404s with an explanatory body when its subsystem is
off, but nothing *listed* them).

Always 200: the index itself has no inactive state.  Each entry carries
``active`` (would the endpoint serve data right now), ``activation``
(what turns it on), and the supported query params.
"""

from __future__ import annotations

import json


def _traces_active() -> bool:
    from k8s_tpu import trace

    return bool(trace.enabled())


def _scheduler_active() -> bool:
    from k8s_tpu import scheduler as scheduler_mod

    return scheduler_mod.active() is not None


def _timeline_active() -> bool:
    from k8s_tpu import flight

    return bool(flight.TIMELINE.active)


def _fleet_active() -> bool:
    from k8s_tpu import fleet

    plane = fleet.active()
    return plane is not None and plane.active


def _compiles_active() -> bool:
    from k8s_tpu.analysis import compileledger

    return compileledger.active() is not None


def _requests_active() -> bool:
    from k8s_tpu.models import requestlog

    return requestlog.active() is not None


def _router_entry() -> dict:
    from k8s_tpu import router as router_mod

    r = router_mod.active()
    return router_mod.router_index_entry(
        active=r is not None and r.active)


def debug_index_response(query: str = "") -> tuple[int, str, str]:
    """(status_code, body, content_type) for GET /debug (and /debug/)."""
    del query  # no parameters; kept for the shared responder signature
    endpoints = [
        {
            "path": "/debug/traces",
            "subsystem": "reconcile tracing (k8s_tpu.trace)",
            "active": _traces_active(),
            "activation": "K8S_TPU_TRACE_SAMPLE > 0",
            "params": ["job", "n"],
        },
        {
            "path": "/debug/scheduler",
            "subsystem": "gang admission & capacity (k8s_tpu.scheduler)",
            "active": _scheduler_active(),
            "activation": "a v2 controller registers its scheduler on "
                          "construction",
            "params": ["queue", "events"],
        },
        {
            "path": "/debug/timeline",
            "subsystem": "flight-recorder lifecycle journal "
                         "(k8s_tpu.flight)",
            "active": _timeline_active(),
            "activation": "a v2 controller activates the recorder on "
                          "construction",
            "params": ["job", "since", "n"],
        },
        {
            "path": "/debug/fleet",
            "subsystem": "fleet telemetry plane (k8s_tpu.fleet)",
            "active": _fleet_active(),
            "activation": "K8S_TPU_FLEET_SCRAPE=1 (the v2 controller "
                          "starts the scrape plane)",
            "params": ["job", "since", "n"],
        },
        {
            "path": "/debug/compiles",
            "subsystem": "XLA compile ledger "
                         "(k8s_tpu.analysis.compileledger)",
            "active": _compiles_active(),
            "activation": "K8S_TPU_COMPILE_LEDGER=1 (the engine/server "
                          "declare their compile-budget seams on "
                          "construction)",
            "params": ["seam", "n", "stacks"],
        },
        {
            "path": "/debug/requests",
            "subsystem": "request lifecycle recorder "
                         "(k8s_tpu.models.requestlog)",
            "active": _requests_active(),
            "activation": "K8S_TPU_REQUEST_LOG=1 (the serving engine "
                          "binds the recorder on construction)",
            "params": ["id", "slow", "phase", "n"],
        },
        {
            "path": "/debug/engine",
            "subsystem": "engine step ledger "
                         "(k8s_tpu.models.requestlog)",
            "active": _requests_active(),
            "activation": "K8S_TPU_REQUEST_LOG=1 (the serving engine "
                          "binds the recorder on construction)",
            "params": ["n"],
        },
        # serving front-door router (ISSUE 13): the row definition lives
        # with the responder so the router's own minimal /debug index and
        # this one cannot drift
        _router_entry(),
    ]
    body = json.dumps({"endpoints": endpoints}, indent=2)
    return 200, body + "\n", "application/json"

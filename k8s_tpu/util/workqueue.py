"""Controller workqueue with client-go semantics.

The reference controllers (pkg/controller/controller.go:122-126,
pkg/controller.v2/controller.go:165-170) rely on the behavior of
k8s.io/client-go/util/workqueue:

- **Dedup**: an item added while already queued is coalesced; an item added
  while being *processed* is re-queued only after ``done()`` is called, so one
  key is never handled by two workers concurrently (this is the concurrency
  model the reference leans on — pkg/controller/controller.go:77-95).
- **Rate limiting**: per-item exponential backoff (5 ms → 1000 s) combined
  with an overall token bucket (10 qps, burst 100); the max of the two delays
  wins (controller.go:122-126).
- **Delaying**: ``add_after`` for the periodic re-reconcile loop.

Implemented with condition variables; workers block in ``get()`` like Go's
``queue.Get()``.
"""

from __future__ import annotations

import heapq
import threading
from k8s_tpu.analysis import checkedlock
import time
from collections import deque
from typing import Any, Hashable, Optional


class ItemExponentialFailureRateLimiter:
    """Per-item exponential backoff: base*2^failures capped at max_delay.

    Mirrors workqueue.NewItemExponentialFailureRateLimiter(5ms, 1000s) as used
    at pkg/controller/controller.go:123.
    """

    def __init__(self, base_delay: float = 0.005, max_delay: float = 1000.0):
        self.base_delay = base_delay
        self.max_delay = max_delay
        self._failures: dict[Hashable, int] = {}
        self._lock = checkedlock.make_lock("workqueue.backoff")

    def when(self, item: Hashable) -> float:
        with self._lock:
            failures = self._failures.get(item, 0)
            self._failures[item] = failures + 1
        # Clamp the exponent: unbounded 2**failures overflows float conversion
        # after ~1030 requeues of a persistently failing key.
        return min(self.base_delay * (2 ** min(failures, 64)), self.max_delay)

    def forget(self, item: Hashable) -> None:
        with self._lock:
            self._failures.pop(item, None)

    def num_requeues(self, item: Hashable) -> int:
        with self._lock:
            return self._failures.get(item, 0)


class BucketRateLimiter:
    """Overall token bucket (qps, burst) — workqueue.BucketRateLimiter.

    Matches rate.NewLimiter(rate.Limit(10), 100) from controller.go:125.
    """

    def __init__(self, qps: float = 10.0, burst: int = 100):
        self.qps = qps
        self.burst = burst
        self._tokens = float(burst)
        self._last = time.monotonic()
        self._lock = checkedlock.make_lock("workqueue.bucket")

    def when(self, item: Hashable) -> float:
        with self._lock:
            now = time.monotonic()
            self._tokens = min(self.burst, self._tokens + (now - self._last) * self.qps)
            self._last = now
            self._tokens -= 1.0
            if self._tokens >= 0:
                return 0.0
            return -self._tokens / self.qps

    def forget(self, item: Hashable) -> None:
        """Deliberate no-op: a token bucket has no per-item state to reset —
        consumed tokens are gone regardless of whether the item later
        succeeded.  Composite limiters (MaxOfRateLimiter) therefore only
        reset their *backoff* member on forget; callers must not expect
        forget() to refund bucket tokens."""

    def num_requeues(self, item: Hashable) -> int:
        return 0


class MaxOfRateLimiter:
    """Worst (longest) delay of the child limiters — workqueue.MaxOfRateLimiter."""

    def __init__(self, *limiters):
        self.limiters = limiters

    def when(self, item: Hashable) -> float:
        return max(l.when(item) for l in self.limiters)

    def forget(self, item: Hashable) -> None:
        # Fans out to every child, but only the per-item backoff member
        # actually resets: BucketRateLimiter.forget is a documented no-op
        # (no per-item state), so "forgetting" a key in the default
        # composite limiter means exactly "clear its exponential backoff".
        for l in self.limiters:
            l.forget(item)

    def num_requeues(self, item: Hashable) -> int:
        return max(l.num_requeues(item) for l in self.limiters)


def default_controller_rate_limiter() -> MaxOfRateLimiter:
    """workqueue.DefaultControllerRateLimiter as configured in the reference."""
    return MaxOfRateLimiter(
        ItemExponentialFailureRateLimiter(0.005, 1000.0),
        BucketRateLimiter(10.0, 100),
    )


# Queue-wait telemetry: one histogram for every queue in the process
# (client-go's workqueue_queue_duration_seconds analogue).  Registered
# lazily so importing this module never touches the metrics registry.
_wait_histogram = None
_wait_histogram_lock = checkedlock.make_lock("workqueue.wait_histogram")

# Bench-measured queue waits span sub-ms (idle) to tens of seconds
# (rate-limited backoff), so the default request-latency buckets clip
# both ends.
_WAIT_BUCKETS = (0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0,
                 5.0, 10.0, 30.0)


def workqueue_wait_histogram():
    """The ``workqueue_wait_seconds`` histogram (enqueue→dequeue latency),
    shared by every WorkQueue in the process."""
    global _wait_histogram
    if _wait_histogram is None:
        with _wait_histogram_lock:
            if _wait_histogram is None:
                from k8s_tpu.util import metrics

                _wait_histogram = metrics.REGISTRY.histogram(
                    "workqueue_wait_seconds",
                    "Enqueue-to-dequeue wait of workqueue items (time an "
                    "item sat in the ready backlog before a worker picked "
                    "it up).",
                    buckets=_WAIT_BUCKETS,
                )
    return _wait_histogram


class WaitTracker:
    """Enqueue→dequeue wait bookkeeping, shared by the Python WorkQueue
    and the native queue wrapper so the pop_wait contract has exactly one
    implementation: ``stamp()`` when an item (is expected to) land in the
    ready backlog, ``claim()`` at dequeue (measures and stores the wait),
    ``pop()`` by the consumer turning it into telemetry, ``evict()`` at
    done() so consumers that never pop don't leak one entry per key.

    claim() deliberately does NOT observe the histogram — callers record
    the returned wait outside whatever queue lock they hold.
    """

    __slots__ = ("_lock", "_enqueued_at", "_waits")

    def __init__(self):
        self._lock = checkedlock.make_lock("workqueue.waits")
        self._enqueued_at: dict[Any, float] = {}
        self._waits: dict[Any, float] = {}

    def stamp(self, item: Hashable, at: Optional[float] = None) -> None:
        with self._lock:
            self._enqueued_at.setdefault(
                item, time.monotonic() if at is None else at)

    def claim(self, item: Hashable) -> Optional[float]:
        with self._lock:
            enqueued = self._enqueued_at.pop(item, None)
            if enqueued is None:
                return None
            wait = max(0.0, time.monotonic() - enqueued)
            self._waits[item] = wait
            return wait

    def pop(self, item: Hashable) -> Optional[float]:
        with self._lock:
            return self._waits.pop(item, None)

    def evict(self, item: Hashable) -> None:
        with self._lock:
            self._waits.pop(item, None)


class WorkQueue:
    """FIFO queue with client-go dirty/processing dedup semantics."""

    def __init__(self):
        self._cond = checkedlock.make_condition("workqueue.cond")
        self._queue: deque[Any] = deque()
        self._dirty: set[Any] = set()
        self._processing: set[Any] = set()
        self._shutting_down = False
        # enqueue→dequeue wait accounting: stamped when an item lands in
        # the READY deque (a delayed add_after item starts its clock on
        # delivery, so the deliberate delay is not counted as wait).
        self._wait_tracker = WaitTracker()

    def add(self, item: Hashable) -> None:
        with self._cond:
            if self._shutting_down or item in self._dirty:
                return
            self._dirty.add(item)
            if item not in self._processing:
                self._queue.append(item)
                self._wait_tracker.stamp(item)
                self._cond.notify()

    def get(self, timeout: Optional[float] = None):
        """Block for the next item.  Returns (item, shutdown) like Go's Get.

        A ``timeout`` (used by tests) returns (None, False) on expiry.
        """
        wait = None
        with self._cond:
            deadline = None if timeout is None else time.monotonic() + timeout
            while not self._queue and not self._shutting_down:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None, False
                self._cond.wait(remaining)
            if not self._queue:
                return None, True
            item = self._queue.popleft()
            self._processing.add(item)
            self._dirty.discard(item)
            wait = self._wait_tracker.claim(item)
        if wait is not None:
            # outside the queue mutex: the histogram has its own locks and
            # must not extend the dequeue critical section
            workqueue_wait_histogram().observe(wait)
        return item, False

    def pop_wait(self, item: Hashable) -> Optional[float]:
        """The enqueue→dequeue wait measured when ``item`` was last handed
        out by get(), consumed on read (the controller turns it into the
        sync's queue_wait span).  None when unknown — e.g. an item whose
        delivery wasn't stamped (the native queue's rate-limited re-adds)."""
        return self._wait_tracker.pop(item)

    def done(self, item: Hashable) -> None:
        with self._cond:
            self._processing.discard(item)
            # Evict any unclaimed wait: consumers that never call
            # pop_wait (the v1 controller) must not grow the tracker by
            # one entry per distinct key forever.  Consumers that do claim
            # it (v2) read it between get() and done(), so this is a no-op
            # for them.
            self._wait_tracker.evict(item)
            if item in self._dirty:
                self._queue.append(item)
                self._wait_tracker.stamp(item)
                self._cond.notify()

    def shut_down(self) -> None:
        with self._cond:
            self._shutting_down = True
            self._cond.notify_all()

    def shutting_down(self) -> bool:
        with self._cond:
            return self._shutting_down

    def __len__(self) -> int:
        with self._cond:
            return len(self._queue)

    def depth(self) -> int:
        """Ready backlog: items queued and waiting for a worker.  Excludes
        in-flight (processing) items and delayed items still on the timer
        heap — the number a ``workqueue_depth`` gauge should export, matching
        client-go's workqueue depth metric."""
        return len(self)


class DelayingQueue(WorkQueue):
    """WorkQueue + add_after, via a background timer thread."""

    def __init__(self):
        super().__init__()
        self._heap: list[tuple[float, int, Any]] = []
        self._seq = 0
        self._timer_cond = checkedlock.make_condition("workqueue.timer")
        self._timer = threading.Thread(target=self._loop, daemon=True)
        self._timer.start()

    def add_after(self, item: Hashable, delay: float) -> None:
        if delay <= 0:
            self.add(item)
            return
        with self._timer_cond:
            self._seq += 1
            heapq.heappush(self._heap, (time.monotonic() + delay, self._seq, item))
            self._timer_cond.notify()

    def _loop(self) -> None:
        while True:
            with self._timer_cond:
                if self.shutting_down():
                    return
                if not self._heap:
                    self._timer_cond.wait(0.05)
                    continue
                when, _, item = self._heap[0]
                now = time.monotonic()
                if when > now:
                    self._timer_cond.wait(min(when - now, 0.05))
                    continue
                heapq.heappop(self._heap)
            self.add(item)


class RateLimitingQueue(DelayingQueue):
    """DelayingQueue + rate limiter — workqueue.NewRateLimitingQueue."""

    def __init__(self, rate_limiter=None):
        super().__init__()
        self.rate_limiter = rate_limiter or default_controller_rate_limiter()

    def add_rate_limited(self, item: Hashable) -> None:
        self.add_after(item, self.rate_limiter.when(item))

    def forget(self, item: Hashable) -> None:
        self.rate_limiter.forget(item)

    def num_requeues(self, item: Hashable) -> int:
        return self.rate_limiter.num_requeues(item)


def new_rate_limiting_queue():
    """Factory seam: the native (C++) queue when libk8stpu_runtime builds,
    else this module's pure-Python implementation.  Selection policy lives
    in one place: k8s_tpu.native.select (env K8S_TPU_NATIVE=1/0/unset).
    Both implementations expose identical semantics (tests/test_native.py)."""
    from k8s_tpu import native

    def _native():
        from k8s_tpu.native.runtime import NativeRateLimitingQueue

        return NativeRateLimitingQueue()

    return native.select(_native, RateLimitingQueue)

"""Exit-code retryability policy (reference: pkg/util/train/train_util.go:18-53).

This is the contract between user training code and the operator's restart
logic.  The classification below mirrors the reference table and extends it
with the TPU-preemption reality: Cloud TPU preemptions surface to the workload
as SIGTERM (exit 143), which the reference already classed retryable — the
rebuild keeps that and treats it as the primary preemption signal
(SURVEY.md §5 "Failure detection").

Permanent (do not retry):
  1   general error            (train_util.go:21-24)
  2   misuse of shell builtin
  126 command not executable
  127 command not found
  128 invalid exit argument
  139 SIGSEGV

Retryable:
  130 SIGINT                   (train_util.go:32-43)
  137 SIGKILL  (often the OS OOM-killer or forced preemption)
  143 SIGTERM  (graceful preemption — the normal TPU-preemption path)
  138 reserved for user-defined retryable errors (train_util.go:45-48)

Anything else is "unknown" and treated as permanent by callers
(pkg/trainer/replicas.go:347-359 maps unknown codes to failure).
"""

from __future__ import annotations

PERMANENT_EXIT_CODES = frozenset({1, 2, 126, 127, 128, 139})
RETRYABLE_EXIT_CODES = frozenset({130, 137, 143, 138})

# v1alpha2 RestartPolicyExitCode contract (pkg/apis/tensorflow/v1alpha2/
# types.go:86-92): 1-127 permanent, 128-255 retryable.  Enforcement was a TODO
# in the reference (controller_pod.go:149); implemented here.
_EXITCODE_POLICY_RETRYABLE_MIN = 128


def is_retryable_exit_code(exit_code: int) -> bool:
    """Reference semantics (train_util.go:18-53): explicit-list classification."""
    return exit_code in RETRYABLE_EXIT_CODES


def is_permanent_exit_code(exit_code: int) -> bool:
    return exit_code in PERMANENT_EXIT_CODES


def is_retryable_under_exit_code_policy(exit_code: int) -> bool:
    """RestartPolicy=ExitCode classification (v1alpha2/types.go:86-92).

    1-127: permanent failure — do not restart.
    128-255: retryable (signal-caused or user-defined retryable).
    0 is success and not a restart candidate at all.
    """
    return exit_code >= _EXITCODE_POLICY_RETRYABLE_MIN

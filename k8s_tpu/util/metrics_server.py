"""Operator observability endpoint: /metrics (Prometheus text 0.0.4 from
util.metrics.Registry), /healthz, /debug/traces (recent span trees
from the tracing ring buffer, slowest-first; 404 with an explicit
"tracing disabled" body when K8S_TPU_TRACE_SAMPLE is 0),
/debug/scheduler (gang-admission capacity ledger + priority queue; 404
with an explicit body when no controller registered a scheduler),
/debug/timeline (flight-recorder lifecycle journal), /debug/fleet
(fleet telemetry plane rollups + SLO burn state), and /debug/ — the
index listing every debug endpoint with its active/inactive state.

The reference operator exposed no scrape endpoint at all (cmd/tf-operator*/
app/server.go wires no HTTP server); a production operator needs one, so
this is an intentional superset.  Served on ``--metrics-port`` (0 =
disabled, the default, preserving reference behavior).

/healthz gates on the registry: until the first successful scrape
(``registry.expose()`` completing without raising — attempted lazily by
the probe itself if no /metrics request came first), it answers 503.  A
registry wedged by a broken callable gauge therefore fails the liveness
probe instead of reporting a healthy process that can't be observed.
"""

from __future__ import annotations

import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from k8s_tpu.util import metrics as metrics_mod

log = logging.getLogger(__name__)


class MetricsServer:
    """Threaded HTTP server for /metrics and /healthz.

    ``health_fn`` (optional) returns True when the process is healthy —
    wire the leader elector / controller liveness there; without one,
    /healthz answers 200 while the process serves at all.
    """

    def __init__(self, port: int, registry: Optional[metrics_mod.Registry] = None,
                 host: str = "127.0.0.1",
                 health_fn: Optional[Callable[[], bool]] = None):
        # Default bind is loopback: /metrics and /healthz are
        # UNAUTHENTICATED, so exposing them is an explicit deployment
        # decision (pass host="0.0.0.0" — the operator manifests do, inside
        # the pod network, where the scrape must reach them).
        registry = registry or metrics_mod.REGISTRY
        # flips True at the first successful registry.expose(); /healthz
        # stays 503 until then (shared mutable cell: the handler class has
        # one instance per request)
        scrape_state = {"ok": False}

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # route through logging
                log.debug("metrics: " + fmt, *args)

            def _send(self, code: int, body: str, ctype: str) -> None:
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802
                path, _, query = self.path.partition("?")
                if path == "/metrics":
                    try:
                        body = registry.expose()
                    except Exception as e:  # noqa: BLE001 - broken collector
                        return self._send(500, f"scrape failed: {e}\n",
                                          "text/plain")
                    scrape_state["ok"] = True
                    return self._send(
                        200, body,
                        "text/plain; version=0.0.4; charset=utf-8")
                if path == "/healthz":
                    if not scrape_state["ok"]:
                        # no scraper came by yet: probe the registry
                        # ourselves so a healthy process isn't 503 forever
                        try:
                            registry.expose()
                            scrape_state["ok"] = True
                        except Exception:  # noqa: BLE001
                            return self._send(
                                503,
                                "no successful scrape of the metrics "
                                "registry yet\n", "text/plain")
                    try:
                        healthy = health_fn() if health_fn else True
                    except Exception:  # noqa: BLE001 - a broken probe is unhealthy
                        healthy = False
                    return self._send(200 if healthy else 503,
                                      "ok\n" if healthy else "unhealthy\n",
                                      "text/plain")
                if path == "/debug/traces":
                    from k8s_tpu import trace

                    code, body, ctype = trace.debug_traces_response(
                        trace.TRACER, query)
                    return self._send(code, body, ctype)
                if path == "/debug/scheduler":
                    # gang-admission state: capacity ledger, priority queue
                    # with effective priorities/waits, recent admit/preempt
                    # events (404 with an explicit body when no controller
                    # registered a scheduler in this process)
                    from k8s_tpu import scheduler as scheduler_mod

                    code, body, ctype = scheduler_mod.debug_response(query)
                    return self._send(code, body, ctype)
                if path == "/debug/timeline":
                    # flight-recorder lifecycle journal: ?job=<ns/name>
                    # for one job's ordered events, ?since=/?n= filters
                    # (404 with an explicit body until a controller
                    # activates the recorder — /debug/traces parity)
                    from k8s_tpu import flight

                    code, body, ctype = flight.timeline_response(query)
                    return self._send(code, body, ctype)
                if path == "/debug/fleet":
                    # fleet telemetry plane: per-job scrape rollups +
                    # SLO burn state (?job=/?since=/?n=; 404 with an
                    # explicit body until a controller starts a plane)
                    from k8s_tpu import fleet

                    code, body, ctype = fleet.debug_response(query)
                    return self._send(code, body, ctype)
                if path == "/debug/router":
                    # serving front-door router (ISSUE 13): ring state,
                    # per-backend health/in-flight, recent placements
                    # (?n=/?backends=; 404 with an explicit body until a
                    # router is active in this process — /debug/fleet
                    # parity)
                    from k8s_tpu import router as router_mod

                    code, body, ctype = router_mod.debug_response(query)
                    return self._send(code, body, ctype)
                if path == "/debug/compiles":
                    # XLA compile ledger: per-seam budgets, fingerprint
                    # counts/stacks, recent compile events (?seam=/?n=/
                    # ?stacks; 404 with an explicit body until a consumer
                    # activates the ledger — /debug/traces parity)
                    from k8s_tpu.analysis import compileledger

                    code, body, ctype = \
                        compileledger.debug_compiles_response(query)
                    return self._send(code, body, ctype)
                if path == "/debug/requests":
                    # request lifecycle recorder (ISSUE 12): per-request
                    # serving timelines with dominant-phase attribution
                    # (?id=/?slow=/?phase=/?n=; 404 with an explicit
                    # body until K8S_TPU_REQUEST_LOG activates it)
                    from k8s_tpu.models import requestlog

                    code, body, ctype = \
                        requestlog.debug_requests_response(query)
                    return self._send(code, body, ctype)
                if path == "/debug/engine":
                    # engine step ledger: per-iteration records +
                    # windowed rollups (same 404 contract)
                    from k8s_tpu.models import requestlog

                    code, body, ctype = \
                        requestlog.debug_engine_response(query)
                    return self._send(code, body, ctype)
                if path in ("/debug", "/debug/"):
                    # index of the debug endpoints with active state —
                    # the same responder the dashboard serves
                    from k8s_tpu.util.debug_index import debug_index_response

                    code, body, ctype = debug_index_response(query)
                    return self._send(code, body, ctype)
                return self._send(404, "not found\n", "text/plain")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "MetricsServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            daemon=True, name="metrics-server",
        )
        self._thread.start()
        log.info("metrics endpoint on :%d (/metrics, /healthz)", self.port)
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)


def maybe_start(port: int, **kwargs) -> Optional[MetricsServer]:
    """Start a MetricsServer when ``port`` is non-zero; 0 disables (the
    reference-parity default)."""
    if not port:
        return None
    return MetricsServer(port, **kwargs).start()

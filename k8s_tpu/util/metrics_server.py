"""Operator observability endpoint: /metrics (Prometheus text 0.0.4 from
util.metrics.Registry) and /healthz.

The reference operator exposed no scrape endpoint at all (cmd/tf-operator*/
app/server.go wires no HTTP server); a production operator needs one, so
this is an intentional superset.  Served on ``--metrics-port`` (0 =
disabled, the default, preserving reference behavior).
"""

from __future__ import annotations

import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from k8s_tpu.util import metrics as metrics_mod

log = logging.getLogger(__name__)


class MetricsServer:
    """Threaded HTTP server for /metrics and /healthz.

    ``health_fn`` (optional) returns True when the process is healthy —
    wire the leader elector / controller liveness there; without one,
    /healthz answers 200 while the process serves at all.
    """

    def __init__(self, port: int, registry: Optional[metrics_mod.Registry] = None,
                 host: str = "127.0.0.1",
                 health_fn: Optional[Callable[[], bool]] = None):
        # Default bind is loopback: /metrics and /healthz are
        # UNAUTHENTICATED, so exposing them is an explicit deployment
        # decision (pass host="0.0.0.0" — the operator manifests do, inside
        # the pod network, where the scrape must reach them).
        registry = registry or metrics_mod.REGISTRY

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # route through logging
                log.debug("metrics: " + fmt, *args)

            def _send(self, code: int, body: str, ctype: str) -> None:
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    return self._send(
                        200, registry.expose(),
                        "text/plain; version=0.0.4; charset=utf-8")
                if path == "/healthz":
                    try:
                        healthy = health_fn() if health_fn else True
                    except Exception:  # noqa: BLE001 - a broken probe is unhealthy
                        healthy = False
                    return self._send(200 if healthy else 503,
                                      "ok\n" if healthy else "unhealthy\n",
                                      "text/plain")
                return self._send(404, "not found\n", "text/plain")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "MetricsServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            daemon=True, name="metrics-server",
        )
        self._thread.start()
        log.info("metrics endpoint on :%d (/metrics, /healthz)", self.port)
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)


def maybe_start(port: int, **kwargs) -> Optional[MetricsServer]:
    """Start a MetricsServer when ``port`` is non-zero; 0 disables (the
    reference-parity default)."""
    if not port:
        return None
    return MetricsServer(port, **kwargs).start()

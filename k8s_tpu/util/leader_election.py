"""Leader election (reference: cmd/tf-operator/app/server.go:109-132, using
an Endpoints resource lock with lease 15s / renew 5s / retry 3s —
server.go:49-52).

The lock record is an annotation on an Endpoints object, exactly like
client-go's EndpointsLock: ``{holderIdentity, leaseDurationSeconds,
acquireTime, renewTime}``.  ``run_or_die`` blocks in the acquire loop, runs
``on_started_leading`` while renewing in the background, and calls
``on_stopped_leading`` if the lease is lost.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from k8s_tpu.client import errors
from k8s_tpu.client.clientset import Clientset

log = logging.getLogger(__name__)

LOCK_ANNOTATION = "control-plane.alpha.kubernetes.io/leader"

# server.go:49-52
DEFAULT_LEASE_DURATION = 15.0
DEFAULT_RENEW_DEADLINE = 5.0
DEFAULT_RETRY_PERIOD = 3.0


@dataclass
class LeaderElectionConfig:
    namespace: str
    name: str
    identity: str
    lease_duration: float = DEFAULT_LEASE_DURATION
    renew_deadline: float = DEFAULT_RENEW_DEADLINE
    retry_period: float = DEFAULT_RETRY_PERIOD


class LeaderElector:
    def __init__(self, clientset: Clientset, config: LeaderElectionConfig):
        self.clientset = clientset
        self.config = config
        self._stop = threading.Event()

    def _read_record(self) -> tuple[Optional[dict], Optional[dict]]:
        try:
            obj = self.clientset.endpoints(self.config.namespace).get(self.config.name)
        except errors.ApiError as e:
            if errors.is_not_found(e):
                return None, None
            raise
        raw = (obj.get("metadata", {}).get("annotations") or {}).get(LOCK_ANNOTATION)
        return obj, json.loads(raw) if raw else None

    def _write_record(self, obj: Optional[dict], record: dict) -> bool:
        ann = {LOCK_ANNOTATION: json.dumps(record, sort_keys=True)}
        try:
            if obj is None:
                self.clientset.endpoints(self.config.namespace).create(
                    {
                        "metadata": {
                            "name": self.config.name,
                            "namespace": self.config.namespace,
                            "annotations": ann,
                        }
                    }
                )
            else:
                obj.setdefault("metadata", {}).setdefault("annotations", {}).update(ann)
                self.clientset.endpoints(self.config.namespace).update(obj)
            return True
        except errors.ApiError as e:
            log.info("lock write failed: %s", e)
            return False

    def try_acquire_or_renew(self) -> bool:
        now = time.time()
        obj, record = self._read_record()
        if record is not None and record.get("holderIdentity") != self.config.identity:
            renew = float(record.get("renewTime", 0))
            if now - renew < float(record.get("leaseDurationSeconds", 15)):
                return False  # someone else holds a live lease
        new_record = {
            "holderIdentity": self.config.identity,
            "leaseDurationSeconds": self.config.lease_duration,
            "acquireTime": (
                record.get("acquireTime", now)
                if record and record.get("holderIdentity") == self.config.identity
                else now
            ),
            "renewTime": now,
        }
        return self._write_record(obj, new_record)

    def stop(self) -> None:
        self._stop.set()

    def run_or_die(
        self,
        on_started_leading: Callable[[threading.Event], None],
        on_stopped_leading: Optional[Callable[[], None]] = None,
    ) -> None:
        """Block until leadership, run the callback, renew until lost/stopped."""
        while not self._stop.is_set():
            if self.try_acquire_or_renew():
                break
            log.info("waiting to acquire leadership...")
            self._stop.wait(self.config.retry_period)
        if self._stop.is_set():
            return
        log.info("acquired leadership: %s", self.config.identity)

        lost = threading.Event()

        def renew_loop():
            while not self._stop.is_set() and not lost.is_set():
                deadline = time.time() + self.config.renew_deadline
                ok = False
                while time.time() < deadline:
                    if self.try_acquire_or_renew():
                        ok = True
                        break
                    time.sleep(0.2)
                if not ok:
                    log.error("failed to renew lease; stepping down")
                    lost.set()
                    return
                self._stop.wait(self.config.retry_period)

        renewer = threading.Thread(target=renew_loop, daemon=True, name="lease-renew")
        renewer.start()
        try:
            # The workload observes `lost` (or process stop) via this event.
            stop_work = threading.Event()

            def watchdog():
                while not self._stop.is_set() and not lost.is_set():
                    time.sleep(0.2)
                stop_work.set()

            threading.Thread(target=watchdog, daemon=True, name="lease-watchdog").start()
            on_started_leading(stop_work)
        finally:
            if lost.is_set() and on_stopped_leading is not None:
                on_stopped_leading()

"""Prometheus-style metrics (filling the observability gap SURVEY.md §5
documents: "No Prometheus metrics anywhere — a gap to fill").

A minimal, thread-safe registry producing the Prometheus text exposition
format (version 0.0.4) with Counter / Gauge / Histogram supporting label
sets.  Stdlib-only like the rest of the control plane; the dashboard backend
serves it at ``/metrics`` and both controllers record reconcile telemetry
through the default registry.
"""

from __future__ import annotations

import bisect
from k8s_tpu.analysis import checkedlock
from typing import Iterable, Optional, Sequence

_DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def _format_labels(label_names: Sequence[str], label_values: Sequence[str]) -> str:
    if not label_names:
        return ""
    pairs = ",".join(
        f'{k}="{_escape(v)}"' for k, v in zip(label_names, label_values)
    )
    return "{" + pairs + "}"


def _escape(value: str) -> str:
    return str(value).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _format_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


class _Metric:
    """Base: one named metric with zero or more labeled children."""

    kind = "untyped"

    def __init__(self, name: str, help_text: str, label_names: Sequence[str] = ()):
        self.name = name
        self.help = help_text
        self.label_names = tuple(label_names)
        self._lock = checkedlock.make_lock("metrics.family")
        self._children: dict[tuple, object] = {}

    def labels(self, *label_values: str):
        if len(label_values) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, got {label_values}"
            )
        key = tuple(str(v) for v in label_values)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._new_child()
                self._children[key] = child
            return child

    def _default_child(self):
        return self.labels()

    def _new_child(self):
        raise NotImplementedError

    def collect(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} {self.kind}"
        with self._lock:
            items = list(self._children.items())
        for key, child in items:
            yield from self._collect_child(key, child)

    def _collect_child(self, key: tuple, child) -> Iterable[str]:
        raise NotImplementedError


class _CounterChild:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = checkedlock.make_lock("metrics.counter")

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Counter(_Metric):
    kind = "counter"

    def _new_child(self):
        return _CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    @property
    def value(self) -> float:
        return self._default_child().value

    def _collect_child(self, key, child):
        yield f"{self.name}{_format_labels(self.label_names, key)} {_format_value(child.value)}"


class _GaugeChild:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = checkedlock.make_lock("metrics.gauge")

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, help_text, label_names=(), fn=None):
        super().__init__(name, help_text, label_names)
        self._fn = fn  # callable gauges (e.g. workqueue depth)

    def _new_child(self):
        return _GaugeChild()

    def set(self, value: float) -> None:
        self._default_child().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default_child().dec(amount)

    @property
    def value(self) -> float:
        return self._default_child().value

    def collect(self):
        if self._fn is not None:
            yield f"# HELP {self.name} {self.help}"
            yield f"# TYPE {self.name} {self.kind}"
            yield f"{self.name} {_format_value(float(self._fn()))}"
            return
        yield from super().collect()

    def _collect_child(self, key, child):
        yield f"{self.name}{_format_labels(self.label_names, key)} {_format_value(child.value)}"


class _HistogramChild:
    __slots__ = ("buckets", "counts", "total", "count", "_lock")

    def __init__(self, buckets: Sequence[float]):
        self.buckets = buckets
        self.counts = [0] * len(buckets)
        self.total = 0.0
        self.count = 0
        self._lock = checkedlock.make_lock("metrics.histogram")

    def observe(self, value: float) -> None:
        with self._lock:
            self.total += value
            self.count += 1
            # per-bucket counts; collect() accumulates into cumulative le= form
            i = bisect.bisect_left(self.buckets, value)
            if i < len(self.buckets):
                self.counts[i] += 1


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help_text, label_names=(), buckets: Sequence[float] = _DEFAULT_BUCKETS):
        super().__init__(name, help_text, label_names)
        self.buckets = tuple(sorted(buckets))

    def _new_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, value: float) -> None:
        self._default_child().observe(value)

    def _collect_child(self, key, child):
        cumulative = 0
        for bound, count in zip(child.buckets, child.counts):
            cumulative += count
            labels = _format_labels(
                self.label_names + ("le",), key + (_format_value(bound),)
            )
            yield f"{self.name}_bucket{labels} {cumulative}"
        inf_labels = _format_labels(self.label_names + ("le",), key + ("+Inf",))
        yield f"{self.name}_bucket{inf_labels} {child.count}"
        yield f"{self.name}_sum{_format_labels(self.label_names, key)} {_format_value(child.total)}"
        yield f"{self.name}_count{_format_labels(self.label_names, key)} {child.count}"


class Registry:
    def __init__(self):
        self._lock = checkedlock.make_lock("metrics.registry")
        self._metrics: dict[str, _Metric] = {}

    def register(self, metric: _Metric) -> _Metric:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                return existing
            self._metrics[metric.name] = metric
            return metric

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)

    def counter(self, name, help_text="", label_names=()) -> Counter:
        return self.register(Counter(name, help_text, label_names))  # type: ignore[return-value]

    def gauge(self, name, help_text="", label_names=(), fn=None) -> Gauge:
        return self.register(Gauge(name, help_text, label_names, fn=fn))  # type: ignore[return-value]

    def histogram(self, name, help_text="", label_names=(), buckets=_DEFAULT_BUCKETS) -> Histogram:
        return self.register(Histogram(name, help_text, label_names, buckets))  # type: ignore[return-value]

    def expose(self) -> str:
        """Text exposition format 0.0.4."""
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        lines: list[str] = []
        for m in metrics:
            lines.extend(m.collect())
        return "\n".join(lines) + "\n" if lines else ""


class ProxyMetric(_Metric):
    """A metric whose samples are computed at collect time from an external
    source (the flight recorder's own counters): ``sample_fn(name)`` yields
    fully-formatted exposition lines.  Unlike the callable-Gauge shortcut
    this supports labeled families and histograms, which is what the
    apiserver/watch adapters need."""

    def __init__(self, name, help_text, kind, sample_fn):
        super().__init__(name, help_text)
        self.kind = kind
        self._sample_fn = sample_fn

    def collect(self):
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} {self.kind}"
        # sample_fn may be rebound (latest-registrant-wins, the
        # queue_depth contract) and released to None on close — an
        # unbound proxy exposes an empty family, never a broken scrape
        if self._sample_fn is not None:
            yield from self._sample_fn(self.name)


REGISTRY = Registry()


# --- flight-recorder exposition (ISSUE 7) -----------------------------------
#
# The flight recorder (k8s_tpu.flight) keeps its own counters — it is
# stdlib-only by policy and may not import this module — so exposition is a
# set of ProxyMetric adapters reading its snapshots at scrape time.  The v2
# controller registers the family on construction; benches read the flight
# counters directly (same substrate, no scrape needed).


def flight_metrics(registry: Optional[Registry] = None) -> dict:
    """Register the apiserver call-accounting, watch-stream health, and
    event-recorder families backed by ``k8s_tpu.flight``'s process-global
    instruments.  Idempotent (the registry dedupes by name)."""
    from k8s_tpu import flight

    r = registry or REGISTRY

    def _requests(name):
        for (verb, resource, code), n in sorted(
                flight.ACCOUNTING.snapshot().items()):
            labels = _format_labels(("verb", "resource", "code"),
                                    (verb, resource, str(code)))
            yield f"{name}{labels} {_format_value(n)}"

    def _request_duration(name):
        bounds, counts, total, count = flight.ACCOUNTING.duration_samples()
        cumulative = 0
        for bound, c in zip(bounds, counts):
            cumulative += c
            labels = _format_labels(("le",), (_format_value(bound),))
            yield f"{name}_bucket{labels} {cumulative}"
        yield f"{name}_bucket{{le=\"+Inf\"}} {count}"
        yield f"{name}_sum {_format_value(total)}"
        yield f"{name}_count {count}"

    def _relists(name):
        for (resource, reason), n in sorted(
                flight.WATCH.labeled()["relists"].items()):
            labels = _format_labels(("resource", "reason"), (resource, reason))
            yield f"{name}{labels} {_format_value(n)}"

    def _restarts(name):
        for resource, n in sorted(flight.WATCH.labeled()["restarts"].items()):
            yield (f"{name}{_format_labels(('resource',), (resource,))} "
                   f"{_format_value(n)}")

    def _watch_events(name):
        for (resource, etype), n in sorted(
                flight.WATCH.labeled()["events"].items()):
            labels = _format_labels(("resource", "type"), (resource, etype))
            yield f"{name}{labels} {_format_value(n)}"

    def _stream_age(name):
        for resource, age in sorted(
                flight.WATCH.labeled()["stream_age_s"].items()):
            yield (f"{name}{_format_labels(('resource',), (resource,))} "
                   f"{_format_value(round(age, 3))}")

    def _event_counter(field):
        def sample(name):
            yield f"{name} {_format_value(flight.EVENTS.snapshot()[field])}"
        return sample

    def _timeline_gauge(field):
        def sample(name):
            yield f"{name} {_format_value(flight.TIMELINE.stats()[field])}"
        return sample

    return {
        "requests": r.register(ProxyMetric(
            "apiserver_requests_total",
            "Apiserver requests by verb/resource/HTTP status (one count "
            "per wire attempt; code 0 = transport failure; collection "
            "GETs count as LIST, streaming GETs as WATCH).",
            "counter", _requests)),
        "duration": r.register(ProxyMetric(
            "apiserver_request_duration_seconds",
            "Apiserver request attempt latency.",
            "histogram", _request_duration)),
        "relists": r.register(ProxyMetric(
            "watch_relists_total",
            "Reflector full-relist cycles by resource and reason "
            "(initial / 410 / error / no_rv).  Beyond the initial lists, "
            "410 and error mean watch gaps; no_rv is the by-design "
            "per-cycle relist of a backend that mints no resourceVersions.",
            "counter", _relists)),
        "restarts": r.register(ProxyMetric(
            "watch_restarts_total",
            "Watch streams reopened after a previous one ended (the "
            "steady state restarts on the server's watch timeout; a "
            "spike means streams are dying early).",
            "counter", _restarts)),
        "watch_events": r.register(ProxyMetric(
            "watch_events_total",
            "Watch events delivered to reflectors, by resource and type.",
            "counter", _watch_events)),
        "stream_age": r.register(ProxyMetric(
            "watch_stream_age_seconds",
            "Age of each resource's live watch stream (absent = no open "
            "stream).",
            "gauge", _stream_age)),
        "events_recorded": r.register(ProxyMetric(
            "events_recorded_total",
            "K8s Events accepted by the recorder (buffered enqueue on the "
            "async recorder; not necessarily posted yet).",
            "counter", _event_counter("recorded"))),
        "events_dropped": r.register(ProxyMetric(
            "events_dropped_total",
            "K8s Events lost by the recorder — queue overflow, post-close "
            "sends, or failed apiserver posts (counted, never raised).",
            "counter", _event_counter("dropped"))),
        "events_aggregated": r.register(ProxyMetric(
            "events_aggregated_total",
            "Exact-repeat events folded into an existing Event object by "
            "count/lastTimestamp bump instead of a fresh create.",
            "counter", _event_counter("aggregated"))),
        "timeline_jobs": r.register(ProxyMetric(
            "timeline_jobs_tracked",
            "Jobs with entries in the flight-recorder lifecycle journal.",
            "gauge", _timeline_gauge("jobs"))),
        "timeline_events": r.register(ProxyMetric(
            "timeline_events_recorded_total",
            "Lifecycle events recorded into the journal (including "
            "ring-evicted entries).",
            "counter", _timeline_gauge("events_total"))),
    }


# --- fleet telemetry plane exposition (ISSUE 8) ------------------------------
#
# The fleet plane (k8s_tpu.fleet) is stdlib-only like flight/ and keeps its
# own counters; exposition is ProxyMetric adapters reading the ACTIVE plane
# at scrape time.  With no plane active the families expose HELP/TYPE lines
# with zero samples (parseable either way — the round-trip test covers it).


def fleet_metrics(registry: Optional[Registry] = None) -> dict:
    """Register the fleet scrape-plane families backed by
    ``k8s_tpu.fleet.active()``.  Idempotent (the registry dedupes)."""
    from k8s_tpu import fleet

    r = registry or REGISTRY

    def _scrapes(name):
        plane = fleet.active()
        if plane is None:
            return
        for (job, outcome), n in sorted(plane.stats.counts().items()):
            labels = _format_labels(("job", "outcome"), (job, outcome))
            yield f"{name}{labels} {_format_value(n)}"

    def _scrape_duration(name):
        plane = fleet.active()
        if plane is None:
            return
        bounds, counts, total, count = plane.stats.duration_samples()
        cumulative = 0
        for bound, c in zip(bounds, counts):
            cumulative += c
            labels = _format_labels(("le",), (_format_value(bound),))
            yield f"{name}_bucket{labels} {cumulative}"
        yield f"{name}_bucket{{le=\"+Inf\"}} {count}"
        yield f"{name}_sum {_format_value(round(total, 6))}"
        yield f"{name}_count {count}"

    def _targets(name):
        plane = fleet.active()
        if plane is None:
            return
        for job, n in sorted(plane.stats.target_count().items()):
            yield (f"{name}{_format_labels(('job',), (job,))} "
                   f"{_format_value(n)}")

    def _staleness(name):
        plane = fleet.active()
        if plane is None:
            return
        for job, age in sorted(plane.stats.staleness().items()):
            if age == float("inf"):
                continue  # never-scraped: absent is the signal
            yield (f"{name}{_format_labels(('job',), (job,))} "
                   f"{_format_value(round(age, 3))}")

    def _burn(name):
        plane = fleet.active()
        if plane is None:
            return
        for (job, rule), burn in sorted(plane.burn_rates().items()):
            labels = _format_labels(("job", "rule"), (job, rule))
            yield f"{name}{labels} {_format_value(round(burn, 4))}"

    def _breaches(name):
        plane = fleet.active()
        if plane is None:
            return
        for (job, rule), n in sorted(plane.slo.breaches().items()):
            labels = _format_labels(("job", "rule"), (job, rule))
            yield f"{name}{labels} {_format_value(n)}"

    return {
        "scrapes": r.register(ProxyMetric(
            "fleet_scrape_total",
            "Fleet-plane scrapes by job and outcome (ok / http_error / "
            "timeout / parse_error / error).",
            "counter", _scrapes)),
        "scrape_duration": r.register(ProxyMetric(
            "fleet_scrape_duration_seconds",
            "Per-target scrape latency (fetch + parse + ingest).",
            "histogram", _scrape_duration)),
        "targets": r.register(ProxyMetric(
            "fleet_targets",
            "Scrape targets currently tracked per job (Running pods "
            "with a fleet scrape port, from the informer cache).",
            "gauge", _targets)),
        "staleness": r.register(ProxyMetric(
            "fleet_staleness_seconds",
            "Seconds since the job's least-recently-successful target "
            "was scraped (the straggler defines fleet freshness; a "
            "never-scraped job exposes no sample).",
            "gauge", _staleness)),
        "burn_rate": r.register(ProxyMetric(
            "fleet_slo_burn_rate",
            "Short-window SLO burn rate per job and rule (>= 1 means "
            "the error budget is burning at or above the sustainable "
            "rate; breach requires both windows).",
            "gauge", _burn)),
        "breaches": r.register(ProxyMetric(
            "fleet_slo_breaches_total",
            "SLO rule ok->breached transitions per job and rule.",
            "counter", _breaches)),
    }


# --- the operator's own telemetry (consumed by controllers and dashboard) ---

def controller_metrics(generation: str, registry: Optional[Registry] = None) -> dict:
    """The reconcile metric family for one controller generation ("v1"/"v2"):
    sync latency (replacing the log-only timing at
    pkg/controller.v2/controller.go:337-340), sync totals by result, and
    pod/service create/delete counters."""
    r = registry or REGISTRY
    return {
        "sync_duration": r.histogram(
            "tfjob_sync_duration_seconds",
            "Time spent in one syncTFJob pass.",
            ("generation",),
        ),
        "sync_total": r.counter(
            "tfjob_sync_total",
            "syncTFJob passes by result (success/error).",
            ("generation", "result"),
        ),
        "queue_retries": r.counter(
            "tfjob_workqueue_retries_total",
            "Rate-limited requeues of a job key.",
            ("generation",),
        ),
        # -- reconcile fan-out telemetry (parallel create waves) --------------
        "workqueue_depth": r.gauge(
            "tfjob_workqueue_depth",
            "Ready backlog of the controller workqueue, sampled per work "
            "item (client-go workqueue depth analogue).",
            ("generation",),
        ),
        "create_batch_duration": r.histogram(
            "tfjob_create_batch_duration_seconds",
            "Wall time of one bounded-concurrency create wave (all missing "
            "replicas of one type).",
            ("generation", "kind"),
        ),
        "creates_total": r.counter(
            "tfjob_creates_total",
            "Pod/service creates issued by the fan-out layer, by result.",
            ("generation", "kind", "result"),
        ),
        # -- teardown fan-out telemetry (parallel delete waves) ----------------
        "delete_batch_duration": r.histogram(
            "tfjob_delete_batch_duration_seconds",
            "Wall time of one bounded-concurrency delete wave (gang "
            "restart, single-pod restart, or terminal cleanup).",
            ("generation", "kind"),
        ),
        "deletes_total": r.counter(
            "tfjob_deletes_total",
            "Pod/service deletes issued by the teardown fan-out layer, by "
            "result (success / not_found / error; not_found counts as "
            "deleted — the object was already gone).",
            ("generation", "kind", "result"),
        ),
        # -- gang admission / capacity scheduler (ISSUE 4) --------------------
        "admitted_total": r.counter(
            "tfjob_admitted_total",
            "Gang admissions granted (new whole-slice chip reservations, "
            "including adoptions and preemption-backed admissions).",
            ("generation",),
        ),
        "preemptions_total": r.counter(
            "tfjob_preemptions_total",
            "Running gangs evicted to seat a higher-priority job (one per "
            "victim).",
            ("generation",),
        ),
        "queue_depth": r.gauge(
            "tfjob_queue_depth",
            "TFJobs parked by gang admission (holding zero pods), sampled "
            "after each scheduler interaction.",
            ("generation",),
        ),
        "admission_wait": r.histogram(
            "tfjob_admission_wait_seconds",
            "Seconds between a job first asking for capacity and its gang "
            "being admitted.",
            ("generation",),
            # admission waits are queueing times, minutes-scale under
            # contention — the default request-latency buckets top out at 10s
            buckets=(0.05, 0.25, 1.0, 5.0, 15.0, 60.0, 300.0, 900.0, 3600.0),
        ),
        "generation": generation,
    }


def serving_metrics(registry: Optional[Registry] = None,
                    queue_depth_fn=None) -> dict:
    # NOTE: on a name collision the registry returns the EXISTING gauge,
    # so queue_depth_fn only takes effect for the first registrant —
    # callers that can be instantiated repeatedly (models/server.py)
    # rebind the gauge's _fn to themselves instead of passing it here.
    """The inference-server metric family (ISSUE 5): request totals by
    result, backpressure rejections, emitted tokens, live batch occupancy
    and admission-queue depth, and end-to-end request latency — exported
    on the serving pod's own ``/metrics`` (models/server.py) so the
    serving half of the train→serve story is observable like the control
    plane."""
    r = registry or REGISTRY
    return {
        "requests": r.counter(
            "serve_requests_total",
            "Generate requests by result (ok / bad_request / rejected / "
            "error).",
            ("result",),
        ),
        "rejected": r.counter(
            "serve_rejected_total",
            "Requests shed by admission-queue backpressure (HTTP 503 + "
            "Retry-After).",
        ),
        "tokens": r.counter(
            "serve_tokens_total",
            "Tokens emitted across all completed generations.",
        ),
        "occupancy": r.gauge(
            "serve_batch_occupancy",
            "Active decode slots in the most recent batched step "
            "(continuous-batching engine; 0..K8S_TPU_SERVE_SLOTS).",
        ),
        "queue_depth": r.gauge(
            "serve_queue_depth",
            "Requests waiting in the bounded admission queue, sampled at "
            "scrape time.",
            fn=queue_depth_fn,
        ),
        "duration": r.histogram(
            "serve_request_duration_seconds",
            "End-to-end /v1/generate latency (parse to response body), "
            "successful requests.",
        ),
        # -- paged KV cache / shared-prefix reuse (ISSUE 6) ----------------
        "prefix_hits": r.counter(
            "serve_prefix_hits_total",
            "Requests that attached to at least one shared-prefix KV "
            "block instead of prefilling it (radix prefix tree).",
        ),
        "prefill_saved": r.counter(
            "serve_prefill_tokens_saved_total",
            "Prompt tokens whose prefill was skipped by shared-prefix "
            "KV reuse (attached by reference or copy-on-write).",
        ),
        "sampled_batched": r.counter(
            "serve_sampled_batched_total",
            "temperature>0 generations served on the batched slot lanes "
            "(row-wise sampling) instead of the exclusive lane.",
        ),
        "blocks_in_use": r.gauge(
            "serve_kv_blocks_in_use",
            "Live KV-cache pool blocks (slot tables + prefix tree), "
            "sampled after each allocation/release.",
        ),
        # -- batched speculative decoding (ISSUE 9) ------------------------
        "spec_proposed": r.counter(
            "serve_spec_proposed_total",
            "Draft tokens proposed to speculative verify steps (batched "
            "slot lanes; draft_k - 1 per verify).",
        ),
        "spec_accepted": r.counter(
            "serve_spec_accepted_total",
            "Draft tokens accepted by speculative verify steps — "
            "accepted/proposed is the drafting hit rate the fleet plane "
            "can rate per job.",
        ),
        # -- per-request phase metrics (ISSUE 12) --------------------------
        # The TTFT/TPOT split the Gemma-on-TPU serving comparison
        # reports: whole-request duration decomposed into time-to-first-
        # token (queue + prefill + first sample) and per-output-token
        # decode latency.  Histograms, so the fleet plane's merged-
        # bucket quantiles and `serve_ttft_seconds:p99<…` SLO burn-rate
        # rules work on them unchanged.
        "ttft": r.histogram(
            "serve_ttft_seconds",
            "Time to first token: request submit to the first emitted "
            "token (queue wait + prefill + first sample), batched-lane "
            "generations.",
        ),
        "tpot": r.histogram(
            "serve_tpot_seconds",
            "Time per output token after the first: (e2e - TTFT) / "
            "(tokens - 1), per completed generation with >= 2 tokens.",
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 1.0),
        ),
        "queue_wait": r.histogram(
            "serve_queue_wait_seconds",
            "Admission-queue wait: request submit to slot admission "
            "(or to the exclusive lane picking it up).",
        ),
        "step_duration": r.histogram(
            "serve_step_duration_seconds",
            "Wall time of one batched engine program call (fused decode "
            "scan or speculative verify step), host read included.",
            buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                     0.25, 1.0, 2.5),
        ),
        "prefill_convoy": r.counter(
            "serve_prefill_convoy_total",
            "Admissions whose prefill ran while >= 1 decode-ready slot "
            "waited (the prefill convoy: decode stalled behind another "
            "request's prefill).",
        ),
        # -- disaggregated prefill/decode migration (ISSUE 15) -------------
        "kv_migrated": r.counter(
            "serve_kv_blocks_migrated_total",
            "KV blocks grafted into this pod's pool from a prefill-tier "
            "peer (counted on the RECEIVING decode pod).",
        ),
        "kv_migrate": r.histogram(
            "serve_kv_migrate_seconds",
            "Cross-pod KV migration latency on the SENDING prefill pod: "
            "block-chain send to the decode pod's seated ack (transfer "
            "+ graft, decode excluded).",
            buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                     0.25, 1.0, 2.5),
        ),
        # -- tiered KV memory hierarchy (ISSUE 17) -------------------------
        "kv_spilled_blocks": r.gauge(
            "serve_kv_spilled_blocks",
            "KV blocks resident in the host-RAM spill tier (evicted "
            "prefix-tree leaves demoted instead of dropped), sampled "
            "after each demote/promote.",
        ),
        "kv_spill_bytes": r.gauge(
            "serve_kv_spill_bytes",
            "Host bytes held by the spill tier (quantized payloads), "
            "bounded by K8S_TPU_SERVE_SPILL_MB.",
        ),
        "kv_promotions": r.counter(
            "serve_kv_promotions_total",
            "Spilled KV blocks promoted back into the device pool on a "
            "prefix hit (each one a block-sized re-prefill avoided).",
        ),
        "kvxfer_dedup_skipped": r.counter(
            "serve_kvxfer_dedup_blocks_skipped_total",
            "KV blocks the migration wire skipped because the receiver "
            "already held them in-tree or in-spill (counted on the "
            "SENDING pod after the offer/need handshake).",
        ),
    }

"""Signal handling (reference: pkg/util/signals/signal.go:29).

``setup_signal_handler`` returns a ``threading.Event`` that is set on the
first SIGINT/SIGTERM; a second signal hard-exits with code 1, mirroring the
reference's double-signal contract (signal.go:36-43).
"""

from __future__ import annotations

import os
import signal
import threading

_only_one = threading.Lock()
_installed = False


def setup_signal_handler() -> threading.Event:
    """Install SIGINT/SIGTERM handler; may only be called once per process."""
    global _installed
    if not _only_one.acquire(blocking=False) or _installed:
        raise RuntimeError("setup_signal_handler called twice")
    _installed = True
    _only_one.release()

    stop = threading.Event()

    def _handler(signum, frame):  # noqa: ARG001
        if stop.is_set():
            os._exit(1)  # second signal: exit directly (signal.go:40-42)
        stop.set()

    signal.signal(signal.SIGINT, _handler)
    signal.signal(signal.SIGTERM, _handler)
    return stop


def merge_stop_events(*events: threading.Event, poll: float = 0.2) -> threading.Event:
    """Return an Event that is set as soon as any of ``events`` is set.

    Used by the operator binaries to merge the process signal handler's stop
    event with the leader elector's per-term stop-work event."""
    if not events:
        raise ValueError("merge_stop_events requires at least one event")
    merged = threading.Event()

    def wait_any():
        while not any(e.is_set() for e in events):
            events[0].wait(poll)
        merged.set()

    threading.Thread(target=wait_any, daemon=True).start()
    return merged

"""Signal handling (reference: pkg/util/signals/signal.go:29).

``setup_signal_handler`` returns a ``threading.Event`` that is set on the
first SIGINT/SIGTERM; a second signal hard-exits with code 1, mirroring the
reference's double-signal contract (signal.go:36-43).
"""

from __future__ import annotations

import os
import signal
import threading
from k8s_tpu.analysis import checkedlock

_only_one = checkedlock.make_lock("signals.once")
_installed = False
_setup_called = False
_callbacks: list = []
_stop = threading.Event()
_prev_handlers: dict = {}


def _handler(signum, frame):  # noqa: ARG001
    if _stop.is_set():
        os._exit(1)  # second signal: exit directly (signal.go:40-42)
    _stop.set()
    for cb in list(_callbacks):
        try:
            cb()
        # except-ok: a signal handler must never raise past one callback
        except Exception:  # noqa: BLE001 - shutdown path must not raise
            pass


def _install() -> None:
    global _installed
    _installed = True
    _prev_handlers[signal.SIGINT] = signal.signal(signal.SIGINT, _handler)
    _prev_handlers[signal.SIGTERM] = signal.signal(signal.SIGTERM, _handler)


def _uninstall() -> None:
    global _installed
    _installed = False
    for sig, prev in _prev_handlers.items():
        signal.signal(sig, prev)
    _prev_handlers.clear()


def setup_signal_handler() -> threading.Event:
    """Install SIGINT/SIGTERM handler; may only be called once per process
    (operator binaries).  Composes with ``on_shutdown``: callbacks
    registered before or after still fire on the first signal."""
    global _setup_called
    with _only_one:
        if _setup_called:
            raise RuntimeError("setup_signal_handler called twice")
        _setup_called = True
        if not _installed:
            _install()
    return _stop


def on_shutdown(callback):
    """Register ``callback`` to run on the first SIGINT/SIGTERM (before the
    double-signal hard-exit window).  Used for best-effort work on the way
    out — e.g. a final checkpoint save inside the pod's SIGTERM grace period
    (cooperative loop in k8s_tpu.models.train.fit, handler-side fallback in
    Checkpointer.save_on_preemption).  Installs the shared handler if no one
    has yet.

    Returns an unsubscribe callable.  Unsubscribing the last callback
    restores the original signal disposition when ``setup_signal_handler``
    was never called — a library user's Ctrl-C behaves normally again after
    fit() returns."""
    with _only_one:
        if not _setup_called and not _callbacks:
            # Library-only usage starting a fresh run: clear any latch left
            # by a consumed signal from a previous run, else that run's
            # first SIGTERM takes the second-signal os._exit(1) path and no
            # shutdown callback (preemption checkpoint) ever fires.  With a
            # setup_signal_handler owner the latch persists: the operator
            # binaries keep the reference's double-signal hard-exit contract.
            _stop.clear()
        _callbacks.append(callback)
        if not _installed:
            _install()

    def unsubscribe() -> None:
        with _only_one:
            try:
                _callbacks.remove(callback)
            except ValueError:
                pass
            if not _callbacks and not _setup_called and _installed:
                _uninstall()

    return unsubscribe


def reset() -> None:
    """Clear the first-signal latch (multi-run drivers: a consumed SIGTERM
    from run N must not turn run N+1's first signal into a hard exit)."""
    _stop.clear()


def merge_stop_events(*events: threading.Event, poll: float = 0.2) -> threading.Event:
    """Return an Event that is set as soon as any of ``events`` is set.

    Used by the operator binaries to merge the process signal handler's stop
    event with the leader elector's per-term stop-work event."""
    if not events:
        raise ValueError("merge_stop_events requires at least one event")
    merged = threading.Event()

    def wait_any():
        while not any(e.is_set() for e in events):
            events[0].wait(poll)
        merged.set()

    threading.Thread(target=wait_any, daemon=True).start()
    return merged

"""TAP e2e binary (reference: test/e2e/main.go:62-252).

Runs N TFJobs (in parallel threads like main.go:195-221), each through the
full lifecycle: create → wait Succeeded → verify runtime id + per-replica
resources → delete → verify GC.  Emits TAP output
("ok 1 - Successfully ran TFJob", main.go:244-252).

Against ``--local`` (default) it provisions an in-process LocalCluster
(fake apiserver + operator + kubelet simulator); pointed at a kubeconfig it
drives a real apiserver the way the Go binary does in-cluster.

The reference checked ``BatchV1().Jobs`` for per-replica resources — stale
against the pod-based trainer (SURVEY.md §3.4 note); this checks the
pod-created events + services, matching the maintained Python runner.
"""

from __future__ import annotations

import argparse
import logging
import sys
import threading
import time

from k8s_tpu.e2e.components import core_component, smoke_command
from k8s_tpu.harness import test_runner, tf_job_client
from k8s_tpu.util.util import rand_string

log = logging.getLogger(__name__)


def run_one(clientset, namespace: str, version: str, timeout_s: float) -> tuple[str, str]:
    """One job lifecycle; returns (name, error) with error == "" on success
    (main.go:62-186)."""
    import datetime

    name = "e2e-test-job-" + rand_string(4)
    component = core_component(
        {
            "name": name,
            "namespace": namespace,
            "num_masters": 1,
            "num_workers": 1,
            "num_ps": 1,
            "command": smoke_command(),
        },
        version,
    )
    try:
        tf_job_client.create_tf_job(clientset, component, version)
        deadline = time.time() + timeout_s
        tf_job = None
        while time.time() < deadline:
            tf_job = clientset.tfjobs_unstructured(
                namespace, f"kubeflow.org/{version}"
            ).get(name)
            state = (tf_job.get("status") or {}).get("state")
            conditions = (tf_job.get("status") or {}).get("conditions") or []
            if version.endswith("v1alpha1") and state in ("Succeeded", "Failed"):
                break
            if not version.endswith("v1alpha1") and any(
                c.get("type") in ("Succeeded", "Failed") and c.get("status") == "True"
                for c in conditions
            ):
                break
            time.sleep(0.1)

        if tf_job is None:
            return name, f"Failed to get TFJob {name}"
        if not test_runner._succeeded(tf_job, version):
            return name, f"TFJob {name} did not succeed; {tf_job.get('status')}"

        if version.endswith("v1alpha1"):
            if not (tf_job.get("spec") or {}).get("RuntimeId"):
                return name, f"TFJob {name} doesn't have a RuntimeId"

        # per-replica resources: creation events for every expected replica
        uid = tf_job["metadata"]["uid"]
        pods, services = test_runner.parse_events(
            test_runner.get_events(clientset, namespace, uid)
        )
        expected = test_runner._expected_replicas(tf_job, version)
        if len(pods) < expected:
            return name, f"TFJob {name} created {len(pods)} pods, want {expected}"
        if len(services) < expected:
            return name, (
                f"TFJob {name} created {len(services)} services, want {expected}"
            )

        # delete and verify GC (main.go:151-186)
        tf_job_client.delete_tf_job(clientset, namespace, name, version)
        test_runner.wait_for_delete(
            clientset, namespace, name, version,
            timeout=datetime.timedelta(seconds=timeout_s),
        )
        test_runner.wait_for_pods_to_be_deleted(
            clientset, namespace, {"tf_job_name": name},
            timeout=datetime.timedelta(seconds=timeout_s),
        )
        if any(
            (s.get("metadata") or {}).get("labels", {}).get("tf_job_name") == name
            for s in clientset.services(namespace).list()
        ):
            return name, f"TFJob {name} services were not garbage collected"
        return name, ""
    except Exception as e:  # noqa: BLE001 - report as TAP failure
        log.exception("job %s failed", name)
        return name, str(e)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="TAP e2e test.")
    parser.add_argument("--namespace", default="default")
    parser.add_argument("--num_jobs", type=int, default=1)
    parser.add_argument("--version", default="v1alpha1")
    parser.add_argument("--timeout_s", type=float, default=120.0)
    parser.add_argument(
        "--kubeconfig", default="",
        help="Drive a real apiserver; default is the in-process LocalCluster.",
    )
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    cluster = None
    if args.kubeconfig:
        from k8s_tpu.client.clientset import Clientset
        from k8s_tpu.client.rest import RestClient, kubeconfig_config

        clientset = Clientset(RestClient(kubeconfig_config(args.kubeconfig)))
    else:
        from k8s_tpu.e2e.local import LocalCluster

        cluster = LocalCluster(version=args.version, namespace=args.namespace)
        cluster.__enter__()
        clientset = cluster.clientset

    results: list[tuple[str, str]] = [None] * args.num_jobs

    def worker(i: int) -> None:
        results[i] = run_one(clientset, args.namespace, args.version, args.timeout_s)

    threads = [
        threading.Thread(target=worker, args=(i,)) for i in range(args.num_jobs)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if cluster:
        cluster.stop()

    # TAP output (main.go:244-252)
    print(f"1..{args.num_jobs}")
    failures = 0
    for i, (name, err) in enumerate(results, start=1):
        if err:
            failures += 1
            print(f"not ok {i} - TFJob {name} failed: {err}")
        else:
            print(f"ok {i} - Successfully ran TFJob {name}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())

"""Real-protocol HTTP apiserver fixture.

The reference tests its HTTP layer against ``utiltesting.FakeHandler`` — a
fake apiserver that records request bodies
(/root/reference/pkg/controller.v2/service_control_test.go:35).  This module
extends that pattern into a *functioning* apiserver: Kubernetes REST
semantics (GET/POST/PUT/PATCH/DELETE plus streaming ``?watch=true``) over the
same in-memory store the fake clientset uses (k8s_tpu.client.fake), so the
operator binary, informers, and leader election can run end-to-end over
``k8s_tpu.client.rest.RestClient`` with **no FakeCluster imports on the
operator side** — the wire protocol is the only contract.

Protocol coverage (the subset the controllers + harness speak):
- paths: ``/api/v1/...`` (core) and ``/apis/<group>/<version>/...``;
  namespaced (``.../namespaces/<ns>/<plural>[/<name>]``), cluster-scoped
  (``/api/v1/nodes``), all-namespace collections, and the ``namespaces``
  resource itself;
- queries: ``labelSelector``, ``fieldSelector``, ``watch=true``,
  ``timeoutSeconds``, ``propagationPolicy``;
- errors: Kubernetes ``Status`` JSON bodies with the right HTTP codes;
- watch: newline-delimited ``{"type": ..., "object": ...}`` frames on an
  EOF-terminated stream (``Connection: close``), ended by client disconnect,
  ``timeoutSeconds``, or server shutdown — the relist/rewatch path real
  apiservers force on clients is exercised for free.
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from k8s_tpu.client import errors
from k8s_tpu.client import gvr as gvr_mod
from k8s_tpu.client.fake import FakeCluster
from k8s_tpu.client.gvr import GVR

log = logging.getLogger(__name__)

# Known resources (kind + scope) by (group, plural); anything else gets a
# best-effort namespaced GVR so CRDs not listed here still round-trip.
_KNOWN = {
    (g.group, g.plural): g
    for g in vars(gvr_mod).values()
    if isinstance(g, GVR)
}


def _resolve_gvr(group: str, version: str, plural: str) -> GVR:
    known = _KNOWN.get((group, plural))
    if known is not None and known.version == version:
        return known
    kind = known.kind if known else plural[:-1].capitalize() if plural.endswith("s") else plural.capitalize()
    namespaced = known.namespaced if known else True
    return GVR(group, version, plural, kind, namespaced=namespaced)


class _Route:
    """Parsed request target: resource + namespace + optional name."""

    def __init__(self, resource: GVR, namespace: Optional[str], name: str):
        self.resource = resource
        self.namespace = namespace
        self.name = name


def parse_route(path: str) -> Optional[_Route]:
    parts = [p for p in path.split("/") if p]
    if len(parts) >= 2 and parts[0] == "api":
        group, version, rest = "", parts[1], parts[2:]
    elif len(parts) >= 3 and parts[0] == "apis":
        group, version, rest = parts[1], parts[2], parts[3:]
    else:
        return None
    if not rest:
        return None
    if rest[0] == "namespaces":
        if group == "" and len(rest) == 1:  # the namespaces collection
            return _Route(gvr_mod.NAMESPACES, None, "")
        if group == "" and len(rest) == 2:  # one namespace object
            return _Route(gvr_mod.NAMESPACES, None, rest[1])
        if len(rest) >= 3:  # .../namespaces/<ns>/<plural>[/<name>]
            ns, plural = rest[1], rest[2]
            name = rest[3] if len(rest) > 3 else ""
            return _Route(_resolve_gvr(group, version, plural), ns, name)
        return None
    # no namespaces segment: cluster-scoped resource (name allowed) or a
    # namespaced collection across all namespaces (collection ops only)
    plural = rest[0]
    name = rest[1] if len(rest) > 1 else ""
    res = _resolve_gvr(group, version, plural)
    if res.namespaced and name:
        # a named, namespaced object MUST be addressed through its
        # namespace (real apiservers 404 here); silently listing instead
        # would mask client URL bugs
        return None
    return _Route(res, None, name)


class _LeanHeaders(dict):
    """Case-insensitive header lookup over lowercased keys — the minimal
    surface the handlers (and stdlib's Expect check) actually use."""

    def get(self, key, default=None):  # type: ignore[override]
        return dict.get(self, key.lower(), default)

    def __getitem__(self, key):
        return dict.__getitem__(self, key.lower())

    def __contains__(self, key):
        return dict.__contains__(self, key.lower())


class _Handler(BaseHTTPRequestHandler):
    # HTTP/1.1 keep-alive for unary requests (Content-Length is always
    # set); watch streams opt out via Connection: close + close_connection
    # so their EOF-terminated bodies still end at server close.
    # self.server is the ThreadingHTTPServer, onto which ApiServer.__init__
    # pins cluster/token/watch_timeout/stopping/resource_version.
    protocol_version = "HTTP/1.1"
    disable_nagle_algorithm = True  # pair with the client's TCP_NODELAY
    # Buffer response writes: send_response/send_header each wrote straight
    # to the socket (wbufsize=0), costing 5+ syscalls per response; stdlib's
    # handle_one_request flushes after every handler, and the watch stream
    # flushes per frame, so buffering never delays a byte that matters.
    wbufsize = 64 * 1024

    # -- plumbing ----------------------------------------------------------

    def log_message(self, fmt, *args):  # quiet: route through logging
        log.debug("apiserver: " + fmt, *args)

    def parse_request(self) -> bool:
        """Lean HTTP/1.1 request parse.

        Replaces stdlib's parse (which builds an email.message.Message per
        request) with a request-line split + flat header dict — measured at
        ~100us/request saved, a double-digit share of the wire bench where
        a 200-gang-job burst is ~6000 requests on one core.  Same contract:
        sets command/path/request_version/headers/close_connection.
        """
        # one handler instance serves many keep-alive requests: the
        # body-consumed flag is per REQUEST, so reset it here
        self._body_consumed = False
        self.command = None
        self.request_version = version = "HTTP/0.9"
        self.close_connection = True
        requestline = str(self.raw_requestline, "iso-8859-1").rstrip("\r\n")
        self.requestline = requestline
        parts = requestline.split()
        if len(parts) != 3:
            self.send_error(400, f"Bad request syntax ({requestline!r})")
            return False
        self.command, self.path, version = parts
        if not version.startswith("HTTP/1."):
            self.send_error(505, f"Invalid HTTP version ({version})")
            return False
        self.request_version = version
        headers = _LeanHeaders()
        while True:
            line = self.rfile.readline(65537)
            if len(line) > 65536:
                self.send_error(431, "Header line too long")
                return False
            if line in (b"\r\n", b"\n", b""):
                break
            key, _, value = line.decode("iso-8859-1").partition(":")
            headers[key.strip().lower()] = value.strip()
        self.headers = headers
        conn = (headers.get("connection") or "").lower()
        self.close_connection = (
            conn == "close"
            or (version == "HTTP/1.0" and conn != "keep-alive")
        )
        return True

    def _send_json(self, code: int, obj: dict) -> None:
        # Keep-alive hygiene: if the request body was never consumed (early
        # 401/route errors), drain it first — leftover bytes would be parsed
        # as the NEXT request line, desynchronizing the pooled connection.
        self._drain_unread_body()
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _drain_unread_body(self) -> None:
        if getattr(self, "_body_consumed", False):
            return
        self._body_consumed = True
        length = int(self.headers.get("Content-Length") or 0)
        if length > 0:
            self.rfile.read(length)

    def _send_status_error(self, err: errors.ApiError) -> None:
        self._send_json(
            err.code,
            {
                "apiVersion": "v1",
                "kind": "Status",
                "status": "Failure",
                "code": err.code,
                "reason": err.reason,
                "message": str(err),
            },
        )

    def _read_body(self) -> dict:
        self._body_consumed = True
        length = int(self.headers.get("Content-Length") or 0)
        if not length:
            return {}
        return json.loads(self.rfile.read(length).decode())

    def _route_and_query(self):
        parsed = urllib.parse.urlsplit(self.path)
        route = parse_route(parsed.path)
        query = dict(urllib.parse.parse_qsl(parsed.query))
        return route, query

    def _authorized(self) -> bool:
        token = self.server.token
        if not token:
            return True
        sent = self.headers.get("Authorization", "")
        if sent == f"Bearer {token}":
            return True
        self._send_status_error(errors.ApiError(401, "Unauthorized", "bad bearer token"))
        return False

    @staticmethod
    def _field_selector(query) -> Optional[dict]:
        raw = query.get("fieldSelector")
        if not raw:
            return None
        out = {}
        for term in raw.split(","):
            k, _, v = term.partition("=")
            out[k] = v
        return out

    # -- verbs -------------------------------------------------------------

    def do_GET(self):
        if not self._authorized():
            return
        route, query = self._route_and_query()
        if route is None:
            return self._send_status_error(errors.not_found(f"unknown path {self.path}"))
        cluster = self.server.cluster
        try:
            if route.name:
                return self._send_json(
                    200, cluster.get(route.resource, route.namespace or "", route.name)
                )
            if query.get("watch") in ("true", "1"):
                return self._stream_watch(route, query)
            # items + rv must come from one atomic snapshot: an event
            # between the list and the rv read would be invisible both in
            # the items and in a watch resumed from that rv.
            items, rv = cluster.list_with_rv(
                route.resource,
                route.namespace,
                label_selector=query.get("labelSelector"),
                field_selector=self._field_selector(query),
            )
            return self._send_json(
                200,
                {
                    "apiVersion": route.resource.api_version,
                    "kind": route.resource.kind + "List",
                    "metadata": {"resourceVersion": str(rv)},
                    "items": items,
                },
            )
        except errors.ApiError as e:
            return self._send_status_error(e)

    def do_POST(self):
        if not self._authorized():
            return
        route, _ = self._route_and_query()
        if route is None or route.name:
            return self._send_status_error(errors.invalid(f"bad create path {self.path}"))
        try:
            # namespace-mismatch validation lives in the store
            # (FakeCluster._check_namespace_match) so the in-process
            # clientset and this wire surface agree
            obj = self.server.cluster.create(
                route.resource, route.namespace or "", self._read_body()
            )
            return self._send_json(201, obj)
        except errors.ApiError as e:
            return self._send_status_error(e)

    def do_PUT(self):
        if not self._authorized():
            return
        route, _ = self._route_and_query()
        if route is None or not route.name:
            return self._send_status_error(errors.invalid(f"bad update path {self.path}"))
        try:
            body = self._read_body()
            body_name = ((body.get("metadata") or {}).get("name") or "")
            if body_name and body_name != route.name:
                # real apiserver conformance: update bodies must name the
                # URL's object — silently honoring the body name would let
                # a buggy client update the wrong object
                return self._send_status_error(errors.bad_request(
                    f"the name of the object ({body_name}) does not match "
                    f"the name on the URL ({route.name})"))
            obj = self.server.cluster.update(
                route.resource, route.namespace or "", body
            )
            return self._send_json(200, obj)
        except errors.ApiError as e:
            return self._send_status_error(e)

    def do_PATCH(self):
        if not self._authorized():
            return
        route, _ = self._route_and_query()
        if route is None or not route.name:
            return self._send_status_error(errors.invalid(f"bad patch path {self.path}"))
        # real apiservers dispatch PATCH semantics on Content-Type; a JSON
        # merge patch and a strategic merge patch differ on every
        # merge-keyed list (containers, env, ownerReferences, ...)
        ctype = (self.headers.get("Content-Type") or "").split(";")[0].strip()
        try:
            body = self._read_body()
            if ctype == "application/merge-patch+json":
                obj = self.server.cluster.patch_merge(
                    route.resource, route.namespace or "", route.name, body)
            elif ctype == "application/strategic-merge-patch+json":
                obj = self.server.cluster.patch_strategic(
                    route.resource, route.namespace or "", route.name, body)
            else:
                # real apiservers accept only the registered patch media
                # types — a bare application/json (or nothing) gets 415,
                # and so does this fixture, so a client that forgets the
                # header fails here, not first on a real cluster
                return self._send_status_error(errors.unsupported_media_type(
                    f"unsupported patch type {ctype!r}; use "
                    "application/merge-patch+json or "
                    "application/strategic-merge-patch+json"))
            return self._send_json(200, obj)
        except errors.ApiError as e:
            return self._send_status_error(e)

    def do_DELETE(self):
        if not self._authorized():
            return
        route, query = self._route_and_query()
        if route is None or not route.name:
            return self._send_status_error(errors.invalid(f"bad delete path {self.path}"))
        try:
            self.server.cluster.delete(
                route.resource,
                route.namespace or "",
                route.name,
                propagation=query.get("propagationPolicy", "Background"),
            )
            return self._send_json(
                200, {"apiVersion": "v1", "kind": "Status", "status": "Success"}
            )
        except errors.ApiError as e:
            return self._send_status_error(e)

    # -- watch streaming ----------------------------------------------------

    def _stream_watch(self, route: _Route, query) -> None:
        import time as _time

        timeout = float(query.get("timeoutSeconds") or self.server.watch_timeout)
        rv = query.get("resourceVersion")
        try:
            w = self.server.cluster.watch(
                route.resource,
                route.namespace,
                resource_version=int(rv) if rv is not None else None,
            )
        except errors.ApiError as e:  # 410 Expired: client must relist
            return self._send_status_error(e)
        self.close_connection = True  # stream body ends at server close
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Connection", "close")
        self.end_headers()
        # wfile is buffered (wbufsize): push the headers NOW — the client
        # blocks on them before it considers the watch established, and the
        # first frame may be arbitrarily far away
        self.wfile.flush()
        deadline = _time.monotonic() + timeout
        try:
            while not self.server.stopping.is_set():
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    return  # server-side watch timeout -> client relists
                item = w.next(timeout=min(remaining, 0.2))
                if item is None:
                    if getattr(w, "stopped", False):
                        return
                    continue
                event_type, obj = item
                frame = json.dumps({"type": event_type, "object": obj}) + "\n"
                self.wfile.write(frame.encode())
                self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            return  # client went away
        finally:
            w.stop()


class ApiServer:
    """A threaded HTTP apiserver over a FakeCluster store.

    Usage::

        server = ApiServer()          # or ApiServer(cluster=my_fake)
        server.start()
        cfg = ClusterConfig(host=server.url)
        backend = RestClient(cfg)     # full CRUD + watch over the wire
        ...
        server.stop()
    """

    def __init__(self, cluster: Optional[FakeCluster] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 token: str = "", watch_timeout: float = 60.0):
        # Behind the wire protocol, store objects are serialized at the
        # boundary and never handed to in-process consumers, so the store
        # runs copy-free (copy_on_io=False): ~5 deepcopies per create was
        # the dominant per-request CPU under the 200-job wire bench.
        self.cluster = (cluster if cluster is not None
                        else FakeCluster(copy_on_io=False))
        # This store is the SERVER side of a wire protocol: the REST client
        # accounts every request per attempt already, so the store must not
        # count the same call a second time into the flight recorder.
        self.cluster.account_flight = False
        self.token = token
        self.watch_timeout = watch_timeout
        self.stopping = threading.Event()
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        # hand the handler a back-reference via the server object
        self._httpd.cluster = self.cluster  # type: ignore[attr-defined]
        self._httpd.token = token  # type: ignore[attr-defined]
        self._httpd.watch_timeout = watch_timeout  # type: ignore[attr-defined]
        self._httpd.stopping = self.stopping  # type: ignore[attr-defined]
        self._httpd.resource_version = (  # type: ignore[attr-defined]
            self.cluster.latest_rv
        )
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "ApiServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.1},
            daemon=True, name="apiserver",
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self.stopping.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    def __enter__(self) -> "ApiServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

"""Multi-process rendezvous e2e driver: operator env contract → N real
processes → one distributed train step → exit-code policy.

The reference's e2e actually executed a distributed cluster: every pod ran
``tf.train.Server`` and the master drove remote ops over gRPC
(examples/tf_sample/tf_sample/tf_smoke.py:88-138).  This driver is the
rebuild's equivalent proof, with the operator in the loop:

1. builds a real v1alpha2 TFJob gang spec;
2. generates each worker's pod env with
   ``controller_v2.tpu_config.gen_env_vars`` — the exact function the
   operator injects through — and passes it to the subprocess VERBATIM.
   The single localhost seam: k8s headless-service DNS names cannot
   resolve outside a cluster, so the coordinator hostname is mapped to
   127.0.0.1 (port and every other byte untouched);
3. spawns the N workers as real OS processes running
   ``k8s_tpu.e2e.rendezvous_worker`` (jax.distributed.initialize →
   membership collective → one sharded Transformer train step);
4. supervises them with the operator's gang semantics: the first non-zero
   exit SIGTERMs the rest of the gang (whole-gang restart,
   controller_v2.pod restart policy) and the failure is classified with
   ``util.train_util`` exactly as the operator classifies a dead pod's
   container exit code.

Used by tests/test_multiprocess_e2e.py (CI tier ``e2e_multiprocess``) and
runnable standalone:  python -m k8s_tpu.e2e.multiprocess --workers 4
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import re
import socket
import subprocess
import sys
import tempfile
import time
from typing import Optional

from k8s_tpu.api import v1alpha2
from k8s_tpu.api.common import TPUSpec
from k8s_tpu.api.meta import ObjectMeta
from k8s_tpu.controller_v2 import tpu_config
from k8s_tpu.util import train_util

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def build_gang_tfjob(n_workers: int, port: int, *, num_slices: int = 1,
                     name: str = "rdzv", namespace: str = "e2e") -> v1alpha2.TFJob:
    """A real TFJob spec for an n-worker SPMD gang (container/port shapes
    exactly as a user manifest would carry them)."""
    spec = v1alpha2.TFReplicaSpec(
        replicas=n_workers,
        template={
            "spec": {
                "containers": [
                    {
                        "name": "tensorflow",
                        "image": "k8s-tpu/launcher:test",
                        "ports": [{"name": "tfjob-port", "containerPort": port}],
                    }
                ]
            }
        },
    )
    tpu = TPUSpec(num_slices=num_slices) if num_slices > 1 else None
    return v1alpha2.TFJob(
        metadata=ObjectMeta(name=name, namespace=namespace, uid="rdzv-uid"),
        spec=v1alpha2.TFJobSpec(tf_replica_specs={"Worker": spec}, tpu=tpu),
    )


_DNS_RE = re.compile(r"^[a-z0-9.-]+\.svc\.cluster\.local$")


def localhost_env(tfjob: v1alpha2.TFJob, rtype: str, index: int) -> dict:
    """The operator-generated env for one replica, with ONLY the k8s DNS
    seam mapped to loopback."""
    env = {e["name"]: e["value"]
           for e in tpu_config.gen_env_vars(tfjob, rtype, index)}
    coord = env["JAX_COORDINATOR_ADDRESS"]
    host, port = coord.rsplit(":", 1)
    assert _DNS_RE.match(host), f"unexpected coordinator host {host!r}"
    env["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
    return env


@dataclasses.dataclass
class GangResult:
    exit_codes: list
    chief_result: Optional[dict]
    worker_outputs: list
    duration_s: float
    death_order: list  # worker indices in observed exit order

    @property
    def success(self) -> bool:
        return all(rc == 0 for rc in self.exit_codes)

    @property
    def first_failure(self) -> Optional[int]:
        """Exit code of the CHRONOLOGICALLY first failing worker.

        The operator classifies the pod that died first — once one member of
        an SPMD gang is gone, the survivors' deaths (SIGTERM from the gang
        kill, collective errors) are collateral, and classifying those would
        turn e.g. a retryable preemption into a permanent failure.
        """
        for i in self.death_order:
            if self.exit_codes[i] != 0:
                return self.exit_codes[i]
        for rc in self.exit_codes:  # fallback: unrecorded stragglers
            if rc != 0:
                return rc
        return None

    @property
    def restart_decision(self) -> str:
        """Classify the gang outcome the way the operator classifies a dead
        pod (controller_v2.pod → util.train_util policy)."""
        rc = self.first_failure
        if rc is None:
            return "succeeded"
        rc = rc if rc >= 0 else 128 - rc  # Popen signal convention → wait(2)
        if train_util.is_retryable_exit_code(rc):
            return "restart"
        if train_util.is_permanent_exit_code(rc):
            return "failed"
        return "failed"  # unknown codes are permanent (replicas.go:347-359)


def run_gang(n_workers: int = 4, *, num_slices: int = 1,
             fail: Optional[str] = None, timeout: float = 420.0,
             extra_env: Optional[dict] = None,
             module: str = "k8s_tpu.e2e.rendezvous_worker") -> GangResult:
    """Spawn the gang and supervise it with whole-gang failure semantics.

    ``module``: the in-pod entrypoint each worker executes (``python -m``);
    defaults to the rendezvous worker.  ``k8s_tpu.launcher.tpu_smoke`` runs
    the operator's actual smoke workload through the same env contract.
    """
    port = free_port()
    tfjob = build_gang_tfjob(n_workers, port, num_slices=num_slices)

    procs = []
    logs = []
    t0 = time.time()
    for i in range(n_workers):
        env = dict(os.environ)
        env.update(localhost_env(tfjob, "worker", i))
        env["K8S_TPU_PLATFORM"] = "cpu"
        # one local device per process — the "one chip per pod" model; also
        # strips the virtual-8-device flag tests/conftest.py exports, which
        # would otherwise inflate every worker to 8 local devices
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        env["XLA_FLAGS"] = " ".join(
            flags + ["--xla_force_host_platform_device_count=1"])
        env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
        if fail:
            env["K8S_TPU_E2E_FAIL"] = fail
        if extra_env:
            env.update(extra_env)
        # output goes to an unbuffered temp file, NOT a pipe: nobody drains
        # pipes during supervision, so a worker writing more than the pipe
        # buffer (verbose JAX logging) would block forever and deadlock the
        # gang against the poll loop
        logf = tempfile.TemporaryFile()
        logs.append(logf)
        procs.append(subprocess.Popen(
            [sys.executable, "-m", module],
            env=env, cwd=REPO_ROOT,
            stdout=logf, stderr=subprocess.STDOUT,
        ))

    # Gang supervision: first non-zero exit kills the rest (the operator's
    # whole-gang restart — a half-dead SPMD world can only hang).
    deadline = t0 + timeout
    exit_codes: list = [None] * n_workers
    death_order: list = []
    gang_kill_at: Optional[float] = None
    while time.time() < deadline:
        for i, p in enumerate(procs):
            if exit_codes[i] is None and p.poll() is not None:
                exit_codes[i] = p.returncode
                death_order.append(i)
                if p.returncode != 0 and gang_kill_at is None:
                    gang_kill_at = time.time()
                    for q in procs:
                        if q.poll() is None:
                            q.terminate()
        if all(rc is not None for rc in exit_codes):
            break
        if gang_kill_at is not None and time.time() > gang_kill_at + 20:
            # a survivor stuck inside a collective can ignore SIGTERM for
            # a long gloo timeout — escalate like the kubelet's grace period
            for q in procs:
                if q.poll() is None:
                    q.kill()
        time.sleep(0.1)
    else:
        for q in procs:
            if q.poll() is None:
                q.kill()

    outputs = []
    chief_result = None
    for i, p in enumerate(procs):
        try:
            p.wait(timeout=30)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()
        exit_codes[i] = p.returncode
        logs[i].seek(0)
        out = logs[i].read().decode(errors="replace")
        logs[i].close()
        outputs.append(out or "")
        for line in (out or "").splitlines():
            if line.startswith("RDZV_OK "):
                parsed = json.loads(line[len("RDZV_OK "):])
                if parsed.get("is_chief"):
                    chief_result = parsed
    return GangResult(
        exit_codes=exit_codes,
        chief_result=chief_result,
        worker_outputs=outputs,
        duration_s=time.time() - t0,
        death_order=death_order,
    )


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--workers", type=int, default=4)
    p.add_argument("--num-slices", type=int, default=1)
    p.add_argument("--fail", default=None,
                   help="pid:rc:phase failure injection")
    p.add_argument("--timeout", type=float, default=420.0)
    args = p.parse_args(argv)

    res = run_gang(args.workers, num_slices=args.num_slices, fail=args.fail,
                   timeout=args.timeout)
    print(json.dumps({
        "success": res.success,
        "exit_codes": res.exit_codes,
        "restart_decision": res.restart_decision,
        "chief": res.chief_result,
        "duration_s": round(res.duration_s, 1),
    }, sort_keys=True))
    if not res.success:
        for i, out in enumerate(res.worker_outputs):
            sys.stderr.write(f"--- worker {i} rc={res.exit_codes[i]} ---\n")
            sys.stderr.write(out[-2000:] + "\n")
    return 0 if res.success else 1


if __name__ == "__main__":
    sys.exit(main())

"""Local e2e cluster: fake apiserver + operator + kubelet simulator.

The in-process analogue of py/deploy.py's GKE setup (deploy.py:91-189): one
call brings up everything a TFJob needs to run end-to-end on this machine.
"""

from __future__ import annotations

import threading

from k8s_tpu.api import v1alpha1
from k8s_tpu.client.clientset import Clientset
from k8s_tpu.client.fake import FakeCluster
from k8s_tpu.client.informer import SharedInformerFactory
from k8s_tpu.e2e.kubelet import KubeletSimulator

RESYNC_S = 0.1  # e2e-speed resync (reference runs 30s, server.go:86)


class LocalCluster:
    """Context manager owning the fake backend, an operator (v1 or v2), and
    a kubelet simulator."""

    def __init__(
        self,
        version: str = "v1alpha1",
        namespace: str = "default",
        enable_gang_scheduling: bool = False,
        kubelet_kwargs: dict | None = None,
        threadiness: int = 1,
        resync_period_s: float = RESYNC_S,
        backend_mode: str = "fake",
        create_concurrency: int | None = None,
        create_delay_s: float = 0.0,
        delete_concurrency: int | None = None,
        delete_delay_s: float = 0.0,
        metrics_port: int | None = None,
        cluster_chips: int | None = None,
        fleet_scrape: bool | None = None,
        fleet_interval_s: float | None = None,
    ):
        # cluster_chips: total TPU chips the v2 controller's gang-admission
        # scheduler may reserve (ISSUE 4).  None = unlimited/off (the
        # compatibility default) unless K8S_TPU_CLUSTER_CHIPS or node
        # allocatables say otherwise.
        # metrics_port wires the operator observability endpoint
        # (/metrics, /healthz, /debug/traces) into the local cluster:
        # None = off (default), 0 = ephemeral port (read it back from
        # self.metrics_server.port — what e2e/tests use).
        self.metrics_server = None
        self._metrics_port = metrics_port
        # threadiness mirrors the operator flag (reference default: v1 runs
        # 1 worker, v2's flag defaults to 2 — options.go:42, server.go:95)
        self.threadiness = threadiness
        self._api_server = None
        if backend_mode == "fake":
            self.backend = FakeCluster()
        elif backend_mode == "rest":
            # full wire protocol: operator + kubelet talk HTTP to the real
            # apiserver fixture, exactly as a deployed operator would
            from k8s_tpu.client.rest import ClusterConfig, RestClient
            from k8s_tpu.e2e.apiserver import ApiServer

            # watch_timeout matches real-apiserver magnitudes: aggressive
            # recycling (measured at 5 s under 200-job load) trims the rv
            # history past the informers' resume points mid-burst, and the
            # resulting 410 relist storm over the wire melts the bench
            self._api_server = ApiServer(watch_timeout=60.0).start()
            self.backend = RestClient(ClusterConfig(host=self._api_server.url))
        else:
            raise ValueError(f"unknown backend_mode {backend_mode!r} "
                             "(expected 'fake' or 'rest')")
        if create_delay_s and hasattr(self.backend, "create_delay_s"):
            # fake-backend RTT injection for creation fan-out benches
            self.backend.create_delay_s = create_delay_s
        if delete_delay_s and hasattr(self.backend, "delete_delay_s"):
            # symmetric RTT injection for teardown/restart benches
            self.backend.delete_delay_s = delete_delay_s
        self.clientset = Clientset(self.backend)
        self.namespace = namespace
        self.version = version
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

        factory = SharedInformerFactory(self.backend, resync_period=resync_period_s)
        if version.endswith("v1alpha1"):
            from k8s_tpu.controller.controller import Controller

            self.controller = Controller(
                self.clientset,
                config=v1alpha1.ControllerConfig(),
                informer_factory=factory,
                enable_gang_scheduling=enable_gang_scheduling,
            )
        else:
            from k8s_tpu.controller_v2.controller import TFJobController

            self.controller = TFJobController(
                self.clientset,
                informer_factory=factory,
                enable_gang_scheduling=enable_gang_scheduling,
                create_concurrency=create_concurrency,
                delete_concurrency=delete_concurrency,
                cluster_chips=cluster_chips,
                # fleet telemetry plane (ISSUE 8): None defers to
                # K8S_TPU_FLEET_SCRAPE (default off)
                fleet_scrape=fleet_scrape,
                fleet_interval_s=fleet_interval_s,
            )
        self.kubelet = KubeletSimulator(
            self.clientset, namespace, **(kubelet_kwargs or {})
        )

    def __enter__(self) -> "LocalCluster":
        if self._metrics_port is not None:
            from k8s_tpu.util.metrics_server import MetricsServer

            self.metrics_server = MetricsServer(
                self._metrics_port, host="127.0.0.1",
                health_fn=getattr(self.controller, "healthy", None),
            ).start()
        t = threading.Thread(
            target=self.controller.run,
            kwargs={"threadiness": self.threadiness, "stop_event": self._stop},
            daemon=True,
            name="operator",
        )
        t.start()
        self._threads.append(t)
        self.kubelet.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def stop(self) -> None:
        self._stop.set()
        self.kubelet.stop()
        shutdown = getattr(self.controller, "shutdown", None)
        if shutdown:
            shutdown()
        for t in self._threads:
            t.join(timeout=5)
        if self.metrics_server is not None:
            self.metrics_server.stop()
            self.metrics_server = None
        if self._api_server is not None:
            self._api_server.stop()

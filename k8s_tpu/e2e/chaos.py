"""Chaos monkey: fault injection for the local e2e cluster.

The reference carries a ``--chaos-level`` operator flag whose implementation
was already excised in the surveyed snapshot (options.go:39-41 keeps the
flag, nothing reads it — SURVEY.md §5 "fault injection").  Here the knob is
functional: at level N the monkey deletes up to N randomly-chosen running
pods per tick straight from the apiserver — the node-crash/preemption
analogue (the kubelet simulator kills the underlying process exactly as a
real kubelet reaps a deleted pod's containers).

What it proves when run under the operator: pod-delete events unwind
creation expectations, the gang policy restarts the affected job, and the
job still completes once the storm stops — the control-plane half of the
preemption story (the exit-code half is tests/test_restart_semantics.py).
"""

from __future__ import annotations

import logging
import random
import threading

log = logging.getLogger(__name__)


def is_managed_pod(pod: dict) -> bool:
    """True for pods the TFJob controllers created: v1 stamps
    ``tf_job_name`` (trainer/replicas.py:64), v2 stamps the kubeflow.org
    group label (controller_v2.tpu_config.gen_labels:52).  Keeps the
    monkey off bystanders — most importantly the operator's own pod."""
    from k8s_tpu.controller_v2 import tpu_config

    labels = (pod.get("metadata") or {}).get("labels") or {}
    return ("tf_job_name" in labels
            or labels.get(tpu_config.LABEL_GROUP_NAME) == "kubeflow.org")


class ChaosMonkey:
    """Deletes random running *managed* pods at a rate set by ``level``.

    level <= 0 disables (the operator flag's default of -1); level N kills
    up to N pods per ``interval_s`` tick.  ``victims`` records what was
    killed so tests can assert chaos actually struck.  ``victim_filter``
    defaults to :func:`is_managed_pod`; pass ``None`` to storm every pod
    in the namespace.
    """

    def __init__(self, clientset, namespace: str = "default", *,
                 level: int = 0, interval_s: float = 0.2, seed: int = 0,
                 victim_filter=is_managed_pod):
        from k8s_tpu.util import metrics

        self.clientset = clientset
        self.namespace = namespace
        self.level = level
        self.interval_s = interval_s
        self.victims: list[str] = []
        self.delete_errors: list[str] = []
        # Scrapeable chaos telemetry: the in-memory lists above only exist
        # for in-process test asserts, but a long-lived drill (the leader's
        # whole tenure) needs its kill/error rate on /metrics like any
        # other component.  Counters are process-wide cumulative across
        # monkeys, exactly like Prometheus counters across restarts.
        self.kills_total = metrics.REGISTRY.counter(
            "chaos_kills_total",
            "Pods deleted by the chaos monkey.")
        self.delete_errors_total = metrics.REGISTRY.counter(
            "chaos_delete_errors_total",
            "Chaos-monkey pod deletes that failed for non-404 reasons.")
        self._victim_filter = victim_filter or (lambda pod: True)
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "ChaosMonkey":
        if self.level > 0 and self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="chaos-monkey")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)

    def _loop(self) -> None:
        from k8s_tpu.client import errors

        while not self._stop.wait(self.interval_s):
            try:
                pods = [
                    p for p in self.clientset.pods(self.namespace).list()
                    if (p.get("status") or {}).get("phase")
                    in ("Running", "Pending") and self._victim_filter(p)
                ]
            # except-ok: chaos injection is best-effort by design —
            # a cluster shutting down mid-list is not a monkey failure
            except Exception:  # noqa: BLE001 - cluster shutting down
                continue
            self._rng.shuffle(pods)
            for pod in pods[: self._rng.randint(0, self.level)]:
                name = pod["metadata"]["name"]
                try:
                    self.clientset.pods(self.namespace).delete(name)
                except Exception as e:  # noqa: BLE001 - keep the storm alive
                    # Any failure — 404 race or a transport error from a
                    # REST backend mid-teardown — must not kill the thread:
                    # the e2e would believe fault injection continues while
                    # nothing is being deleted.  Non-404s are recorded so
                    # tests can detect a sick monkey.
                    if not errors.is_not_found(e):
                        # cap: the monkey lives for the leader's whole
                        # tenure, so a persistent failure (RBAC denies
                        # delete) must not grow memory without bound
                        if len(self.delete_errors) >= 100:
                            del self.delete_errors[0]
                        self.delete_errors.append(f"{name}: {e}")
                        self.delete_errors_total.inc()
                        log.warning("chaos: delete %s failed: %s", name, e)
                    continue
                self.victims.append(name)
                self.kills_total.inc()
                log.info("chaos: deleted pod %s", name)

"""In-pod payload for the multi-process rendezvous e2e.

This is the TPU-native analogue of the reference's smoke workload — every
pod of a distributed TFJob ran a real ``tf.train.Server`` and the master
drove remote ops over gRPC (examples/tf_sample/tf_sample/tf_smoke.py:88-138);
real between-graph training did the same through replica_device_setter
(test/e2e/dist-mnist/dist_mnist.py:48-80).  Here every process:

1. reads the operator-injected env contract VERBATIM through
   ``launcher.bootstrap.LauncherConfig.from_env`` and brings up
   ``jax.distributed.initialize`` against the coordinator;
2. cross-checks the legacy-shaped ``TPU_CONFIG`` JSON against its own
   process identity (the two halves of the contract must agree);
3. runs a membership collective in which every process contributes a
   distinct value — proving all N processes joined one world, not N
   single-process worlds;
4. runs ONE real sharded train step of the repo Transformer through
   ``models.train.make_sharded_train_step`` (FSDP state shardings, donated
   buffers, psum-inserted grads) over the mesh built by
   ``launcher.bootstrap.make_training_mesh`` — including the hybrid
   DCN-over-slices mesh when MEGASCALE env is present;
5. prints one ``RDZV_OK {json}`` line; the chief's line is the gang's
   result artifact.

Failure injection (gang-semantics testing): ``K8S_TPU_E2E_FAIL=pid:rc:phase``
makes process ``pid`` exit ``rc`` at ``phase`` (``startup`` before any
rendezvous, ``post_init`` after the world is up).
"""

from __future__ import annotations

import json
import logging
import os
import sys

log = logging.getLogger(__name__)

SEQ = 16


def _maybe_fail(phase: str, process_id: int) -> None:
    spec = os.environ.get("K8S_TPU_E2E_FAIL", "")
    if not spec:
        return
    pid_s, rc_s, fail_phase = spec.split(":")
    if int(pid_s) == process_id and fail_phase == phase:
        print(f"rendezvous_worker: injected failure at {phase} "
              f"rc={rc_s}", flush=True)
        # os._exit so a signal-style death (137/143) isn't converted into a
        # Python exception by any cleanup machinery
        os._exit(int(rc_s))


def main() -> int:
    logging.basicConfig(level=logging.INFO)
    from k8s_tpu.launcher import bootstrap

    # localhost e2e: the driver injects K8S_TPU_PLATFORM=cpu; the bootstrap
    # owns the sitecustomize workaround
    bootstrap.apply_platform_env()

    cfg = bootstrap.LauncherConfig.from_env()
    _maybe_fail("startup", cfg.process_id)
    cfg = bootstrap.initialize_distributed(cfg)

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    assert jax.process_count() == cfg.num_processes, (
        jax.process_count(), cfg.num_processes)
    assert jax.process_index() == cfg.process_id

    # Contract consistency: the legacy-shaped TPU_CONFIG must describe the
    # same world the jax.distributed env does (controller_tensorflow.go's
    # two outputs must agree).
    tpu_config = json.loads(os.environ["TPU_CONFIG"])
    cluster_size = sum(len(v) for v in tpu_config["cluster"].values())
    assert cluster_size >= cfg.num_processes, (tpu_config, cfg)
    task = tpu_config["task"]
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",")
    assert len(hostnames) == len(tpu_config["cluster"][task["type"]])

    _maybe_fail("post_init", cfg.process_id)

    mesh, cfg = bootstrap.make_training_mesh(config=cfg)

    # Membership collective: every process contributes (process_id + 1) per
    # local device; the global sum is wrong unless every process's distinct
    # value arrived — N independent single-process worlds can't fake it.
    local = np.full((jax.local_device_count(), 1),
                    float(cfg.process_id + 1), np.float32)
    flat = NamedSharding(mesh, P(mesh.axis_names))
    x = jax.make_array_from_process_local_data(flat, local)
    total = float(jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(x))
    expect = float(sum(
        (pid + 1) * jax.local_device_count()
        for pid in range(cfg.num_processes)
    ))
    assert total == expect, f"membership psum {total} != {expect}"

    # One REAL sharded train step of the repo Transformer: FSDP-sharded
    # state initialized inside jit (no host-side global transfer), batch
    # sharded over the data axes, gradients psum'd by XLA.
    from k8s_tpu.models import train as train_lib
    from k8s_tpu.models.transformer import Transformer, TransformerConfig
    from k8s_tpu.parallel.sharding import fsdp_sharding

    tcfg = TransformerConfig(
        vocab_size=64, hidden=32, ffn_hidden=64, layers=1, heads=2,
        kv_heads=2, max_seq_len=SEQ, use_flash_attention=False,
    )
    model = Transformer(tcfg)
    optimizer = train_lib.default_optimizer(1e-2)

    def init_all():
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, SEQ), jnp.int32))
        return train_lib.init_state(params, optimizer)

    state_shape = jax.eval_shape(init_all)
    shardings = {
        "params": fsdp_sharding(state_shape["params"], mesh),
        "opt_state": jax.tree.map(
            lambda x: fsdp_sharding(x, mesh) if hasattr(x, "shape")
            else NamedSharding(mesh, P()),
            state_shape["opt_state"],
        ),
        "step": NamedSharding(mesh, P()),
    }
    state = jax.jit(init_all, out_shardings=shardings)()

    step = train_lib.make_sharded_train_step(
        model.apply, train_lib.lm_loss, optimizer, mesh, shardings)

    batch_sharding = NamedSharding(mesh, P(("dp", "fsdp")))
    n_local = jax.local_device_count()
    total_steps = int(os.environ.get("K8S_TPU_E2E_STEPS", "1"))
    ckpt_every = int(os.environ.get("K8S_TPU_E2E_CKPT_EVERY", "0"))
    process_id = cfg.process_id

    class _Batches:
        """Deterministic per-(process, step) stream with fit's skip()
        resume contract; a resumed run replays exactly what an
        uninterrupted run would have seen.  Failure injection lives in
        __next__: serving batch j means j steps completed and step j-1's
        checkpoint committed — the same post-save boundary the gang
        preemption scenarios target."""

        def __init__(self):
            self.i = 0

        def skip(self, n: int) -> None:
            self.i += n

        def __iter__(self):
            return self

        def __next__(self):
            _maybe_fail(f"step_{self.i}", process_id)
            rng = np.random.default_rng(1234 + process_id * 1000 + self.i)
            local_tokens = rng.integers(
                0, tcfg.vocab_size, (n_local, SEQ)).astype(np.int32)
            self.i += 1
            t = jax.make_array_from_process_local_data(
                batch_sharding, local_tokens)
            return (t, t)

    # Checkpoint/resume through the PRODUCTION fit() loop (orbax-backed,
    # sharding-aware): after a gang restart each process restores its own
    # shards via the operator-injected CHECKPOINT_DIR — executed here with
    # a real multi-process world, not a virtual mesh.
    result_fit = train_lib.fit(
        model.apply, train_lib.lm_loss, optimizer, state, mesh, _Batches(),
        steps=total_steps,
        checkpoint_dir=cfg.checkpoint_dir if ckpt_every else "",
        checkpoint_every=ckpt_every or 1,
        log_every=0,
        step_fn=step,
        state_shardings=shardings,
    )
    _maybe_fail(f"step_{total_steps}", process_id)

    loss = float(result_fit.losses[-1]) if result_fit.losses else None
    if loss is not None:
        assert np.isfinite(loss), loss
    state = result_fit.state
    step_no = int(jax.device_get(
        jax.jit(lambda s: s["step"],
                out_shardings=NamedSharding(mesh, P()))(state)))
    assert step_no == total_steps, (step_no, total_steps)

    result = {
        "process_id": cfg.process_id,
        "num_processes": cfg.num_processes,
        "is_chief": cfg.is_chief,
        "global_devices": jax.device_count(),
        "mesh": {k: int(v) for k, v in mesh.shape.items()},
        "num_slices": cfg.num_slices,
        "membership_sum": total,
        "loss": loss,
        "step": step_no,
        "start_step": result_fit.start_step,
    }
    print("RDZV_OK " + json.dumps(result, sort_keys=True), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Kubelet simulator: executes pods as local subprocesses.

For each pod the apiserver (fake or REST backend) holds, the simulator
starts the ``tensorflow`` container's command as a subprocess with the
container's env vars, marks the pod Running, and on exit records
Succeeded/Failed with the real exit code in ``containerStatuses`` — the
exact surface the operator's status engine reads
(pkg/trainer/replicas.go:310-363, pkg/controller.v2/controller_status.go).

Pods whose container has no command are completed synthetically after
``default_runtime_s`` with ``default_exit_code`` (the stand-in for a real
training image).
"""

from __future__ import annotations

import heapq
import itertools
import logging
import os
import subprocess
import threading
from k8s_tpu.analysis import checkedlock
import time

from k8s_tpu.client import errors

log = logging.getLogger(__name__)

CONTAINER_NAME = "tensorflow"


class KubeletSimulator:
    def __init__(
        self,
        clientset,
        namespace: str = "default",
        env_transform=None,
        default_exit_code: int = 0,
        default_runtime_s: float = 0.05,
        poll_interval_s: float = 0.05,
        restart_backoff_s: float = 0.2,
        max_restarts: int | None = None,
        termination_grace_s: float = 10.0,
    ):
        self.clientset = clientset
        self.namespace = namespace
        self.env_transform = env_transform
        self.default_exit_code = default_exit_code
        self.default_runtime_s = default_runtime_s
        self.poll_interval_s = poll_interval_s
        self.restart_backoff_s = restart_backoff_s
        self.max_restarts = max_restarts
        self.termination_grace_s = termination_grace_s
        self._claimed: set[str] = set()  # pod uids this kubelet started
        self._procs: dict[str, subprocess.Popen] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._active_watch = None
        self._watch_lock = checkedlock.make_lock("kubelet.watch")
        # Command-less (synthetic) pods run on a single timer wheel instead
        # of a thread each: at e2e scale (1600+ pods) thread-per-pod meant
        # a thread + its own pooled REST connection + a server-side handler
        # thread PER POD, and the connection storm dominated the wire bench.
        # One timer thread issues every synthetic status patch over one
        # pooled connection — which is also what a real kubelet is: an event
        # loop, not a thread per container.
        self._timer_heap: list = []
        self._timer_seq = itertools.count()
        self._timer_cond = checkedlock.make_condition("kubelet.timer")
        self._timer_thread: threading.Thread | None = None
        self._deleted: set[str] = set()  # synthetic pods deleted mid-flight

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "KubeletSimulator":
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="kubelet-sim"
        )
        self._thread.start()
        self._timer_thread = threading.Thread(
            target=self._timer_loop, daemon=True, name="kubelet-timers"
        )
        self._timer_thread.start()
        return self

    # -- synthetic-pod timer wheel -------------------------------------------

    def _schedule(self, delay_s: float, fn) -> None:
        with self._timer_cond:
            heapq.heappush(
                self._timer_heap,
                (time.monotonic() + delay_s, next(self._timer_seq), fn),
            )
            self._timer_cond.notify()

    def _timer_loop(self) -> None:
        while not self._stop.is_set():
            with self._timer_cond:
                while not self._timer_heap and not self._stop.is_set():
                    self._timer_cond.wait(0.5)
                if self._stop.is_set():
                    return
                due_at = self._timer_heap[0][0]
                now = time.monotonic()
                if due_at > now:
                    self._timer_cond.wait(min(due_at - now, 0.5))
                    continue
                _at, _seq, fn = heapq.heappop(self._timer_heap)
            try:
                fn()
            except Exception:
                if not self._stop.is_set():
                    log.exception("kubelet timer task failed")

    def stop(self) -> None:
        self._stop.set()
        # Close the in-flight watch so a loop blocked in w.next() (the REST
        # backend's next() blocks on the stream regardless of its timeout
        # argument) unblocks instead of leaking the thread + connection —
        # the SharedInformer._active_watch pattern.
        with self._watch_lock:
            if self._active_watch is not None:
                try:
                    self._active_watch.stop()
                # except-ok: best-effort close on simulator shutdown
                except Exception:
                    pass
        for proc in list(self._procs.values()):
            if proc.poll() is None:
                proc.kill()
        with self._timer_cond:
            self._timer_cond.notify_all()
        if self._timer_thread:
            self._timer_thread.join(timeout=5)
        if self._thread:
            self._thread.join(timeout=5)

    # -- main loop -----------------------------------------------------------

    # Periodic full-relist fallback behind the watch stream.  A real
    # kubelet is watch-driven; the relist only reconciles anything a
    # dropped stream missed, so it can be orders slower than the old
    # poll-everything loop (at 1600 pods a 50 ms list-poll deep-copied the
    # whole namespace 20x/s — the e2e-scale bottleneck).
    RELIST_FALLBACK_S = 10.0

    def _loop(self) -> None:
        w = None
        last_relist = 0.0
        try:
            while not self._stop.is_set():
                try:
                    if w is None:
                        w = self.clientset.pods(self.namespace).watch()
                        with self._watch_lock:
                            self._active_watch = w
                        self._sync_once()  # catch up across the watch gap
                        last_relist = time.monotonic()
                    item = w.next(timeout=0.2)
                    if item is None:
                        if getattr(w, "stopped", False):
                            w.stop()
                            w = None
                            with self._watch_lock:
                                self._active_watch = None
                        elif (time.monotonic() - last_relist
                              > self.RELIST_FALLBACK_S):
                            self._sync_once()
                            last_relist = time.monotonic()
                        continue
                    event_type, pod = item
                    if event_type == "DELETED":
                        self._kill_deleted(pod)
                    else:
                        self._maybe_claim(pod)
                except Exception:
                    if self._stop.is_set():
                        return
                    log.exception("kubelet sync error")
                    if w is not None:
                        w.stop()
                        w = None
                        with self._watch_lock:
                            self._active_watch = None
                    self._stop.wait(self.poll_interval_s)
        finally:
            if w is not None:
                w.stop()

    def _maybe_claim(self, pod: dict) -> None:
        uid = (pod.get("metadata") or {}).get("uid")
        if not uid:
            return
        phase = (pod.get("status") or {}).get("phase")
        if uid in self._claimed or phase in ("Succeeded", "Failed"):
            return
        self._claimed.add(uid)
        container = self._container(pod)
        command = list(container.get("command") or []) + list(
            container.get("args") or []
        )
        if not command:
            # synthetic pod: no subprocess to babysit — run its whole
            # lifecycle on the timer wheel (Running now, completion after
            # default_runtime_s), all from the single timer thread
            self._schedule(0.0, lambda: self._start_sleep_pod(pod))
            return
        threading.Thread(
            target=self._run_pod, args=(pod,), daemon=True,
            name=f"pod-{pod['metadata']['name']}",
        ).start()

    def _start_sleep_pod(self, pod: dict) -> None:
        uid = pod["metadata"]["uid"]
        if uid in self._deleted:
            return
        self._set_status(pod, "Running", {"running": {}})
        self._schedule(
            self.default_runtime_s,
            lambda: self._finish_sleep_pod(pod, restart_count=0),
        )

    def _finish_sleep_pod(self, pod: dict, restart_count: int) -> None:
        """Synthetic completion with the same semantics as _run_pod's loop
        for command-less pods: exit default_exit_code; 0 → Succeeded,
        nonzero → crash-loop (restartable) or terminal Failed."""
        uid = pod["metadata"]["uid"]
        name = pod["metadata"]["name"]
        if uid in self._deleted or self._stop.is_set():
            return
        exit_code = self.default_exit_code
        if exit_code == 0:
            self._set_status(pod, "Succeeded", {"terminated": {"exitCode": 0}})
            return
        restart_policy = (pod.get("spec") or {}).get("restartPolicy", "Always")
        restartable = restart_policy in ("Always", "OnFailure")
        if not restartable or (
            self.max_restarts is not None and restart_count >= self.max_restarts
        ):
            self._set_status(
                pod, "Failed", {"terminated": {"exitCode": exit_code}})
            return
        restart_count += 1
        try:
            current = self.clientset.pods(self.namespace).get(name)
        except errors.ApiError:
            return  # pod deleted while it was "running"
        status = {
            "phase": "Running",
            "startTime": (current.get("status") or {}).get("startTime"),
            "containerStatuses": [
                {
                    "name": CONTAINER_NAME,
                    "restartCount": restart_count,
                    "state": {"waiting": {"reason": "CrashLoopBackOff"}},
                    "lastState": {"terminated": {"exitCode": exit_code}},
                }
            ],
        }
        try:
            self.clientset.pods(self.namespace).patch(name, {"status": status})
        except errors.ApiError:
            return
        self._schedule(
            self.restart_backoff_s + self.default_runtime_s,
            lambda: self._finish_sleep_pod(pod, restart_count),
        )

    def _kill_deleted(self, pod: dict) -> None:
        uid = (pod.get("metadata") or {}).get("uid")
        if uid:
            self._deleted.add(uid)  # cancels pending synthetic timers
        proc = self._procs.get(uid)
        if proc is not None and proc.poll() is None:
            # Real kubelet contract: SIGTERM first, SIGKILL after
            # terminationGracePeriodSeconds.  The grace window is what lets
            # a training process run its cooperative-preemption path (save
            # checkpoint at the next step boundary, exit 143) instead of
            # losing state to an immediate kill.
            grace = float(
                (pod.get("spec") or {}).get("terminationGracePeriodSeconds",
                                            self.termination_grace_s))
            proc.terminate()
            def _force_kill(p=proc):
                if p.poll() is None:
                    p.kill()
            self._schedule(grace, _force_kill)

    def _sync_once(self) -> None:
        pods = self.clientset.pods(self.namespace).list()
        live_uids = set()
        for pod in pods:
            uid = (pod.get("metadata") or {}).get("uid")
            if uid:
                live_uids.add(uid)
            self._maybe_claim(pod)
        # pods deleted from the apiserver: kill their processes (kubelet
        # behavior for deleted pods)
        for uid, proc in list(self._procs.items()):
            if uid not in live_uids and proc.poll() is None:
                proc.kill()

    # -- pod execution -------------------------------------------------------

    def _container(self, pod: dict) -> dict:
        containers = (pod.get("spec") or {}).get("containers") or []
        for c in containers:
            if c.get("name") == CONTAINER_NAME:
                return c
        return containers[0] if containers else {}

    def _set_status(self, pod: dict, phase: str, container_state: dict) -> None:
        name = pod["metadata"]["name"]
        status = {
            "phase": phase,
            "startTime": (pod.get("status") or {}).get("startTime")
            or time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "containerStatuses": [
                {"name": CONTAINER_NAME, "state": container_state}
            ],
        }
        try:
            self.clientset.pods(self.namespace).patch(name, {"status": status})
        except errors.ApiError as e:
            if not errors.is_not_found(e):
                raise

    def _run_pod(self, pod: dict) -> None:
        name = pod["metadata"]["name"]
        uid = pod["metadata"]["uid"]
        restart_policy = (pod.get("spec") or {}).get("restartPolicy", "Always")
        container = self._container(pod)
        command = list(container.get("command") or []) + list(
            container.get("args") or []
        )
        env = {
            "PATH": os.environ.get("PATH", ""),
            "HOME": os.environ.get("HOME", "/tmp"),
            "PYTHONPATH": os.pathsep.join(
                p for p in (
                    os.environ.get("PYTHONPATH", ""),
                    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
                ) if p
            ),
        }
        for item in container.get("env") or []:
            env[item["name"]] = item.get("value", "")
        if self.env_transform:
            env = self.env_transform(pod, env)

        self._set_status(pod, "Running", {"running": {}})

        restart_count = 0
        while True:
            if not command:
                time.sleep(self.default_runtime_s)
                exit_code = self.default_exit_code
            else:
                try:
                    proc = subprocess.Popen(
                        command, env=env,
                        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                    )
                except OSError as e:
                    log.error("pod %s: failed to start %s: %s", name, command, e)
                    self._set_status(
                        pod, "Failed",
                        {"terminated": {"exitCode": 127, "reason": "StartError"}},
                    )
                    return
                self._procs[uid] = proc
                out, _ = proc.communicate()
                exit_code = proc.returncode
                self._procs.pop(uid, None)
                if out:
                    self._store_log(name, out.decode(errors="replace"))

            if exit_code == 0:
                self._set_status(pod, "Succeeded", {"terminated": {"exitCode": 0}})
                return
            log.info("pod %s exited %d", name, exit_code)
            restartable = restart_policy in ("Always", "OnFailure")
            if self._stop.is_set() or not restartable or (
                self.max_restarts is not None and restart_count >= self.max_restarts
            ):
                # restartPolicy Never (or restart budget exhausted): the pod
                # fails terminally.
                self._set_status(
                    pod, "Failed", {"terminated": {"exitCode": exit_code}}
                )
                return
            # restartPolicy Always/OnFailure: the kubelet restarts the
            # container IN the same pod — pod stays Running, the exit lands
            # in lastState.terminated, which is exactly what the operator's
            # exit-code policy reads (pkg/trainer/replicas.go:326-362: a
            # permanent code there fails the replica even though the pod
            # object never reaches phase Failed).
            restart_count += 1
            try:
                current = self.clientset.pods(self.namespace).get(name)
            except errors.ApiError:
                return  # pod deleted while we were running it
            status = {
                "phase": "Running",
                "startTime": (current.get("status") or {}).get("startTime"),
                "containerStatuses": [
                    {
                        "name": CONTAINER_NAME,
                        "restartCount": restart_count,
                        "state": {"waiting": {"reason": "CrashLoopBackOff"}},
                        "lastState": {"terminated": {"exitCode": exit_code}},
                    }
                ],
            }
            self.clientset.pods(self.namespace).patch(name, {"status": status})
            # crash-loop backoff, then run again (status flips back to
            # running on the next iteration's subprocess start)
            if self._stop.wait(self.restart_backoff_s):
                return

    def _store_log(self, pod_name: str, text: str) -> None:
        """Stash container output under status.log — the convention the fake
        backend/dashboard use for log retrieval."""
        try:
            self.clientset.pods(self.namespace).patch(
                pod_name, {"status": {"log": text[-65536:]}}
            )
        except errors.ApiError:
            pass

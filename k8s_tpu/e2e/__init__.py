"""End-to-end test layer (reference: test/e2e/main.go, py/test_runner.py).

The reference e2e runs on a real GKE cluster.  This rebuild adds what the
reference lacked (SURVEY.md §4: "a fake TPU topology/device layer"): a
**kubelet simulator** that actually executes pod containers as local
subprocesses, so a TFJob drives a real process end-to-end — operator creates
the pod, the simulator runs it with the injected env (TF_CONFIG / JAX
bootstrap), the exit code flows back through pod status into the operator's
exit-code policy and job conditions — all without a cluster.
"""

from k8s_tpu.e2e.kubelet import KubeletSimulator  # noqa: F401
from k8s_tpu.e2e.local import LocalCluster  # noqa: F401

"""Parameterized TFJob components for e2e runs.

The reference deploys its e2e job through a ksonnet app
(``ks env add`` / ``ks param set`` / ``ks apply``, py/test_runner.py:239-276,
test/test-app/components/core.jsonnet).  Here the component is a pure
function: params → TFJob dict, in either API version.
"""

from __future__ import annotations

import sys

DEFAULT_PORT = 2222


def _container(params: dict) -> dict:
    c = {"name": "tensorflow", "image": params.get("image", "k8s-tpu/smoke:latest")}
    if params.get("command"):
        c["command"] = list(params["command"])
    return c


def _template(params: dict) -> dict:
    return {
        "spec": {
            "containers": [_container(params)],
            "restartPolicy": "OnFailure",
        }
    }


def core_v1alpha1(params: dict) -> dict:
    """MASTER/WORKER/PS TFJob, v1alpha1 list-of-replica-specs shape
    (test/e2e/main.go:83-96)."""
    replica_specs = []
    for rtype, count in (
        ("MASTER", params.get("num_masters", 1)),
        ("WORKER", params.get("num_workers", 1)),
        ("PS", params.get("num_ps", 0)),
    ):
        if count <= 0:
            continue
        replica_specs.append(
            {
                "replicas": count,
                "tfPort": params.get("port", DEFAULT_PORT),
                "tfReplicaType": rtype,
                "template": _template(params),
            }
        )
    return {
        "apiVersion": "kubeflow.org/v1alpha1",
        "kind": "TFJob",
        "metadata": {
            "name": params["name"],
            "namespace": params.get("namespace", "default"),
            "labels": {"test.mlkube.io": ""},
        },
        "spec": {"replicaSpecs": replica_specs},
    }


def core_v1alpha2(params: dict) -> dict:
    """Chief/Worker/PS TFJob, v1alpha2 map-of-replica-specs shape
    (pkg/apis/tensorflow/v1alpha2/types.go:53)."""
    tf_replica_specs = {}
    for rtype, count in (
        ("Chief", params.get("num_masters", 1)),
        ("Worker", params.get("num_workers", 1)),
        ("PS", params.get("num_ps", 0)),
    ):
        if count <= 0:
            continue
        tf_replica_specs[rtype] = {
            "replicas": count,
            "restartPolicy": params.get("restartPolicy", "OnFailure"),
            "template": _template(params),
        }
    return {
        "apiVersion": "kubeflow.org/v1alpha2",
        "kind": "TFJob",
        "metadata": {
            "name": params["name"],
            "namespace": params.get("namespace", "default"),
        },
        "spec": {"tfReplicaSpecs": tf_replica_specs},
    }


def core_component(params: dict, version: str = "v1alpha1") -> dict:
    if version.endswith("v1alpha1"):
        return core_v1alpha1(params)
    return core_v1alpha2(params)


def smoke_command(exit_code: int = 0) -> list[str]:
    """A real subprocess workload: sanity-checks the injected TF_CONFIG /
    JAX env the way tf_smoke.py parses TF_CONFIG (tf_smoke.py:88-118), then
    exits with ``exit_code``."""
    script = (
        "import json, os, sys\n"
        "tf_config = json.loads(os.environ['TF_CONFIG'])\n"
        "assert 'cluster' in tf_config and 'task' in tf_config, tf_config\n"
        "task = tf_config['task']\n"
        "assert task['type'] in tf_config['cluster'], tf_config\n"
        "if task['type'] in ('master', 'worker', 'tpu_worker'):\n"
        "    assert os.environ.get('JAX_COORDINATOR_ADDRESS'), 'missing coordinator'\n"
        "    assert os.environ.get('JAX_PROCESS_ID') is not None\n"
        f"sys.exit({exit_code})\n"
    )
    return [sys.executable, "-c", script]

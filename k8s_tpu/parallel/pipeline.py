"""Pipeline parallelism over the ``pp`` mesh axis (GPipe-style microbatch
schedule via shard_map + ppermute).

Absent from the reference (SURVEY.md §2.4: its only axes were PS-vs-worker
data parallelism); in the TPU-native design pipeline stages are a mesh-axis
choice like every other form of parallelism.

Mechanics: the network is split into ``S = |pp|`` homogeneous stages; each
device along ``pp`` holds one stage's parameters (stack stage params on a
leading axis sharded ``P("pp", ...)``).  A batch is split into ``M``
microbatches.  The schedule runs ``M + S - 1`` ticks; on every tick each
stage applies its layer to the microbatch it currently holds, then the
activations rotate one step along the ring (``lax.ppermute``).  Stage 0
feeds fresh microbatches for the first ``M`` ticks; the last stage emits
finished microbatches from tick ``S-1`` on.  The bubble is the standard
GPipe ``(S-1)/(M+S-1)`` fraction — pick ``M >> S``.

Everything is differentiable: ppermute's transpose is the reverse permute,
so ``jax.grad`` through a pipelined forward produces the 1B backward
schedule automatically.

Outputs land on the last stage; a masked psum broadcasts them to every
device (also differentiable), so the loss can be computed uniformly.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map


def _pipeline_local(stage_params, microbatches, *, stage_fn: Callable,
                    axis: str):
    """Per-device schedule body (under shard_map).

    stage_params: this stage's params (leading stage axis already sliced to
      size 1 by shard_map; squeezed here).
    microbatches: [M, mb, ...] — replicated input; only stage 0 reads it.
    Returns [M, mb, ...] finished outputs (valid on the last stage, zeros
    elsewhere).
    """
    from k8s_tpu.parallel.collectives import ring_shift

    S = lax.axis_size(axis)
    s = lax.axis_index(axis)
    M = microbatches.shape[0]
    params = jax.tree.map(lambda x: jnp.squeeze(x, 0), stage_params)

    def tick(carry, t):
        holding, outputs = carry
        # stage 0 ingests microbatch t (while t < M); others use what they
        # received last tick
        mb_in = microbatches[jnp.minimum(t, M - 1)]
        x = jnp.where(s == 0, mb_in, holding)
        y = stage_fn(params, x)
        # the last stage's result at tick t is finished microbatch t-(S-1)
        out_idx = t - (S - 1)
        is_done = jnp.logical_and(s == S - 1, out_idx >= 0)
        outputs = lax.cond(
            is_done,
            lambda o: lax.dynamic_update_index_in_dim(
                o, y, jnp.maximum(out_idx, 0), 0),
            lambda o: o,
            outputs,
        )
        # ring: stage i sends to i+1; last stage's wrap to 0 is discarded
        holding = ring_shift(y, axis)
        return (holding, outputs), None

    holding0 = jnp.zeros_like(microbatches[0])
    outputs0 = jnp.zeros_like(microbatches)
    (_, outputs), _ = lax.scan(
        tick, (holding0, outputs0), jnp.arange(M + S - 1))

    # make outputs visible everywhere: only the last stage holds non-zero
    # data, so a psum over the axis broadcasts it (differentiable)
    mask = jnp.where(s == S - 1, 1.0, 0.0).astype(outputs.dtype)
    return lax.psum(outputs * mask, axis)


def pipeline_apply(mesh: Mesh, stage_fn: Callable, stage_params, batch, *,
                   num_microbatches: int, axis: str = "pp",
                   batch_axes=("dp", "fsdp")):
    """Run ``batch`` through the pipeline.

    stage_fn(params, x) -> y: one stage's computation, same activation shape
      in and out (homogeneous stages).
    stage_params: pytree with leading stage axis of size ``|pp|``.
    batch: [B, ...] global; B must divide into num_microbatches.
    Returns [B, ...] outputs.
    """
    B = batch.shape[0]
    if B % num_microbatches:
        raise ValueError(f"batch {B} not divisible into {num_microbatches} microbatches")
    mb = B // num_microbatches
    data_shards = 1
    for a in (batch_axes if isinstance(batch_axes, (tuple, list)) else (batch_axes,)):
        data_shards *= mesh.shape[a]
    if mb % data_shards:
        raise ValueError(
            f"microbatch size {mb} not divisible by data shards {data_shards} "
            f"(axes {batch_axes}); use fewer microbatches or a bigger batch")
    micro = batch.reshape((num_microbatches, mb) + batch.shape[1:])

    param_specs = jax.tree.map(lambda _: P(axis), stage_params)
    # microbatch data stays sharded over the data axes; every pp rank sees
    # its slice of each microbatch
    mspec = P(None, batch_axes)

    fn = shard_map(
        partial(_pipeline_local, stage_fn=stage_fn, axis=axis),
        mesh=mesh,
        in_specs=(param_specs, mspec),
        out_specs=mspec,
        check_vma=False,
    )
    out = fn(stage_params, micro)
    return out.reshape((B,) + out.shape[2:])


def stack_stage_params(params_list):
    """Stack per-stage param pytrees into the leading-stage-axis layout
    pipeline_apply expects, e.g. from S separately-initialized stages."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, 0), *params_list)


# ---------------------------------------------------------------------------
# 1F1B schedule
# ---------------------------------------------------------------------------
#
# GPipe above runs all M forwards, then jax.grad's reverse scan runs all M
# backwards — every stage must keep (or remat from) M microbatches of
# activations.  The 1F1B (one-forward-one-backward) schedule interleaves:
# the last stage starts microbatch m's backward the moment its forward
# finishes, so at any instant a stage has at most O(S) microbatches in
# flight regardless of M.  Non-interleaved 1F1B has the SAME bubble fraction
# as GPipe — (S-1)/(M+S-1) — its win is peak activation memory O(S) vs O(M)
# (see bubble_fraction / peak_activation_microbatches below, asserted in
# tests/test_pipeline.py).
#
# SPMD formulation: one lax.scan over ticks; per tick every device does one
# forward compute (activations ppermute down-ring) AND one backward compute
# (cotangents ppermute up-ring), with index masks selecting which microbatch
# each stream is on.  Timeline (stage s, microbatch m):
#   forward  of m at tick m + s
#   backward of m at tick m + 2S - 2 - s   (last stage: same tick as fwd)
# Total ticks: M + 2S - 2.  Residuals: each stage stores only the stage
# *input* x_m in a circular buffer of min(M, 2S-1) slots and re-linearizes
# (jax.vjp) at backward time — rematerialization, the standard TPU
# HBM-for-FLOPs trade.
#
# The loss must decompose over microbatches (loss = 1/M sum loss_mb), which
# lets the last stage emit dL/dout_m immediately; LM/cross-entropy losses
# all have this shape.


def bubble_fraction(schedule: str, num_microbatches: int, num_stages: int) -> float:
    """Fraction of stage-time idle; identical for gpipe and (non-interleaved)
    1f1b: (S-1)/(M+S-1)."""
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"unknown schedule {schedule!r}")
    M, S = num_microbatches, num_stages
    return (S - 1) / (M + S - 1)


def peak_activation_microbatches(schedule: str, num_microbatches: int,
                                 num_stages: int) -> int:
    """Peak in-flight microbatch residuals a stage must hold — the metric
    1f1b exists to bound: O(M) for gpipe, O(S) for 1f1b."""
    M, S = num_microbatches, num_stages
    if schedule == "gpipe":
        return M
    if schedule == "1f1b":
        return min(M, 2 * S - 1)
    raise ValueError(f"unknown schedule {schedule!r}")


def _pipeline_1f1b_local(stage_params, microbatches, targets, *,
                         stage_fn: Callable, loss_fn: Callable, axis: str,
                         batch_axes):
    """Per-device 1F1B train tick-loop (under shard_map).

    Returns (loss, param_grads) with loss replicated and grads in the
    size-1-leading-stage-axis layout shard_map expects back.
    """
    from k8s_tpu.parallel.collectives import ring_shift

    S = lax.axis_size(axis)
    s = lax.axis_index(axis)
    M = microbatches.shape[0]
    BUF = min(M, 2 * S - 1)
    params = jax.tree.map(lambda x: jnp.squeeze(x, 0), stage_params)
    inv_m = 1.0 / M

    def tick(carry, t):
        fwd_holding, bwd_holding, buf, gacc, loss_acc = carry

        # ---- forward stream: stage s computes microbatch m_f = t - s ----
        m_f = t - s
        fwd_live = jnp.logical_and(m_f >= 0, m_f < M)
        m_f_c = jnp.clip(m_f, 0, M - 1)
        x_in = jnp.where(s == 0, microbatches[m_f_c], fwd_holding)
        y = stage_fn(params, x_in)
        # stash this tick's stage input for the backward re-linearization
        buf = lax.cond(
            fwd_live,
            lambda b: lax.dynamic_update_index_in_dim(b, x_in, m_f_c % BUF, 0),
            lambda b: b,
            buf,
        )

        # ---- backward stream: stage s computes microbatch m_b ----
        m_b = t - (2 * S - 2) + s
        bwd_live = jnp.logical_and(m_b >= 0, m_b < M)
        m_b_c = jnp.clip(m_b, 0, M - 1)
        x_saved = buf[m_b_c % BUF]

        def stage_loss(p, x):
            out = stage_fn(p, x)
            mb_loss = loss_fn(out, targets[m_b_c])
            return out, mb_loss

        (out_b, mb_loss), vjp = jax.vjp(stage_loss, params, x_saved)
        # last stage seeds the cotangent from the loss; upstream stages use
        # the cotangent that just arrived from the next stage
        is_last = s == S - 1
        d_out = jnp.where(is_last, jnp.zeros_like(out_b), bwd_holding)
        d_loss = jnp.where(is_last, inv_m, 0.0).astype(mb_loss.dtype)
        dparams, dx = vjp((d_out, d_loss))

        live_f = fwd_live.astype(jnp.float32)
        live_b = bwd_live.astype(jnp.float32)
        gacc = jax.tree.map(
            lambda g, d: g + live_b * d.astype(g.dtype), gacc, dparams)
        loss_acc = loss_acc + live_b * jnp.where(is_last, inv_m, 0.0) * (
            mb_loss.astype(loss_acc.dtype))

        # rotate both streams: activations down-ring, cotangents up-ring
        fwd_holding = ring_shift(y * live_f.astype(y.dtype), axis)
        bwd_holding = ring_shift(dx * live_b.astype(dx.dtype), axis,
                                 reverse=True)
        return (fwd_holding, bwd_holding, buf, gacc, loss_acc), None

    zero_act = jnp.zeros_like(microbatches[0])
    buf0 = jnp.zeros((BUF,) + zero_act.shape, zero_act.dtype)
    gacc0 = jax.tree.map(lambda x: jnp.zeros_like(x, jnp.float32), params)
    carry0 = (zero_act, zero_act, buf0, gacc0, jnp.zeros((), jnp.float32))
    (_, _, _, gacc, loss_acc), _ = lax.scan(
        tick, carry0, jnp.arange(M + 2 * S - 2))

    # loss lives on the last stage only -> broadcast over pp, then average
    # the data-parallel shards of each microbatch
    loss = lax.psum(loss_acc, axis)
    loss = lax.pmean(loss, batch_axes)
    # param grads: data-sharded inputs mean the local grad covers this data
    # shard; average over the batch axes, restore stage axis for shard_map
    gacc = jax.tree.map(lambda g: lax.pmean(g, batch_axes), gacc)
    gacc = jax.tree.map(lambda g, p: g.astype(p.dtype)[None], gacc, stage_params)
    return loss, gacc


def pipeline_train_step_1f1b(mesh: Mesh, stage_fn: Callable, stage_params,
                             batch, targets, loss_fn: Callable, *,
                             num_microbatches: int, axis: str = "pp",
                             batch_axes=("dp", "fsdp")):
    """Loss + parameter gradients under the 1F1B schedule.

    stage_fn(params, x) -> y: one homogeneous stage.
    loss_fn(out_mb, target_mb) -> scalar: per-microbatch loss; the total is
      the mean over microbatches (the decomposition 1F1B requires).
    batch/targets: [B, ...] global, B divisible by num_microbatches.
    Returns (loss, grads) with grads matching stage_params' stacked layout.
    """
    B = batch.shape[0]
    if B % num_microbatches:
        raise ValueError(f"batch {B} not divisible into {num_microbatches} microbatches")
    mb = B // num_microbatches
    axes = batch_axes if isinstance(batch_axes, (tuple, list)) else (batch_axes,)
    data_shards = 1
    for a in axes:
        data_shards *= mesh.shape[a]
    if mb % data_shards:
        raise ValueError(
            f"microbatch size {mb} not divisible by data shards {data_shards} "
            f"(axes {batch_axes}); use fewer microbatches or a bigger batch")
    micro = batch.reshape((num_microbatches, mb) + batch.shape[1:])
    tmicro = targets.reshape((num_microbatches, mb) + targets.shape[1:])

    param_specs = jax.tree.map(lambda _: P(axis), stage_params)
    mspec = P(None, tuple(axes))

    fn = shard_map(
        partial(_pipeline_1f1b_local, stage_fn=stage_fn, loss_fn=loss_fn,
                axis=axis, batch_axes=tuple(axes)),
        mesh=mesh,
        in_specs=(param_specs, mspec, mspec),
        out_specs=(P(), param_specs),
        check_vma=False,
    )
    return fn(stage_params, micro, tmicro)


def stage_sharding(mesh: Mesh, stage_params, axis: str = "pp"):
    """NamedShardings placing each stage's params on its pp rank."""
    return jax.tree.map(
        lambda _: NamedSharding(mesh, P(axis)), stage_params)

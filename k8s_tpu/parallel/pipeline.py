"""Pipeline parallelism over the ``pp`` mesh axis (GPipe-style microbatch
schedule via shard_map + ppermute).

Absent from the reference (SURVEY.md §2.4: its only axes were PS-vs-worker
data parallelism); in the TPU-native design pipeline stages are a mesh-axis
choice like every other form of parallelism.

Mechanics: the network is split into ``S = |pp|`` homogeneous stages; each
device along ``pp`` holds one stage's parameters (stack stage params on a
leading axis sharded ``P("pp", ...)``).  A batch is split into ``M``
microbatches.  The schedule runs ``M + S - 1`` ticks; on every tick each
stage applies its layer to the microbatch it currently holds, then the
activations rotate one step along the ring (``lax.ppermute``).  Stage 0
feeds fresh microbatches for the first ``M`` ticks; the last stage emits
finished microbatches from tick ``S-1`` on.  The bubble is the standard
GPipe ``(S-1)/(M+S-1)`` fraction — pick ``M >> S``.

Everything is differentiable: ppermute's transpose is the reverse permute,
so ``jax.grad`` through a pipelined forward produces the 1B backward
schedule automatically.

Outputs land on the last stage; a masked psum broadcasts them to every
device (also differentiable), so the loss can be computed uniformly.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map


def _act_template(pre_fn, pre_params, mb0):
    """Shape/dtype of one microbatch's ring activation (what flows between
    stages): pre_fn's output when the input end is heterogeneous, the raw
    microbatch otherwise."""
    if pre_fn is None:
        return jax.eval_shape(lambda m: m, mb0)
    return jax.eval_shape(pre_fn, pre_params, mb0)


def _make_ingest(pre_fn, microbatches):
    """First-stage input selection, shared by forward and re-linearization
    in both the plain and interleaved schedules.

    Returns ingest(pre_p, idx, x_ring, is_first): the stage input for
    microbatch ``idx`` — pre_fn applied to the raw microbatch when
    ``is_first`` (non-interleaved: s == 0; interleaved: device 0 on its
    chunk-0 ticks), under a lax.cond so only that rank pays for it; the
    ring activation otherwise.
    """
    if pre_fn is None:
        return lambda _pre_p, idx, x_ring, is_first: jnp.where(
            is_first, microbatches[idx], x_ring)

    def ingest(pre_p, idx, x_ring, is_first):
        return lax.cond(
            is_first,
            lambda: pre_fn(pre_p, microbatches[idx]).astype(x_ring.dtype),
            lambda: x_ring,
        )

    return ingest


def _pipeline_local(stage_params, pre_params, post_params, microbatches, *,
                    stage_fn: Callable, pre_fn, post_fn, axis: str):
    """Per-device schedule body (under shard_map).

    stage_params: this stage's params (leading stage axis already sliced to
      size 1 by shard_map; squeezed here).
    microbatches: [M, mb, ...] — replicated input; only stage 0 reads it.
    pre_fn/post_fn: optional heterogeneous ends — stage 0 maps the raw
      microbatch into ring-activation space (e.g. an embedding lookup), the
      last stage maps its activation into output space (e.g. an LM head).
    Returns [M, mb, ...] finished outputs (valid on the last stage, zeros
    elsewhere).
    """
    from k8s_tpu.parallel.collectives import ring_shift

    S = lax.axis_size(axis)
    s = lax.axis_index(axis)
    M = microbatches.shape[0]
    params = jax.tree.map(lambda x: jnp.squeeze(x, 0), stage_params)
    ingest = _make_ingest(pre_fn, microbatches)

    act = _act_template(pre_fn, pre_params, microbatches[0])
    if post_fn is None:
        out_t = act
    else:
        out_t = jax.eval_shape(post_fn, post_params,
                               jnp.zeros(act.shape, act.dtype))

    def tick(carry, t):
        holding, outputs = carry
        # stage 0 ingests microbatch t (while t < M); others use what they
        # received last tick
        x = ingest(pre_params, jnp.minimum(t, M - 1), holding, s == 0)
        y = stage_fn(params, x)
        # the last stage's result at tick t is finished microbatch t-(S-1)
        out_idx = t - (S - 1)
        is_done = jnp.logical_and(s == S - 1, out_idx >= 0)

        def emit(o):
            out = y if post_fn is None else post_fn(post_params, y)
            return lax.dynamic_update_index_in_dim(
                o, out.astype(o.dtype), jnp.maximum(out_idx, 0), 0)

        # post_fn (an LM head is a double-digit share of forward FLOPs)
        # runs only on the last stage's emitting ticks, via the cond
        outputs = lax.cond(is_done, emit, lambda o: o, outputs)
        # ring: stage i sends to i+1; last stage's wrap to 0 is discarded
        holding = ring_shift(y, axis)
        return (holding, outputs), None

    holding0 = jnp.zeros(act.shape, act.dtype)
    outputs0 = jnp.zeros((M,) + out_t.shape, out_t.dtype)
    (_, outputs), _ = lax.scan(
        tick, (holding0, outputs0), jnp.arange(M + S - 1))

    # make outputs visible everywhere: only the last stage holds non-zero
    # data, so a psum over the axis broadcasts it (differentiable)
    mask = jnp.where(s == S - 1, 1.0, 0.0).astype(outputs.dtype)
    return lax.psum(outputs * mask, axis)


def _check_microbatching(mesh, batch, num_microbatches, batch_axes):
    B = batch.shape[0]
    if B % num_microbatches:
        raise ValueError(f"batch {B} not divisible into {num_microbatches} microbatches")
    mb = B // num_microbatches
    axes = batch_axes if isinstance(batch_axes, (tuple, list)) else (batch_axes,)
    data_shards = 1
    for a in axes:
        data_shards *= mesh.shape[a]
    if mb % data_shards:
        raise ValueError(
            f"microbatch size {mb} not divisible by data shards {data_shards} "
            f"(axes {batch_axes}); use fewer microbatches or a bigger batch")
    return mb, tuple(axes)


def pipeline_apply(mesh: Mesh, stage_fn: Callable, stage_params, batch, *,
                   num_microbatches: int, axis: str = "pp",
                   batch_axes=("dp", "fsdp"),
                   pre_fn: Callable | None = None, pre_params=None,
                   post_fn: Callable | None = None, post_params=None):
    """Run ``batch`` through the pipeline.

    stage_fn(params, x) -> y: one stage's computation, same activation shape
      in and out (homogeneous ring body).
    stage_params: pytree with leading stage axis of size ``|pp|``.
    batch: [B, ...] global; B must divide into num_microbatches.
    pre_fn(pre_params, mb) -> x / post_fn(post_params, y) -> out: optional
      heterogeneous input/output stages (embedding in, LM head out) run on
      the first/last pp rank only; their params are replicated over pp.
    Returns [B, ...] outputs (post_fn's output space when given).
    """
    mb, axes = _check_microbatching(mesh, batch, num_microbatches, batch_axes)
    micro = batch.reshape((num_microbatches, mb) + batch.shape[1:])

    if pre_params is None:
        pre_params = ()
    if post_params is None:
        post_params = ()
    param_specs = jax.tree.map(lambda _: P(axis), stage_params)
    end_specs = lambda tree: jax.tree.map(lambda _: P(), tree)  # noqa: E731
    # microbatch data stays sharded over the data axes; every pp rank sees
    # its slice of each microbatch
    mspec = P(None, axes)

    fn = shard_map(
        partial(_pipeline_local, stage_fn=stage_fn, pre_fn=pre_fn,
                post_fn=post_fn, axis=axis),
        mesh=mesh,
        in_specs=(param_specs, end_specs(pre_params), end_specs(post_params),
                  mspec),
        out_specs=mspec,
        check_vma=False,
    )
    out = fn(stage_params, pre_params, post_params, micro)
    return out.reshape((out.shape[0] * out.shape[1],) + out.shape[2:])


def stack_stage_params(params_list):
    """Stack per-stage param pytrees into the leading-stage-axis layout
    pipeline_apply expects, e.g. from S separately-initialized stages."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, 0), *params_list)


# ---------------------------------------------------------------------------
# 1F1B schedule
# ---------------------------------------------------------------------------
#
# GPipe above runs all M forwards, then jax.grad's reverse scan runs all M
# backwards — every stage must keep (or remat from) M microbatches of
# activations.  The 1F1B (one-forward-one-backward) schedule interleaves:
# the last stage starts microbatch m's backward the moment its forward
# finishes, so at any instant a stage has at most O(S) microbatches in
# flight regardless of M.  Non-interleaved 1F1B has the SAME bubble fraction
# as GPipe — (S-1)/(M+S-1) — its win is peak activation memory O(S) vs O(M)
# (see bubble_fraction / peak_activation_microbatches below, asserted in
# tests/test_pipeline.py).
#
# SPMD formulation: one lax.scan over ticks; per tick every device does one
# forward compute (activations ppermute down-ring) AND one backward compute
# (cotangents ppermute up-ring), with index masks selecting which microbatch
# each stream is on.  Timeline (stage s, microbatch m):
#   forward  of m at tick m + s
#   backward of m at tick m + 2S - 2 - s   (last stage: same tick as fwd)
# Total ticks: M + 2S - 2.  Residuals: each stage stores only the stage
# *input* x_m in a circular buffer of min(M, 2S-1) slots and re-linearizes
# (jax.vjp) at backward time — rematerialization, the standard TPU
# HBM-for-FLOPs trade.
#
# The loss must decompose over microbatches (loss = 1/M sum loss_mb), which
# lets the last stage emit dL/dout_m immediately; LM/cross-entropy losses
# all have this shape.


def bubble_fraction(schedule: str, num_microbatches: int, num_stages: int,
                    num_virtual: int = 1) -> float:
    """Fraction of stage-time idle.

    gpipe and non-interleaved 1f1b are identical: (S-1)/(M+S-1).
    interleaved 1f1b with v virtual stages per device cuts the fill/drain
    to (S-1)/(v*M + S-1) — each device's work grows v-fold (v chunk
    computes per microbatch) while the pipeline fill stays S-1 ticks.
    """
    M, S, v = num_microbatches, num_stages, num_virtual
    if schedule in ("gpipe", "1f1b"):
        return (S - 1) / (M + S - 1)
    if schedule == "interleaved":
        return (S - 1) / (v * M + S - 1)
    raise ValueError(f"unknown schedule {schedule!r}")


def _interleaved_base(m: int, S: int, v: int) -> int:
    """Tick at which microbatch m's first chunk is computed: microbatches
    run in groups of S; group g starts at tick g*S*v (the device needs S*v
    ticks to push a group through its v chunks)."""
    return (m // S) * S * v + (m % S)


def _simulate_interleaved(M: int, S: int, v: int) -> tuple[int, int]:
    """Exact trace-time accounting of the interleaved schedule.

    Chunk c of microbatch m runs forward at tick base(m)+c and backward at
    tick base(m)+2(C-1)-c (C = S*v chunks).  Returns (buf_slots,
    peak_total): the per-chunk circular-buffer depth the kernel needs (max
    in-flight residuals of any single chunk — the in-flight set of a chunk
    is a contiguous m-interval, so `m mod buf_slots` indexing is
    collision-free), and the peak total residuals a device holds across its
    v chunks (the memory figure peak_activation_microbatches reports).
    """
    C = S * v
    ticks = M * v + 2 * C + S + 2
    bases = [_interleaved_base(m, S, v) for m in range(M)]

    def peak_of(chunks: list[int]) -> int:
        # difference-array sweep: O(M·|chunks| + ticks), not a full
        # per-tick scan (this runs at trace time on every step build)
        delta = [0] * (ticks + 1)
        for c in chunks:
            for base in bases:
                delta[base + c] += 1          # fwd tick, inclusive
                delta[base + 2 * (C - 1) - c + 1] -= 1  # past bwd tick
        peak = cur = 0
        for x in delta:
            cur += x
            peak = max(peak, cur)
        return peak

    per_chunk_peak = max(peak_of([c]) for c in range(C))
    device_peak = max(
        peak_of([q * S + d for q in range(v)]) for d in range(S))
    return per_chunk_peak, device_peak


def peak_activation_microbatches(schedule: str, num_microbatches: int,
                                 num_stages: int, num_virtual: int = 1) -> int:
    """Peak in-flight microbatch residuals a stage must hold — the metric
    1f1b exists to bound: O(M) for gpipe, O(S) for 1f1b.  Interleaving
    trades some of that memory back (plus v× the comm volume) for the
    smaller bubble; its peak is computed exactly from the schedule."""
    M, S, v = num_microbatches, num_stages, num_virtual
    if schedule == "gpipe":
        return M
    if schedule == "1f1b":
        return min(M, 2 * S - 1)
    if schedule == "interleaved":
        return _simulate_interleaved(M, S, v)[1]
    raise ValueError(f"unknown schedule {schedule!r}")


def _pipeline_1f1b_local(stage_params, pre_params, post_params,
                         microbatches, targets, *,
                         stage_fn: Callable, loss_fn, pre_fn, post_fn,
                         axis: str, batch_axes):
    """Per-device 1F1B train tick-loop (under shard_map).

    Returns (loss, (stage_grads, pre_grads, post_grads)) with loss
    replicated, stage grads in the size-1-leading-stage-axis layout
    shard_map expects back, and end-stage grads psum'd over pp (stage 0 /
    the last stage are the only contributors).

    The per-microbatch loss is loss_fn(y, target) applied to the ring
    output when the output end is homogeneous, or
    post_fn(post_params, y, target) when heterogeneous (e.g. final norm +
    LM head + cross entropy); either way the total loss is the mean over
    microbatches — the decomposition 1F1B requires.
    """
    from k8s_tpu.parallel.collectives import ring_shift

    S = lax.axis_size(axis)
    s = lax.axis_index(axis)
    M = microbatches.shape[0]
    BUF = min(M, 2 * S - 1)
    params = jax.tree.map(lambda x: jnp.squeeze(x, 0), stage_params)
    inv_m = 1.0 / M
    ingest = _make_ingest(pre_fn, microbatches)
    act = _act_template(pre_fn, pre_params, microbatches[0])

    def tick(carry, t):
        fwd_holding, bwd_holding, buf, gacc, pre_gacc, post_gacc, loss_acc = carry

        # ---- forward stream: stage s computes microbatch m_f = t - s ----
        m_f = t - s
        fwd_live = jnp.logical_and(m_f >= 0, m_f < M)
        m_f_c = jnp.clip(m_f, 0, M - 1)
        x_in = ingest(pre_params, m_f_c, fwd_holding, s == 0)
        y = stage_fn(params, x_in)
        # stash this tick's RING input for the backward re-linearization
        # (pre-ingest: stage 0's backward re-applies pre_fn from the raw
        # microbatch so its cotangents reach pre_params)
        buf = lax.cond(
            fwd_live,
            lambda b: lax.dynamic_update_index_in_dim(
                b, fwd_holding, m_f_c % BUF, 0),
            lambda b: b,
            buf,
        )

        # ---- backward stream: stage s computes microbatch m_b ----
        m_b = t - (2 * S - 2) + s
        bwd_live = jnp.logical_and(m_b >= 0, m_b < M)
        m_b_c = jnp.clip(m_b, 0, M - 1)
        x_saved = buf[m_b_c % BUF]

        def stage_loss(p, pre_p, post_p, x):
            h = ingest(pre_p, m_b_c, x, s == 0)
            out = stage_fn(p, h)
            if post_fn is None:
                mb_loss = loss_fn(out, targets[m_b_c])
            else:
                # the loss head (norm + vocab projection) runs only on the
                # last stage, via the cond
                mb_loss = lax.cond(
                    s == S - 1,
                    lambda: post_fn(post_p, out, targets[m_b_c])
                    .astype(jnp.float32),
                    lambda: jnp.zeros((), jnp.float32),
                )
            return out, mb_loss

        (out_b, mb_loss), vjp = jax.vjp(
            stage_loss, params, pre_params, post_params, x_saved)
        # last stage seeds the cotangent from the loss; upstream stages use
        # the cotangent that just arrived from the next stage
        is_last = s == S - 1
        d_out = jnp.where(is_last, jnp.zeros_like(out_b), bwd_holding)
        d_loss = jnp.where(is_last, inv_m, 0.0).astype(mb_loss.dtype)
        dparams, dpre, dpost, dx = vjp((d_out, d_loss))

        live_f = fwd_live.astype(jnp.float32)
        live_b = bwd_live.astype(jnp.float32)
        acc = lambda g, d: g + live_b * d.astype(g.dtype)  # noqa: E731
        gacc = jax.tree.map(acc, gacc, dparams)
        pre_gacc = jax.tree.map(acc, pre_gacc, dpre)
        post_gacc = jax.tree.map(acc, post_gacc, dpost)
        loss_acc = loss_acc + live_b * jnp.where(is_last, inv_m, 0.0) * (
            mb_loss.astype(loss_acc.dtype))

        # rotate both streams: activations down-ring, cotangents up-ring
        fwd_holding = ring_shift(y * live_f.astype(y.dtype), axis)
        bwd_holding = ring_shift(dx * live_b.astype(dx.dtype), axis,
                                 reverse=True)
        return (fwd_holding, bwd_holding, buf, gacc, pre_gacc, post_gacc,
                loss_acc), None

    zero_act = jnp.zeros(act.shape, act.dtype)
    buf0 = jnp.zeros((BUF,) + zero_act.shape, zero_act.dtype)
    f32_zeros = lambda tree: jax.tree.map(  # noqa: E731
        lambda x: jnp.zeros_like(x, jnp.float32), tree)
    carry0 = (zero_act, zero_act, buf0, f32_zeros(params),
              f32_zeros(pre_params), f32_zeros(post_params),
              jnp.zeros((), jnp.float32))
    (_, _, _, gacc, pre_gacc, post_gacc, loss_acc), _ = lax.scan(
        tick, carry0, jnp.arange(M + 2 * S - 2))

    # loss lives on the last stage only -> broadcast over pp, then average
    # the data-parallel shards of each microbatch
    loss = lax.psum(loss_acc, axis)
    loss = lax.pmean(loss, batch_axes)
    # param grads: data-sharded inputs mean the local grad covers this data
    # shard; average over the batch axes, restore stage axis for shard_map
    gacc = jax.tree.map(lambda g: lax.pmean(g, batch_axes), gacc)
    gacc = jax.tree.map(lambda g, p: g.astype(p.dtype)[None], gacc, stage_params)
    # end-stage grads: only stage 0 (pre) / the last stage (post)
    # contributed non-zeros; psum over pp replicates the true value
    end = lambda tree, ref: jax.tree.map(  # noqa: E731
        lambda g, p: lax.pmean(lax.psum(g, axis), batch_axes).astype(p.dtype),
        tree, ref)
    return loss, (gacc, end(pre_gacc, pre_params), end(post_gacc, post_params))


def pipeline_train_step_1f1b(mesh: Mesh, stage_fn: Callable, stage_params,
                             batch, targets, loss_fn: Callable = None, *,
                             num_microbatches: int, axis: str = "pp",
                             batch_axes=("dp", "fsdp"),
                             pre_fn: Callable | None = None, pre_params=None,
                             post_fn: Callable | None = None, post_params=None):
    """Loss + parameter gradients under the 1F1B schedule.

    stage_fn(params, x) -> y: one homogeneous ring stage.
    loss_fn(out_mb, target_mb) -> scalar: per-microbatch loss; the total is
      the mean over microbatches (the decomposition 1F1B requires).
    batch/targets: [B, ...] global, B divisible by num_microbatches.
    pre_fn(pre_params, mb) -> x: optional heterogeneous input stage
      (embedding lookup) run on pp rank 0 only.
    post_fn(post_params, y, target_mb) -> scalar: optional heterogeneous
      loss head (final norm + LM head + loss) run on the last rank only;
      replaces loss_fn.
    Returns (loss, grads): grads matches stage_params' stacked layout when
    no end stages are given, else (stage_grads, pre_grads, post_grads).
    """
    if (loss_fn is None) == (post_fn is None):
        raise ValueError("exactly one of loss_fn / post_fn must be given")
    mb, axes = _check_microbatching(mesh, batch, num_microbatches, batch_axes)
    micro = batch.reshape((num_microbatches, mb) + batch.shape[1:])
    tmicro = targets.reshape((num_microbatches, mb) + targets.shape[1:])

    hetero = pre_fn is not None or post_fn is not None
    if pre_params is None:
        pre_params = ()
    if post_params is None:
        post_params = ()
    param_specs = jax.tree.map(lambda _: P(axis), stage_params)
    end_specs = lambda tree: jax.tree.map(lambda _: P(), tree)  # noqa: E731
    mspec = P(None, axes)

    fn = shard_map(
        partial(_pipeline_1f1b_local, stage_fn=stage_fn, loss_fn=loss_fn,
                pre_fn=pre_fn, post_fn=post_fn, axis=axis, batch_axes=axes),
        mesh=mesh,
        in_specs=(param_specs, end_specs(pre_params), end_specs(post_params),
                  mspec, mspec),
        out_specs=(P(), (param_specs, end_specs(pre_params),
                         end_specs(post_params))),
        check_vma=False,
    )
    loss, (g_stage, g_pre, g_post) = fn(
        stage_params, pre_params, post_params, micro, tmicro)
    if not hetero:
        return loss, g_stage
    return loss, (g_stage, g_pre, g_post)


def _pipeline_interleaved_local(chunk_params, pre_params, post_params,
                                microbatches, targets, *,
                                stage_fn: Callable, loss_fn, pre_fn, post_fn,
                                S: int, v: int, buf_slots: int,
                                axis: str, batch_axes):
    """Per-device interleaved-1F1B tick loop (under shard_map).

    Device d holds chunks {d, S+d, ..., (v-1)S+d} of the C = S*v-deep
    virtual pipeline (sliced to local leading axis v, device-major order —
    the wrapper pre-permutes).  Because chunk c lives on device c mod S,
    every chunk→chunk handoff is one down-ring hop, so the dataflow is the
    same two counter-rotating ppermute rings as non-interleaved 1F1B; only
    the tick→(microbatch, chunk) maps change:

      forward  of (m, c) at tick base(m) + c
      backward of (m, c) at tick base(m) + 2(C-1) - c
      base(m) = (m//S)*S*v + m%S     (microbatches ingested in groups of S)

    Both maps are, per device, bijections over ticks (each device does at
    most one forward and one backward chunk-compute per tick), and both
    handoffs always take exactly one tick — the schedule property that
    makes the (S-1)/(vM+S-1) bubble claim real.
    """
    from k8s_tpu.parallel.collectives import ring_shift

    d = lax.axis_index(axis)
    M = microbatches.shape[0]
    C = S * v
    Sv = S * v
    inv_m = 1.0 / M
    act = _act_template(pre_fn, pre_params, microbatches[0])
    ingest = _make_ingest(pre_fn, microbatches)

    def chunk(cp, q):
        return jax.tree.map(
            lambda x: lax.dynamic_index_in_dim(x, q, 0, keepdims=False), cp)

    def tick(carry, t):
        fwd_holding, bwd_holding, buf, gacc, pre_gacc, post_gacc, loss_acc = carry

        # ---- forward: invert u = t - d = g*Sv + q*S + j ----
        u = t - d
        fwd_live = jnp.logical_and(u >= 0, u < M * v)
        uc = jnp.clip(u, 0, M * v - 1)
        q_f = (uc % Sv) // S
        m_f = (uc // Sv) * S + uc % S
        is_chunk0_f = jnp.logical_and(d == 0, q_f == 0)

        x_ring = fwd_holding
        x_in = ingest(pre_params, m_f, x_ring, is_chunk0_f)
        y = stage_fn(chunk(chunk_params, q_f), x_in)
        # store the RING input (pre-ingest) for backward re-linearization
        buf = lax.cond(
            fwd_live,
            lambda b: b.at[q_f, m_f % buf_slots].set(x_ring),
            lambda b: b,
            buf,
        )

        # ---- backward: invert r + (v-1)S = g*Sv + (v-1-q)*S + j ----
        rv = t - 2 * (C - 1) + d + (v - 1) * S
        bwd_live = jnp.logical_and(rv >= 0, rv < M * v)
        rvc = jnp.clip(rv, 0, M * v - 1)
        q_b = v - 1 - (rvc % Sv) // S
        m_b = (rvc // Sv) * S + rvc % S
        is_chunk0_b = jnp.logical_and(d == 0, q_b == 0)
        is_last_b = jnp.logical_and(d == S - 1, q_b == v - 1)
        x_saved = buf[q_b, m_b % buf_slots]

        def chunk_loss(cp, pre_p, post_p, x):
            h = ingest(pre_p, m_b, x, is_chunk0_b)
            out = stage_fn(chunk(cp, q_b), h)
            if post_fn is None:
                mb_loss = loss_fn(out, targets[m_b]).astype(jnp.float32)
            else:
                mb_loss = lax.cond(
                    is_last_b,
                    lambda: post_fn(post_p, out, targets[m_b])
                    .astype(jnp.float32),
                    lambda: jnp.zeros((), jnp.float32),
                )
            return out, mb_loss

        (out_b, mb_loss), vjp = jax.vjp(
            chunk_loss, chunk_params, pre_params, post_params, x_saved)
        d_out = jnp.where(is_last_b, jnp.zeros_like(out_b), bwd_holding)
        d_loss = jnp.where(is_last_b, inv_m, 0.0).astype(mb_loss.dtype)
        dchunks, dpre, dpost, dx = vjp((d_out, d_loss))

        live_f = fwd_live.astype(jnp.float32)
        live_b = bwd_live.astype(jnp.float32)
        acc = lambda g, dd: g + live_b * dd.astype(g.dtype)  # noqa: E731
        # dchunks already has the full [v, ...] leading axis (the vjp saw
        # the dynamic_index), zero except chunk q_b
        gacc = jax.tree.map(acc, gacc, dchunks)
        pre_gacc = jax.tree.map(acc, pre_gacc, dpre)
        post_gacc = jax.tree.map(acc, post_gacc, dpost)
        loss_acc = loss_acc + live_b * jnp.where(is_last_b, inv_m, 0.0) * (
            mb_loss.astype(loss_acc.dtype))

        fwd_holding = ring_shift(y * live_f.astype(y.dtype), axis)
        bwd_holding = ring_shift(dx * live_b.astype(dx.dtype), axis,
                                 reverse=True)
        return (fwd_holding, bwd_holding, buf, gacc, pre_gacc, post_gacc,
                loss_acc), None

    zero_act = jnp.zeros(act.shape, act.dtype)
    buf0 = jnp.zeros((v, buf_slots) + zero_act.shape, zero_act.dtype)
    f32_zeros = lambda tree: jax.tree.map(  # noqa: E731
        lambda x: jnp.zeros_like(x, jnp.float32), tree)
    carry0 = (zero_act, zero_act, buf0, f32_zeros(chunk_params),
              f32_zeros(pre_params), f32_zeros(post_params),
              jnp.zeros((), jnp.float32))
    total_ticks = M * v + Sv + S - 2
    (_, _, _, gacc, pre_gacc, post_gacc, loss_acc), _ = lax.scan(
        tick, carry0, jnp.arange(total_ticks))

    loss = lax.pmean(lax.psum(loss_acc, axis), batch_axes)
    gacc = jax.tree.map(lambda g: lax.pmean(g, batch_axes), gacc)
    gacc = jax.tree.map(lambda g, p: g.astype(p.dtype), gacc, chunk_params)
    end = lambda tree, ref: jax.tree.map(  # noqa: E731
        lambda g, p: lax.pmean(lax.psum(g, axis), batch_axes).astype(p.dtype),
        tree, ref)
    return loss, (gacc, end(pre_gacc, pre_params), end(post_gacc, post_params))


def pipeline_train_step_interleaved(
        mesh: Mesh, stage_fn: Callable, chunk_params, batch, targets,
        loss_fn: Callable = None, *, num_microbatches: int, num_virtual: int,
        axis: str = "pp", batch_axes=("dp", "fsdp"),
        pre_fn: Callable | None = None, pre_params=None,
        post_fn: Callable | None = None, post_params=None,
        device_major: bool = False):
    """Loss + gradients under the interleaved 1F1B schedule.

    chunk_params: pytree with leading axis C = |pp| * num_virtual, in
      natural chunk order (chunk c is the c-th slice of the model); chunk c
      is placed on device c mod |pp| (the round-robin layout that shrinks
      the bubble to (S-1)/(vM+S-1) at the cost of v× the ring traffic).
    num_microbatches must be a multiple of |pp| (microbatches are ingested
      in groups of S).
    loss_fn / pre_fn / post_fn: as in pipeline_train_step_1f1b.
    device_major: chunk_params (and the returned grads) are already in the
      round-robin device-major layout (interleave_chunks).  Long-lived
      train states should use this: natural order under a P(axis) sharding
      makes every step re-gather (v-1)/v of the weights across the ring.
    Returns (loss, grads) — grads in chunk order matching chunk_params when
    homogeneous, else (chunk_grads, pre_grads, post_grads).
    """
    if (loss_fn is None) == (post_fn is None):
        raise ValueError("exactly one of loss_fn / post_fn must be given")
    S = mesh.shape[axis]
    v = num_virtual
    if v < 1:
        raise ValueError(f"num_virtual must be >= 1, got {v}")
    if num_microbatches % S:
        raise ValueError(
            f"interleaved schedule ingests microbatches in groups of "
            f"{S} (=|{axis}|); {num_microbatches} is not a multiple")
    leading = {x.shape[0] for x in jax.tree.leaves(chunk_params)}
    if leading != {S * v}:
        raise ValueError(
            f"chunk_params leading axis must be S*v={S * v}, got {leading}")
    mb, axes = _check_microbatching(mesh, batch, num_microbatches, batch_axes)
    micro = batch.reshape((num_microbatches, mb) + batch.shape[1:])
    tmicro = targets.reshape((num_microbatches, mb) + targets.shape[1:])

    buf_slots, _ = _simulate_interleaved(num_microbatches, S, v)

    if device_major:
        permuted = chunk_params
    else:
        # device-major permutation: device d's contiguous shard_map slice
        # [d*v:(d+1)*v] must hold chunks d, S+d, ..., (v-1)S+d
        permuted = interleave_chunks(chunk_params, S, v)

    hetero = pre_fn is not None or post_fn is not None
    if pre_params is None:
        pre_params = ()
    if post_params is None:
        post_params = ()
    param_specs = jax.tree.map(lambda _: P(axis), permuted)
    end_specs = lambda tree: jax.tree.map(lambda _: P(), tree)  # noqa: E731
    mspec = P(None, axes)

    fn = shard_map(
        partial(_pipeline_interleaved_local, stage_fn=stage_fn,
                loss_fn=loss_fn, pre_fn=pre_fn, post_fn=post_fn,
                S=S, v=v, buf_slots=buf_slots, axis=axis, batch_axes=axes),
        mesh=mesh,
        in_specs=(param_specs, end_specs(pre_params), end_specs(post_params),
                  mspec, mspec),
        out_specs=(P(), (param_specs, end_specs(pre_params),
                         end_specs(post_params))),
        check_vma=False,
    )
    loss, (g_chunks, g_pre, g_post) = fn(
        permuted, pre_params, post_params, micro, tmicro)
    if not device_major:
        g_chunks = interleave_chunks(g_chunks, S, v, inverse=True)
    if not hetero:
        return loss, g_chunks
    return loss, (g_chunks, g_pre, g_post)


def interleave_chunks(chunk_params, num_stages: int, num_virtual: int,
                      inverse: bool = False):
    """Natural chunk order <-> device-major round-robin layout (chunk c on
    device c mod S): the layout a long-lived interleaved train state should
    be stored in so the step's P(axis) slicing needs no per-step gather."""
    import numpy as np

    S, v = num_stages, num_virtual
    perm = np.array([q * S + d for d in range(S) for q in range(v)])
    if inverse:
        perm = np.argsort(perm)
    return jax.tree.map(lambda x: x[perm], chunk_params)


def stage_sharding(mesh: Mesh, stage_params, axis: str = "pp"):
    """NamedShardings placing each stage's params on its pp rank."""
    return jax.tree.map(
        lambda _: NamedSharding(mesh, P(axis)), stage_params)

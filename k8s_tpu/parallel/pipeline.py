"""Pipeline parallelism over the ``pp`` mesh axis (GPipe-style microbatch
schedule via shard_map + ppermute).

Absent from the reference (SURVEY.md §2.4: its only axes were PS-vs-worker
data parallelism); in the TPU-native design pipeline stages are a mesh-axis
choice like every other form of parallelism.

Mechanics: the network is split into ``S = |pp|`` homogeneous stages; each
device along ``pp`` holds one stage's parameters (stack stage params on a
leading axis sharded ``P("pp", ...)``).  A batch is split into ``M``
microbatches.  The schedule runs ``M + S - 1`` ticks; on every tick each
stage applies its layer to the microbatch it currently holds, then the
activations rotate one step along the ring (``lax.ppermute``).  Stage 0
feeds fresh microbatches for the first ``M`` ticks; the last stage emits
finished microbatches from tick ``S-1`` on.  The bubble is the standard
GPipe ``(S-1)/(M+S-1)`` fraction — pick ``M >> S``.

Everything is differentiable: ppermute's transpose is the reverse permute,
so ``jax.grad`` through a pipelined forward produces the 1B backward
schedule automatically.

Outputs land on the last stage; a masked psum broadcasts them to every
device (also differentiable), so the loss can be computed uniformly.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map


def _pipeline_local(stage_params, microbatches, *, stage_fn: Callable,
                    axis: str):
    """Per-device schedule body (under shard_map).

    stage_params: this stage's params (leading stage axis already sliced to
      size 1 by shard_map; squeezed here).
    microbatches: [M, mb, ...] — replicated input; only stage 0 reads it.
    Returns [M, mb, ...] finished outputs (valid on the last stage, zeros
    elsewhere).
    """
    S = lax.axis_size(axis)
    s = lax.axis_index(axis)
    M = microbatches.shape[0]
    params = jax.tree.map(lambda x: jnp.squeeze(x, 0), stage_params)

    # ring: stage i sends to i+1; last stage's send wraps to 0 (discarded)
    perm = [(i, (i + 1) % S) for i in range(S)]

    def tick(carry, t):
        holding, outputs = carry
        # stage 0 ingests microbatch t (while t < M); others use what they
        # received last tick
        mb_in = microbatches[jnp.minimum(t, M - 1)]
        x = jnp.where(s == 0, mb_in, holding)
        y = stage_fn(params, x)
        # the last stage's result at tick t is finished microbatch t-(S-1)
        out_idx = t - (S - 1)
        is_done = jnp.logical_and(s == S - 1, out_idx >= 0)
        outputs = lax.cond(
            is_done,
            lambda o: lax.dynamic_update_index_in_dim(
                o, y, jnp.maximum(out_idx, 0), 0),
            lambda o: o,
            outputs,
        )
        holding = lax.ppermute(y, axis, perm)
        return (holding, outputs), None

    holding0 = jnp.zeros_like(microbatches[0])
    outputs0 = jnp.zeros_like(microbatches)
    (_, outputs), _ = lax.scan(
        tick, (holding0, outputs0), jnp.arange(M + S - 1))

    # make outputs visible everywhere: only the last stage holds non-zero
    # data, so a psum over the axis broadcasts it (differentiable)
    mask = jnp.where(s == S - 1, 1.0, 0.0).astype(outputs.dtype)
    return lax.psum(outputs * mask, axis)


def pipeline_apply(mesh: Mesh, stage_fn: Callable, stage_params, batch, *,
                   num_microbatches: int, axis: str = "pp",
                   batch_axes=("dp", "fsdp")):
    """Run ``batch`` through the pipeline.

    stage_fn(params, x) -> y: one stage's computation, same activation shape
      in and out (homogeneous stages).
    stage_params: pytree with leading stage axis of size ``|pp|``.
    batch: [B, ...] global; B must divide into num_microbatches.
    Returns [B, ...] outputs.
    """
    B = batch.shape[0]
    if B % num_microbatches:
        raise ValueError(f"batch {B} not divisible into {num_microbatches} microbatches")
    mb = B // num_microbatches
    data_shards = 1
    for a in (batch_axes if isinstance(batch_axes, (tuple, list)) else (batch_axes,)):
        data_shards *= mesh.shape[a]
    if mb % data_shards:
        raise ValueError(
            f"microbatch size {mb} not divisible by data shards {data_shards} "
            f"(axes {batch_axes}); use fewer microbatches or a bigger batch")
    micro = batch.reshape((num_microbatches, mb) + batch.shape[1:])

    param_specs = jax.tree.map(lambda _: P(axis), stage_params)
    # microbatch data stays sharded over the data axes; every pp rank sees
    # its slice of each microbatch
    mspec = P(None, batch_axes)

    fn = shard_map(
        partial(_pipeline_local, stage_fn=stage_fn, axis=axis),
        mesh=mesh,
        in_specs=(param_specs, mspec),
        out_specs=mspec,
        check_vma=False,
    )
    out = fn(stage_params, micro)
    return out.reshape((B,) + out.shape[2:])


def stack_stage_params(params_list):
    """Stack per-stage param pytrees into the leading-stage-axis layout
    pipeline_apply expects, e.g. from S separately-initialized stages."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, 0), *params_list)


def stage_sharding(mesh: Mesh, stage_params, axis: str = "pp"):
    """NamedShardings placing each stage's params on its pp rank."""
    return jax.tree.map(
        lambda _: NamedSharding(mesh, P(axis)), stage_params)

"""Ring attention: exact attention over sequence shards with ppermute
(sequence/context parallelism for long context — absent from the reference,
designed in per SURVEY.md §5 "Long-context / sequence parallelism").

Each device on the ``sp`` ring holds one sequence chunk of Q, K, V.  K/V
blocks rotate around the ring while every device accumulates its Q-chunk's
attention with an online (streaming) softmax, so the full O(L²) score matrix
never materializes and memory stays O(L·L/sp).  Communication is ``sp``
ppermute steps that overlap with the per-block matmuls on ICI.

Causal masking uses global chunk positions: on step ``s`` a device that owns
Q-chunk ``i`` is processing K-chunk ``(i - s) mod sp`` and masks accordingly
(full-block skip for future chunks, triangular mask on the diagonal block).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

NEG_INF = -1e30


def _block_attn(q, k, v, mask, scale):
    """Scores + masked online-softmax pieces for one (Q-chunk, K-chunk) pair.

    q: [B, Lq, H, D], k/v: [B, Lk, H, D], mask: [Lq, Lk] bool or None.
    Returns (numerator [B, Lq, H, D] f32, row_max [B, Lq, H] f32,
             row_sum [B, Lq, H] f32).
    """
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if mask is not None:
        scores = jnp.where(mask[None, None, :, :], scores, NEG_INF)
    m = jnp.max(scores, axis=-1)  # [B, H, Lq]
    # Guard fully-masked rows: exp(NEG_INF - NEG_INF) would be 1.
    safe_m = jnp.where(m <= NEG_INF / 2, 0.0, m)
    p = jnp.exp(scores - safe_m[..., None])
    if mask is not None:
        p = jnp.where(mask[None, None, :, :], p, 0.0)
    l = jnp.sum(p, axis=-1)  # [B, H, Lq]
    num = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    # transpose stats to [B, Lq, H]
    return num, safe_m.transpose(0, 2, 1), l.transpose(0, 2, 1), m.transpose(0, 2, 1)


def _combine(acc, m_acc, l_acc, num, m_blk, l_blk, m_raw):
    """Merge one block's numerator/stats into the running accumulator.

    ``m_blk`` is the (masked-row-safe) max the block's numerator was computed
    against; ``m_raw`` the true row max (NEG_INF for fully-masked rows).
    Fully-masked contributions get weight 0 on either side.
    """
    new_m = jnp.maximum(m_acc, m_raw)
    safe_new_m = jnp.where(new_m <= NEG_INF / 2, 0.0, new_m)
    alpha = jnp.where(m_acc <= NEG_INF / 2, 0.0, jnp.exp(m_acc - safe_new_m))
    beta = jnp.where(m_raw <= NEG_INF / 2, 0.0, jnp.exp(m_blk - safe_new_m))
    acc = acc * alpha[..., None] + num * beta[..., None]
    l_acc = l_acc * alpha + l_blk * beta
    return acc, new_m, l_acc


def ring_attention_local(q, k, v, *, axis_name: str = "sp", causal: bool = True,
                         scale: float | None = None):
    """Per-shard ring attention body; call under shard_map with Q/K/V
    sequence-sharded over ``axis_name``.

    q, k, v: [B, chunk, H, D] local shards.  Returns [B, chunk, H, D] in
    q.dtype.
    """
    B, Lq, H, D = q.shape
    sp = lax.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    if scale is None:
        scale = D ** -0.5

    q32 = q
    acc0 = jnp.zeros((B, Lq, H, D), jnp.float32)
    m0 = jnp.full((B, Lq, H), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, Lq, H), jnp.float32)

    from k8s_tpu.parallel.collectives import ring_shift

    pos_q = jnp.arange(Lq)

    def step(s, carry):
        acc, m_acc, l_acc, k_cur, v_cur = carry
        k_chunk_idx = (my_idx - s) % sp

        if causal:
            # future chunk → fully masked; diagonal → triangular; past → full
            q_global = my_idx * Lq + pos_q[:, None]
            k_global = k_chunk_idx * Lq + pos_q[None, :]
            mask = q_global >= k_global
        else:
            mask = None

        num, m_blk, l_blk, m_raw = _block_attn(q32, k_cur, v_cur, mask, scale)
        acc, m_acc, l_acc = _combine(acc, m_acc, l_acc, num, m_blk, l_blk, m_raw)

        k_nxt = ring_shift(k_cur, axis_name)
        v_nxt = ring_shift(v_cur, axis_name)
        return acc, m_acc, l_acc, k_nxt, v_nxt

    acc, m_acc, l_acc, _, _ = lax.fori_loop(0, sp, step, (acc0, m0, l0, k, v))
    out = acc / jnp.maximum(l_acc, 1e-30)[..., None]
    return out.astype(q.dtype)


def ring_attention(mesh: Mesh, q, k, v, *, causal: bool = True,
                   seq_axis: str = "sp", batch_axes=("dp", "fsdp"),
                   head_axis: str = "tp"):
    """Global entry: shard_map ring attention over the mesh.

    q, k, v: [B, L, H, D] global arrays (or shaped trees thereof); batch is
    sharded over dp/fsdp, sequence over sp, heads over tp.
    """
    spec = P(batch_axes, seq_axis, head_axis, None)

    fn = shard_map(
        partial(ring_attention_local, axis_name=seq_axis, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


def reference_attention(q, k, v, *, causal: bool = True):
    """O(L²) reference for tests: plain softmax attention, f32 accumulation."""
    B, L, H, D = q.shape
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * (D ** -0.5)
    if causal:
        mask = jnp.tril(jnp.ones((L, L), bool))
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)

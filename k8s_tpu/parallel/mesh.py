"""Device-mesh construction (the TPU-native replacement for the reference's
TF cluster-spec: SURVEY.md §2.4 "Cluster membership / rendezvous").

A ``MeshConfig`` names the standard axes:

- ``dp``   — pure data parallelism (params replicated)
- ``pp``   — pipeline parallelism (layer stages; see parallel.pipeline)
- ``fsdp`` — data parallelism with sharded params/optimizer state
- ``ep``   — expert parallelism (MoE expert dim; see models.moe)
- ``tp``   — tensor (model) parallelism, innermost so its collectives ride
             the fastest ICI links
- ``sp``   — sequence/context parallelism for ring attention

Axis sizes of 1 are always present so sharding specs can mention every axis
unconditionally.  ``make_mesh`` lays devices out with dp outermost and tp
innermost, the layout that keeps tensor-parallel collectives on neighbor
chips (scaling-book recipe).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_ORDER = ("dp", "pp", "fsdp", "ep", "sp", "tp")


@dataclass
class MeshConfig:
    dp: int = 1
    fsdp: int = 1
    sp: int = 1
    tp: int = 1
    pp: int = 1
    ep: int = 1

    @property
    def num_devices(self) -> int:
        return self.dp * self.pp * self.fsdp * self.ep * self.sp * self.tp

    def axis_sizes(self) -> dict[str, int]:
        return {"dp": self.dp, "pp": self.pp, "fsdp": self.fsdp,
                "ep": self.ep, "sp": self.sp, "tp": self.tp}

    @classmethod
    def auto(
        cls,
        num_devices: Optional[int] = None,
        tp: int = 1,
        sp: int = 1,
        fsdp: Optional[int] = None,
        *,
        ep: int = 1,
        pp: int = 1,
    ) -> "MeshConfig":
        """Fill the data axes from the device count: fixed model axes
        (tp/sp/ep/pp), remaining devices go to fsdp (default) with dp=1 —
        the fsdp-first default that suits most training jobs."""
        n = num_devices if num_devices is not None else len(jax.devices())
        fixed = tp * sp * ep * pp
        if n % fixed != 0:
            raise ValueError(
                f"{n} devices not divisible by tp*sp*ep*pp={fixed}")
        rest = n // fixed
        if fsdp is None:
            fsdp = rest
        if rest % fsdp != 0:
            raise ValueError(
                f"{rest} remaining devices not divisible by fsdp={fsdp}")
        return cls(dp=rest // fsdp, pp=pp, fsdp=fsdp, ep=ep, sp=sp, tp=tp)


def make_mesh(config: MeshConfig, devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) != config.num_devices:
        raise ValueError(
            f"mesh needs {config.num_devices} devices "
            f"(dp×pp×fsdp×ep×sp×tp), got {len(devices)}"
        )
    arr = np.array(devices).reshape(
        [config.axis_sizes()[a] for a in AXIS_ORDER]
    )
    return Mesh(arr, AXIS_ORDER)


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Batch dimension sharded over every data-ish axis (dp, fsdp, sp)."""
    return NamedSharding(mesh, P(("dp", "fsdp"), "sp"))


def batch_spec() -> P:
    """PartitionSpec for [batch, ...] activations: batch over dp+fsdp."""
    return P(("dp", "fsdp"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def parse_topology(topology: str) -> tuple[int, ...]:
    """Parse a Cloud TPU topology string like '4x4' or '2x2x4'."""
    try:
        dims = tuple(int(d) for d in topology.lower().split("x"))
    except ValueError:
        raise ValueError(f"bad topology string {topology!r}") from None
    if not dims or any(d <= 0 for d in dims):
        raise ValueError(f"bad topology string {topology!r}")
    return dims


def chips_in_topology(topology: str) -> int:
    return math.prod(parse_topology(topology))

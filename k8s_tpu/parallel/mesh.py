"""Device-mesh construction (the TPU-native replacement for the reference's
TF cluster-spec: SURVEY.md §2.4 "Cluster membership / rendezvous").

A ``MeshConfig`` names the standard axes:

- ``dp``   — pure data parallelism (params replicated)
- ``pp``   — pipeline parallelism (layer stages; see parallel.pipeline)
- ``fsdp`` — data parallelism with sharded params/optimizer state
- ``ep``   — expert parallelism (MoE expert dim; see models.moe)
- ``tp``   — tensor (model) parallelism, innermost so its collectives ride
             the fastest ICI links
- ``sp``   — sequence/context parallelism for ring attention

Axis sizes of 1 are always present so sharding specs can mention every axis
unconditionally.  ``make_mesh`` lays devices out with dp outermost and tp
innermost, the layout that keeps tensor-parallel collectives on neighbor
chips (scaling-book recipe).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_ORDER = ("dp", "pp", "fsdp", "ep", "sp", "tp")


@dataclass
class MeshConfig:
    dp: int = 1
    fsdp: int = 1
    sp: int = 1
    tp: int = 1
    pp: int = 1
    ep: int = 1

    @property
    def num_devices(self) -> int:
        return self.dp * self.pp * self.fsdp * self.ep * self.sp * self.tp

    def axis_sizes(self) -> dict[str, int]:
        return {"dp": self.dp, "pp": self.pp, "fsdp": self.fsdp,
                "ep": self.ep, "sp": self.sp, "tp": self.tp}

    @classmethod
    def auto(
        cls,
        num_devices: Optional[int] = None,
        tp: int = 1,
        sp: int = 1,
        fsdp: Optional[int] = None,
        *,
        ep: int = 1,
        pp: int = 1,
    ) -> "MeshConfig":
        """Fill the data axes from the device count: fixed model axes
        (tp/sp/ep/pp), remaining devices go to fsdp (default) with dp=1 —
        the fsdp-first default that suits most training jobs."""
        n = num_devices if num_devices is not None else len(jax.devices())
        fixed = tp * sp * ep * pp
        if n % fixed != 0:
            raise ValueError(
                f"{n} devices not divisible by tp*sp*ep*pp={fixed}")
        rest = n // fixed
        if fsdp is None:
            fsdp = rest
        if rest % fsdp != 0:
            raise ValueError(
                f"{rest} remaining devices not divisible by fsdp={fsdp}")
        return cls(dp=rest // fsdp, pp=pp, fsdp=fsdp, ep=ep, sp=sp, tp=tp)


def make_mesh(config: MeshConfig, devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) != config.num_devices:
        raise ValueError(
            f"mesh needs {config.num_devices} devices "
            f"(dp×pp×fsdp×ep×sp×tp), got {len(devices)}"
        )
    arr = np.array(devices).reshape(
        [config.axis_sizes()[a] for a in AXIS_ORDER]
    )
    return Mesh(arr, AXIS_ORDER)


# Axes that tolerate DCN bandwidth/latency between slices: gradient
# all-reduce (dp/fsdp) and pipeline hops (pp) amortize over a full
# microbatch of compute, while tp/sp/ep collectives sit on the critical
# path of every layer and must stay on ICI (scaling-book multislice recipe).
DCN_AXES = ("dp", "fsdp", "pp")


@dataclass
class DcnConfig:
    """Cross-slice (DCN) factors for the hybrid two-level mesh.  Each factor
    multiplies the same-named ICI axis; only DCN-tolerant axes are legal."""

    dp: int = 1
    fsdp: int = 1
    pp: int = 1

    @property
    def num_slices(self) -> int:
        return self.dp * self.fsdp * self.pp

    def axis_sizes(self) -> dict[str, int]:
        return {a: getattr(self, a, 1) if a in DCN_AXES else 1
                for a in AXIS_ORDER}


def device_slice_groups(devices: Sequence, num_slices: int) -> list[list]:
    """Group devices by TPU slice: honor ``device.slice_index`` when the
    platform reports it (multislice TPU), else split the given order into
    ``num_slices`` equal contiguous chunks (CPU/test meshes)."""
    devices = list(devices)
    if len(devices) % num_slices != 0:
        raise ValueError(
            f"{len(devices)} devices not divisible by {num_slices} slices")
    indices = {getattr(d, "slice_index", None) for d in devices}
    if None not in indices and len(indices) == num_slices:
        groups: dict = {i: [] for i in sorted(indices)}
        for d in devices:
            groups[d.slice_index].append(d)
        sizes = {len(g) for g in groups.values()}
        if len(sizes) != 1:
            raise ValueError(f"uneven slice sizes: { {k: len(v) for k, v in groups.items()} }")
        return [groups[i] for i in sorted(groups)]
    per = len(devices) // num_slices
    return [devices[i * per:(i + 1) * per] for i in range(num_slices)]


def make_hybrid_mesh(
    ici: MeshConfig,
    dcn: DcnConfig,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Two-level multislice mesh: ``dcn`` factors span slices over DCN,
    ``ici`` factors live within a slice on ICI.

    The returned mesh has the standard six axis names with combined sizes
    ``dcn[a] * ici[a]``, laid out so the slice boundary is the *outer*
    stride of each combined axis — a psum over ``fsdp`` therefore
    decomposes into a fast ICI reduce-scatter within each slice plus one
    DCN all-reduce of the partial, which is how XLA lowers hierarchical
    collectives (the TPU-native replacement for the reference's flat
    gRPC worker pool, SURVEY.md §2.4)."""
    devices = list(devices if devices is not None else jax.devices())
    per_slice = ici.num_devices
    total = per_slice * dcn.num_slices
    if len(devices) != total:
        raise ValueError(
            f"hybrid mesh needs {dcn.num_slices} slices x {per_slice} "
            f"devices = {total}, got {len(devices)}")

    groups = device_slice_groups(devices, dcn.num_slices)
    dcn_sizes = dcn.axis_sizes()
    ici_sizes = ici.axis_sizes()
    # [slice, within-slice] -> [d0..d5, i0..i5] -> interleave -> combined
    arr = np.array(groups).reshape(
        [dcn_sizes[a] for a in AXIS_ORDER] + [ici_sizes[a] for a in AXIS_ORDER]
    )
    n = len(AXIS_ORDER)
    perm = [k for i in range(n) for k in (i, n + i)]
    arr = arr.transpose(perm).reshape(
        [dcn_sizes[a] * ici_sizes[a] for a in AXIS_ORDER]
    )
    return Mesh(arr, AXIS_ORDER)


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Batch dimension sharded over every data-ish axis (dp, fsdp, sp)."""
    return NamedSharding(mesh, P(("dp", "fsdp"), "sp"))


def batch_spec() -> P:
    """PartitionSpec for [batch, ...] activations: batch over dp+fsdp."""
    return P(("dp", "fsdp"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def parse_topology(topology: str) -> tuple[int, ...]:
    """Parse a Cloud TPU topology string like '4x4' or '2x2x4'."""
    try:
        dims = tuple(int(d) for d in topology.lower().split("x"))
    except ValueError:
        raise ValueError(f"bad topology string {topology!r}") from None
    if not dims or any(d <= 0 for d in dims):
        raise ValueError(f"bad topology string {topology!r}")
    return dims


def chips_in_topology(topology: str) -> int:
    return math.prod(parse_topology(topology))

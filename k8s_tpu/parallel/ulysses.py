"""Ulysses (all-to-all) sequence parallelism — the second long-context
strategy next to ring attention (SURVEY.md §2.4 maps both; the reference
has neither).

Where the ring keeps K/V moving and attention local, Ulysses re-shards:
inputs arrive sequence-sharded [B, L/sp, H, D]; one all-to-all over the
``sp`` axis exchanges the sequence shards for head shards, giving every
device the FULL sequence for H/sp heads; attention runs completely locally
(the Pallas flash kernel unchanged — heads are independent); a second
all-to-all restores sequence sharding.  Communication is two all-to-alls
of the activations per layer, independent of sequence length — cheaper
than the ring's sp K/V rotations when sp is moderate and heads divide
evenly; the ring wins when H < sp or memory for the full-L slice is the
binding constraint.

Gradients need no custom VJP: all_to_all and the flash kernel are both
differentiable, so autodiff composes them exactly.
"""

from __future__ import annotations

from functools import partial

from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map


def ulysses_attention_local(q, k, v, *, axis_name: str = "sp",
                            causal: bool = True,
                            scale: float | None = None,
                            use_flash: bool = False,
                            block_q: int | None = None,
                            block_k: int | None = None,
                            interpret: bool | None = None):
    """Per-shard Ulysses body; call under shard_map with Q/K/V
    sequence-sharded over ``axis_name``.

    q, k, v: [B, chunk, H, D] local shards; H must be divisible by the
    axis size (each device owns H/sp heads during attention).  Returns
    [B, chunk, H, D] in q.dtype.
    """
    B, Lc, H, D = q.shape
    sp = lax.axis_size(axis_name)
    if H % sp:
        raise ValueError(
            f"Ulysses needs heads ({H}) divisible by the {axis_name} axis "
            f"({sp}); use ring attention for H < sp")
    if k.shape[2] != H:
        raise ValueError(
            f"Ulysses needs H == Hkv (got {H} vs {k.shape[2]}); repeat "
            "grouped-query KV heads before the shard_map")

    def seq_to_heads(x):
        # [B, Lc, H, D] -> [B, sp*Lc, H/sp, D]: give away sp-1 head groups,
        # receive the other devices' sequence chunks for ours.  Tiled
        # all-to-all: the head axis splits sp ways, received chunks
        # concatenate peer-major onto the sequence axis — peer-major IS
        # global sequence order because device d owns chunk d.
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)

    if use_flash:
        from k8s_tpu.ops import flash_attention
        from k8s_tpu.ops.flash_attention import (
            DEFAULT_BLOCK_K,
            DEFAULT_BLOCK_Q,
        )

        out = flash_attention(
            qh, kh, vh, causal=causal, scale=scale,
            block_q=block_q or DEFAULT_BLOCK_Q,
            block_k=block_k or DEFAULT_BLOCK_K,
            interpret=interpret,
        )
    else:
        from k8s_tpu.parallel.ring_attention import reference_attention

        out = reference_attention(qh, kh, vh, causal=causal)
        out = out.astype(q.dtype)
    return heads_to_seq(out)


def ulysses_attention(mesh: Mesh, q, k, v, *, causal: bool = True,
                      seq_axis: str = "sp", batch_axes=("dp", "fsdp"),
                      use_flash: bool = False,
                      block_q: int | None = None,
                      block_k: int | None = None,
                      interpret: bool | None = None):
    """Global entry: shard_map Ulysses attention over the mesh (drop-in for
    ring_attention where heads divide the sp axis).

    Note: unlike the ring entry, heads are NOT additionally sharded over
    tp here — Ulysses already spends the head dimension on the sp axis.
    """
    spec = P(batch_axes, seq_axis, None, None)
    fn = shard_map(
        partial(ulysses_attention_local, axis_name=seq_axis, causal=causal,
                use_flash=use_flash, block_q=block_q, block_k=block_k,
                interpret=interpret),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)

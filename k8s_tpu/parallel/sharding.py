"""Sharding rules: logical parameter axes → mesh axes.

Instead of translating the reference's parameter-server placement
(variables pinned to PS replicas, pkg/trainer-era world), parameters carry
*logical axis names* and a rule table maps them onto mesh axes — the
pjit/GSPMD recipe: annotate, let XLA insert collectives.

Conventions (transformer):
- ``embed``  — the model/hidden dimension: sharded over ``tp`` for the
  embedding table's vocab side stays replicated
- ``mlp``    — the ffn hidden dimension: ``tp``
- ``heads``  — attention heads: ``tp``
- ``vocab``  — vocabulary: ``tp``
- any first surviving non-tp axis additionally shards over ``fsdp`` (ZeRO-3
  style parameter sharding)
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# logical axis -> preferred mesh axis
DEFAULT_RULES: dict[str, Optional[str]] = {
    "batch": "dp",
    "seq": "sp",
    "embed": None,      # hidden dim stays unsharded in params (activations tp-shard it)
    "mlp": "tp",
    "heads": "tp",
    "kv": None,
    "vocab": "tp",
    "conv_out": "tp",
}


def logical_to_spec(
    logical_axes: tuple[str | None, ...],
    rules: dict[str, Optional[str]] | None = None,
    fsdp_axis: str = "fsdp",
    shape: tuple[int, ...] | None = None,
    fsdp_size: int | None = None,
) -> P:
    """Map a tuple of logical axis names to a PartitionSpec.

    After applying the rule table, one still-unsharded named dimension is
    additionally sharded over ``fsdp`` (parameter sharding a la ZeRO-3 /
    FSDP): with ``shape`` (the ``shard_params`` path) the largest such
    dimension divisible by ``fsdp_size`` — replicated if none divides —
    else the first named candidate.
    """
    rules = {**DEFAULT_RULES, **(rules or {})}
    spec: list = [rules.get(a) if a else None for a in logical_axes]
    if fsdp_axis and fsdp_axis not in spec:
        candidates = [
            i
            for i, (axis, assigned) in enumerate(zip(logical_axes, spec))
            if assigned is None and axis is not None
        ]
        if candidates:
            if shape is not None and len(shape) == len(logical_axes):
                if fsdp_size:
                    candidates = [
                        i for i in candidates
                        if shape[i] % fsdp_size == 0 and shape[i] >= fsdp_size
                    ]
                best = max(candidates, key=lambda i: shape[i], default=None)
            else:
                best = candidates[0]
            if best is not None:
                spec[best] = fsdp_axis
    return P(*spec)


def shard_params(
    params: Any, logical_axes: Any, mesh: Mesh, rules=None
) -> Any:
    """Apply NamedShardings to a parameter pytree given a matching pytree of
    logical-axis tuples."""
    fsdp_size = dict(mesh.shape).get("fsdp")

    def to_sharding(x, axes):
        return NamedSharding(
            mesh,
            logical_to_spec(
                axes, rules, shape=getattr(x, "shape", None), fsdp_size=fsdp_size
            ),
        )

    shardings = jax.tree.map(
        to_sharding, params, logical_axes,
    )
    return jax.device_put(params, shardings)


def infer_logical_axes(params: Any) -> Any:
    """Size-heuristic fallback for models without explicit annotations:
    2D+ weights FSDP-shard their largest dim; 1D (bias/scale) replicate."""
    def leaf_axes(x) -> tuple:
        shape = getattr(x, "shape", ())
        if len(shape) < 2:
            return (None,) * len(shape)
        largest = int(np.argmax(shape))
        return tuple("fsdp_dim" if i == largest else None for i in range(len(shape)))

    return jax.tree.map(leaf_axes, params)


def fsdp_sharding(params: Any, mesh: Mesh) -> Any:
    """NamedShardings that FSDP-shard every ≥2D weight's largest divisible
    dimension over the fsdp axis, replicating the rest."""
    fsdp_size = mesh.shape["fsdp"]

    def to_sharding(x):
        shape = getattr(x, "shape", ())
        if len(shape) >= 2:
            order = sorted(range(len(shape)), key=lambda i: -shape[i])
            for i in order:
                if shape[i] % fsdp_size == 0 and shape[i] >= fsdp_size:
                    spec = [None] * len(shape)
                    spec[i] = "fsdp"
                    return NamedSharding(mesh, P(*spec))
        return NamedSharding(mesh, P())

    return jax.tree.map(to_sharding, params)


def apply_shardings(tree: Any, shardings: Any) -> Any:
    return jax.device_put(tree, shardings)


# ---------------------------------------------------------------- serving tp

def serve_tp_spec(path: tuple, leaf) -> P:
    """Tensor-parallel PartitionSpec for one serving-transformer param,
    keyed on its param-tree path (ISSUE 14 — the multi-host decode
    placement).  The Megatron split: q/k/v and gate/up column-sharded
    over ``tp`` (heads / ffn dims), o_proj and down_proj row-sharded so
    their matmuls produce per-shard partials XLA psums, everything that
    operates on the replicated hidden stream (embedding, norms)
    replicated.  The embedding stays whole on every chip: the serving
    configs' vocab side feeds the tied-logits einsum over a replicated
    hidden, and decode-step activations are [B, 1, ...] — replication
    costs HBM, sharding it would cost a per-token collective."""
    names = {str(p) for p in path}
    ndim = len(getattr(leaf, "shape", ()))
    if names & {"q_proj", "k_proj", "v_proj"} and ndim == 3:
        return P(None, "tp", None)       # [hidden, heads, head_dim]
    if "o_proj" in names and ndim == 3:
        return P("tp", None, None)       # [heads, head_dim, hidden]
    if names & {"gate_proj", "up_proj"} and ndim == 2:
        return P(None, "tp")             # [hidden, ffn]
    if "down_proj" in names and ndim == 2:
        return P("tp", None)             # [ffn, hidden]
    return P()


def serve_tp_param_specs(params: Any) -> Any:
    """PartitionSpec pytree for a serving transformer's params under
    tensor parallelism (see :func:`serve_tp_spec`)."""
    def spec(path, leaf):
        return serve_tp_spec(
            tuple(str(getattr(k, "key", k)) for k in path), leaf)

    return jax.tree_util.tree_map_with_path(spec, params)


def serve_pool_spec(leaf) -> P:
    """PartitionSpec for one KV block-pool leaf: the kv-head axis is the
    tp axis, so each host holds ITS head slice of every block and the
    same block tables address every shard.  ``[N, bs, kv_heads, D]``
    K/V leaves and ``[N, bs, kv_heads]`` int8 scale leaves both shard
    axis 2; anything else (there is nothing else today) replicates."""
    ndim = len(getattr(leaf, "shape", ()))
    if ndim == 4:
        return P(None, None, "tp", None)
    if ndim == 3:
        return P(None, None, "tp")
    return P()


def serve_pool_specs(pool: Any) -> Any:
    """PartitionSpec pytree for the serving engine's KV block pool."""
    return jax.tree.map(serve_pool_spec, pool)


def check_serve_tp_config(config, tp: int) -> None:
    """The divisibility contract serving tensor parallelism needs: every
    sharded dimension must split evenly over ``tp`` or a shard would
    hold a ragged slice (XLA would pad, and the shard_map'd paged
    attention island would compute on garbage lanes)."""
    problems = []
    if config.heads % tp:
        problems.append(f"heads {config.heads} % tp {tp}")
    if config.kv_heads % tp:
        problems.append(f"kv_heads {config.kv_heads} % tp {tp}")
    if config.ffn_hidden % tp:
        problems.append(f"ffn_hidden {config.ffn_hidden} % tp {tp}")
    if getattr(config, "num_experts", 0):
        problems.append("MoE serving is single-host for now "
                        "(expert params ride the ep axis, not tp)")
    if problems:
        raise ValueError(
            "config does not shard over tp=%d: %s"
            % (tp, "; ".join(problems)))

"""Sharding rules: logical parameter axes → mesh axes.

Instead of translating the reference's parameter-server placement
(variables pinned to PS replicas, pkg/trainer-era world), parameters carry
*logical axis names* and a rule table maps them onto mesh axes — the
pjit/GSPMD recipe: annotate, let XLA insert collectives.

Conventions (transformer):
- ``embed``  — the model/hidden dimension: sharded over ``tp`` for the
  embedding table's vocab side stays replicated
- ``mlp``    — the ffn hidden dimension: ``tp``
- ``heads``  — attention heads: ``tp``
- ``vocab``  — vocabulary: ``tp``
- any first surviving non-tp axis additionally shards over ``fsdp`` (ZeRO-3
  style parameter sharding)
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# logical axis -> preferred mesh axis
DEFAULT_RULES: dict[str, Optional[str]] = {
    "batch": "dp",
    "seq": "sp",
    "embed": None,      # hidden dim stays unsharded in params (activations tp-shard it)
    "mlp": "tp",
    "heads": "tp",
    "kv": None,
    "vocab": "tp",
    "conv_out": "tp",
}


def logical_to_spec(
    logical_axes: tuple[str | None, ...],
    rules: dict[str, Optional[str]] | None = None,
    fsdp_axis: str = "fsdp",
    shape: tuple[int, ...] | None = None,
    fsdp_size: int | None = None,
) -> P:
    """Map a tuple of logical axis names to a PartitionSpec.

    After applying the rule table, one still-unsharded named dimension is
    additionally sharded over ``fsdp`` (parameter sharding a la ZeRO-3 /
    FSDP): with ``shape`` (the ``shard_params`` path) the largest such
    dimension divisible by ``fsdp_size`` — replicated if none divides —
    else the first named candidate.
    """
    rules = {**DEFAULT_RULES, **(rules or {})}
    spec: list = [rules.get(a) if a else None for a in logical_axes]
    if fsdp_axis and fsdp_axis not in spec:
        candidates = [
            i
            for i, (axis, assigned) in enumerate(zip(logical_axes, spec))
            if assigned is None and axis is not None
        ]
        if candidates:
            if shape is not None and len(shape) == len(logical_axes):
                if fsdp_size:
                    candidates = [
                        i for i in candidates
                        if shape[i] % fsdp_size == 0 and shape[i] >= fsdp_size
                    ]
                best = max(candidates, key=lambda i: shape[i], default=None)
            else:
                best = candidates[0]
            if best is not None:
                spec[best] = fsdp_axis
    return P(*spec)


def shard_params(
    params: Any, logical_axes: Any, mesh: Mesh, rules=None
) -> Any:
    """Apply NamedShardings to a parameter pytree given a matching pytree of
    logical-axis tuples."""
    fsdp_size = dict(mesh.shape).get("fsdp")

    def to_sharding(x, axes):
        return NamedSharding(
            mesh,
            logical_to_spec(
                axes, rules, shape=getattr(x, "shape", None), fsdp_size=fsdp_size
            ),
        )

    shardings = jax.tree.map(
        to_sharding, params, logical_axes,
    )
    return jax.device_put(params, shardings)


def infer_logical_axes(params: Any) -> Any:
    """Size-heuristic fallback for models without explicit annotations:
    2D+ weights FSDP-shard their largest dim; 1D (bias/scale) replicate."""
    def leaf_axes(x) -> tuple:
        shape = getattr(x, "shape", ())
        if len(shape) < 2:
            return (None,) * len(shape)
        largest = int(np.argmax(shape))
        return tuple("fsdp_dim" if i == largest else None for i in range(len(shape)))

    return jax.tree.map(leaf_axes, params)


def fsdp_sharding(params: Any, mesh: Mesh) -> Any:
    """NamedShardings that FSDP-shard every ≥2D weight's largest divisible
    dimension over the fsdp axis, replicating the rest."""
    fsdp_size = mesh.shape["fsdp"]

    def to_sharding(x):
        shape = getattr(x, "shape", ())
        if len(shape) >= 2:
            order = sorted(range(len(shape)), key=lambda i: -shape[i])
            for i in order:
                if shape[i] % fsdp_size == 0 and shape[i] >= fsdp_size:
                    spec = [None] * len(shape)
                    spec[i] = "fsdp"
                    return NamedSharding(mesh, P(*spec))
        return NamedSharding(mesh, P())

    return jax.tree.map(to_sharding, params)


def apply_shardings(tree: Any, shardings: Any) -> Any:
    return jax.device_put(tree, shardings)

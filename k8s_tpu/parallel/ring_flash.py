"""Ring FLASH attention: the Pallas flash kernel as the per-block compute
inside sequence-parallel ring attention.

``parallel.ring_attention`` keeps the full [chunk x chunk] score block in
XLA-managed memory for every ring step; this module runs each (Q-chunk,
K-chunk) pair through the fused flash kernel (k8s_tpu.ops.flash_attention)
instead, so scores never leave VMEM tiles even within a chunk — the
composition long-context training actually wants: O(L/sp) memory from the
ring, flash-level HBM traffic within the shard.

Math: the flash forward emits per-row log-sum-exp, and two partial
attentions over disjoint key sets combine exactly as

    lse = logaddexp(lse_a, lse_b)
    out = out_a * exp(lse_a - lse) + out_b * exp(lse_b - lse)

so each ring step merges one flash call into the running (out, lse).  The
backward is a second ring pass: with the GLOBAL lse and delta = rowsum(do *
out) — both per Q row — the flash backward kernels give the exact dq and
the exact (dk, dv) contribution of each (Q-chunk, K-chunk) pair
independently; dk/dv accumulators travel around the ring with their K/V
chunks and arrive home after sp hops.

Reference counterpart: none (the reference has no sequence parallelism);
the algorithm is the standard ring-flash composition (Liu et al., Ring
Attention; PAPERS.md) expressed with this repo's kernels and collectives.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

from k8s_tpu.ops.flash_attention import (
    DEFAULT_BLOCK_K,
    DEFAULT_BLOCK_Q,
    NEG_INF,
    _auto_interpret,
    _flash_bwd,
    _flash_fwd,
)
from k8s_tpu.parallel.collectives import ring_shift

# step relations on the ring (lax.switch indices)
_SKIP, _DIAG, _FULL = 0, 1, 2


def _repeat_kv(x, group: int):
    """[B, Hkv, L, D] -> [B, Hkv*group, L, D] (GQA query-head expansion)."""
    return x if group == 1 else jnp.repeat(x, group, axis=1)


def _group_sum(dx, group: int):
    """Reduce per-query-head dk/dv back to the kv heads that produced them:
    [B, Hkv*group, L, D] -> [B, Hkv, L, D]."""
    if group == 1:
        return dx
    B, H, L, D = dx.shape
    return dx.reshape(B, H // group, group, L, D).sum(axis=2)


def _relation(my_idx, k_chunk_idx, causal: bool):
    if not causal:
        return jnp.full((), _FULL, jnp.int32)
    return jnp.where(
        k_chunk_idx > my_idx, _SKIP,
        jnp.where(k_chunk_idx == my_idx, _DIAG, _FULL),
    ).astype(jnp.int32)


def _merge(o_acc, lse_acc, o_blk, lse_blk):
    """Combine two partial attentions over disjoint key sets (f32)."""
    lse_new = jnp.logaddexp(lse_acc, lse_blk)
    safe = jnp.where(lse_new <= NEG_INF / 2, 0.0, lse_new)
    w_acc = jnp.where(lse_acc <= NEG_INF / 2, 0.0, jnp.exp(lse_acc - safe))
    w_blk = jnp.where(lse_blk <= NEG_INF / 2, 0.0, jnp.exp(lse_blk - safe))
    return o_acc * w_acc[..., None] + o_blk * w_blk[..., None], lse_new


@lru_cache(maxsize=None)
def _make_ring_flash(axis_name: str, causal: bool, scale: float,
                     block_q: int, block_k: int, interpret: bool,
                     group: int = 1):
    """Build the custom-VJP ring-flash local function for one config.

    ``group`` > 1 is grouped-query attention: K/V ride the ring at their
    NATIVE Hkv = H/group heads — the per-hop ICI traffic the ring exists to
    minimize shrinks by the group factor — and are expanded to H query
    heads only transiently inside each flash call; dk/dv are group-summed
    back to Hkv before joining the travelling accumulators."""

    def fwd_pass(q, k, v):
        """q: [B,H,Lc,D]; k,v: [B,H/group,Lc,D] local shards.
        Returns (out, lse [B,H,Lc,1])."""
        B, H, Lc, D = q.shape
        sp = lax.axis_size(axis_name)
        my_idx = lax.axis_index(axis_name)

        o0 = jnp.zeros((B, H, Lc, D), jnp.float32)
        lse0 = jnp.full((B, H, Lc), NEG_INF, jnp.float32)

        def flash(causal_flag, k_cur, v_cur):
            o_s, lse_s = _flash_fwd(q, _repeat_kv(k_cur, group),
                                    _repeat_kv(v_cur, group), scale,
                                    causal_flag, block_q, block_k, interpret)
            return o_s.astype(jnp.float32), lse_s[..., 0]

        def step(s, carry):
            o, lse, k_cur, v_cur = carry
            c = (my_idx - s) % sp
            o_s, lse_s = lax.switch(
                _relation(my_idx, c, causal),
                [
                    lambda kc, vc: (jnp.zeros((B, H, Lc, D), jnp.float32),
                                    jnp.full((B, H, Lc), NEG_INF, jnp.float32)),
                    lambda kc, vc: flash(True, kc, vc),
                    lambda kc, vc: flash(False, kc, vc),
                ],
                k_cur, v_cur,
            )
            o, lse = _merge(o, lse, o_s, lse_s)
            return o, lse, ring_shift(k_cur, axis_name), \
                ring_shift(v_cur, axis_name)

        o, lse, _, _ = lax.fori_loop(0, sp, step, (o0, lse0, k, v))
        return o.astype(q.dtype), lse[..., None]

    def ring_fwd(q, k, v):
        out, lse = fwd_pass(q, k, v)
        return out, (q, k, v, out, lse)

    def ring_bwd(res, do):
        q, k, v, out, lse = res
        B, H, Lc, D = q.shape
        sp = lax.axis_size(axis_name)
        my_idx = lax.axis_index(axis_name)

        dq0 = jnp.zeros((B, H, Lc, D), jnp.float32)
        Hkv = H // group
        dk0 = jnp.zeros((B, Hkv, Lc, D), jnp.float32)
        dv0 = jnp.zeros((B, Hkv, Lc, D), jnp.float32)

        def flash_bwd(causal_flag, k_cur, v_cur):
            # global lse/delta make each (Q-chunk, K-chunk) contribution
            # exact and independent; _flash_bwd derives delta from (out, do)
            dq_s, dk_s, dv_s = _flash_bwd(
                q, _repeat_kv(k_cur, group).astype(q.dtype),
                _repeat_kv(v_cur, group).astype(q.dtype), out, lse,
                do, scale, causal_flag, block_q, block_k, interpret)
            # dk/dv group-sum back to the native kv heads so the ring
            # accumulators stay Hkv-sized (ICI traffic / group)
            return (dq_s.astype(jnp.float32),
                    _group_sum(dk_s.astype(jnp.float32), group),
                    _group_sum(dv_s.astype(jnp.float32), group))

        zeros = lambda kc, vc: (dq0, dk0, dv0)  # noqa: E731

        def step(s, carry):
            dq, k_cur, v_cur, dk_cur, dv_cur = carry
            c = (my_idx - s) % sp
            dq_s, dk_s, dv_s = lax.switch(
                _relation(my_idx, c, causal),
                [
                    zeros,
                    lambda kc, vc: flash_bwd(True, kc, vc),
                    lambda kc, vc: flash_bwd(False, kc, vc),
                ],
                k_cur, v_cur,
            )
            dq = dq + dq_s
            dk_cur = dk_cur + dk_s
            dv_cur = dv_cur + dv_s
            # K/V chunks travel WITH their gradient accumulators: after the
            # full ring (sp hops) each chunk's grads are back on its owner
            return (dq, ring_shift(k_cur, axis_name),
                    ring_shift(v_cur, axis_name),
                    ring_shift(dk_cur, axis_name),
                    ring_shift(dv_cur, axis_name))

        dq, _, _, dk, dv = lax.fori_loop(
            0, sp, step, (dq0, k, v, dk0, dv0))
        return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)

    ring = jax.custom_vjp(lambda q, k, v: fwd_pass(q, k, v)[0])
    ring.defvjp(ring_fwd, ring_bwd)
    return ring


# -- zigzag (load-balanced) layout -------------------------------------------
#
# With contiguous chunks, causal ring attention is pathologically unbalanced:
# rank 0 computes one diagonal block and then SKIPs sp-1 ring steps while
# rank sp-1 computes on every step — the ring's critical path is the last
# rank's full column, ~2x the mean work.  Zigzag placement fixes it: the
# sequence is cut into 2*sp half-chunks and rank r owns the PAIR
# (r, 2sp-1-r), one early and one late block.  Every ring step then costs
# every rank exactly one chunk-equivalent of flash work:
#
#   step s, incoming pair from rank j=(r-s)%sp:
#     j == r: the own pair — plain causal over [low;high] (low precedes high
#             globally, so the concatenated causal mask is exactly right);
#     j <  r: both my blocks attend j's LOW block fully (q_all x k_low);
#     j >  r: only my HIGH block attends, but fully, to BOTH of j's blocks
#             (q_high x k_all) — same FLOPs as the j < r case.
#
# The exchange between the model's contiguous layout and zigzag ownership is
# two ppermutes of half-chunks each way, hidden inside the shard_map so the
# public API semantics are unchanged.  (Zigzag composition as in the public
# context-parallel literature — e.g. the zigzag ring-flash variants around
# Ring Attention, PAPERS.md — re-expressed with this repo's kernels.)

_Z_DIAG, _Z_LOW, _Z_HIGH = 0, 1, 2


def _zigzag_perms(sp: int):
    p1 = [(r, 2 * r if 2 * r < sp else 2 * sp - 1 - 2 * r)
          for r in range(sp)]
    p2 = [(r, 2 * r + 1 if 2 * r + 1 < sp else 2 * sp - 2 - 2 * r)
          for r in range(sp)]
    return p1, p2


def _zigzag_to(x, axis_name: str):
    """Contiguous halves (2r, 2r+1) -> zigzag pair (r, 2sp-1-r); split on
    axis 2 (the local sequence axis in kernel layout)."""
    sp = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    h0, h1 = jnp.split(x, 2, axis=2)
    p1, p2 = _zigzag_perms(sp)
    a = lax.ppermute(h0, axis_name, p1)
    b = lax.ppermute(h1, axis_name, p2)
    even = (my % 2) == 0  # via p1 even ranks receive their LOW, odd their HIGH
    low = jnp.where(even, a, b)
    high = jnp.where(even, b, a)
    return jnp.concatenate([low, high], axis=2)


def _zigzag_from(x, axis_name: str):
    """Inverse of _zigzag_to."""
    sp = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    low, high = jnp.split(x, 2, axis=2)
    p1, p2 = _zigzag_perms(sp)
    inv1 = [(d, s) for (s, d) in p1]
    inv2 = [(d, s) for (s, d) in p2]
    even = (my % 2) == 0
    send1 = jnp.where(even, low, high)  # what arrived via p1 returns via inv1
    send2 = jnp.where(even, high, low)
    h0 = lax.ppermute(send1, axis_name, inv1)
    h1 = lax.ppermute(send2, axis_name, inv2)
    return jnp.concatenate([h0, h1], axis=2)


@lru_cache(maxsize=None)
def _make_ring_flash_zigzag(axis_name: str, scale: float,
                            block_q: int, block_k: int, interpret: bool,
                            group: int = 1):
    """Causal-only load-balanced variant; external layout stays contiguous.
    ``group`` > 1 = GQA: K/V ring at native Hkv heads (see _make_ring_flash)."""

    def zz_relation(my_idx, j):
        return jnp.where(j == my_idx, _Z_DIAG,
                         jnp.where(j < my_idx, _Z_LOW, _Z_HIGH)
                         ).astype(jnp.int32)

    def fwd_core(q, k, v):
        """q,k,v: ZIGZAG-layout [B,H,Lc,D] shards; returns zigzag (o, lse)."""
        B, H, Lc, D = q.shape
        half = Lc // 2
        sp = lax.axis_size(axis_name)
        my_idx = lax.axis_index(axis_name)

        def flash(causal_flag, q_, k_, v_):
            o_s, lse_s = _flash_fwd(q_, _repeat_kv(k_, group),
                                    _repeat_kv(v_, group), scale,
                                    causal_flag, block_q, block_k, interpret)
            return o_s.astype(jnp.float32), lse_s[..., 0]

        def br_diag(kc, vc):
            return flash(True, q, kc, vc)

        def br_low(kc, vc):
            return flash(False, q, kc[:, :, :half], vc[:, :, :half])

        def br_high(kc, vc):
            o_h, lse_h = flash(False, q[:, :, half:], kc, vc)
            o_s = jnp.concatenate(
                [jnp.zeros((B, H, half, D), jnp.float32), o_h], axis=2)
            lse_s = jnp.concatenate(
                [jnp.full((B, H, half), NEG_INF, jnp.float32), lse_h], axis=2)
            return o_s, lse_s

        def step(s, carry):
            o, lse, k_cur, v_cur = carry
            j = (my_idx - s) % sp
            o_s, lse_s = lax.switch(
                zz_relation(my_idx, j), [br_diag, br_low, br_high],
                k_cur, v_cur)
            o, lse = _merge(o, lse, o_s, lse_s)
            return o, lse, ring_shift(k_cur, axis_name), \
                ring_shift(v_cur, axis_name)

        o0 = jnp.zeros((B, H, Lc, D), jnp.float32)
        lse0 = jnp.full((B, H, Lc), NEG_INF, jnp.float32)
        o, lse, _, _ = lax.fori_loop(0, sp, step, (o0, lse0, k, v))
        return o.astype(q.dtype), lse[..., None]

    def fwd_pass(q, k, v):
        qz = _zigzag_to(q, axis_name)
        kz = _zigzag_to(k, axis_name)
        vz = _zigzag_to(v, axis_name)
        oz, lsez = fwd_core(qz, kz, vz)
        return _zigzag_from(oz, axis_name), (qz, kz, vz, oz, lsez)

    def ring_bwd(res, do):
        qz, kz, vz, oz, lsez = res
        do = _zigzag_to(do, axis_name)
        B, H, Lc, D = qz.shape
        half = Lc // 2
        sp = lax.axis_size(axis_name)
        my_idx = lax.axis_index(axis_name)

        Hkv = H // group
        dq0 = jnp.zeros((B, H, Lc, D), jnp.float32)
        dkv0 = jnp.zeros((B, Hkv, Lc, D), jnp.float32)

        def bwd_diag(kc, vc):
            dq_s, dk_s, dv_s = _flash_bwd(
                qz, _repeat_kv(kc, group).astype(qz.dtype),
                _repeat_kv(vc, group).astype(qz.dtype), oz, lsez, do,
                scale, True, block_q, block_k, interpret)
            return (dq_s.astype(jnp.float32),
                    _group_sum(dk_s.astype(jnp.float32), group),
                    _group_sum(dv_s.astype(jnp.float32), group))

        def bwd_low(kc, vc):
            dq_s, dk_h, dv_h = _flash_bwd(
                qz, _repeat_kv(kc[:, :, :half], group).astype(qz.dtype),
                _repeat_kv(vc[:, :, :half], group).astype(qz.dtype),
                oz, lsez, do,
                scale, False, block_q, block_k, interpret)
            pad = jnp.zeros((B, Hkv, half, D), jnp.float32)
            return (dq_s.astype(jnp.float32),
                    jnp.concatenate(
                        [_group_sum(dk_h.astype(jnp.float32), group), pad],
                        axis=2),
                    jnp.concatenate(
                        [_group_sum(dv_h.astype(jnp.float32), group), pad],
                        axis=2))

        def bwd_high(kc, vc):
            dq_h, dk_s, dv_s = _flash_bwd(
                qz[:, :, half:], _repeat_kv(kc, group).astype(qz.dtype),
                _repeat_kv(vc, group).astype(qz.dtype),
                oz[:, :, half:], lsez[:, :, half:], do[:, :, half:],
                scale, False, block_q, block_k, interpret)
            pad = jnp.zeros((B, H, half, D), jnp.float32)
            return (jnp.concatenate([pad, dq_h.astype(jnp.float32)], axis=2),
                    _group_sum(dk_s.astype(jnp.float32), group),
                    _group_sum(dv_s.astype(jnp.float32), group))

        def step(s, carry):
            dq, k_cur, v_cur, dk_cur, dv_cur = carry
            j = (my_idx - s) % sp
            dq_s, dk_s, dv_s = lax.switch(
                zz_relation(my_idx, j), [bwd_diag, bwd_low, bwd_high],
                k_cur, v_cur)
            dq = dq + dq_s
            dk_cur = dk_cur + dk_s
            dv_cur = dv_cur + dv_s
            return (dq, ring_shift(k_cur, axis_name),
                    ring_shift(v_cur, axis_name),
                    ring_shift(dk_cur, axis_name),
                    ring_shift(dv_cur, axis_name))

        dq, _, _, dk, dv = lax.fori_loop(
            0, sp, step, (dq0, kz, vz, dkv0, dkv0))
        return (_zigzag_from(dq.astype(qz.dtype), axis_name),
                _zigzag_from(dk.astype(qz.dtype), axis_name),
                _zigzag_from(dv.astype(qz.dtype), axis_name))

    ring = jax.custom_vjp(lambda q, k, v: fwd_pass(q, k, v)[0])
    ring.defvjp(fwd_pass, ring_bwd)
    return ring


def ring_flash_attention_local(q, k, v, *, axis_name: str = "sp",
                               causal: bool = True,
                               scale: float | None = None,
                               block_q: int = DEFAULT_BLOCK_Q,
                               block_k: int = DEFAULT_BLOCK_K,
                               interpret: bool | None = None,
                               layout: str = "contiguous",
                               window: int | None = None):
    """Per-shard ring flash attention body; call under shard_map with
    Q/K/V sequence-sharded over ``axis_name``.

    q: [B, chunk, H, D]; k, v: [B, chunk, Hkv, D] local shards (same
    convention as ring_attention_local).  Hkv may DIVIDE H (grouped-query
    attention): K/V then ride the ring at their native head count — the
    per-hop ICI traffic shrinks by H/Hkv vs repeating KV before the ring —
    and are expanded per flash call only.  Returns [B, chunk, H, D].

    ``layout="zigzag"`` (causal only, even sp, even per-rank chunk)
    load-balances the causal ring: every rank computes one chunk-equivalent
    of flash work per ring step instead of rank i skipping sp-1-i steps —
    the critical path drops ~2x at large sp.  External semantics are
    unchanged (contiguous in, contiguous out).
    """
    B, Lc, H, D = q.shape
    hkv = k.shape[2]
    if hkv == 0 or H % hkv:
        raise ValueError(
            f"ring flash needs Hkv dividing H (got H={H}, Hkv={hkv})")
    group = H // hkv
    if scale is None:
        scale = D ** -0.5
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown ring layout {layout!r}")
    if window is not None:
        # windowed ring: only the ceil((window-1)/Lc) preceding chunks are
        # exchanged — O(window/Lc) ICI hops instead of sp; causal by
        # construction, already balanced (no zigzag needed)
        if not causal:
            raise ValueError("window requires causal=True (sliding-window "
                             "attention is a causal construction)")
        if window < 1:
            raise ValueError(f"window must be >= 1 (got {window})")
        ring = _make_windowed_ring(
            axis_name, int(window), float(scale), int(block_q), int(block_k),
            bool(_auto_interpret(interpret)), group)
    elif layout == "zigzag":
        if not causal:
            raise ValueError(
                "zigzag layout only balances the CAUSAL ring (non-causal "
                "rings are already uniform); use layout='contiguous'")
        if Lc % 2:
            raise ValueError(
                f"zigzag needs an even per-rank chunk (got {Lc})")
        ring = _make_ring_flash_zigzag(
            axis_name, float(scale), int(block_q), int(block_k),
            bool(_auto_interpret(interpret)), group)
    else:
        ring = _make_ring_flash(axis_name, bool(causal), float(scale),
                                int(block_q), int(block_k),
                                bool(_auto_interpret(interpret)), group)
    # kernels use [B, H, L, D]
    out = ring(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
               v.transpose(0, 2, 1, 3))
    return out.transpose(0, 2, 1, 3)


def ring_flash_attention(mesh: Mesh, q, k, v, *, causal: bool = True,
                         seq_axis: str = "sp", batch_axes=("dp", "fsdp"),
                         head_axis: str = "tp",
                         block_q: int = DEFAULT_BLOCK_Q,
                         block_k: int = DEFAULT_BLOCK_K,
                         interpret: bool | None = None,
                         layout: str = "contiguous",
                         window: int | None = None):
    """Global entry: shard_map ring flash attention over the mesh
    (drop-in for parallel.ring_attention.ring_attention).  ``layout``:
    "contiguous" | "zigzag" (causal load balancing; needs even sp).
    ``window``: sliding-window attention — only the ceil((window-1)/chunk)
    neighbor chunks are exchanged (O(window/chunk) ICI hops, not sp)."""
    if layout == "zigzag" and mesh.shape[seq_axis] % 2:
        # odd ring size cannot pair early/late blocks; stay contiguous
        layout = "contiguous"
    spec = P(batch_axes, seq_axis, head_axis, None)
    fn = shard_map(
        partial(ring_flash_attention_local, axis_name=seq_axis,
                causal=causal, block_q=block_q, block_k=block_k,
                interpret=interpret, layout=layout, window=window),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


# -- windowed ring (sliding-window attention across chunks) ------------------
#
# Sliding-window attention bounds how far back a query looks, so the ring
# does not need to rotate K/V all the way around: a chunk of length Lc needs
# its own chunk plus the M = ceil((window-1)/Lc) preceding chunks — the ring
# becomes M+1 hops instead of sp, and total work is O(L*window/sp) per rank.
#
# Masking per hop m (k chunk base = q chunk base - m*Lc):
#   m = 0: positions are aligned — the flash kernel's own `window` parameter
#          applies directly (causal + q-k < window);
#   1 <= m, window - m*Lc >= Lc: every (q,k) pair in the block is in-window
#          and strictly causal — plain flash(causal=False);
#   the single BOUNDARY hop (0 < window - m*Lc < Lc): the band
#          q_rel - k_rel < window - m*Lc crosses the block; it is computed
#          with a masked XLA block (one [Lc x Lc] score block on one hop —
#          the same cost envelope parallel.ring_attention pays every hop).
#
# Because the hop count is BOUNDED (M+1, not sp), the custom-VJP backward
# simply REPLAYS the same M+1 hops (residuals: q, k, v, out, global lse —
# O(chunk) memory) instead of running a full backward ring: each hop's
# dk/dv are computed against the global lse/delta (the same convention the
# flash backward kernels use), group-summed for GQA, and sent home with m
# reverse ring hops.


def _xla_band_block(q, k_cur, v_cur, scale, band):
    """Partial attention of q against a k chunk where only
    q_rel - k_rel < band is visible (band in (0, Lc)); returns (o, lse)
    in the _merge convention.  [B,H,Lc,D] kernel layout."""
    B, H, Lc, D = q.shape
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k_cur.astype(jnp.float32)) * scale
    q_pos = jnp.arange(Lc)[:, None]
    k_pos = jnp.arange(Lc)[None, :]
    keep = (q_pos - k_pos) < band
    s = jnp.where(keep, s, NEG_INF)
    m = jnp.max(s, axis=-1)
    safe_m = jnp.where(m <= NEG_INF / 2, 0.0, m)
    p = jnp.where(keep, jnp.exp(s - safe_m[..., None]), 0.0)
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32))
    o = o / jnp.maximum(l, 1e-30)[..., None]
    lse = jnp.where(m <= NEG_INF / 2, NEG_INF,
                    m + jnp.log(jnp.maximum(l, 1e-30)))
    return o, lse


@lru_cache(maxsize=None)
def _make_windowed_ring(axis_name: str, window: int, scale: float,
                        block_q: int, block_k: int, interpret: bool,
                        group: int):
    """custom-VJP windowed ring for one config ([B,H,Lc,D] kernel layout,
    k/v at Hkv heads).  Hop count is bounded by the window, so the backward
    replays the same M+1 hops instead of a full backward ring."""

    def _hop_band(Lc: int, m: int) -> int:
        return window - m * Lc

    def fwd_pass(q, k, v):
        B, H, Lc, D = q.shape
        sp = lax.axis_size(axis_name)
        my_idx = lax.axis_index(axis_name)
        hops = min(sp - 1, -(-(window - 1) // Lc))

        w0 = window if window < Lc else None  # window >= Lc: plain causal
        o, lse = _flash_fwd(q, _repeat_kv(k, group), _repeat_kv(v, group),
                            scale, True, block_q, block_k, interpret, w0)
        o, lse = o.astype(jnp.float32), lse[..., 0]

        k_cur, v_cur = k, v
        for m in range(1, hops + 1):
            k_cur = ring_shift(k_cur, axis_name)
            v_cur = ring_shift(v_cur, axis_name)
            band = _hop_band(Lc, m)
            if band >= Lc:
                o_s, lse_s = _flash_fwd(
                    q, _repeat_kv(k_cur, group), _repeat_kv(v_cur, group),
                    scale, False, block_q, block_k, interpret, None)
                o_s, lse_s = o_s.astype(jnp.float32), lse_s[..., 0]
            else:
                o_s, lse_s = _xla_band_block(
                    q, _repeat_kv(k_cur, group), _repeat_kv(v_cur, group),
                    scale, band)
            # chunk c attends chunks c-m >= 0 only: wrap-around ranks
            # contribute nothing from this hop
            valid = my_idx >= m
            lse_s = jnp.where(valid, lse_s, NEG_INF)
            o_s = jnp.where(valid, o_s, 0.0)
            o, lse = _merge(o, lse, o_s, lse_s)
        return o.astype(q.dtype), lse

    def vjp_fwd(q, k, v):
        out, lse = fwd_pass(q, k, v)
        return out, (q, k, v, out, lse)

    def vjp_bwd(res, do):
        q, k, v, out, lse = res
        B, H, Lc, D = q.shape
        sp = lax.axis_size(axis_name)
        my_idx = lax.axis_index(axis_name)
        hops = min(sp - 1, -(-(window - 1) // Lc))
        lse4 = lse[..., None]

        w0 = window if window < Lc else None
        dq, dk_h, dv_h = _flash_bwd(
            q, _repeat_kv(k, group).astype(q.dtype),
            _repeat_kv(v, group).astype(q.dtype), out, lse4, do,
            scale, True, block_q, block_k, interpret, w0)
        dq = dq.astype(jnp.float32)
        dk = _group_sum(dk_h.astype(jnp.float32), group)
        dv = _group_sum(dv_h.astype(jnp.float32), group)

        k_cur, v_cur = k, v
        for m in range(1, hops + 1):
            k_cur = ring_shift(k_cur, axis_name)
            v_cur = ring_shift(v_cur, axis_name)
            band = _hop_band(Lc, m)
            if band >= Lc:
                dq_m, dk_m, dv_m = _flash_bwd(
                    q, _repeat_kv(k_cur, group).astype(q.dtype),
                    _repeat_kv(v_cur, group).astype(q.dtype), out, lse4, do,
                    scale, False, block_q, block_k, interpret, None)
                dq_m = dq_m.astype(jnp.float32)
                dk_m = dk_m.astype(jnp.float32)
                dv_m = dv_m.astype(jnp.float32)
            else:
                dq_m, dk_m, dv_m = _xla_band_bwd(
                    q, _repeat_kv(k_cur, group), _repeat_kv(v_cur, group),
                    out, lse, do, scale, band)
            valid = (my_idx >= m).astype(jnp.float32)
            dq = dq + dq_m * valid
            dk_m = _group_sum(dk_m, group) * valid
            dv_m = _group_sum(dv_m, group) * valid
            # this hop's dk/dv belong to the chunk m ranks UP-ring; send
            # them home (m reverse hops — M is small, O(M^2) total hops)
            for _ in range(m):
                dk_m = ring_shift(dk_m, axis_name, reverse=True)
                dv_m = ring_shift(dv_m, axis_name, reverse=True)
            dk = dk + dk_m
            dv = dv + dv_m
        return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))

    ring = jax.custom_vjp(lambda q, k, v: fwd_pass(q, k, v)[0])
    ring.defvjp(vjp_fwd, vjp_bwd)
    return ring


def _xla_band_bwd(q, k_cur, v_cur, out, lse, do, scale, band):
    """Backward of _xla_band_block given the GLOBAL lse (same convention as
    the flash backward kernels: p from global lse, delta = rowsum(do*out))."""
    B, H, Lc, D = q.shape
    qf = q.astype(jnp.float32)
    kf = k_cur.astype(jnp.float32)
    vf = v_cur.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    delta = jnp.sum(dof * out.astype(jnp.float32), axis=-1)  # [B,H,Lc]
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale
    q_pos = jnp.arange(Lc)[:, None]
    k_pos = jnp.arange(Lc)[None, :]
    keep = (q_pos - k_pos) < band
    safe_lse = jnp.where(lse <= NEG_INF / 2, 0.0, lse)
    p = jnp.where(keep, jnp.exp(s - safe_lse[..., None]), 0.0)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, dof)
    dp = jnp.einsum("bhqd,bhkd->bhqk", dof, vf)
    ds = p * (dp - delta[..., None]) * scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, kf)
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
    return dq, dk, dv


def ring_flash_attention_windowed(mesh: Mesh, q, k, v, *, window: int,
                                  seq_axis: str = "sp",
                                  batch_axes=("dp", "fsdp"),
                                  head_axis: str = "tp",
                                  block_q: int = DEFAULT_BLOCK_Q,
                                  block_k: int = DEFAULT_BLOCK_K,
                                  interpret: bool | None = None):
    """Sliding-window attention over a sequence-parallel mesh: thin alias
    for ring_flash_attention(window=...) — each rank exchanges only the
    ceil((window-1)/chunk) neighbor chunks instead of rotating the whole
    ring.  Causal by construction; GQA supported."""
    return ring_flash_attention(
        mesh, q, k, v, causal=True, seq_axis=seq_axis,
        batch_axes=batch_axes, head_axis=head_axis,
        block_q=block_q, block_k=block_k, interpret=interpret,
        window=window)

"""Explicit ring collectives with compute/communication overlap.

The reference's "communication backend" is TF gRPC sessions over kube-dns
(SURVEY.md §5 "Distributed communication backend"); the TPU-native
replacement is XLA collectives over ICI.  For most code the pjit recipe —
annotate shardings, let XLA insert psum/all-gather — is the whole story and
callers should use ``jax.lax`` directly.  This module holds the cases where
the *schedule* of a collective matters: manual ring algorithms (ppermute
chains under ``shard_map``) that interleave each hop's transfer with the
compute that consumes it, hiding ICI latency under MXU work.  Ring attention
(k8s_tpu.parallel.ring_attention) and both pipeline schedules
(k8s_tpu.parallel.pipeline) are built on the same ``ring_shift`` primitive.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax


def ring_shift(x, axis: str, *, reverse: bool = False):
    """Send our shard to the next rank on the ring (ppermute); the backbone
    of ring attention, pipeline microbatch rotation, and the ring collectives
    below.  ``reverse`` sends up-ring (rank i -> i-1), the direction pipeline
    backward passes use."""
    n = lax.axis_size(axis)
    if reverse:
        perm = [(i, (i - 1) % n) for i in range(n)]
    else:
        perm = [(i, (i + 1) % n) for i in range(n)]
    return lax.ppermute(x, axis_name=axis, perm=perm)


def ring_all_gather(x, axis: str, *, fold_fn: Optional[Callable] = None):
    """Ring all-gather of per-rank shards, one hop per step.

    Where ``lax.all_gather`` leaves scheduling to XLA, the explicit ring
    exposes each shard to ``fold_fn(acc, shard, src_rank)`` the step it
    lands, so per-shard compute overlaps the next hop's transfer (``acc`` is
    None on the first fold).  Without ``fold_fn``, returns ``[n, ...]``
    stacked shards in rank order (equivalent to ``lax.all_gather``); with
    it, returns the final accumulator (see ``collective_matmul``).
    """
    n = lax.axis_size(axis)
    i = lax.axis_index(axis)

    if fold_fn is None:
        def fold_fn(acc, shard, src):  # default: stack into rank order
            if acc is None:
                acc = jnp.zeros((n,) + shard.shape, shard.dtype)
            return lax.dynamic_update_index_in_dim(acc, shard, src, 0)

    # step 0 folds our own shard, then each hop delivers the shard that
    # originated t ranks up-ring
    acc = fold_fn(None, x, i)
    cur = ring_shift(x, axis)

    def body(t, carry):
        cur, acc = carry
        acc = fold_fn(acc, cur, (i - t) % n)
        # the final iteration's send is dead; XLA drops it (static loop
        # structure keeps the whole chain one fused while on TPU)
        cur = ring_shift(cur, axis)
        return cur, acc

    _, acc = lax.fori_loop(1, n, body, (cur, acc))
    return acc


def ring_reduce_scatter(x, axis: str):
    """Ring reduce-scatter: ``x`` is ``[n, chunk...]`` per rank (one chunk
    addressed to each rank); returns this rank's ``[chunk...]`` sum across
    ranks — equivalent to ``lax.psum_scatter(x, tiled=False)``.

    Classic bandwidth-optimal ring: the partial sum for chunk ``c`` starts
    at rank ``c+1`` and travels the ring once, each rank adding its local
    contribution as it passes through, arriving fully reduced at rank ``c``
    after ``n-1`` hops.  Each hop's addition overlaps the next transfer.
    """
    n = lax.axis_size(axis)
    i = lax.axis_index(axis)

    # rank i initializes the partial for chunk i-1 (which will land on rank
    # i-1 after the full loop of the ring)
    partial = x[(i - 1) % n]

    def body(k, partial):
        partial = ring_shift(partial, axis)
        # after hop k we hold the partial for chunk i-1-k; fold in our piece
        return partial + x[(i - 1 - k) % n]

    return lax.fori_loop(1, n, body, partial)


def collective_matmul(x_shard, w, axis: str):
    """Latency-hiding tensor-parallel matmul: ``x`` row-sharded over
    ``axis`` (``x_shard: [rows/n, k]``), ``w`` replicated; returns the full
    ``x @ w`` (``[rows, out]``) by overlapping each ring hop of the
    all-gather with the matmul of the shard that just arrived — the
    "collective matmul" pattern XLA fuses for all-gather+dot under pjit,
    written explicitly for shard_map code where that fusion isn't available.
    """
    n = lax.axis_size(axis)
    rows = x_shard.shape[0]

    def fold(acc, shard, src):
        y = shard @ w  # MXU work for this hop, overlapping the next transfer
        if acc is None:
            acc = jnp.zeros((n * rows,) + y.shape[1:], y.dtype)
        return lax.dynamic_update_slice_in_dim(acc, y, src * rows, 0)

    return ring_all_gather(x_shard, axis, fold_fn=fold)


def host_local_array_to_global(mesh, arrays, pspec):
    """Multi-host input plumbing: assemble per-host shards into a global
    jax.Array (the jax.make_array_from_process_local_data path)."""
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, pspec)
    return jax.make_array_from_process_local_data(sharding, arrays)

"""Collective helpers over mesh axes (the XLA-collectives replacement for the
reference's TF gRPC sessions, SURVEY.md §5 "Distributed communication
backend").

Thin, named wrappers so model code reads as topology ("ring shift over sp")
rather than raw lax calls; all usable under ``shard_map``/``pjit``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def psum(x, axis: str):
    return lax.psum(x, axis_name=axis)


def pmean(x, axis: str):
    return lax.pmean(x, axis_name=axis)


def all_gather(x, axis: str, *, tiled: bool = True, gather_dim: int = 0):
    return lax.all_gather(x, axis_name=axis, axis=gather_dim, tiled=tiled)


def reduce_scatter(x, axis: str, *, scatter_dim: int = 0):
    return lax.psum_scatter(x, axis_name=axis, scatter_dimension=scatter_dim, tiled=True)


def ring_shift(x, axis: str, *, reverse: bool = False):
    """Send our shard to the next rank on the ring (ppermute); the backbone
    of ring attention and bidirectional pipelining over ICI."""
    n = lax.axis_size(axis)
    if reverse:
        perm = [(i, (i - 1) % n) for i in range(n)]
    else:
        perm = [(i, (i + 1) % n) for i in range(n)]
    return lax.ppermute(x, axis_name=axis, perm=perm)


def axis_index(axis: str):
    return lax.axis_index(axis)


def axis_size(axis: str):
    return lax.axis_size(axis)


def global_mean_over(axes: tuple[str, ...]):
    """Gradient reduction across every data-ish axis: psum-normalized mean."""

    def reduce_fn(tree):
        def one(x):
            for a in axes:
                x = lax.pmean(x, axis_name=a)
            return x

        return jax.tree.map(one, tree)

    return reduce_fn


def host_local_array_to_global(mesh, arrays, pspec):
    """Multi-host input plumbing: assemble per-host shards into a global
    jax.Array (the jax.make_array_from_process_local_data path)."""
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, pspec)
    return jax.make_array_from_process_local_data(sharding, arrays)

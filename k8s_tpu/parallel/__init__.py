"""Parallelism layer: mesh axes, sharding rules, collectives, ring attention.

The reference's only parallelism axes were PS-vs-worker data parallelism over
gRPC (SURVEY.md §2.4).  Here the axes are a first-class design: a
``jax.sharding.Mesh`` with named axes (dp/pp/fsdp/ep/sp/tp) over which
pjit/XLA insert ICI/DCN collectives, plus shard_map-level sequence
parallelism (ring attention) for long context and two pipeline microbatch
schedules (parallel.pipeline: GPipe and memory-bounded 1F1B).  Explicit
latency-hiding ring collectives for shard_map code live in
parallel.collectives.
"""

from k8s_tpu.parallel.mesh import MeshConfig, make_mesh  # noqa: F401
from k8s_tpu.parallel.pipeline import (  # noqa: F401
    pipeline_apply,
    pipeline_train_step_1f1b,
    stack_stage_params,
    stage_sharding,
)

"""GroupVersionResource identifiers for every resource the operator touches."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class GVR:
    group: str
    version: str
    plural: str
    kind: str
    namespaced: bool = True

    @property
    def api_version(self) -> str:
        return f"{self.group}/{self.version}" if self.group else self.version

    @property
    def path_prefix(self) -> str:
        """URL prefix: /api/v1 for core, /apis/<group>/<version> otherwise."""
        return f"/api/{self.version}" if not self.group else f"/apis/{self.group}/{self.version}"


PODS = GVR("", "v1", "pods", "Pod")
SERVICES = GVR("", "v1", "services", "Service")
EVENTS = GVR("", "v1", "events", "Event")
NAMESPACES = GVR("", "v1", "namespaces", "Namespace", namespaced=False)
NODES = GVR("", "v1", "nodes", "Node", namespaced=False)
ENDPOINTS = GVR("", "v1", "endpoints", "Endpoints")
CONFIGMAPS = GVR("", "v1", "configmaps", "ConfigMap")
PDBS = GVR("policy", "v1beta1", "poddisruptionbudgets", "PodDisruptionBudget")
CRDS = GVR(
    "apiextensions.k8s.io",
    "v1beta1",
    "customresourcedefinitions",
    "CustomResourceDefinition",
    namespaced=False,
)
TFJOBS_V1ALPHA1 = GVR("kubeflow.org", "v1alpha1", "tfjobs", "TFJob")
TFJOBS_V1ALPHA2 = GVR("kubeflow.org", "v1alpha2", "tfjobs", "TFJob")


def tfjobs_gvr(api_version: str) -> GVR:
    if api_version.endswith("v1alpha1"):
        return TFJOBS_V1ALPHA1
    return TFJOBS_V1ALPHA2

"""Client machinery (reference: pkg/client/ generated clientset/informers/listers).

The reference ships ~10k lines of code-generated typed clients.  Here the
same capabilities are a small hand-written stack over one backend protocol:

- ``gvr``        — GroupVersionResource identifiers for every kind we touch
- ``errors``     — ApiError taxonomy (NotFound/Conflict/AlreadyExists)
- ``fake``       — in-memory apiserver with watch, action log, GC by owner
                   refs (the fake-clientset tier of SURVEY.md §4)
- ``rest``       — real apiserver over stdlib HTTPS (in-cluster or kubeconfig)
- ``clientset``  — typed per-resource CRUD façade over either backend
- ``informer``   — reflector (list+watch) → thread-safe store → handlers,
                   the SharedInformerFactory/lister layer
"""

from k8s_tpu.client.clientset import Clientset  # noqa: F401
from k8s_tpu.client.errors import ApiError, is_not_found, is_conflict, is_already_exists  # noqa: F401
from k8s_tpu.client.fake import FakeCluster  # noqa: F401

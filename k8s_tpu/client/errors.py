"""API error taxonomy (reference: pkg/util/k8sutil/k8sutil.go:84-106 helpers
plus apierrors.IsNotFound/IsConflict usage throughout the controllers)."""

from __future__ import annotations


class ApiError(Exception):
    """A Kubernetes-style API error with an HTTP status code and reason."""

    def __init__(self, code: int, reason: str, message: str = ""):
        super().__init__(message or reason)
        self.code = code
        self.reason = reason


def not_found(message: str = "") -> ApiError:
    return ApiError(404, "NotFound", message)


def already_exists(message: str = "") -> ApiError:
    return ApiError(409, "AlreadyExists", message)


def conflict(message: str = "") -> ApiError:
    return ApiError(409, "Conflict", message)


def invalid(message: str = "") -> ApiError:
    return ApiError(422, "Invalid", message)


def bad_request(message: str = "") -> ApiError:
    return ApiError(400, "BadRequest", message)


def unsupported_media_type(message: str = "") -> ApiError:
    return ApiError(415, "UnsupportedMediaType", message)


def expired(message: str = "") -> ApiError:
    """410 Gone: a watch resourceVersion older than the server's retained
    event window.  Clients must relist and re-watch from the fresh list's
    resourceVersion (the client-go reflector's 410 path)."""
    return ApiError(410, "Expired", message)


def is_not_found(err: Exception) -> bool:
    return isinstance(err, ApiError) and err.code == 404


def is_already_exists(err: Exception) -> bool:
    return isinstance(err, ApiError) and err.reason == "AlreadyExists"


def is_conflict(err: Exception) -> bool:
    return isinstance(err, ApiError) and err.reason == "Conflict"


def is_expired(err: Exception) -> bool:
    return isinstance(err, ApiError) and err.code == 410

"""Strategic merge patch (``application/strategic-merge-patch+json``).

Real PodControl paths patch with *strategic* merge semantics, not JSON merge
(reference: pkg/controller.v2/controller_pod.go:99-169 uses client-go's
PatchPod, which sends types.StrategicMergePatchType): lists tagged with a
``patchMergeKey`` in the Kubernetes API structs merge element-by-element on
that key instead of being replaced wholesale, and ``$patch`` directives can
delete or replace individual elements.  A fixture that only speaks JSON
merge patch (RFC 7386) silently diverges on every list the operator touches
— containers, env, ports, volumes, ownerReferences.

This module implements the subset of SMP semantics the operator's shapes
exercise, driven by the core-v1 merge-key schema below:

- maps merge recursively; an explicit ``null`` deletes the key (as in JSON
  merge patch); a map carrying ``{"$patch": "replace"}`` replaces the
  target map wholesale;
- lists whose field has a merge key merge by that key: patch elements
  update matching current elements (recursively), unmatched patch elements
  append, and ``{"$patch": "delete", <key>: v}`` elements remove the
  matching current element; a literal ``{"$patch": "replace"}`` element
  makes the remainder of the patch list replace the current list;
- ``$setElementOrder/<field>`` reorders a merged list by its merge keys;
- ``$deleteFromPrimitiveList/<field>`` removes values from a primitive
  list; primitive lists tagged ``patchStrategy: merge`` (finalizers) union;
- every other list is atomic and replaces, exactly like JSON merge patch.

Not implemented (the operator never generates them, and the fixture should
fail loudly rather than guess): ``$retainKeys``, merge keys nested beyond
one level of the same field name, ``patchStrategy: retainKeys``.
"""

from __future__ import annotations

from typing import Optional

# patchMergeKey by FIELD NAME, as tagged in the core-v1 / apps / policy Go
# structs (k8s.io/api).  Several distinct structs share a field name with
# different keys ("ports" is containerPort on containers, port/name on
# services), so each entry lists candidates; _resolve_merge_key picks the
# first candidate present in every element on both sides, which is exactly
# the element shape the API guarantees for that struct.
MERGE_KEYS: dict[str, tuple[str, ...]] = {
    "containers": ("name",),
    "initContainers": ("name",),
    "ephemeralContainers": ("name",),
    "env": ("name",),
    "ports": ("containerPort", "port", "name"),
    "volumes": ("name",),
    "volumeMounts": ("mountPath",),
    "volumeDevices": ("devicePath",),
    "hostAliases": ("ip",),
    "imagePullSecrets": ("name",),
    "ownerReferences": ("uid",),
    "conditions": ("type",),
    "secrets": ("name",),
}
# NOT merge-keyed, deliberately: tolerations, taints, and readinessGates
# carry no patchMergeKey tag in k8s.io/api structs — they are atomic lists
# that replace wholesale, and merging them here would diverge from a real
# apiserver in the opposite direction.

# primitive lists tagged patchStrategy=merge in the API structs: the patch
# list unions into the current list instead of replacing it
PRIMITIVE_MERGE_FIELDS = frozenset({"finalizers"})

_PATCH = "$patch"
_ORDER_PREFIX = "$setElementOrder/"
_DELETE_PRIMITIVE_PREFIX = "$deleteFromPrimitiveList/"


class StrategicMergeError(ValueError):
    """Malformed strategic merge patch (unknown directive, bad shape)."""


def _resolve_merge_key(field: str, current: list, patch: list) -> Optional[str]:
    """The merge key for ``field``, or None for non-merge-keyed fields.

    For a merge-keyed field, every patch element must CARRY the key — a
    real apiserver rejects the patch otherwise ("does not contain declared
    merge key"); silently degrading to atomic replacement would let a buggy
    controller patch pass the fixture and fail the real cluster.
    """
    candidates = MERGE_KEYS.get(field, ())
    if not candidates:
        return None
    elems = [e for e in (*current, *patch) if isinstance(e, dict)]
    if not elems:
        return None
    for cand in candidates:
        if all(cand in e for e in elems):
            return cand
    raise StrategicMergeError(
        f"strategic merge patch for {field!r} needs every element to carry "
        f"one of the merge keys {list(candidates)}")


def _merge_list(field: str, current: list, patch: list, order: Optional[list]):
    # a literal {"$patch": "replace"} element: the rest of the patch list IS
    # the new list
    cleaned = []
    replace = False
    for e in patch:
        if isinstance(e, dict) and e.get(_PATCH) == "replace" and len(e) == 1:
            replace = True
            continue
        cleaned.append(e)
    if replace:
        return [e for e in cleaned if not (
            isinstance(e, dict) and e.get(_PATCH) == "delete")]

    key = _resolve_merge_key(field, current, cleaned)
    if key is None:
        if field in PRIMITIVE_MERGE_FIELDS and all(
                not isinstance(e, (dict, list)) for e in (*current, *cleaned)):
            return current + [e for e in cleaned if e not in current]
        return cleaned  # atomic: replace wholesale (JSON-merge behavior)

    out = list(current)
    for e in cleaned:
        if not isinstance(e, dict):
            raise StrategicMergeError(
                f"list field {field!r} merges on {key!r} but patch element "
                f"{e!r} is not an object")
        directive = e.get(_PATCH)
        idx = next((i for i, c in enumerate(out)
                    if isinstance(c, dict) and c.get(key) == e.get(key)), None)
        if directive == "delete":
            if idx is not None:
                out.pop(idx)
            continue
        if directive is not None:
            raise StrategicMergeError(
                f"unknown $patch directive {directive!r} in {field!r}")
        if idx is None:
            out.append(e)
        else:
            out[idx] = strategic_merge(out[idx], e)
    if order is not None:
        # order entries are objects carrying the merge key (the format
        # kubectl emits), but tolerate raw key values too
        pos = {}
        for i, e in enumerate(order):
            pos[e.get(key) if isinstance(e, dict) else e] = i
        out.sort(key=lambda c: pos.get(
            c.get(key) if isinstance(c, dict) else None, len(pos)))
    return out


def strategic_merge(current: dict, patch: dict) -> dict:
    """Apply ``patch`` to ``current`` with strategic-merge semantics.

    Pure: returns a new dict; neither input is mutated (callers hand in
    store-aliased objects).
    """
    if patch.get(_PATCH) == "replace":
        return {k: v for k, v in patch.items() if k != _PATCH}
    if _PATCH in patch:
        raise StrategicMergeError(
            f"unknown map-level $patch directive {patch[_PATCH]!r}")
    out = dict(current)
    orders: dict[str, list] = {}
    deletes: dict[str, list] = {}
    for k, v in patch.items():
        if k.startswith(_ORDER_PREFIX):
            orders[k[len(_ORDER_PREFIX):]] = v
        elif k.startswith(_DELETE_PRIMITIVE_PREFIX):
            deletes[k[len(_DELETE_PRIMITIVE_PREFIX):]] = v
    for k, v in patch.items():
        if k.startswith(_ORDER_PREFIX) or k.startswith(_DELETE_PRIMITIVE_PREFIX):
            continue
        cur = out.get(k)
        if v is None:
            out.pop(k, None)
        elif isinstance(v, dict) and v.get(_PATCH) == "delete":
            # {"$patch": "delete"} as a map value deletes the key —
            # consistently whether or not the target currently exists
            if len(v) > 1:
                raise StrategicMergeError(
                    f"map-level $patch delete for {k!r} must not carry "
                    "other fields")
            out.pop(k, None)
        elif isinstance(v, dict) and isinstance(cur, dict):
            out[k] = strategic_merge(cur, v)
        elif isinstance(v, dict) and v.get(_PATCH) == "replace":
            out[k] = {kk: vv for kk, vv in v.items() if kk != _PATCH}
        elif isinstance(v, dict):
            # target absent/non-dict: merge into {} so NESTED directives
            # are still applied and stripped — storing the patch subtree
            # verbatim would persist literal "$patch" keys into the object
            out[k] = strategic_merge({}, v)
        elif isinstance(v, list):
            out[k] = _merge_list(k, cur if isinstance(cur, list) else [],
                                 v, orders.pop(k, None))
        else:
            out[k] = v
    # $setElementOrder / $deleteFromPrimitiveList can arrive WITHOUT a
    # sibling patch list (reorder-only / delete-only patches)
    for field, order in orders.items():
        cur = out.get(field)
        if isinstance(cur, list):
            out[field] = _merge_list(field, cur, [], order)
    for field, victims in deletes.items():
        cur = out.get(field)
        if isinstance(cur, list):
            if not isinstance(victims, list):
                raise StrategicMergeError(
                    f"$deleteFromPrimitiveList/{field} must be a list, "
                    f"got {victims!r}")
            out[field] = [e for e in cur if e not in victims]
    return out

"""Real-apiserver backend over the Python stdlib (no kubernetes-client dep).

Replaces k8s.io/client-go's rest.Config + dynamic client for our purposes:
implements the same backend protocol as ``FakeCluster`` by translating calls
to apiserver REST paths (GET/POST/PUT/PATCH/DELETE + chunked watch streams).

Config resolution mirrors pkg/util/k8sutil/k8sutil.go:52-76: in-cluster
service-account credentials first, then $KUBECONFIG / ~/.kube/config.
"""

from __future__ import annotations

import json
import os
import ssl
import time
import urllib.parse
import urllib.request
from dataclasses import dataclass
from typing import Iterator, Optional

from k8s_tpu import flight
from k8s_tpu.client import errors
from k8s_tpu.client.gvr import GVR
from k8s_tpu.client.selectors import parse_label_selector

SERVICE_ACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

# Wire profiling (K8S_TPU_WIRE_PROFILE=1): per-(method, resource) request
# counts and cumulative seconds across every RestClient in the process.
# This is how the round-4/5 wire-gap numbers were derived (BASELINE.md) —
# committed so the profile can be reproduced, not re-invented, whenever the
# rest-vs-fake ratio needs re-auditing.  Counters are plain dict updates
# under a lock; zero cost when the env var is unset.
WIRE_PROFILE_ENABLED = os.environ.get("K8S_TPU_WIRE_PROFILE") == "1"
_wire_profile: dict = {}
_wire_profile_lock = None
if WIRE_PROFILE_ENABLED:
    from k8s_tpu.analysis import checkedlock as _checkedlock

    _wire_profile_lock = _checkedlock.make_lock("rest.wire_profile")


def _profile_key(method: str, path: str) -> str:
    # /api/v1/namespaces/ns/pods/name?q -> "GET pods"; /apis/g/v/t -> t.
    # Resource parsing is shared with the flight recorder (ONE parser —
    # the wire-profile key and the accounting label must never disagree
    # about a request's resource).
    return f"{method} {_verb_and_resource(method, path)[1]}"


def _verb_and_resource(method: str, path: str) -> tuple[str, str]:
    """Flight-recorder (verb, resource) for one request, in ONE pass over
    the path (this runs per wire attempt on the lean unary hot path).

    Verb is the HTTP method except that streaming GETs count as WATCH and
    collection GETs as LIST — the steady-state proof ("zero per-sync
    LISTs") needs LIST to be a label, not a path-parsing exercise at
    query time.  LIST is decided by path SHAPE (no name segment after the
    resource segment), so a single object legally named like its plural
    (GET .../pods/pods) still counts as a GET."""
    raw, _, query = path.partition("?")
    parts = [p for p in raw.split("/") if p]
    resource, has_name = "?", True
    # Anchor on the API root (the first api/apis segment — any earlier
    # segments are a proxy base path) and parse by POSITION from there:
    # a token scan for "namespaces" would misparse a cluster-scoped
    # object literally named "namespaces" (GET /api/v1/nodes/namespaces).
    root = next((j for j, p in enumerate(parts) if p in ("api", "apis")),
                None)
    if root is not None:
        # after /api/<version> or /apis/<group>/<version>
        rest = parts[root + (2 if parts[root] == "api" else 3):]
        if rest[:1] == ["namespaces"] and len(rest) >= 3:
            resource = rest[2]
            has_name = len(rest) > 3
        elif rest[:1] == ["namespaces"]:
            # the namespaces resource itself: /api/v1/namespaces[/<name>]
            resource = "namespaces"
            has_name = len(rest) > 1
        elif rest:  # cluster-scoped: /api/v1/nodes[/<name>]
            resource = rest[0]
            has_name = len(rest) > 1
    if "watch=true" in query:
        return "WATCH", resource
    if method == "GET" and not has_name:
        return "LIST", resource
    return method, resource


def _profile_record(method: str, path: str, seconds: float) -> None:
    key = _profile_key(method, path)
    with _wire_profile_lock:
        ent = _wire_profile.setdefault(key, [0, 0.0])
        ent[0] += 1
        ent[1] += seconds


def wire_profile_snapshot() -> dict:
    """{key: {"count": n, "seconds": s}} sorted by cumulative seconds."""
    if not WIRE_PROFILE_ENABLED:
        return {}
    with _wire_profile_lock:
        items = {k: {"count": v[0], "seconds": round(v[1], 4)}
                 for k, v in _wire_profile.items()}
    return dict(sorted(items.items(),
                       key=lambda kv: -kv[1]["seconds"]))


@dataclass
class ClusterConfig:
    """Connection parameters for one apiserver."""

    host: str  # e.g. https://10.0.0.1:443
    token: str = ""
    ca_cert_file: str = ""
    client_cert_file: str = ""
    client_key_file: str = ""
    insecure_skip_verify: bool = False

    def ssl_context(self) -> Optional[ssl.SSLContext]:
        if not self.host.startswith("https"):
            return None
        ctx = ssl.create_default_context(
            cafile=self.ca_cert_file if os.path.exists(self.ca_cert_file or "") else None
        )
        if self.client_cert_file:
            ctx.load_cert_chain(self.client_cert_file, self.client_key_file or None)
        # Verification is only disabled on explicit opt-in; a missing CA file
        # must fail verification, not silently trust the network.
        if self.insecure_skip_verify:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        return ctx


def in_cluster_config() -> ClusterConfig:
    """In-cluster service-account config (k8sutil.go:61-68 equivalent)."""
    host = os.environ.get("KUBERNETES_SERVICE_HOST")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    token_file = os.path.join(SERVICE_ACCOUNT_DIR, "token")
    if not host or not os.path.exists(token_file):
        raise RuntimeError("not running in a cluster (no service account)")
    with open(token_file) as f:
        token = f.read().strip()
    return ClusterConfig(
        host=f"https://{host}:{port}",
        token=token,
        ca_cert_file=os.path.join(SERVICE_ACCOUNT_DIR, "ca.crt"),
    )


def _materialize_inline(data_b64: str, suffix: str) -> str:
    """Write a kubeconfig inline `*-data` credential to a private temp file
    and return its path (GKE/kind/minikube embed credentials this way)."""
    import base64
    import tempfile

    fd, path = tempfile.mkstemp(prefix="k8s-tpu-", suffix=suffix)
    with os.fdopen(fd, "wb") as f:
        f.write(base64.b64decode(data_b64))
    os.chmod(path, 0o600)
    return path


def kubeconfig_config(path: Optional[str] = None) -> ClusterConfig:
    """Minimal kubeconfig loader: current-context cluster + user, supporting
    both file-path and inline base64 `*-data` credentials
    (k8sutil.go:34-50, cmd/tf-operator.v2/app/server.go:55-80)."""
    import yaml

    path = path or os.environ.get("KUBECONFIG") or os.path.expanduser("~/.kube/config")
    with open(path) as f:
        cfg = yaml.safe_load(f)
    ctx_name = cfg.get("current-context")
    ctx = next(c["context"] for c in cfg.get("contexts", []) if c["name"] == ctx_name)
    cluster = next(c["cluster"] for c in cfg["clusters"] if c["name"] == ctx["cluster"])
    user = next(u["user"] for u in cfg.get("users", []) if u["name"] == ctx.get("user"))

    ca = cluster.get("certificate-authority", "")
    if not ca and cluster.get("certificate-authority-data"):
        ca = _materialize_inline(cluster["certificate-authority-data"], ".crt")
    cert = user.get("client-certificate", "")
    if not cert and user.get("client-certificate-data"):
        cert = _materialize_inline(user["client-certificate-data"], ".crt")
    key = user.get("client-key", "")
    if not key and user.get("client-key-data"):
        key = _materialize_inline(user["client-key-data"], ".key")

    return ClusterConfig(
        host=cluster["server"],
        token=user.get("token", ""),
        ca_cert_file=ca,
        client_cert_file=cert,
        client_key_file=key,
        insecure_skip_verify=bool(cluster.get("insecure-skip-tls-verify")),
    )


def get_cluster_config() -> ClusterConfig:
    """GetClusterConfig (k8sutil.go:52-76): in-cluster, then kubeconfig."""
    try:
        return in_cluster_config()
    except RuntimeError:
        return kubeconfig_config()


class _RestWatch:
    """Streaming watch: iterates (type, object) from a chunked response.

    ``stopped`` flips when the stream ends for ANY reason (client stop or
    server-side watch timeout) so the informer's consume loop returns to its
    relist instead of spinning on a dead stream.
    """

    def __init__(self, response):
        self._resp = response
        self._lines = iter(response)
        self.stopped = False

    def stop(self) -> None:
        self.stopped = True
        # Shut down the socket FIRST: close() must take the BufferedReader
        # lock, which a reader blocked in readline() holds until the next
        # frame arrives — stop() from another thread would block for the
        # rest of the watch.  shutdown() needs no lock and makes the
        # blocked recv return EOF immediately.  The socket reference was
        # captured at request time (_k8s_tpu_sock): for Connection: close
        # responses http.client detaches conn.sock (it is None by now), so
        # only that early-captured reference reaches the live socket — no
        # BufferedReader internals involved.
        try:
            sock = getattr(self._resp, "_k8s_tpu_sock", None)
            if sock is not None:
                import socket as _socket

                sock.shutdown(_socket.SHUT_RDWR)
        # except-ok: best-effort shutdown of an already-dying socket
        except Exception:
            pass
        try:
            self._resp.close()
        # except-ok: best-effort close on watch teardown
        except Exception:
            pass
        conn = getattr(self._resp, "_k8s_tpu_conn", None)
        if conn is not None:
            try:
                conn.close()
            # except-ok: best-effort close on watch teardown
            except Exception:
                pass

    def __iter__(self) -> Iterator[tuple[str, dict]]:
        while True:
            item = self.next()
            if item is None:
                return
            yield item

    def next(self, timeout: Optional[float] = None):
        """One event, or None once the stream is exhausted/closed.  The
        timeout parameter is accepted for protocol compatibility with the
        fake's queue-based watch; blocking is bounded by the server's own
        watch timeout instead."""
        if self.stopped:
            return None
        try:
            for raw in self._lines:
                line = raw.strip()
                if not line:
                    continue
                evt = json.loads(line)
                return evt.get("type", ""), evt.get("object", {})
        # except-ok: connection torn down — treat as end-of-stream
        except Exception:
            pass
        self.stopped = True
        return None


class RestClient:
    """Backend-protocol implementation against a real apiserver.

    Unary requests ride thread-local keep-alive connections (http.client) —
    one TCP handshake per thread, not per call, which is the difference
    between 20 and 100+ reconciled jobs/s over the wire.  Watch streams
    get dedicated connections (the server closes them at its watch
    timeout; the reflector's resume path reopens).
    """

    def __init__(self, config: Optional[ClusterConfig] = None):
        self.config = config or get_cluster_config()
        self._ctx = self.config.ssl_context()
        import threading as _threading
        import urllib.parse as _parse

        parsed = _parse.urlsplit(self.config.host)
        self._scheme = parsed.scheme or "http"
        self._netloc = parsed.netloc
        # proxy-fronted apiservers (kubeconfig cluster.server with a path,
        # e.g. https://gw/k8s/clusters/c-abc) need the base path prefixed
        # onto every request target
        self._base_path = parsed.path.rstrip("/")
        self._local = _threading.local()
        # drop pooled connections idle past this: LBs/servers close idle
        # keep-alives, and a write on a dead socket must not fail the call
        # (writes are not retried — resending a processed POST would
        # double-execute)
        self._idle_limit_s = 30.0
        # Precomposed header block for the lean plain-HTTP unary path (the
        # hot path: http.client + its email-parsed responses measured
        # ~150us/call of pure overhead; a wire bench burst is ~6000 calls).
        self._static_hdr = f"Host: {self._netloc}\r\nAccept: application/json\r\n"
        if self.config.token:
            self._static_hdr += f"Authorization: Bearer {self.config.token}\r\n"

    def _new_conn(self, timeout):
        import http.client
        import socket as socket_mod

        if self._scheme == "https":
            conn = http.client.HTTPSConnection(
                self._netloc, timeout=timeout, context=self._ctx)
        else:
            conn = http.client.HTTPConnection(self._netloc, timeout=timeout)
        conn.connect()
        # Nagle + delayed-ACK interact to ~40ms/request on keep-alive
        # connections with small header+body writes; kill Nagle.
        try:
            conn.sock.setsockopt(socket_mod.IPPROTO_TCP,
                                 socket_mod.TCP_NODELAY, 1)
        except OSError:
            pass
        return conn

    # -- lean plain-HTTP unary transport -------------------------------------

    def _new_sock(self):
        import socket as socket_mod

        host, _, port_s = self._netloc.rpartition(":")
        if not host:  # no explicit port in netloc
            host, port_s = self._netloc, "80"
        sock = socket_mod.create_connection((host, int(port_s)), timeout=30)
        try:
            sock.setsockopt(socket_mod.IPPROTO_TCP, socket_mod.TCP_NODELAY, 1)
        except OSError:
            pass
        return sock, sock.makefile("rb", buffering=64 * 1024)

    def _pooled_sock(self):
        import time as time_mod

        sock = getattr(self._local, "sock", None)
        last = getattr(self._local, "sock_last_use", 0.0)
        now = time_mod.monotonic()
        if sock is not None and now - last > self._idle_limit_s:
            self._drop_sock()
            sock = None
        if sock is None:
            sock, rfile = self._new_sock()
            self._local.sock, self._local.sock_rfile = sock, rfile
        self._local.sock_last_use = now
        return self._local.sock, self._local.sock_rfile

    def _drop_sock(self) -> None:
        sock = getattr(self._local, "sock", None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
            self._local.sock = None

    def _lean_unary(self, method: str, path: str, data: Optional[bytes],
                    content_type: str, extra_hdr: str = ""):
        """One keep-alive request/response on the raw pooled socket.

        Handles exactly the protocol the unary path needs — status line,
        flat headers, Content-Length body (every unary apiserver response
        carries one) — and raises ConnectionError on anything else so the
        caller's stale-connection logic takes over.  ``extra_hdr`` carries
        per-request header lines (CRLF-terminated) the precomposed static
        block can't: today that's the traceparent header.
        """
        head = (
            f"{method} {path} HTTP/1.1\r\n" + self._static_hdr + extra_hdr
            + (f"Content-Type: {content_type}\r\n" if data is not None else "")
            + f"Content-Length: {len(data) if data is not None else 0}\r\n\r\n"
        )
        sock, rfile = self._pooled_sock()
        sock.sendall(head.encode("latin-1") + (data or b""))
        status_line = rfile.readline(65537)
        if not status_line:
            raise ConnectionError("server closed keep-alive connection")
        parts = status_line.split(None, 2)
        if len(parts) < 2 or not parts[0].startswith(b"HTTP/1."):
            raise ConnectionError(f"bad status line {status_line[:80]!r}")
        status = int(parts[1])
        reason = parts[2].strip().decode("latin-1") if len(parts) > 2 else ""
        clen = 0
        chunked = False
        # HTTP/1.0 servers close after each response unless they opt into
        # keep-alive explicitly; 1.1 is persistent unless told otherwise
        close = parts[0] == b"HTTP/1.0"
        while True:
            line = rfile.readline(65537)
            if line in (b"\r\n", b"\n"):
                break
            if not line:
                raise ConnectionError("eof inside response headers")
            key, _, value = line.partition(b":")
            kl = key.strip().lower()
            if kl == b"content-length":
                clen = int(value.strip())
            elif kl == b"connection":
                v = value.strip().lower()
                if b"close" in v:
                    close = True
                elif b"keep-alive" in v:
                    close = False
            elif kl == b"transfer-encoding":
                # kubectl proxy / Go servers chunk large list responses.
                # Decode it HERE: by this point the server has already
                # processed the request, so bailing out and re-sending
                # through another transport would double-execute writes.
                chunked = b"chunked" in value.lower()
        if chunked:
            body = self._read_chunked(rfile)
        else:
            body = rfile.read(clen) if clen else b""
        if close:
            self._drop_sock()
        return status, reason, body

    @staticmethod
    def _read_chunked(rfile) -> bytes:
        """RFC 7230 §4.1 chunked body (trailers tolerated and discarded)."""
        out = []
        while True:
            size_line = rfile.readline(65537)
            if not size_line:
                raise ConnectionError("eof inside chunked body")
            try:
                size = int(size_line.split(b";", 1)[0].strip(), 16)
            except ValueError:
                raise ConnectionError(
                    f"bad chunk size line {size_line[:40]!r}") from None
            if size == 0:
                while True:  # trailer section ends at a blank line
                    t = rfile.readline(65537)
                    if t in (b"\r\n", b"\n", b""):
                        break
                return b"".join(out)
            chunk = rfile.read(size)
            if len(chunk) != size:
                raise ConnectionError("eof inside chunk")
            out.append(chunk)
            rfile.read(2)  # trailing CRLF

    def _pooled_conn(self):
        import time as time_mod

        conn = getattr(self._local, "conn", None)
        last = getattr(self._local, "last_use", 0.0)
        now = time_mod.monotonic()
        if conn is not None and now - last > self._idle_limit_s:
            self._drop_conn()
            conn = None
        if conn is None:
            conn = self._new_conn(timeout=30)
            self._local.conn = conn
        self._local.last_use = now
        return conn

    def _drop_conn(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            try:
                conn.close()
            # except-ok: dropping a broken keep-alive connection; close
            # failures are the reason it is being dropped
            except Exception:
                pass
            self._local.conn = None

    # -- plumbing ------------------------------------------------------------

    def _url(self, resource: GVR, namespace: Optional[str], name: str = "", query=None) -> str:
        """Request target (path + query; the pooled connections already
        know the host).  Any base path from config.host is preserved."""
        parts = [self._base_path, resource.path_prefix.strip("/")]
        if resource.namespaced and namespace:
            parts += ["namespaces", namespace]
        parts.append(resource.plural)
        if name:
            parts.append(name)
        url = "/".join(parts)
        if query:
            url += "?" + urllib.parse.urlencode(query)
        return url

    def _headers(self, body) -> dict:
        headers = {"Accept": "application/json"}
        if body is not None:
            headers["Content-Type"] = "application/json"
        if self.config.token:
            headers["Authorization"] = f"Bearer {self.config.token}"
        return headers

    def _request(self, method: str, url: str, body: Optional[dict] = None,
                 stream: bool = False, content_type: Optional[str] = None):
        data = json.dumps(body).encode() if body is not None else None
        headers = self._headers(body)
        if body is not None and method == "PATCH":
            headers["Content-Type"] = content_type or "application/merge-patch+json"
        path = url
        # Flight-recorder accounting (ISSUE 7): one record per WIRE ATTEMPT
        # — a transport-retried GET is two attempts and two counts, exactly
        # what the apiserver saw.  Transport failures with no status = 0.
        acct_verb, acct_resource = _verb_and_resource(method, path)

        if stream:
            # dedicated connection: the response body is an open stream the
            # caller consumes until server close — never pooled
            a0 = time.perf_counter()
            conn = self._new_conn(timeout=None)
            try:
                conn.request(method, path, body=data, headers=headers)
                # Capture the socket BEFORE getresponse(): for Connection:
                # close responses (every watch stream) http.client detaches —
                # conn.sock becomes None and the socket lives on only inside
                # the response's buffered reader.  _RestWatch.stop() needs
                # this direct reference to shutdown() a blocked reader;
                # without it the stop blocks until the server's watch
                # timeout (measured 59s, 2x per LocalCluster teardown in
                # rest mode).
                sock = conn.sock
                resp = conn.getresponse()
            except Exception:
                flight.record_api_call(acct_verb, acct_resource, 0,
                                       time.perf_counter() - a0)
                raise
            flight.record_api_call(acct_verb, acct_resource, resp.status,
                                   time.perf_counter() - a0)
            if resp.status >= 400:
                raw = resp.read()
                conn.close()
                raise self._api_error(resp, raw)
            resp._k8s_tpu_conn = conn  # keep the connection alive with it
            resp._k8s_tpu_sock = sock
            return resp

        # Only idempotent methods are retried on a transport error: a POST
        # whose connection died after the server processed it would
        # double-execute on resend (spurious 409s, lost-update PUTs).
        attempts = (0, 1) if method in ("GET", "HEAD") else (0,)

        if self._scheme == "http":
            # lean raw-socket path (TLS stays on http.client below)
            t0 = time.perf_counter() if WIRE_PROFILE_ENABLED else 0.0
            for attempt in attempts:
                a0 = time.perf_counter()
                span, traceparent = self._trace_attempt(method, path, attempt)
                try:
                    status, reason, raw = self._lean_unary(
                        method, path, data, headers.get("Content-Type", ""),
                        extra_hdr=(f"traceparent: {traceparent}\r\n"
                                   if traceparent else ""))
                    flight.record_api_call(acct_verb, acct_resource, status,
                                           time.perf_counter() - a0)
                    if span is not None:
                        span.set_attribute("http_status", status)
                        span.finish()
                    break
                except (ConnectionError, OSError, ValueError) as e:
                    flight.record_api_call(acct_verb, acct_resource, 0,
                                           time.perf_counter() - a0)
                    if span is not None:
                        span.set_error(e)
                        span.finish()
                    self._drop_sock()
                    if attempt == attempts[-1]:
                        raise
            if WIRE_PROFILE_ENABLED:
                _profile_record(method, path, time.perf_counter() - t0)
            if status >= 400:
                raise self._api_error_from(status, reason, raw)
            payload = raw.decode()
            return json.loads(payload) if payload else {}

        import http.client

        t0 = time.perf_counter() if WIRE_PROFILE_ENABLED else 0.0
        for attempt in attempts:
            a0 = time.perf_counter()
            span, traceparent = self._trace_attempt(method, path, attempt)
            if traceparent:
                headers["traceparent"] = traceparent
            conn = self._pooled_conn()
            try:
                conn.request(method, path, body=data, headers=headers)
                resp = conn.getresponse()
                raw = resp.read()  # fully drain so the connection can be reused
                flight.record_api_call(acct_verb, acct_resource, resp.status,
                                       time.perf_counter() - a0)
                if span is not None:
                    span.set_attribute("http_status", resp.status)
                    span.finish()
                break
            except (http.client.HTTPException, ConnectionError, OSError) as e:
                # stale keep-alive (server closed between requests) or
                # transport hiccup
                flight.record_api_call(acct_verb, acct_resource, 0,
                                       time.perf_counter() - a0)
                if span is not None:
                    span.set_error(e)
                    span.finish()
                self._drop_conn()
                if attempt == attempts[-1]:
                    raise
        if WIRE_PROFILE_ENABLED:
            _profile_record(method, path, time.perf_counter() - t0)
        if resp.status >= 400:
            raise self._api_error(resp, raw)
        payload = raw.decode()
        return json.loads(payload) if payload else {}

    @staticmethod
    def _trace_attempt(method: str, path: str, attempt: int):
        """(span, traceparent-header-value) for one wire attempt, or
        (None, None) when tracing is off or no span is current.

        One FRESH span per attempt — same trace-id, new span-id — so a
        transport-retried GET shows up as two wire calls in the span tree
        and in whatever the apiserver logged, instead of two server-side
        operations claiming one client span."""
        from k8s_tpu import trace

        if not trace.enabled() or trace.current_span() is None:
            return None, None
        span = trace.TRACER.start_span(
            f"http {_profile_key(method, path)}", method=method,
            attempt=attempt)
        return span, trace.format_traceparent(
            span.trace_id, span.span_id, span.head_sampled)

    @staticmethod
    def _api_error_from(code: int, reason: str, raw: bytes) -> errors.ApiError:
        try:
            status = json.loads(raw.decode())
        except Exception:
            status = {}
        return errors.ApiError(
            code,
            status.get("reason", reason),
            status.get("message", f"HTTP {code} {reason}"),
        )

    @classmethod
    def _api_error(cls, resp, raw: bytes) -> errors.ApiError:
        return cls._api_error_from(resp.status, resp.reason, raw)

    # -- backend protocol ----------------------------------------------------

    def create(self, resource: GVR, namespace: str, obj: dict) -> dict:
        obj.setdefault("apiVersion", resource.api_version)
        obj.setdefault("kind", resource.kind)
        return self._request("POST", self._url(resource, namespace), obj)

    def get(self, resource: GVR, namespace: str, name: str) -> dict:
        return self._request("GET", self._url(resource, namespace, name))

    def list(self, resource: GVR, namespace=None, label_selector=None, field_selector=None):
        items, _rv = self.list_with_rv(resource, namespace, label_selector, field_selector)
        return items

    def list_with_rv(self, resource: GVR, namespace=None, label_selector=None,
                     field_selector=None):
        """List plus ListMeta.resourceVersion (None if the server omits it,
        so the reflector falls back to resume-free watches instead of
        treating rv=0 as a real resume point) —
        the rv a reflector resumes its watch from."""
        query = {}
        required = parse_label_selector(label_selector)
        if required:
            query["labelSelector"] = ",".join(f"{k}={v}" for k, v in required.items())
        if field_selector:
            query["fieldSelector"] = ",".join(f"{k}={v}" for k, v in field_selector.items())
        out = self._request("GET", self._url(resource, namespace, query=query))
        # rv is an OPAQUE string per the K8s API contract: return it
        # verbatim (or None when omitted).  Parsing int() here made every
        # watch cycle against a server with non-numeric rvs degrade to a
        # full relist — correct but defeating the resume optimization.
        rv = (out.get("metadata") or {}).get("resourceVersion") or None
        return out.get("items", []), rv

    def update(self, resource: GVR, namespace: str, obj: dict) -> dict:
        name = obj["metadata"]["name"]
        ns = obj["metadata"].get("namespace", namespace)
        return self._request("PUT", self._url(resource, ns, name), obj)

    def patch_merge(self, resource: GVR, namespace: str, name: str, patch: dict) -> dict:
        return self._request("PATCH", self._url(resource, namespace, name), patch)

    def patch_strategic(self, resource: GVR, namespace: str, name: str,
                        patch: dict) -> dict:
        """PATCH with application/strategic-merge-patch+json (merge-keyed
        list semantics; 415 from real apiservers for custom resources)."""
        return self._request(
            "PATCH", self._url(resource, namespace, name), patch,
            content_type="application/strategic-merge-patch+json")

    def delete(self, resource: GVR, namespace: str, name: str, propagation="Background"):
        url = self._url(resource, namespace, name, query={"propagationPolicy": propagation})
        self._request("DELETE", url)

    def delete_collection(self, resource: GVR, namespace: str, label_selector=None) -> int:
        victims = self.list(resource, namespace, label_selector)
        deleted = 0
        for v in victims:
            vns = v["metadata"].get("namespace", namespace)
            try:
                self.delete(resource, vns, v["metadata"]["name"])
                deleted += 1
            except errors.ApiError:
                pass
        return deleted

    def watch(self, resource: GVR, namespace=None, resource_version=None) -> _RestWatch:
        query = {"watch": "true"}
        if resource_version is not None:
            query["resourceVersion"] = str(resource_version)
        resp = self._request("GET", self._url(resource, namespace, query=query), stream=True)
        return _RestWatch(resp)

"""Typed clientset façade (reference: pkg/client/clientset/versioned/).

``Clientset`` wraps any backend implementing the API protocol (FakeCluster or
RestClient) and exposes per-resource accessors mirroring the generated Go
clientset's surface: ``cs.pods(ns).create(obj)``, ``cs.tfjobs(ns).update(job)``
etc.  TFJob accessors speak typed objects (with to_dict/from_dict); core
resources stay unstructured dicts.
"""

from __future__ import annotations

from typing import Optional

from k8s_tpu.api import register
from k8s_tpu.client import gvr as gvrs
from k8s_tpu.client.gvr import GVR


class ResourceClient:
    """CRUD for one (resource, namespace) pair over the backend protocol."""

    def __init__(self, backend, resource: GVR, namespace: str = ""):
        self._backend = backend
        self.resource = resource
        self.namespace = namespace

    def create(self, obj: dict) -> dict:
        return self._backend.create(self.resource, self.namespace, obj)

    def get(self, name: str) -> dict:
        return self._backend.get(self.resource, self.namespace, name)

    def list(self, label_selector=None, field_selector=None) -> list[dict]:
        return self._backend.list(
            self.resource, self.namespace or None, label_selector, field_selector
        )

    def update(self, obj: dict) -> dict:
        return self._backend.update(self.resource, self.namespace, obj)

    def patch(self, name: str, patch: dict,
              patch_type: str = "merge") -> dict:
        """``patch_type`` selects the wire semantics: ``"merge"`` (RFC 7386
        JSON merge patch, the default) or ``"strategic"`` (merge-keyed list
        semantics — built-in API groups only; apiservers answer 415 for
        custom resources)."""
        if patch_type == "strategic":
            return self._backend.patch_strategic(
                self.resource, self.namespace, name, patch)
        if patch_type != "merge":
            raise ValueError(f"unknown patch_type {patch_type!r}")
        return self._backend.patch_merge(self.resource, self.namespace, name, patch)

    def delete(self, name: str, propagation: str = "Background") -> None:
        self._backend.delete(self.resource, self.namespace, name, propagation)

    def delete_collection(self, label_selector=None) -> int:
        return self._backend.delete_collection(self.resource, self.namespace, label_selector)

    def watch(self, namespace: Optional[str] = None):
        return self._backend.watch(self.resource, namespace or self.namespace or None)


class TFJobClient(ResourceClient):
    """Typed TFJob CRUD (reference: generated tfjob clientset) — accepts and
    returns typed TFJob objects for either API version."""

    def create(self, job) -> object:
        return register.tfjob_from_unstructured(super().create(job.to_dict()))

    def get(self, name: str) -> object:
        return register.tfjob_from_unstructured(super().get(name))

    def list(self, label_selector=None, field_selector=None) -> list:
        return [
            register.tfjob_from_unstructured(o)
            for o in super().list(label_selector, field_selector)
        ]

    def update(self, job) -> object:
        return register.tfjob_from_unstructured(super().update(job.to_dict()))


class Clientset:
    """One handle over the whole API surface the operator uses."""

    def __init__(self, backend):
        self.backend = backend

    def pods(self, namespace: str) -> ResourceClient:
        return ResourceClient(self.backend, gvrs.PODS, namespace)

    def services(self, namespace: str) -> ResourceClient:
        return ResourceClient(self.backend, gvrs.SERVICES, namespace)

    def events(self, namespace: str) -> ResourceClient:
        return ResourceClient(self.backend, gvrs.EVENTS, namespace)

    def endpoints(self, namespace: str) -> ResourceClient:
        return ResourceClient(self.backend, gvrs.ENDPOINTS, namespace)

    def configmaps(self, namespace: str) -> ResourceClient:
        return ResourceClient(self.backend, gvrs.CONFIGMAPS, namespace)

    def namespaces(self) -> ResourceClient:
        return ResourceClient(self.backend, gvrs.NAMESPACES, "")

    def pdbs(self, namespace: str) -> ResourceClient:
        return ResourceClient(self.backend, gvrs.PDBS, namespace)

    def crds(self) -> ResourceClient:
        return ResourceClient(self.backend, gvrs.CRDS, "")

    def tfjobs(self, namespace: str, api_version: str = "kubeflow.org/v1alpha2") -> TFJobClient:
        return TFJobClient(self.backend, gvrs.tfjobs_gvr(api_version), namespace)

    def tfjobs_unstructured(
        self, namespace: str, api_version: str = "kubeflow.org/v1alpha2"
    ) -> ResourceClient:
        """Dynamic-client style access (pkg/util/unstructured/informer.go)."""
        return ResourceClient(self.backend, gvrs.tfjobs_gvr(api_version), namespace)
